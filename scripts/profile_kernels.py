#!/usr/bin/env python
"""Clean kernel microbenchmarks: all outputs reduced to scalars on-device so
the tunnel transfer never pollutes timing.  Measures dispatch latency, MXU
matmul ceiling, and representative ResNet conv fwd/bwd shapes."""

import jax
import jax.numpy as jnp
import numpy as np
from functools import partial

from benchlib import timed_scalar as _timed_scalar  # noqa: E402

# microbenchmark sampling: more iters/warmup than benchlib's quick default
timed_scalar = partial(_timed_scalar, iters=30, warmup=5)


def main():
    # dispatch latency: trivial op
    x1 = jnp.float32(1.0)
    triv = jax.jit(lambda v: v + 1.0)
    t = timed_scalar(triv, x1, iters=50)
    print(f"dispatch latency (trivial jit): {t*1e3:.3f} ms")

    # MXU ceiling: bf16 matmul, scalar readout
    for m in (4096, 8192):
        a = jnp.ones((m, m), jnp.bfloat16)

        @jax.jit
        def mm(a):
            return (a @ a).astype(jnp.float32).sum()

        t = timed_scalar(mm, a)
        print(f"matmul {m}^2 bf16: {t*1e3:.2f} ms -> {2*m**3/t/1e12:.1f} TFLOP/s")

    # f32 matmul for contrast
    a = jnp.ones((4096, 4096), jnp.float32)

    @jax.jit
    def mmf(a):
        return (a @ a).sum()

    t = timed_scalar(mmf, a)
    print(f"matmul 4096^2 f32: {t*1e3:.2f} ms -> {2*4096**3/t/1e12:.1f} TFLOP/s")

    # chained matmuls (amortize any per-launch overhead inside one program)
    m = 4096
    a = jnp.ones((m, m), jnp.bfloat16)

    @jax.jit
    def mm8(a):
        x = a
        for _ in range(8):
            x = x @ a
        return x.astype(jnp.float32).sum()

    t = timed_scalar(mm8, a)
    print(f"8x chained matmul {m}^2 bf16: {t*1e3:.2f} ms -> "
          f"{8*2*m**3/t/1e12:.1f} TFLOP/s")

    # representative ResNet-50 convs (NHWC, bf16): (batch,h,w,cin) x (k,k,cin,cout)
    shapes = [
        (256, 56, 56, 64, 64, 3),    # stage1 3x3
        (256, 28, 28, 128, 128, 3),  # stage2 3x3
        (256, 14, 14, 256, 256, 3),  # stage3 3x3
        (256, 7, 7, 512, 512, 3),    # stage4 3x3
        (256, 56, 56, 64, 256, 1),   # 1x1 expand
    ]
    for (b, h, w, cin, cout, k) in shapes:
        x = jnp.ones((b, h, w, cin), jnp.bfloat16)
        wgt = jnp.ones((k, k, cin, cout), jnp.bfloat16)

        @jax.jit
        def conv(x, wgt):
            y = jax.lax.conv_general_dilated(
                x, wgt, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32,
            )
            return y.sum()

        t = timed_scalar(conv, x, wgt)
        flops = 2 * b * h * w * cin * cout * k * k
        print(f"conv fwd b{b} {h}x{w} {cin}->{cout} k{k}: {t*1e3:.2f} ms -> "
              f"{flops/t/1e12:.1f} TFLOP/s")

        @jax.jit
        def convg(x, wgt):
            def f(x, wgt):
                y = jax.lax.conv_general_dilated(
                    x, wgt, (1, 1), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                return y.astype(jnp.float32).sum()

            gx, gw = jax.grad(f, argnums=(0, 1))(x, wgt)
            return gx.astype(jnp.float32).sum() + gw.astype(jnp.float32).sum()

        t = timed_scalar(convg, x, wgt)
        print(f"  conv fwd+bwd: {t*1e3:.2f} ms -> {3*flops/t/1e12:.1f} TFLOP/s eq")


if __name__ == "__main__":
    main()
