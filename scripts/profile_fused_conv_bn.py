#!/usr/bin/env python
"""Per-shape micro-bench of the fused conv+BN backward kernels vs the
unfused XLA sequence they replace (form dy from (y, do) -> dgrad -> wgrad).

The full-model triage (runs/fused_triage.py, v5e 2026-07-31) showed the
fused variant losing 2,536 -> 1,208 img/s; this isolates which shapes lose
and by how much so the kernels (tile sizing, matmul shaping) can be tuned
one shape at a time without 5-minute full-model compiles.

Run on the real chip:  PYTHONPATH=. python scripts/profile_fused_conv_bn.py
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from benchlib import timed_scalar  # noqa: E402

from pytorch_distributed_tpu.ops import fused_conv_bn as fcb  # noqa: E402

B = int(os.environ.get("FCB_BATCH", "256"))

# (H, Ci, Co, ksz): every distinct conv->BN backward shape in ResNet-50
# bottlenecks (1x1 reduce / 1x1 expand / 3x3 middle per stage).
SHAPES = [
    (56, 64, 64, 1), (56, 64, 256, 1), (56, 256, 64, 1), (56, 64, 64, 3),
    (28, 128, 128, 3), (28, 128, 512, 1), (28, 512, 128, 1),
    (14, 256, 256, 3), (14, 256, 1024, 1), (14, 1024, 256, 1),
    (7, 512, 512, 3), (7, 512, 2048, 1), (7, 2048, 512, 1),
]


def run_shape(h, ci, co, ksz, dtype=jnp.bfloat16):
    key = jax.random.split(jax.random.PRNGKey(0), 3)
    y = jax.random.normal(key[0], (B, h, h, co), dtype)
    do = jax.random.normal(key[1], (B, h, h, co), dtype)
    a = jax.random.normal(key[2], (B, h, h, ci), dtype)
    if ksz == 3:
        w = jnp.ones((3, 3, ci, co), jnp.float32) / (3 * ci)
    else:
        w = jnp.ones((ci, co), jnp.float32) / ci
    s = jnp.ones((co,), jnp.float32)
    t = jnp.full((co,), 0.1, jnp.float32)
    u = jnp.zeros((co,), jnp.float32)
    v = jnp.zeros((co,), jnp.float32)

    # Bytes the backward must move at minimum: read y, do, a once; write da.
    nbytes = (y.nbytes + do.nbytes + a.nbytes
              + a.size * jnp.dtype(dtype).itemsize)

    @jax.jit
    def fused(y, do, a, w):
        if ksz == 3:
            da, dw = fcb._fused_dgrad_wgrad_3x3(
                y, do, a, w, s, t, u, v, True, False)
        else:
            da, dw = fcb._fused_dgrad_wgrad(
                y, do, a, w, s, t, u, v, True, False)
        return da.astype(jnp.float32).sum() + dw.sum()

    @jax.jit
    def unfused(y, do, a, w):
        yf = y.astype(jnp.float32)
        dof = do.astype(jnp.float32)
        dof = jnp.where(yf * s + v > 0, dof, 0.0)
        dy = (dof * s + yf * t + u).astype(dtype)
        if ksz == 3:
            da = jax.lax.conv_general_dilated(
                dy, jnp.transpose(w, (0, 1, 3, 2))[::-1, ::-1].astype(dtype),
                (1, 1), ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"))
            dw = jax.lax.conv_general_dilated(
                jnp.transpose(a, (3, 1, 2, 0)).astype(dtype),
                jnp.transpose(dy, (1, 2, 0, 3)).astype(dtype),
                (1, 1), ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
                preferred_element_type=jnp.float32)
            dw = jnp.transpose(dw, (1, 2, 0, 3))
        else:
            m = y.shape[0] * h * h
            da = (dy.reshape(m, co) @ w.astype(dtype).T).reshape(a.shape)
            dw = jax.lax.dot_general(
                a.reshape(m, ci), dy.reshape(m, co),
                (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        return da.astype(jnp.float32).sum() + dw.sum()

    tf = timed_scalar(fused, y, do, a, w, iters=10, warmup=3)
    tu = timed_scalar(unfused, y, do, a, w, iters=10, warmup=3)
    tag = f"{ksz}x{ksz} {h:3d}x{h:<3d} {ci:4d}->{co:<4d}"
    print(f"{tag}  fused {tf*1e3:7.3f} ms ({nbytes/tf/1e9:6.1f} GB/s)  "
          f"xla {tu*1e3:7.3f} ms ({nbytes/tu/1e9:6.1f} GB/s)  "
          f"ratio {tu/tf:5.2f}x", flush=True)
    return tf, tu


def main():
    total_f = total_u = 0.0
    for h, ci, co, ksz in SHAPES:
        tf, tu = run_shape(h, ci, co, ksz)
        total_f += tf
        total_u += tu
    print(f"TOTAL fused {total_f*1e3:.2f} ms  xla {total_u*1e3:.2f} ms  "
          f"ratio {total_u/total_f:.2f}x")


if __name__ == "__main__":
    main()
