"""Shared timing + event helpers for the benchmark/profiling scripts.

Sync discipline on this platform: fetch a SCALAR value — on the tunneled
axon backend ``block_until_ready`` can return before the device queue
drains, so ``float(out)`` (a value fetch) is the only reliable barrier.
Benchmarked computations must therefore reduce to a scalar on-device.
"""

import json
import os
import time


def timed_scalar(fn, *args, iters=5, warmup=2):
    """Mean seconds/call of ``fn(*args)``, which must return a device scalar."""
    for _ in range(warmup):
        out = fn(*args)
    float(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(out)
    return (time.perf_counter() - t0) / iters


def timed_tree(fn, *args, iters=5, warmup=2):
    """Mean seconds/call of ``fn(*args)`` whose output is a pytree: syncs
    by value-fetching one element of the first leaf (same barrier rationale
    as ``timed_scalar`` — see module docstring).  Use when the benchmarked
    fn can't reduce to a scalar (grad trees, optimizer updates)."""
    import jax
    import numpy as np

    def _sync(out):
        np.asarray(jax.tree_util.tree_leaves(out)[0]).ravel()[:1]

    for _ in range(warmup):
        out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / iters


def timed_step_loop(step, state, batch, lr, iters=20, warmup=3,
                    scalar_key="loss"):
    """Warmup + timed loop over a stateful train step
    ``state, met = step(state, batch, lr)``, syncing via a value fetch of
    ``met[scalar_key]``.  Threads the state (donated steps consume it),
    so returns ``(mean_seconds, final_state)``."""
    for _ in range(warmup):
        state, met = step(state, batch, lr)
    float(met[scalar_key])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, met = step(state, batch, lr)
    float(met[scalar_key])
    return (time.perf_counter() - t0) / iters, state


def bench_event(kind, path=None, **fields):
    """Append one structured ``bench_event`` record to a JSONL file in the
    metrics-stream schema (``{"bench_event": kind, "t": ..., ...}``) —
    ``scripts/obs_report.py`` folds it into the run summary alongside step
    and ft_event records.

    The headline use: ``bench.py`` marking a *stale* probe (tunnel down,
    last-known-good number replayed) with the reason and the last-good
    timestamp, so a replayed benchmark is visible out-of-band of the
    stdout JSON contract.  ``path`` defaults to ``$BENCH_EVENTS_JSONL`` or
    ``bench_events.jsonl`` next to this repo's ``bench.py``.  Best-effort:
    never raises — an unwritable event log must not take down the
    benchmark emission itself."""
    if path is None:
        path = os.environ.get("BENCH_EVENTS_JSONL") or os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "bench_events.jsonl")
    rec = {"bench_event": str(kind), "t": time.time()}
    rec.update(fields)
    try:
        with open(path, "a") as f:
            f.write(json.dumps(rec) + "\n")
    except OSError:
        pass
    return rec


def _default_events_path():
    return os.environ.get("BENCH_EVENTS_JSONL") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "bench_events.jsonl")


def parse_lkg_time(stamp):
    """``captured_at`` (``%Y-%m-%dT%H:%M:%S%z``) -> epoch seconds, or None
    on anything unparseable."""
    from datetime import datetime

    try:
        return datetime.strptime(str(stamp), "%Y-%m-%dT%H:%M:%S%z").timestamp()
    except (TypeError, ValueError):
        return None


def bench_staleness(lkg_path=None, events_path=None, now=None):
    """Days since the benchmark's last *fresh* capture.

    A successful ``bench.py`` run rewrites ``BENCH_LKG.json`` (its
    ``captured_at`` is the last-good mark); ``stale``/``failed`` events in
    ``bench_events.jsonl`` never refresh it — they only echo the LKG — but
    an explicit ``captured`` event does.  Both files are optional: a
    missing events log is the common case on a fresh checkout, and with no
    parseable timestamp anywhere the answer is ``None`` rather than a
    guess.  Returns ``{"metric", "last_good", "days_stale",
    "stale_events"}``, plus the planner-drift fields bench.py stamps on a
    fresh capture (``predicted_mfu``/``measured_mfu``/
    ``prediction_drift_pct`` — plan/planner.py ``predicted_mfu`` vs the
    measured step) when the freshest capture carries them."""
    if lkg_path is None:
        lkg_path = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "BENCH_LKG.json")
    if events_path is None:
        events_path = _default_events_path()
    metric, last_good_t, last_good = None, None, None
    drift = {}

    def _drift_fields(rec):
        return {k: rec[k] for k in ("predicted_mfu", "measured_mfu",
                                    "prediction_drift_pct")
                if rec.get(k) is not None}

    try:
        with open(lkg_path) as f:
            lkg = json.load(f)
        metric = lkg.get("metric")
        last_good = lkg.get("captured_at")
        last_good_t = parse_lkg_time(last_good)
        drift = _drift_fields(lkg)
    except (OSError, ValueError):
        pass
    stale_events = 0
    try:
        with open(events_path) as f:
            for line in f:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict) or "bench_event" not in rec:
                    continue
                kind = str(rec["bench_event"])
                if kind in ("stale", "failed"):
                    stale_events += 1
                elif kind == "captured" and rec.get("t") is not None:
                    t = float(rec["t"])
                    if last_good_t is None or t > last_good_t:
                        last_good_t, last_good = t, rec.get("captured_at")
                        drift = _drift_fields(rec)
                    metric = rec.get("metric", metric)
    except OSError:
        pass
    if last_good_t is None:
        return None
    if now is None:
        now = time.time()
    out = {
        "metric": metric,
        "last_good": last_good,
        "days_stale": max(0.0, (now - last_good_t) / 86400.0),
        "stale_events": stale_events,
    }
    out.update(drift)
    return out
