"""Shared timing helper for the profiling scripts.

Sync discipline on this platform: fetch a SCALAR value — on the tunneled
axon backend ``block_until_ready`` can return before the device queue
drains, so ``float(out)`` (a value fetch) is the only reliable barrier.
Benchmarked computations must therefore reduce to a scalar on-device.
"""

import time


def timed_scalar(fn, *args, iters=5, warmup=2):
    """Mean seconds/call of ``fn(*args)``, which must return a device scalar."""
    for _ in range(warmup):
        out = fn(*args)
    float(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    float(out)
    return (time.perf_counter() - t0) / iters
