#!/usr/bin/env python
"""obs_roofline — per-step training-time attribution + roofline (ISSUE 20).

Reads the ``attr_*`` fields a ``--step-attr`` run stamps into the metrics
JSONL (recorder in obs/stepattr.py) plus the one-time ``stepattr_phases``
ft_event, and answers *where did my step go* exactly:

    step_time == compute + exposed_comm + host_sync + data_wait + other

    # human report: the identity, shares, and the fix-first table
    obs_roofline.py --metrics-jsonl /tmp/train.jsonl

    # machine form (summary + roofline)
    obs_roofline.py --metrics-jsonl /tmp/train.jsonl --json

    # per-component Perfetto counter tracks over the run's step clock
    obs_roofline.py --metrics-jsonl /tmp/train.jsonl --perfetto /tmp/attr.json

    # the measured profile for the planner loop (autoplan --attr-from)
    obs_roofline.py --metrics-jsonl /tmp/train.jsonl --attr-out /tmp/attr.json

The roofline needs no hardware tables: the trainer embeds per-phase
FLOPs/HBM bytes and the chip peaks in the ``stepattr_phases`` event, so
each phase is labeled compute-bound / hbm-bound / comm-bound / host-bound
from the event alone.

Runs with **no jax in the process** — obs/stepattr.py is loaded by file
path, never through the package ``__init__`` (which imports jax for the
shard_map bridge); ``--selftest`` asserts it, like obs_trace.py, and
round-trips the checked-in fixture ``tests/data/stepattr_fixture.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS = os.path.join(_REPO, "pytorch_distributed_tpu", "obs")
FIXTURE = os.path.join(_REPO, "tests", "data", "stepattr_fixture.jsonl")


def _load_obs(name: str):
    """Load ``pytorch_distributed_tpu/obs/<name>.py`` by path under the
    same ``_ptd_obs_<name>`` alias obs/alerts.py uses, so the sibling
    modules share one instance and jax never enters the process."""
    import importlib.util

    full = f"pytorch_distributed_tpu.obs.{name}"
    if full in sys.modules:
        return sys.modules[full]
    alias = f"_ptd_obs_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(
        alias, os.path.join(_OBS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


stepattr = _load_obs("stepattr")
metrics = _load_obs("metrics")


# ------------------------------------------------------------------ analysis

def analyze(path: str, top_k: int = 5):
    """Parse the JSONL and return ``(records, summary, roofline)`` —
    summary None without ``--step-attr`` records, roofline None without a
    ``stepattr_phases`` event to anchor it."""
    records = metrics.read_metrics(path)
    summ = stepattr.summarize(records)
    roof = None
    if summ is not None:
        ev = stepattr.phase_event(records)
        if ev is not None:
            roof = stepattr.roofline(summ, ev, top_k=top_k)
    return records, summ, roof


def render(summ, roof) -> str:
    lines = ["== step attribution =="]
    if summ is None:
        lines.append("no attr_* step records (run a trainer with "
                     "--step-attr)")
        return "\n".join(lines)
    lines.append(
        f"steps {summ['steps']}  "
        f"recon err max {summ['recon_err_ms_max']:.3f}ms "
        f"({summ['recon_err_pct_p50']:.2f}% of step p50)")
    lines.append(stepattr.format_summary_line(summ))
    lines.append(
        f"data_wait_share p50 {summ['data_wait_share_p50']:.1f}%  "
        f"p95 {summ['data_wait_share_p95']:.1f}%  "
        f"host_sync p95 {summ['host_sync_ms_p95']:.2f}ms")
    ov = summ.get("overlap_measured")
    if ov is not None:
        lines.append(f"comm overlap measured {ov:.2f} "
                     f"(exposure source: {summ['exposure_source']})")
    if roof is None:
        lines.append("no stepattr_phases event — roofline unavailable "
                     "(the trainer books it once per --step-attr run)")
        return "\n".join(lines)
    lines.append("== roofline ==")
    lines.append(f"ridge {roof['ridge_flops_per_byte']:.1f} flops/byte")
    for p in roof["phases"]:
        util = ""
        if "flops_util_pct" in p:
            util = (f"flops {p['flops_util_pct']:.1f}% of peak, "
                    f"hbm {p['hbm_util_pct']:.1f}%")
        elif "link_util_pct" in p:
            util = f"link {p['link_util_pct']:.1f}%"
        lines.append(f"  {p['phase']:<12} {p['ms']:8.2f}ms  "
                     f"{p['label']:<14} {util}")
    lines.append("fix first (headroom = ms a perfectly-utilized phase "
                 "gives back):")
    for i, p in enumerate(roof["fix_first"], 1):
        lines.append(f"  {i}. {p['phase']:<12} {p['headroom_ms']:8.2f}ms  "
                     f"({p['label']})")
    return "\n".join(lines)


# ------------------------------------------------------------------ selftest

def _selftest() -> int:
    assert "jax" not in sys.modules, \
        "obs_roofline selftest must run jax-free (import-time hygiene)"
    assert os.path.exists(FIXTURE), f"missing fixture {FIXTURE}"

    records, summ, roof = analyze(FIXTURE)
    assert summ is not None and summ["steps"] >= 8, summ
    # the identity reconciles on the checked-in artifact, inside the
    # runtime fence (<= 0.5% of step p50)
    assert summ["recon_err_pct_p50"] <= 0.5, summ["recon_err_pct_p50"]
    # shares sum back to ~100% of step p50 (the identity, in share form)
    assert abs(sum(summ["shares_pct"].values()) - 100.0) < 1.5, \
        summ["shares_pct"]
    assert summ["dominant"] == "compute", summ["dominant"]
    assert roof is not None, "fixture lost its stepattr_phases event"
    labels = {p["phase"]: p["label"] for p in roof["phases"]}
    # the fixture's phase ledger pins one of each class: fwd/bwd clear
    # the ridge, the optimizer streams state, grad_sync is the wire
    assert labels["forward"] == "compute-bound", labels
    assert labels["backward"] == "compute-bound", labels
    assert labels["update"] == "hbm-bound", labels
    assert labels["grad_sync"] == "comm-bound", labels
    assert labels["data_wait"] == "host-bound", labels
    assert roof["fix_first"], roof
    out = render(summ, roof)
    for needle in ("== step attribution ==", "== roofline ==",
                   "dominant: compute", "fix first", "ridge",
                   "recon err max"):
        assert needle in out, f"missing {needle!r} in:\n{out}"

    # counter tracks: one track per component + the share track
    evs = stepattr.chrome_counter_events(records)
    names = {e["name"] for e in evs if e.get("ph") == "C"}
    for c in stepattr.COMPONENTS:
        assert f"attr · {c}_ms" in names, names
    assert "data_wait_share" in names, names

    # runtime round-trip in a tempdir: StepAttr windows -> MetricsLogger
    # -> summarize names the planted bottleneck, write/load_attr carries
    # it to the planner form
    import tempfile
    import time as _time

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "train.jsonl")
        sa = stepattr.StepAttr(comm_bytes_per_step=1e6,
                               link_bytes_per_s=1e10)
        with metrics.MetricsLogger(path, flush_every=1) as log:
            for step in range(6):
                with sa.data_wait():
                    _time.sleep(0.012)  # the planted loader stall
                with sa.device():
                    _time.sleep(0.002)
                t0 = _time.perf_counter()
                with sa.host_sync():
                    pass
                dt = 0.016 + (_time.perf_counter() - t0)
                log.log_step(step, step_time=dt, n_items=8, lr=1e-3,
                             scalars={}, extra=sa.fields(dt))
        rt = metrics.read_metrics(path)
        s2 = stepattr.summarize(rt)
        assert s2 is not None and s2["dominant"] == "data_wait", s2
        assert s2["recon_err_pct_p50"] <= 0.5, s2
        apath = os.path.join(d, "attr.json")
        prof = stepattr.write_attr(apath, s2)
        back = stepattr.load_attr(apath)
        assert back["kind"] == "stepattr_profile", back
        assert back["bottleneck"] == "data_wait", back
        assert back["attr_source"] == apath, back
        assert abs(back["step_ms_p50"] - prof["step_ms_p50"]) < 1e-9
        # a non-profile JSON is rejected loudly
        bogus = os.path.join(d, "bogus.json")
        with open(bogus, "w") as f:
            json.dump({"overlap": 0.5}, f)
        try:
            stepattr.load_attr(bogus)
            raise AssertionError("load_attr accepted a non-profile JSON")
        except ValueError:
            pass

    assert "jax" not in sys.modules
    print("obs_roofline selftest: OK")
    return 0


# ---------------------------------------------------------------------- main

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-step training-time attribution + roofline")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="metrics JSONL from a --step-attr run")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable summary + roofline")
    ap.add_argument("--top-k", type=int, default=5,
                    help="fix-first table depth")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="write per-component counter tracks as a "
                         "Chrome-trace JSON")
    ap.add_argument("--attr-out", default=None, metavar="ATTR",
                    help="write the measured profile for "
                         "autoplan --attr-from")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture round-trip + jax-free assertion")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest()
    if not args.metrics_jsonl:
        ap.error("--metrics-jsonl is required (or --selftest)")
    records, summ, roof = analyze(args.metrics_jsonl, top_k=args.top_k)
    if args.perfetto:
        trace = {"traceEvents": stepattr.chrome_counter_events(records),
                 "displayTimeUnit": "ms"}
        with open(args.perfetto, "w") as f:
            json.dump(trace, f)
        print(f"wrote {args.perfetto} "
              f"({len(trace['traceEvents'])} events)")
    if args.attr_out:
        if summ is None:
            print("no attr_* step records — nothing to write",
                  file=sys.stderr)
            return 2
        prof = stepattr.write_attr(args.attr_out, summ)
        print(f"wrote {args.attr_out} (bottleneck: {prof['bottleneck']}, "
              f"overlap: {prof['overlap']})")
    if args.as_json:
        out = dict(summ) if summ else {}
        if roof is not None:
            out["roofline"] = roof
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(render(summ, roof))
    return 0


if __name__ == "__main__":
    sys.exit(main())
