#!/usr/bin/env python
"""HBM bandwidth ceiling + full-step batch-size sensitivity."""

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


from benchlib import timed_scalar, timed_step_loop  # noqa: E402


def hbm():
    n = 128 * 1024 * 1024  # 256MB bf16
    x = jnp.ones((n,), jnp.bfloat16)
    REPS = 20

    @jax.jit
    def chain(x):
        def body(i, x):
            return x * 1.0000001 + 0.0000001

        return jax.lax.fori_loop(0, REPS, body, x).astype(jnp.float32).mean()

    t = timed_scalar(chain, x) / REPS
    traffic = 2 * n * 2  # read + write bf16
    print(f"elementwise chain: {t*1e3:.3f} ms -> {traffic/t/1e9:.0f} GB/s")

    @jax.jit
    def reduce_chain(x):
        def body(i, acc):
            return acc + (x * (1.0 + acc)).astype(jnp.float32).mean()

        return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

    t = timed_scalar(reduce_chain, x) / REPS
    print(f"reduce chain (read-only): {t*1e3:.3f} ms -> {n*2/t/1e9:.0f} GB/s")


def step_bench(batch):
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    image = 224
    mesh = data_parallel_mesh()
    model = models.create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
                          train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    b = {"images": jnp.asarray(rng.normal(size=(batch, image, image, 3)).astype(np.float32)),
         "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
         "weights": jnp.ones((batch,), jnp.float32)}
    lr = jnp.float32(0.1)
    dt, _ = timed_step_loop(step, state, b, lr, iters=10, warmup=3)
    print(f"batch {batch}: {dt*1e3:.1f} ms/step -> {batch/dt:.0f} img/s")


if __name__ == "__main__":
    if sys.argv[1] == "hbm":
        hbm()
    else:
        step_bench(int(sys.argv[1]))
