#!/usr/bin/env python
"""Resilient serving fleet CLI: replicas, router, arbiter, bench
(ISSUE 19).

Subcommands:

- ``replica`` — boot one serving replica (deterministic jax-free sim
  backend by default; ``--engine`` runs the real ``ServingEngine``).
  Beats heartbeats into ``--hb-dir``, serves ``/generate`` ``/healthz``
  ``/metrics`` ``/drain`` ``/cancel``, and writes its bound port to
  ``--port-file`` so parents can find an ephemeral-port replica.
- ``router`` — health-checked least-loaded router over N replicas with
  deadline-budgeted retries, optional tail hedging, a completion ledger
  (exactly-once), graceful ``/drain``, and ``ptd_fleet_*`` gauges.
- ``arbiter`` — elastic replica-set arbiter (sibling of
  ``elastic_agent.py``): evicts dead replicas through
  ``ft/elastic.py``'s membership protocol and grows/shrinks against
  measured SLO headroom, booking scale events as ft_events.
- ``bench`` — the Poisson scaling harness: boots fleets of 1..N sim
  replicas behind a router, drives the same arrival process at each
  size, and pins tokens/s scaling into ``RESULTS_fleet.json``.

Import-time jax-free throughout (``--selftest`` asserts it): everything
loads by file path, same discipline as ``obs/alerts.py``.
"""

from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import subprocess
import sys
import threading
import time

_SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))
_REPO = os.path.dirname(_SCRIPTS_DIR)
_PKG = os.path.join(_REPO, "pytorch_distributed_tpu")


def _load_mod(sub: str, name: str):
    """Path-load ``pytorch_distributed_tpu/<sub>/<name>.py`` jax-free."""
    full = f"pytorch_distributed_tpu.{sub}.{name}"
    if full in sys.modules:
        return sys.modules[full]
    if "pytorch_distributed_tpu" in sys.modules:
        return importlib.import_module(full)
    alias = f"_ptd_{sub}_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(
        alias, os.path.join(_PKG, sub, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def _load_serving(name: str):
    return _load_mod("serving", name)


def _load_obs(name: str):
    return _load_mod("obs", name)


# ---------------------------------------------------------------------------
# parser


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="serve_fleet",
        description="resilient serving fleet: replicas, router, arbiter")
    p.add_argument("--selftest", action="store_true",
                   help="run the jax-free fleet selftest and exit")
    sub = p.add_subparsers(dest="cmd")

    r = sub.add_parser("replica", help="boot one serving replica")
    r.add_argument("--replica-id", type=int, default=0)
    r.add_argument("--port", type=int, default=0,
                   help="0 = ephemeral; see --port-file")
    r.add_argument("--port-file", default="",
                   help="write the bound port here once listening")
    r.add_argument("--hb-dir", default="",
                   help="heartbeat directory (fleet membership)")
    r.add_argument("--hb-interval", type=float, default=1.0)
    r.add_argument("--epoch", type=int, default=0)
    r.add_argument("--metrics-jsonl", default="")
    r.add_argument("--engine", action="store_true",
                   help="real ServingEngine backend (imports jax)")
    r.add_argument("--vocab-size", type=int, default=64)
    r.add_argument("--max-batch", type=int, default=4)
    r.add_argument("--seed", type=int, default=0)
    r.add_argument("--slo-ttft-ms", type=float, default=0.0)
    r.add_argument("--sim-itl-ms", type=float, default=2.0,
                   help="sim backend per-token latency")
    r.add_argument("--sim-prefill-ms", type=float, default=0.2,
                   help="sim backend prefill cost per prompt token")
    r.add_argument("--d-model", type=int, default=32)
    r.add_argument("--n-heads", type=int, default=4)
    r.add_argument("--n-layers", type=int, default=2)
    r.add_argument("--kv-blocks", type=int, default=64)
    r.add_argument("--block-size", type=int, default=16)
    r.add_argument("--blocks-per-seq", type=int, default=8)
    r.add_argument("--chunk-size", type=int, default=8)
    r.add_argument("--max-new-tokens", type=int, default=16)

    t = sub.add_parser("router", help="boot the fleet router")
    t.add_argument("--port", type=int, default=0)
    t.add_argument("--port-file", default="")
    t.add_argument("--replicas", default="",
                   help="comma list of id=url (e.g. 0=http://127.0.0.1:8100)")
    t.add_argument("--hb-dir", default="")
    t.add_argument("--metrics-jsonl", default="")
    t.add_argument("--deadline-s", type=float, default=30.0)
    t.add_argument("--max-retries", type=int, default=2)
    t.add_argument("--retry-backoff-ms", type=float, default=50.0)
    t.add_argument("--retry-jitter", type=float, default=0.5)
    t.add_argument("--hedge", action="store_true",
                   help="arm tail hedging (p95-derived delay)")
    t.add_argument("--hedge-quantile", type=float, default=0.95)
    t.add_argument("--hedge-min-ms", type=float, default=20.0)
    t.add_argument("--probe-interval", type=float, default=1.0)
    t.add_argument("--probe-timeout", type=float, default=2.0)
    t.add_argument("--quarantine-backoff-ms", type=float, default=500.0)
    t.add_argument("--quarantine-backoff-max-s", type=float, default=30.0)
    t.add_argument("--max-beat-age", type=float, default=60.0)
    t.add_argument("--seed", type=int, default=0)

    a = sub.add_parser("arbiter", help="elastic replica-set arbiter")
    a.add_argument("--replicas", default="")
    a.add_argument("--hb-dir", required=True)
    a.add_argument("--metrics-jsonl", default="")
    a.add_argument("--slo-ttft-ms", type=float, default=500.0)
    a.add_argument("--min-replicas", type=int, default=1)
    a.add_argument("--max-replicas", type=int, default=8)
    a.add_argument("--scale-up-pct", type=float, default=85.0)
    a.add_argument("--scale-down-pct", type=float, default=30.0)
    a.add_argument("--interval", type=float, default=5.0)
    a.add_argument("--once", action="store_true",
                   help="one arbiter cycle, then exit (cron idiom)")
    a.add_argument("--spawn-cmd", default="",
                   help="shell template to boot a new replica; {rid} and "
                        "{port_file} are substituted")

    b = sub.add_parser("bench", help="Poisson replica-scaling harness")
    b.add_argument("--fleet-sizes", default="1,2",
                   help="comma list of replica counts to bench")
    b.add_argument("--requests", type=int, default=64)
    b.add_argument("--rate-rps", type=float, default=400.0)
    b.add_argument("--max-new-tokens", type=int, default=8)
    b.add_argument("--prompt-len", type=int, default=8)
    b.add_argument("--sim-itl-ms", type=float, default=5.0)
    b.add_argument("--sim-prefill-ms", type=float, default=0.5)
    b.add_argument("--max-batch", type=int, default=2)
    b.add_argument("--seed", type=int, default=0)
    b.add_argument("--min-scaling", type=float, default=0.8,
                   help="fence: tokens/s scaling ratio vs linear")
    b.add_argument("--out", default="",
                   help="write RESULTS_fleet-style JSON here")
    return p


def parse_replicas(spec: str):
    """``"0=http://h:p,1=http://h:q"`` → ``{0: url, 1: url}`` (bare urls
    get sequential ids)."""
    out = {}
    for i, part in enumerate(x for x in spec.split(",") if x.strip()):
        part = part.strip()
        if "=" in part:
            rid, url = part.split("=", 1)
            out[int(rid)] = url
        else:
            out[i] = part
    return out


def _write_port_file(path: str, port: int) -> None:
    if not path:
        return
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(port))
    os.replace(tmp, path)


def _make_obs(path: str, process_index: int):
    if not path:
        return None
    metrics = _load_obs("metrics")
    return metrics.MetricsLogger(path, process_index=process_index,
                                 flush_every=1)


# ---------------------------------------------------------------------------
# subcommands


def cmd_replica(args) -> int:
    replica = _load_serving("replica")
    obs = _make_obs(args.metrics_jsonl, args.replica_id)
    if args.engine:
        backend = replica.EngineBackend(
            replica_id=args.replica_id, vocab_size=args.vocab_size,
            d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, max_batch=args.max_batch,
            kv_blocks=args.kv_blocks, block_size=args.block_size,
            blocks_per_seq=args.blocks_per_seq, chunk_size=args.chunk_size,
            max_new_tokens=args.max_new_tokens, seed=args.seed, obs=obs)
    else:
        backend = replica.SimEngineBackend(
            replica_id=args.replica_id, vocab_size=args.vocab_size,
            max_batch=args.max_batch,
            prefill_ms_per_token=args.sim_prefill_ms,
            itl_ms=args.sim_itl_ms, seed=args.seed,
            slo_ttft_ms=args.slo_ttft_ms or None, obs=obs)
    srv = replica.ReplicaServer(
        backend, replica_id=args.replica_id, port=args.port,
        hb_dir=args.hb_dir or None, hb_interval_s=args.hb_interval,
        epoch=args.epoch)
    srv.start()
    _write_port_file(args.port_file, srv.port)
    print(f"replica {args.replica_id} listening on {srv.url}", flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        srv.stop()
        if obs is not None:
            obs.close()
    return 0


def _build_router(args, replicas):
    router = _load_serving("router")
    obs = _make_obs(args.metrics_jsonl, -2)
    alert_engine = None
    if obs is not None:
        alerts = _load_obs("alerts")
        alert_engine = alerts.AlertEngine(
            [alerts.Rule(kind="replica_down", name="replica_down",
                         severity="page", params={})],
            emit=lambda **f: obs.log_event("alert", **f),
            process_index=-2)
    registry = router.ReplicaRegistry(
        replicas, hb_dir=args.hb_dir or None,
        probe_timeout=args.probe_timeout,
        backoff_initial_s=args.quarantine_backoff_ms / 1000.0,
        backoff_max_s=args.quarantine_backoff_max_s,
        max_beat_age_s=args.max_beat_age)
    policy = router.RouterPolicy(
        deadline_s=args.deadline_s, max_retries=args.max_retries,
        retry_backoff_s=args.retry_backoff_ms / 1000.0,
        retry_jitter=args.retry_jitter, hedge=args.hedge,
        hedge_quantile=args.hedge_quantile,
        hedge_min_s=args.hedge_min_ms / 1000.0, seed=args.seed)
    rt = router.FleetRouter(registry, policy, obs=obs,
                            alert_engine=alert_engine, port=args.port,
                            probe_interval_s=args.probe_interval)
    return rt, obs


def cmd_router(args) -> int:
    replicas = parse_replicas(args.replicas)
    if not replicas:
        print("router: no replicas given (--replicas)", file=sys.stderr)
        return 2
    rt, obs = _build_router(args, replicas)
    rt.registry.probe()
    rt.start()
    _write_port_file(args.port_file, rt.port)
    print(f"router listening on {rt.url} over {len(replicas)} replicas",
          flush=True)
    try:
        while True:
            time.sleep(1.0)
    except KeyboardInterrupt:
        pass
    finally:
        rt.stop()
        if obs is not None:
            obs.close()
    return 0


def _spawn_from_template(template: str, hb_dir: str):
    """Build a ``spawn_cb`` that boots a replica from a shell template
    and returns its url once the port file lands."""
    def spawn(rid: int):
        port_file = os.path.join(hb_dir, f"replica-{rid:05d}.port")
        try:
            os.remove(port_file)
        except OSError:
            pass
        cmd = template.format(rid=rid, port_file=port_file)
        subprocess.Popen(cmd, shell=True)
        t_end = time.monotonic() + 30.0
        while time.monotonic() < t_end:
            if os.path.exists(port_file):
                with open(port_file) as f:
                    return f"http://127.0.0.1:{int(f.read().strip())}"
            time.sleep(0.05)
        return None
    return spawn


def cmd_arbiter(args) -> int:
    router = _load_serving("router")
    obs = _make_obs(args.metrics_jsonl, -3)
    registry = router.ReplicaRegistry(parse_replicas(args.replicas),
                                      hb_dir=args.hb_dir)
    spawn_cb = (_spawn_from_template(args.spawn_cmd, args.hb_dir)
                if args.spawn_cmd else None)

    def drain_cb(rid: int) -> bool:
        rep = registry.replicas.get(rid)
        if rep is None:
            return True
        try:
            res = router.http_json("POST", rep.base_url + "/drain",
                                   {"wait": True}, 30.0)
            return bool(res.get("drained", res.get("draining")))
        except router.TRANSPORT_ERRORS:
            return True  # already dead counts as drained

    arb = router.FleetArbiter(
        registry, args.hb_dir, slo_ttft_ms=args.slo_ttft_ms,
        min_replicas=args.min_replicas, max_replicas=args.max_replicas,
        scale_up_pct=args.scale_up_pct, scale_down_pct=args.scale_down_pct,
        obs=obs, spawn_cb=spawn_cb, drain_cb=drain_cb)
    try:
        while True:
            decision, reason = arb.cycle()
            m = arb.co.membership()
            print(f"arbiter: epoch {m.epoch} world {m.world} "
                  f"decision={decision or 'hold'}: {reason}", flush=True)
            if args.once:
                return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 0
    finally:
        if obs is not None:
            obs.close()


# ---------------------------------------------------------------------------
# bench: Poisson replica-scaling harness


def _poisson_arrivals(n: int, rate_rps: float, seed: int):
    import random as _random
    rng = _random.Random(seed)
    t, out = 0.0, []
    for _ in range(n):
        t += rng.expovariate(rate_rps)
        out.append(t)
    return out


def _drive_fleet(n_replicas: int, args):
    """Boot n sim replicas + router in-process, drive the Poisson load
    over HTTP, and measure fleet tokens/s over the makespan."""
    import random as _random
    replica = _load_serving("replica")
    router = _load_serving("router")
    reps, urls = [], {}
    for rid in range(n_replicas):
        backend = replica.SimEngineBackend(
            replica_id=rid, max_batch=args.max_batch,
            prefill_ms_per_token=args.sim_prefill_ms,
            itl_ms=args.sim_itl_ms, seed=args.seed)
        srv = replica.ReplicaServer(backend, replica_id=rid)
        srv.start()
        reps.append(srv)
        urls[rid] = srv.url
    registry = router.ReplicaRegistry(urls)
    rt = router.FleetRouter(registry,
                            router.RouterPolicy(deadline_s=60.0, seed=args.seed))
    registry.probe()
    rt.start()

    rng = _random.Random(args.seed)
    prompts = [[rng.randrange(64) for _ in range(args.prompt_len)]
               for _ in range(args.requests)]
    arrivals = _poisson_arrivals(args.requests, args.rate_rps, args.seed)
    results = [None] * args.requests
    lock = threading.Lock()

    def fire(i: int, t0: float):
        delay = t0 + arrivals[i] - time.monotonic()
        if delay > 0:
            time.sleep(delay)
        res = router.http_json("POST", rt.url + "/generate",
                               {"rid": i, "prompt": prompts[i],
                                "max_new_tokens": args.max_new_tokens}, 120.0)
        with lock:
            results[i] = res

    t0 = time.monotonic()
    threads = [threading.Thread(target=fire, args=(i, t0), daemon=True)
               for i in range(args.requests)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180.0)
    wall = time.monotonic() - t0
    ok = [r for r in results if r and r.get("ok")]
    tokens = sum(len(r["tokens"]) for r in ok)
    ttfts = sorted(r["router_ttft_ms"] for r in ok)
    out = {"replicas": n_replicas, "completed": len(ok),
           "requests": args.requests, "wall_s": round(wall, 3),
           "tokens": tokens, "tokens_per_s": round(tokens / wall, 2),
           "ttft_p99_ms": round(
               ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))], 2)
           if ttfts else None,
           "retries": rt.stats.as_dict()["retries"]}
    rt.stop()
    for srv in reps:
        srv.stop()
    return out


def cmd_bench(args) -> int:
    sizes = sorted({int(x) for x in args.fleet_sizes.split(",") if x.strip()})
    runs = []
    for n in sizes:
        run = _drive_fleet(n, args)
        print(f"bench: {n} replica(s): {run['tokens_per_s']} tokens/s "
              f"({run['completed']}/{run['requests']} completed, "
              f"ttft_p99 {run['ttft_p99_ms']} ms)", flush=True)
        runs.append(run)
    base = next((r for r in runs if r["replicas"] == min(sizes)), None)
    scaling = None
    if base and len(runs) > 1:
        top = runs[-1]
        linear = base["tokens_per_s"] * top["replicas"] / base["replicas"]
        scaling = round(top["tokens_per_s"] / linear, 3)
        print(f"bench: scaling {scaling}x of linear at "
              f"{top['replicas']} replicas (fence >= {args.min_scaling})",
              flush=True)
    result = {"bench": "fleet_scaling", "runs": runs,
              "scaling_vs_linear": scaling,
              "min_scaling_fence": args.min_scaling,
              "all_completed": all(r["completed"] == r["requests"]
                                   for r in runs)}
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
            f.write("\n")
    if not result["all_completed"]:
        print("FAIL: bench lost requests", file=sys.stderr)
        return 1
    if scaling is not None and scaling < args.min_scaling:
        print(f"FAIL: scaling {scaling} < fence {args.min_scaling}",
              file=sys.stderr)
        return 1
    return 0


# ---------------------------------------------------------------------------
# selftest


def _selftest() -> int:  # noqa: C901
    import tempfile
    assert "jax" not in sys.modules, "selftest must start jax-free"
    replica = _load_serving("replica")
    router = _load_serving("router")
    metrics = _load_obs("metrics")
    failures = []

    def check(cond, msg):
        if not cond:
            failures.append(msg)
            print(f"FAIL: {msg}")

    # 1. deterministic sim decode: pure function of (prompt, seed).
    p = [3, 1, 4, 1, 5]
    check(replica.sim_tokens(p, 8, 64, 7) == replica.sim_tokens(p, 8, 64, 7),
          "sim_tokens not deterministic")
    check(replica.sim_tokens(p, 8, 64, 7) != replica.sim_tokens(p, 8, 64, 8),
          "sim_tokens ignores seed")

    with tempfile.TemporaryDirectory() as td:
        jsonl = os.path.join(td, "router.jsonl")
        hb_dir = os.path.join(td, "hb")
        obs = metrics.MetricsLogger(jsonl, process_index=-2, flush_every=1)

        def boot(rid):
            backend = replica.SimEngineBackend(
                replica_id=rid, max_batch=2, prefill_ms_per_token=0.05,
                itl_ms=0.5, seed=0)
            srv = replica.ReplicaServer(backend, replica_id=rid,
                                        hb_dir=hb_dir, hb_interval_s=0.2)
            srv.start()
            return srv

        r0, r1 = boot(0), boot(1)
        registry = router.ReplicaRegistry(
            {0: r0.url, 1: r1.url}, hb_dir=hb_dir,
            backoff_initial_s=0.05, probe_timeout=1.0)
        rt = router.FleetRouter(
            registry, router.RouterPolicy(deadline_s=10.0, max_retries=2,
                                          retry_backoff_s=0.01),
            obs=obs, probe_interval_s=0.2)
        registry.probe()
        check(len(registry.up()) == 2, "both replicas should probe UP")
        check(registry.replicas[0].queue_depth is not None,
              "probe should scrape serving gauges")

        # 2. dispatch: every request completes with the sim-exact tokens.
        for rid in range(6):
            code, res = rt.submit({"rid": rid, "prompt": p,
                                   "max_new_tokens": 6})
            check(code == 200 and res["ok"], f"rid {rid} failed: {res}")
            check(res["tokens"] == replica.sim_tokens(p, 6, 64, 0),
                  f"rid {rid} tokens not sim-exact")
        check(len(rt.ledger) == 6, "ledger should hold 6 completions")

        # 3. one trace spans router -> engine -> completion.
        code, res = rt.submit({"rid": 10, "prompt": p, "max_new_tokens": 4})
        hops = res["ctx"]["hops"]
        for needle in ("router:recv", "dispatch:replica", ":recv", "queue",
                       "admit", "finish", "router:done"):
            check(any(needle in h for h in hops),
                  f"trace hop chain missing {needle!r}: {hops}")

        # 4. idempotent replay: same rid returns the original bit-for-bit.
        code, replay = rt.submit({"rid": 10, "prompt": p,
                                  "max_new_tokens": 4})
        check(replay.get("replayed") and replay["tokens"] == res["tokens"],
              "replay should return the cached completion")
        check(rt.stats.as_dict()["duplicates_suppressed"] >= 1,
              "replay should count as suppressed duplicate")

        # 5. replica death: quarantine + redispatch, nothing lost.
        r1.stop()
        registry.probe()
        check(registry.replicas[1].state == router.QUARANTINED,
              "dead replica should be QUARANTINED")
        back0 = registry.replicas[1].backoff_s
        registry.replicas[1].next_probe_t = 0.0
        registry.probe()
        check(registry.replicas[1].backoff_s > back0,
              "quarantine re-probe backoff should grow")
        for rid in range(20, 26):
            code, res = rt.submit({"rid": rid, "prompt": p,
                                   "max_new_tokens": 6})
            check(code == 200 and res["ok"] and res["replica"] == 0,
                  f"rid {rid} should complete on the survivor")
        obs_records = metrics.read_metrics(jsonl)
        downs = [r for r in obs_records
                 if r.get("ft_event") == "replica_down"]
        check(len(downs) >= 1, "replica_down ft_event should be booked")
        fleettraces = [r for r in obs_records
                       if r.get("ft_event") == "fleettrace"]
        check(len(fleettraces) >= 7, "fleettrace events should be booked")
        # attribution decomposition is exact by construction.
        for ftr in fleettraces:
            lhs = ftr["router_ttft_ms"]
            rhs = (ftr["router_wait_ms"] + ftr["redispatch_ms"]
                   + ftr["hedge_wait_ms"] + ftr["engine_ttft_ms"])
            check(abs(lhs - rhs) < 1e-6,
                  f"router attribution not exact: {lhs} vs {rhs}")

        # 6. hedging: a slow primary is beaten by the hedge.
        hrt = router.FleetRouter(
            registry,
            router.RouterPolicy(deadline_s=5.0, hedge=True,
                                hedge_min_s=0.01, hedge_floor_samples=2))
        hrt._latency_ms.extend([5.0] * 4)

        def fake_call(rep, payload, ctx, timeout):
            if rep.rid == 0:
                time.sleep(0.25)
                return True, {"ok": True, "rid": payload["rid"],
                              "tokens": [1], "ttft_ms": 250.0,
                              "e2e_ms": 250.0, "replica": 0}
            return True, {"ok": True, "rid": payload["rid"], "tokens": [1],
                          "ttft_ms": 1.0, "e2e_ms": 1.0, "replica": 1}

        hrt._call_replica = fake_call
        registry.replicas[1].state = router.UP
        code, res = hrt.submit({"rid": 50, "prompt": p, "max_new_tokens": 1})
        d = hrt.stats.as_dict()
        check(code == 200 and res["ok"], "hedged request should complete")
        check(d["hedges"] == 1 and d["hedges_won"] == 1,
              f"hedge should launch and win: {d}")
        check(res["replica"] == 1 and res["hedged"],
              "winner should be the hedge replica")

        # 7. graceful drain: replica refuses new work, finishes in-flight.
        res = r0.handle_drain(wait=True, timeout_s=2.0)
        check(res["drained"], "drain should settle with no in-flight")
        registry.probe()
        check(registry.replicas[0].state == router.DRAINING,
              "draining replica should probe DRAINING")
        check(registry.pick() is None,
              "pick must exclude DRAINING replicas")
        rt.drain()
        code, res = rt.submit({"rid": 60, "prompt": p, "max_new_tokens": 2})
        check(code == 503, "draining router must refuse admission")

        # 8. scale decisions are pure and directional.
        rows_hot = [{"rid": 0, "state": "UP", "ttft_p99_ms": 480.0,
                     "queue_depth": 2.0, "inflight": 1}]
        rows_cold = [{"rid": i, "state": "UP", "ttft_p99_ms": 20.0,
                      "queue_depth": 0.0, "inflight": 0} for i in range(2)]
        d, v, _ = router.decide_scale(rows_hot, slo_ttft_ms=500.0)
        check(d == "up", "hot fleet should scale up")
        d, v, _ = router.decide_scale(rows_cold, slo_ttft_ms=500.0)
        check(d == "down" and v in (0, 1), "cold fleet should scale down")
        d, v, _ = router.decide_scale(rows_cold[:1], slo_ttft_ms=500.0)
        check(d is None, "min_replicas floor must refuse scale-down")

        # 9. arbiter: eviction through the one membership path + booked
        # scale events.
        arb_jsonl = os.path.join(td, "arbiter.jsonl")
        arb_obs = metrics.MetricsLogger(arb_jsonl, process_index=-3,
                                        flush_every=1)
        r2 = boot(2)
        areg = router.ReplicaRegistry(
            {2: r2.url, 3: "http://127.0.0.1:1"},  # 3 is dead
            hb_dir=hb_dir, backoff_initial_s=0.01, probe_timeout=0.3)
        arb = router.FleetArbiter(
            areg, hb_dir, slo_ttft_ms=500.0, min_replicas=1,
            max_replicas=4, obs=arb_obs, dead_failures=1,
            spawn_cb=lambda rid: None)
        check(arb.co.membership().world >= 1, "membership should exist")
        areg.probe()
        areg.replicas[3].next_probe_t = 0.0
        areg.probe()  # second failure -> eligible for eviction
        arb.cycle()
        check(3 not in arb.co.membership().ranks,
              "dead replica must be evicted from membership")
        # force a scale-up: pretend headroom is exhausted.
        arb.scale_up_pct = -1.0
        r3 = boot(4)
        arb.spawn_cb = lambda rid: r3.url
        decision, reason = arb.cycle()
        check(decision == "up", f"forced scale-up expected: {reason}")
        check(arb.stats.as_dict()["scale_up_events"] == 1,
              "scale_up should be counted")
        arb_records = metrics.read_metrics(arb_jsonl)
        kinds = {r.get("ft_event") for r in arb_records}
        check("replica_evict" in kinds and "scale_up" in kinds,
              f"arbiter should book eviction + scale ft_events: {kinds}")

        # 10. fleet gauges render and parse.
        export = _load_obs("export")
        samples = export.parse_prometheus(rt.render_metrics())
        check(export.sample_value(samples, "ptd_fleet_replicas") == 2.0,
              "fleet gauge ptd_fleet_replicas should render")
        check(export.sample_value(samples, "ptd_fleet_completed_total") >= 13,
              "fleet completions gauge should count")
        stray = {name for name, _lab, _v in samples
                 if name not in export.FLEET_GAUGES}
        check(not stray,
              f"rendered gauges missing from export.FLEET_GAUGES: {stray}")

        rt.stop()
        hrt.stop()
        for srv in (r0, r2, r3):
            srv.stop()
        obs.close()
        arb_obs.close()

    assert "jax" not in sys.modules, "fleet selftest must stay jax-free"
    if failures:
        print(f"serve_fleet selftest: {len(failures)} failure(s)")
        return 1
    print("serve_fleet selftest: OK")
    return 0


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.cmd == "replica":
        return cmd_replica(args)
    if args.cmd == "router":
        return cmd_router(args)
    if args.cmd == "arbiter":
        return cmd_arbiter(args)
    if args.cmd == "bench":
        return cmd_bench(args)
    build_parser().print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
