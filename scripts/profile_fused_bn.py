#!/usr/bin/env python
"""Fused BN+ReLU (custom_vjp, backward reads only the pre-BN tensor) vs
flax-style BN — bottleneck-shaped conv chain, fwd+bwd."""

import time
from functools import partial

import jax
import jax.numpy as jnp

REPS = 10


from benchlib import timed_scalar  # noqa: E402


def conv1x1(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


# ---------------- flax-style BN+relu (baseline) ----------------
def bn_relu_ref(y, gamma, beta):
    yf = y.astype(jnp.float32)
    mu = yf.mean(axis=(0, 1, 2))
    var = (yf * yf).mean(axis=(0, 1, 2)) - mu * mu
    inv = jax.lax.rsqrt(var + 1e-5)
    o = ((yf - mu) * inv * gamma + beta).astype(y.dtype)
    return jax.nn.relu(o)


# ---------------- fused BN+relu with custom bwd ----------------
@partial(jax.custom_vjp, nondiff_argnums=())
def bn_relu_fused(y, gamma, beta):
    o, _ = _bnr_fwd(y, gamma, beta)
    return o


def _bnr_fwd(y, gamma, beta):
    yf = y.astype(jnp.float32)
    n = y.shape[0] * y.shape[1] * y.shape[2]
    mu = yf.mean(axis=(0, 1, 2))
    var = (yf * yf).mean(axis=(0, 1, 2)) - mu * mu
    inv = jax.lax.rsqrt(var + 1e-5)
    o = ((yf - mu) * (inv * gamma) + beta).astype(y.dtype)
    o = jax.nn.relu(o)
    # residuals: pre-BN tensor + per-channel vectors only (o NOT saved)
    return o, (y, mu, inv, gamma, beta)


def _bnr_bwd(res, do):
    y, mu, inv, gamma, beta = res
    n = y.shape[0] * y.shape[1] * y.shape[2]
    yf = y.astype(jnp.float32)
    xhat = (yf - mu) * inv
    act = (gamma * xhat + beta) > 0  # relu mask recomputed from y
    dof = jnp.where(act, do.astype(jnp.float32), 0.0)
    dbeta = dof.sum(axis=(0, 1, 2))
    dgamma = (dof * xhat).sum(axis=(0, 1, 2))
    dx = (gamma * inv) * (dof - dbeta / n - xhat * (dgamma / n))
    return dx.astype(y.dtype), dgamma, dbeta


bn_relu_fused.defvjp(_bnr_fwd, _bnr_bwd)


def bench(b, h, w, cin, cout, bn_fn, label):
    x0 = jnp.ones((b, h, w, cin), jnp.bfloat16)
    w1 = jnp.ones((1, 1, cin, cout), jnp.bfloat16) / cin
    w2 = jnp.ones((1, 1, cout, cin), jnp.bfloat16) / cout
    g1 = jnp.ones((cout,), jnp.float32)
    b1 = jnp.zeros((cout,), jnp.float32)
    g2 = jnp.ones((cin,), jnp.float32)
    b2 = jnp.zeros((cin,), jnp.float32)
    flops = 2 * b * h * w * cin * cout * 2

    def block(x, w1, w2, g1, b1, g2, b2):
        y = bn_fn(conv1x1(x, w1), g1, b1)
        return bn_fn(conv1x1(y, w2), g2, b2)

    @jax.jit
    def fwdbwd(x0, w1, w2, g1, b1, g2, b2):
        def loss(x, w1, w2):
            return block(x, w1, w2, g1, b1, g2, b2).astype(jnp.float32).mean()

        def body(i, carry):
            x, acc = carry
            gx, gw1, gw2 = jax.grad(loss, argnums=(0, 1, 2))(x, w1, w2)
            return gx.astype(jnp.bfloat16), acc + gw1.astype(jnp.float32).mean()

        x, acc = jax.lax.fori_loop(0, REPS, body, (x0, jnp.float32(0)))
        return x.astype(jnp.float32).mean() + acc

    t = timed_scalar(fwdbwd, x0, w1, w2, g1, b1, g2, b2) / REPS
    print(f"{label} {h}x{w} {cin}<->{cout} f+b: {t*1e3:.3f} ms "
          f"-> {3*flops/t/1e12:.1f} conv-TFLOP/s eq")
    return t


def parity():
    import numpy as np
    rng = np.random.default_rng(0)
    y = jnp.asarray(rng.normal(size=(8, 4, 4, 16)).astype(np.float32))
    g = jnp.asarray(rng.normal(size=16).astype(np.float32))
    be = jnp.asarray(rng.normal(size=16).astype(np.float32))
    do = jnp.asarray(rng.normal(size=(8, 4, 4, 16)).astype(np.float32))

    def f_ref(y, g, be):
        return (bn_relu_ref(y, g, be) * do).sum()

    def f_fus(y, g, be):
        return (bn_relu_fused(y, g, be) * do).sum()

    gr = jax.grad(f_ref, argnums=(0, 1, 2))(y, g, be)
    gf = jax.grad(f_fus, argnums=(0, 1, 2))(y, g, be)
    for a, c, name in zip(gr, gf, "y gamma beta".split()):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c), rtol=2e-4,
                                   atol=2e-4)
    print("gradient parity: OK")


if __name__ == "__main__":
    parity()
    for shape in [(256, 56, 56, 64, 256), (256, 28, 28, 128, 512)]:
        t_ref = bench(*shape, bn_relu_ref, "flax-style")
        t_fus = bench(*shape, bn_relu_fused, "fused-vjp ")
        print(f"  speedup: {t_ref/t_fus:.2f}x")
