#!/usr/bin/env python
"""shardlint: static analysis of every jitted step on a CPU mesh.

Lowers each recipe's step builder (image train/eval, LM train/eval, every
fused-CE mode, all three pipeline schedules, greedy decode) on a simulated
4-way CPU mesh and walks the jaxpr + compiled HLO for:

- replicated-large-tensor  full-global-size intermediates on >1-device
                           meshes (loop carries = the PR-1 [V,D] dE class)
- replicated-state         param-shaped per-device updates (declared DP
                           layout; the standing FSDP opportunity) [info]
- lost-donation            donate_argnums leaves XLA silently didn't alias
- no-donation              never-donating steps with alias opportunities
- dtype-promotion          large bf16/f16 -> f32 materialized upcasts
- collective-regression    per-step collective count/bytes vs the
                           checked-in analysis/baseline.json budget
- memory-budget            per-device peak HBM (temp+argument+output) vs
                           the checked-in per-step byte budget
- host-sync                blocking float()/np.asarray/.block_until_ready()
                           inside registered training hot loops (AST pass)

With --sync, the synclint layers fold in (scripts/synclint.py has the
standalone CLI): collective-incongruence / sync-digest-drift per mesh'd
step, plus the collective-desync host pass and protocol-desync model
check — all riding this sweep's lowering cache, zero extra compiles.

Exit status 1 when any error-severity finding survives.

Usage:
  python scripts/shardlint.py                    # full sweep + baseline diff
  python scripts/shardlint.py --steps lm_train_dp,lm_fused_ce_dp
  python scripts/shardlint.py --json report.json # machine-readable output
  python scripts/shardlint.py --update-baseline  # pin current collective
                                                 # budgets as the new fence
  python scripts/shardlint.py --comm-ledger comm_ledger.json
                                                 # itemized per-collective
                                                 # receipt (obs.comms)
  python scripts/shardlint.py --mem-ledger mem_ledger.json
                                                 # per-buffer HBM watermark
                                                 # + peak attribution
                                                 # (obs.memory)
  python scripts/shardlint.py --hlo-cache hlo/   # persist the sweep's
                                                 # lowering artifacts
                                                 # (analysis/lowering.py
                                                 # <name>.hlo/.json layout)
  python scripts/shardlint.py --selftest         # planted-hazard checks
"""

import argparse
import json
import os
import sys

# Must precede the first jax import: the analyzer needs >= 4 simulated
# devices (mirrors tests/conftest.py so baselines match the test sweep).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_threefry_partitionable", True)

from pytorch_distributed_tpu.analysis import (  # noqa: E402
    diff_against_baseline,
    load_baseline,
    render_table,
    save_baseline,
)
from pytorch_distributed_tpu.analysis import core  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--steps", default=None,
                    help="comma-separated subset of steps (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list known step names and exit")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full reports as JSON")
    ap.add_argument("--baseline", default=core.baseline_path(),
                    help="collective-budget baseline to diff against "
                         "(default: the checked-in analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the collective-budget diff")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current collective budgets to --baseline "
                         "instead of diffing (run after a reviewed change "
                         "that intentionally alters the budget)")
    ap.add_argument("--comm-ledger", default=None, metavar="PATH",
                    help="write the itemized communication ledger (every "
                         "collective with bytes/fan-out/scope attribution) "
                         "for the analyzed steps to PATH")
    ap.add_argument("--mem-ledger", default=None, metavar="PATH",
                    help="write the static HBM memory ledger (live-range "
                         "watermark, top buffers at peak, class/phase "
                         "breakdown) for the analyzed steps to PATH")
    ap.add_argument("--hlo-cache", default=None, metavar="DIR",
                    help="persist each analyzed step's lowering artifacts "
                         "(<name>.hlo + <name>.json) under DIR via the "
                         "shared lowering service (analysis/lowering.py) "
                         "so later text-only consumers skip the compile")
    ap.add_argument("--sync", action="store_true",
                    help="fold in the synclint layers: annotate each "
                         "mesh'd step with its collective-schedule digest "
                         "+ congruence findings (zero extra compiles — "
                         "rides this sweep's lowering cache) and append "
                         "the host-desync and protocol-model reports")
    ap.add_argument("--min-replicated-bytes", type=int,
                    default=core.DEFAULT_MIN_REPLICATED_BYTES)
    ap.add_argument("--min-promotion-bytes", type=int,
                    default=core.DEFAULT_MIN_PROMOTION_BYTES)
    ap.add_argument("--selftest", action="store_true",
                    help="run the planted-hazard detector checks and exit")
    args = ap.parse_args()

    if args.list:
        for name in core.RECIPES:
            print(name)
        print("hot-loops")
        return 0

    if args.selftest:
        summary = core.selftest(verbose=True)
        print(f"shardlint selftest OK: {summary}")
        return 0

    names = args.steps.split(",") if args.steps else None
    reports = core.analyze_all(
        names,
        min_replicated_bytes=args.min_replicated_bytes,
        min_promotion_bytes=args.min_promotion_bytes,
    )

    if args.sync:
        # Digest + congruence ride the lowering memo the sweep above
        # already filled, so annotation adds zero compiles; it must
        # precede the baseline branch so --update-baseline pins the
        # digests and the diff path catches digest drift.
        from pytorch_distributed_tpu.analysis import synclint  # noqa: E402
        synclint.annotate_reports(reports)
        reports.append(synclint.lint_sync_scopes())
        reports.append(synclint.check_protocols())

    if args.update_baseline:
        # The hot-loop lint and single-device decode have no collective
        # budget to pin; baseline covers mesh'd steps only.
        save_baseline(args.baseline,
                      [r for r in reports if r.mesh_shape])
        print(f"wrote collective-budget baseline for "
              f"{sum(1 for r in reports if r.mesh_shape)} steps to "
              f"{args.baseline}")
    elif not args.no_baseline:
        baseline = (load_baseline(args.baseline)
                    if os.path.exists(args.baseline) else {})
        if not baseline:
            print(f"note: no baseline at {args.baseline}; run "
                  "--update-baseline to pin collective budgets")
        for r in reports:
            if not r.mesh_shape:
                continue
            for f in diff_against_baseline(r, baseline.get(r.name)):
                r.add(f)

    if args.hlo_cache:
        # The analysis above already paid the compiles (core's memo);
        # persisting is a pure write of the cached records.
        from pytorch_distributed_tpu.analysis import lowering  # noqa: E402
        svc = lowering.service(args.hlo_cache)
        persisted = [n for n in (names or list(core.RECIPES))
                     if n in core.RECIPES and svc.get(n)]
        print(f"persisted {len(persisted)} lowering artifact pairs to "
              f"{args.hlo_cache}")

    if args.comm_ledger:
        # Rides the same lowering cache as the analysis sweep above, so
        # the itemized receipt costs no extra compiles.
        from pytorch_distributed_tpu.obs import comms  # noqa: E402
        ledgers = core.sweep_comm_ledgers(names)
        comms.write_ledgers(args.comm_ledger, ledgers)
        print(f"wrote comm ledger for {len(ledgers)} steps to "
              f"{args.comm_ledger}")

    if args.mem_ledger:
        # Same deal: the watermark is computed from the already-lowered
        # HLO text, so the memory receipt adds zero compiles too.
        from pytorch_distributed_tpu.obs import memory  # noqa: E402
        mledgers = core.sweep_mem_ledgers(names)
        memory.write_ledgers(args.mem_ledger, mledgers)
        print(f"wrote mem ledger for {len(mledgers)} steps to "
              f"{args.mem_ledger}")

    print(render_table(reports))
    if args.json:
        with open(args.json, "w") as f:
            json.dump([r.to_dict() for r in reports], f, indent=2)
            f.write("\n")
        print(f"wrote {args.json}")

    n_err = sum(len(r.errors()) for r in reports)
    if n_err:
        print(f"shardlint: {n_err} error finding(s)", file=sys.stderr)
        return 1
    print("shardlint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
