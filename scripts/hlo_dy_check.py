"""Does XLA materialize dy (the BN-backward conv-output gradient) to HBM in
the unfused ResNet step, or fuse it into the dgrad/wgrad consumers?"""
import jax, jax.numpy as jnp, re
import numpy as np

B, H, Ci, Co = 256, 56, 64, 64
dtype = jnp.bfloat16
s = jnp.ones((Co,), jnp.float32); t = jnp.full((Co,), .1, jnp.float32)
u = jnp.zeros((Co,), jnp.float32); v = jnp.zeros((Co,), jnp.float32)

def unfused(y, do, a, w):
    yf = y.astype(jnp.float32); dof = do.astype(jnp.float32)
    dof = jnp.where(yf * s + v > 0, dof, 0.0)
    dy = (dof * s + yf * t + u).astype(dtype)
    da = jax.lax.conv_general_dilated(
        dy, jnp.transpose(w, (0, 1, 3, 2))[::-1, ::-1].astype(dtype),
        (1, 1), ((1, 1), (1, 1)), dimension_numbers=("NHWC", "HWIO", "NHWC"))
    dw = jax.lax.conv_general_dilated(
        jnp.transpose(a, (3, 1, 2, 0)).astype(dtype),
        jnp.transpose(dy, (1, 2, 0, 3)).astype(dtype),
        (1, 1), ((1, 1), (1, 1)), dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return da.astype(jnp.float32).sum() + dw.sum()

y = jnp.ones((B, H, H, Co), dtype); do = jnp.ones((B, H, H, Co), dtype)
a = jnp.ones((B, H, H, Ci), dtype); w = jnp.ones((3, 3, Ci, Co), jnp.float32)
txt = jax.jit(unfused).lower(y, do, a, w).compile().as_text()
# count fusions producing a [B,H,H,Co]-shaped bf16 output (a materialized dy)
# vs convolution fusions with elementwise producers inside
convs = re.findall(r"kind=kCustom.*convolution", txt)
fus = [l for l in txt.splitlines() if "fusion" in l and "bf16[256,56,56,64]" in l and "ROOT" not in l]
print("convolution custom-calls:", len(convs))
print("lines w/ fusion producing bf16[256,56,56,64]:")
for l in fus[:12]: print("  ", l.strip()[:160])
import os
os.makedirs("runs", exist_ok=True)
open("runs/hlo_unfused_bwd.txt","w").write(txt)
print("total HLO lines:", len(txt.splitlines()))
