"""Does XLA materialize dy (the BN-backward conv-output gradient) to HBM in
the unfused ResNet step, or fuse it into the dgrad/wgrad consumers?

Rewritten on the shardlint matcher layer (analysis/hlo.py): the private
regexes became ``find_materializations`` (fusions producing a buffer of
exactly dy's shape) and ``count_custom_call_convolutions`` — the same
helpers the analyzer's detectors use, so this one-off question and the CI
fence share one parsing path.  Output contract unchanged: prints the
counts and writes the full module to runs/hlo_unfused_bwd.txt.
"""
import os
import sys

import jax
import jax.numpy as jnp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.analysis.hlo import (  # noqa: E402
    count_custom_call_convolutions,
    find_materializations,
)

B, H, Ci, Co = 256, 56, 64, 64
dtype = jnp.bfloat16
s = jnp.ones((Co,), jnp.float32); t = jnp.full((Co,), .1, jnp.float32)  # noqa: E702
u = jnp.zeros((Co,), jnp.float32); v = jnp.zeros((Co,), jnp.float32)  # noqa: E702


def unfused(y, do, a, w):
    yf = y.astype(jnp.float32); dof = do.astype(jnp.float32)  # noqa: E702
    dof = jnp.where(yf * s + v > 0, dof, 0.0)
    dy = (dof * s + yf * t + u).astype(dtype)
    da = jax.lax.conv_general_dilated(
        dy, jnp.transpose(w, (0, 1, 3, 2))[::-1, ::-1].astype(dtype),
        (1, 1), ((1, 1), (1, 1)), dimension_numbers=("NHWC", "HWIO", "NHWC"))
    dw = jax.lax.conv_general_dilated(
        jnp.transpose(a, (3, 1, 2, 0)).astype(dtype),
        jnp.transpose(dy, (1, 2, 0, 3)).astype(dtype),
        (1, 1), ((1, 1), (1, 1)), dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=jnp.float32)
    return da.astype(jnp.float32).sum() + dw.sum()


y = jnp.ones((B, H, H, Co), dtype); do = jnp.ones((B, H, H, Co), dtype)  # noqa: E702
a = jnp.ones((B, H, H, Ci), dtype); w = jnp.ones((3, 3, Ci, Co), jnp.float32)  # noqa: E702
txt = jax.jit(unfused).lower(y, do, a, w).compile().as_text()
# count fusions producing a [B,H,H,Co]-shaped bf16 output (a materialized dy)
# vs convolution custom-calls with elementwise producers fused inside
fus = find_materializations(txt, "bf16", (B, H, H, Co), opcodes=("fusion",))
print("convolution custom-calls:", count_custom_call_convolutions(txt))
print("lines w/ fusion producing bf16[%d,%d,%d,%d]:" % (B, H, H, Co))
for ins in fus[:12]:
    print("  ", ins.line[:160])
os.makedirs("runs", exist_ok=True)
open("runs/hlo_unfused_bwd.txt", "w").write(txt)
print("total HLO lines:", len(txt.splitlines()))
