#!/usr/bin/env python
"""autoplan: static layout planning across the recipe matrix.

Enumerates every recipe-expressible dp x tp x pp x fsdp x remat x
fused-ce-mode x zero x grad-compress plan for a model at one or more
chip counts, prunes statically infeasible points (per-chip peak HBM over
budget, indivisible vocab/head/stage shapes), scores the survivors
analytically (compute time, wire bytes, predicted exposed comm, peak
HBM — obs/flops.py's fenced cost models over plan/cost.py), and emits a
ranked ``plan.json`` with predicted MFU and the exact recipe CLI flags.

The default path is purely analytic: no backend, no mesh, no compiles —
it runs on a login node in milliseconds.  ``--validate`` additionally
lowers each top-k candidate's recipe twin on the simulated CPU mesh and
cross-checks the predictions against the real comm/memory ledgers
(plan/validate.py), riding the shared lowering service
(analysis/lowering.py) so an already-swept process pays zero extra
compiles.

``--overlap-from timeline.json`` closes the measurement loop: the
backward-overlap fraction the scorer assumes (DEFAULT_OVERLAP, env
PTD_PLAN_OVERLAP) is replaced by the overlap the profiler actually
measured on this deployment (obs_timeline.py report), so re-planning
after a calibration run scores comm-bound plans with real numbers.

``--attr-from attr.json`` closes the same loop from the step-attribution
plane (ISSUE 20): a ``--step-attr`` run's measured profile
(``obs_roofline.py --attr-out``) supplies the overlap AND the measured
bottleneck — the payload records ``attr_source``, and when the dominant
class is data_wait/host_sync the report says so, because no layout
re-plan fixes an input-starved step.

Usage:
  python scripts/autoplan.py lm --chips 32 --chip v5p
  python scripts/autoplan.py resnet50 --chips 4,8,32 --out plan.json
  python scripts/autoplan.py lm --chips 32 --overlap-from timeline.json
  python scripts/autoplan.py lm --chips 32 --attr-from attr.json
  python scripts/autoplan.py lm-tiny --chips 4 --validate
  python scripts/autoplan.py --selftest       # resnet50 + LM at 4/8/32
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup_mesh_backend() -> None:
    """--validate needs the simulated mesh; flags must precede jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)


def _load_stepattr():
    """obs/stepattr.py by file path under the shared ``_ptd_obs_*`` alias
    (the obs package ``__init__`` imports jax; the analytic planner path
    must stay jax-free)."""
    import importlib.util

    full = "pytorch_distributed_tpu.obs.stepattr"
    if full in sys.modules:
        return sys.modules[full]
    alias = "_ptd_obs_stepattr"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(
        alias, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "pytorch_distributed_tpu", "obs", "stepattr.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


def overlap_from_timeline(path: str) -> float:
    """Measured backward-overlap fraction (0-1) from an obs_timeline.py
    report: the mean of every capture's ``aggregate.overlap_pct_mean``.
    Replaces the cost model's assumed ``DEFAULT_OVERLAP`` so plan scores
    reflect how much collective time *this* deployment actually hides
    under compute, instead of the literature constant."""
    with open(path) as f:
        doc = json.load(f)
    vals = [c["aggregate"]["overlap_pct_mean"]
            for c in (doc.get("captures") or [])
            if c.get("aggregate", {}).get("steps")]
    if not vals:
        raise ValueError(
            f"no step aggregates in '{path}' — expected an obs_timeline.py "
            "report (captures[].aggregate.overlap_pct_mean)")
    return min(1.0, max(0.0, sum(vals) / len(vals) / 100.0))


def _render(payload) -> str:
    lines = [f"== {payload['model']} @ {payload['chips']} chips "
             f"({payload['hw']['name']}): {payload['feasible']} feasible / "
             f"{payload['enumerated']} enumerated =="]
    if payload.get("overlap_source") == "measured":
        lines.append(f"   overlap: {100.0 * payload['overlap']:.1f}% "
                     "(measured from timeline)")
    elif payload.get("overlap_source") == "schedule":
        lines.append(f"   overlap: {100.0 * payload['overlap']:.1f}% "
                     "(bucketed-schedule model)")
    elif payload.get("overlap_source") == "measured-attr":
        lines.append(f"   overlap: {100.0 * payload['overlap']:.1f}% "
                     f"(measured from step attribution: "
                     f"{payload.get('attr_source')})")
    meas = payload.get("measured")
    if meas:
        lines.append(f"   measured bottleneck: {meas['bottleneck']} "
                     f"(data-wait p95 {meas['data_wait_share_p95']:.1f}% "
                     f"of step, host-sync p95 "
                     f"{meas['host_sync_ms_p95']:.2f}ms)")
        if meas["bottleneck"] in ("data_wait", "host_sync", "other"):
            lines.append("   NOTE: the measured bottleneck is host-side "
                         "— no layout re-plan fixes it; fix the input "
                         "pipeline / host sync first")
    for reason, n in sorted(payload["pruned"].items()):
        lines.append(f"   pruned {n:4d}  {reason}")
    lines.append(f"   {'#':>2} {'plan':<34} {'MFU%':>6} {'step_ms':>10} "
                 f"{'wire_MB':>8} {'peak_GB':>8}")
    for i, entry in enumerate(payload["ranked"], 1):
        p, s = entry["plan"], entry["predicted"]
        lines.append(
            f"   {i:>2} {p['key']:<34} {s['mfu_pct']:>6.2f} "
            f"{s['step_time_ms']:>10.4f} {s['wire_bytes'] / 1e6:>8.3f} "
            f"{s['peak_hbm_bytes'] / 1e9:>8.4f}")
    if payload["ranked"]:
        lines.append(f"   run: {payload['ranked'][0]['plan']['cli']}")
    for world, entry in sorted(payload.get("elastic", {}).items(),
                               key=lambda kv: -int(kv[0])):
        key = entry["plan"]["key"] if entry else "(none feasible)"
        lines.append(f"   elastic {world}: {key}")
    for rec in payload.get("validation", []):
        verdict = {True: "ok", False: "FAIL", None: "n/a"}[rec["ok"]]
        lines.append(f"   validate {rec['plan']} -> "
                     f"{rec['recipe'] or '(no recipe twin)'}: {verdict}")
        for name, c in (rec.get("checks") or {}).items():
            if "residual_pct" in c:
                fence = "" if c.get("fenced", True) else " (unfenced)"
                lines.append(f"      {name}: residual "
                             f"{c['residual_pct']:.2f}% of "
                             f"{c['fence_pct']:.0f}%{fence}")
    return "\n".join(lines)


def selftest() -> int:
    """The acceptance sweep: ranked plans with predicted MFU + runnable
    flags for resnet50 and the LM at 4, 8, and 32 chips — analytically,
    with zero compiles."""
    from pytorch_distributed_tpu.plan import autoplan

    for model in ("resnet50", "lm"):
        for chips in (4, 8, 32):
            out = autoplan(model, chips, chip="v5p", top_k=3)
            assert out["enumerated"] > 0, (model, chips)
            assert out["feasible"] > 0, (model, chips, out["pruned"])
            top = out["ranked"][0]
            assert top["predicted"]["mfu_pct"] > 0, top
            assert top["plan"]["flags"], top
            assert "--batch-size" in top["plan"]["cli"], top
            print(f"  [selftest] {model}@{chips}: top "
                  f"{top['plan']['key']} "
                  f"mfu={top['predicted']['mfu_pct']:.1f}%")
    # tiny LM must rank the fenced plain-DP recipe first (the tie-break
    # contract the validation fences depend on)
    out = autoplan("lm-tiny", 4, top_k=1)
    assert out["ranked"][0]["plan"]["key"] == "c4/dp4", out["ranked"][0]

    # --attr-from: a measured step-attribution profile swaps in its
    # overlap, the payload records attr_source + the measured bottleneck,
    # and the host-side caution renders when data_wait dominates
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        ap_path = os.path.join(d, "attr.json")
        with open(ap_path, "w") as f:
            json.dump({"kind": "stepattr_profile", "attr_source": ap_path,
                       "steps": 40, "step_ms_p50": 100.0, "overlap": 0.8,
                       "bottleneck": "data_wait",
                       "shares_pct": {"compute": 40.0, "data_wait": 45.0},
                       "data_wait_share_p95": 46.0,
                       "host_sync_ms_p95": 3.0,
                       "recon_err_pct_p50": 0.1}, f)
        prof = _load_stepattr().load_attr(ap_path)
        assert prof["bottleneck"] == "data_wait", prof
        out = autoplan("lm-tiny", 4, top_k=1, overlap=prof["overlap"],
                       overlap_source="measured-attr", attr_profile=prof)
        assert out["overlap"] == 0.8 and \
            out["overlap_source"] == "measured-attr", out
        assert out["attr_source"] == ap_path, out
        assert out["measured"]["bottleneck"] == "data_wait", out
        rendered = _render(out)
        for needle in ("measured from step attribution",
                       "measured bottleneck: data_wait",
                       "data-wait p95 46.0% of step",
                       "no layout re-plan fixes it"):
            assert needle in rendered, f"missing {needle!r}\n{rendered}"
        # non-host bottleneck: no caution line
        prof2 = dict(prof, bottleneck="exposed_comm")
        out2 = autoplan("lm-tiny", 4, top_k=1, overlap=0.8,
                        overlap_source="measured-attr", attr_profile=prof2)
        assert "no layout re-plan" not in _render(out2)
        # a non-profile JSON is rejected loudly
        bogus = os.path.join(d, "bogus.json")
        with open(bogus, "w") as f:
            json.dump({"overlap": 0.5}, f)
        try:
            _load_stepattr().load_attr(bogus)
            raise AssertionError("load_attr accepted a non-profile JSON")
        except ValueError:
            pass
    print("  [selftest] --attr-from: overlap 0.8 swapped in, "
          "attr_source recorded, host-side caution rendered")
    print("autoplan selftest OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model", nargs="?", default=None,
                    help="model to plan for (resnet50 | lm | lm-tiny)")
    ap.add_argument("--chips", default="4,8,32",
                    help="comma-separated world sizes (default: 4,8,32)")
    ap.add_argument("--chip", default=None,
                    help="chip generation for the capability tables "
                         "(v4, v5e, v5p, v6e, ...; default: CPU-nominal)")
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--hbm-budget", type=float, default=None,
                    help="override the per-chip HBM byte budget")
    ap.add_argument("--overlap-from", default=None, metavar="TIMELINE_JSON",
                    help="replace the assumed backward-overlap fraction "
                         "with the measured overlap_pct_mean from an "
                         "obs_timeline.py report")
    ap.add_argument("--attr-from", default=None, dest="attr_from",
                    metavar="ATTR_JSON",
                    help="replace the assumed overlap/bottleneck "
                         "constants with a measured step-attribution "
                         "profile (obs_roofline.py --attr-out); the "
                         "payload records attr_source")
    ap.add_argument("--overlap-schedule", nargs="?", const=4.0, type=float,
                    default=None, metavar="BUCKET_MB",
                    help="replace the assumed backward-overlap fraction "
                         "with the bucketed scheduler's schedule-derived "
                         "one (cost.bucketed_overlap over the model's "
                         "gradient bytes at BUCKET_MB-MiB buckets, "
                         "default 4) — use when the recipe runs "
                         "--overlap bucketed; payload records "
                         "overlap_source=schedule")
    ap.add_argument("--no-elastic", action="store_true",
                    help="skip pre-planning the shrunk elastic worlds")
    ap.add_argument("--validate", action="store_true",
                    help="lower the top-k candidates' recipe twins on the "
                         "simulated mesh and fence predictions vs ledgers")
    ap.add_argument("--validate-k", type=int, default=3)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the ranked plan.json to PATH")
    ap.add_argument("--format", choices=("table", "json"), default="table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the zero-compile acceptance sweep and exit")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.model is None:
        ap.error("model is required (or --selftest)")
    if args.validate:
        _setup_mesh_backend()

    from pytorch_distributed_tpu.plan import MODELS, autoplan

    if args.model not in MODELS:
        ap.error(f"unknown model {args.model!r}; known: {sorted(MODELS)}")

    overlap = None
    overlap_source = None
    attr_profile = None
    if sum(bool(x) for x in (args.overlap_from, args.attr_from,
                             args.overlap_schedule is not None)) > 1:
        ap.error("--overlap-from, --attr-from and --overlap-schedule are "
                 "exclusive (one overlap provenance per plan)")
    if args.overlap_from:
        overlap = overlap_from_timeline(args.overlap_from)
        print(f"measured overlap {100.0 * overlap:.1f}% from "
              f"'{args.overlap_from}' (assumed default was 60%)")
    elif args.attr_from:
        attr_profile = _load_stepattr().load_attr(args.attr_from)
        ov = attr_profile.get("overlap")
        if ov is not None:
            overlap = min(1.0, max(0.0, float(ov)))
            overlap_source = "measured-attr"
        print(f"measured attribution from '{args.attr_from}': bottleneck "
              f"{attr_profile.get('bottleneck')}"
              + (f", overlap {100.0 * overlap:.1f}%"
                 if overlap is not None else ", overlap n/a"))
    elif args.overlap_schedule is not None:
        from pytorch_distributed_tpu.plan import cost as cost_mod

        overlap = cost_mod.spec_bucketed_overlap(
            MODELS[args.model](), bucket_mb=args.overlap_schedule)
        overlap_source = "schedule"
        print(f"schedule-derived overlap {100.0 * overlap:.1f}% "
              f"(bucketed model, {args.overlap_schedule:g} MiB buckets)")

    sweeps = []
    rc = 0
    for chips in [int(c) for c in args.chips.split(",") if c]:
        payload = autoplan(
            args.model, chips, chip=args.chip, top_k=args.top_k,
            elastic=not args.no_elastic, validate=args.validate,
            validate_k=args.validate_k, hbm_budget=args.hbm_budget,
            overlap=overlap, overlap_source=overlap_source,
            attr_profile=attr_profile)
        sweeps.append(payload)
        if args.format == "table":
            print(_render(payload))
        if args.validate and not payload.get("validation_ok", True):
            rc = 1
    doc = sweeps[0] if len(sweeps) == 1 else {
        "schema_version": sweeps[0]["schema_version"],
        "model": args.model, "sweeps": sweeps}
    if args.format == "json":
        print(json.dumps(doc, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {args.out}")
    if rc:
        print("autoplan: top-k validation failed its fences",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
