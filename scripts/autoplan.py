#!/usr/bin/env python
"""autoplan: static layout planning across the recipe matrix.

Enumerates every recipe-expressible dp x tp x pp x fsdp x remat x
fused-ce-mode x zero x grad-compress plan for a model at one or more
chip counts, prunes statically infeasible points (per-chip peak HBM over
budget, indivisible vocab/head/stage shapes), scores the survivors
analytically (compute time, wire bytes, predicted exposed comm, peak
HBM — obs/flops.py's fenced cost models over plan/cost.py), and emits a
ranked ``plan.json`` with predicted MFU and the exact recipe CLI flags.

The default path is purely analytic: no backend, no mesh, no compiles —
it runs on a login node in milliseconds.  ``--validate`` additionally
lowers each top-k candidate's recipe twin on the simulated CPU mesh and
cross-checks the predictions against the real comm/memory ledgers
(plan/validate.py), riding the shared lowering service
(analysis/lowering.py) so an already-swept process pays zero extra
compiles.

``--overlap-from timeline.json`` closes the measurement loop: the
backward-overlap fraction the scorer assumes (DEFAULT_OVERLAP, env
PTD_PLAN_OVERLAP) is replaced by the overlap the profiler actually
measured on this deployment (obs_timeline.py report), so re-planning
after a calibration run scores comm-bound plans with real numbers.

Usage:
  python scripts/autoplan.py lm --chips 32 --chip v5p
  python scripts/autoplan.py resnet50 --chips 4,8,32 --out plan.json
  python scripts/autoplan.py lm --chips 32 --overlap-from timeline.json
  python scripts/autoplan.py lm-tiny --chips 4 --validate
  python scripts/autoplan.py --selftest       # resnet50 + LM at 4/8/32
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _setup_mesh_backend() -> None:
    """--validate needs the simulated mesh; flags must precede jax."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_threefry_partitionable", True)


def overlap_from_timeline(path: str) -> float:
    """Measured backward-overlap fraction (0-1) from an obs_timeline.py
    report: the mean of every capture's ``aggregate.overlap_pct_mean``.
    Replaces the cost model's assumed ``DEFAULT_OVERLAP`` so plan scores
    reflect how much collective time *this* deployment actually hides
    under compute, instead of the literature constant."""
    with open(path) as f:
        doc = json.load(f)
    vals = [c["aggregate"]["overlap_pct_mean"]
            for c in (doc.get("captures") or [])
            if c.get("aggregate", {}).get("steps")]
    if not vals:
        raise ValueError(
            f"no step aggregates in '{path}' — expected an obs_timeline.py "
            "report (captures[].aggregate.overlap_pct_mean)")
    return min(1.0, max(0.0, sum(vals) / len(vals) / 100.0))


def _render(payload) -> str:
    lines = [f"== {payload['model']} @ {payload['chips']} chips "
             f"({payload['hw']['name']}): {payload['feasible']} feasible / "
             f"{payload['enumerated']} enumerated =="]
    if payload.get("overlap_source") == "measured":
        lines.append(f"   overlap: {100.0 * payload['overlap']:.1f}% "
                     "(measured from timeline)")
    elif payload.get("overlap_source") == "schedule":
        lines.append(f"   overlap: {100.0 * payload['overlap']:.1f}% "
                     "(bucketed-schedule model)")
    for reason, n in sorted(payload["pruned"].items()):
        lines.append(f"   pruned {n:4d}  {reason}")
    lines.append(f"   {'#':>2} {'plan':<34} {'MFU%':>6} {'step_ms':>10} "
                 f"{'wire_MB':>8} {'peak_GB':>8}")
    for i, entry in enumerate(payload["ranked"], 1):
        p, s = entry["plan"], entry["predicted"]
        lines.append(
            f"   {i:>2} {p['key']:<34} {s['mfu_pct']:>6.2f} "
            f"{s['step_time_ms']:>10.4f} {s['wire_bytes'] / 1e6:>8.3f} "
            f"{s['peak_hbm_bytes'] / 1e9:>8.4f}")
    if payload["ranked"]:
        lines.append(f"   run: {payload['ranked'][0]['plan']['cli']}")
    for world, entry in sorted(payload.get("elastic", {}).items(),
                               key=lambda kv: -int(kv[0])):
        key = entry["plan"]["key"] if entry else "(none feasible)"
        lines.append(f"   elastic {world}: {key}")
    for rec in payload.get("validation", []):
        verdict = {True: "ok", False: "FAIL", None: "n/a"}[rec["ok"]]
        lines.append(f"   validate {rec['plan']} -> "
                     f"{rec['recipe'] or '(no recipe twin)'}: {verdict}")
        for name, c in (rec.get("checks") or {}).items():
            if "residual_pct" in c:
                fence = "" if c.get("fenced", True) else " (unfenced)"
                lines.append(f"      {name}: residual "
                             f"{c['residual_pct']:.2f}% of "
                             f"{c['fence_pct']:.0f}%{fence}")
    return "\n".join(lines)


def selftest() -> int:
    """The acceptance sweep: ranked plans with predicted MFU + runnable
    flags for resnet50 and the LM at 4, 8, and 32 chips — analytically,
    with zero compiles."""
    from pytorch_distributed_tpu.plan import autoplan

    for model in ("resnet50", "lm"):
        for chips in (4, 8, 32):
            out = autoplan(model, chips, chip="v5p", top_k=3)
            assert out["enumerated"] > 0, (model, chips)
            assert out["feasible"] > 0, (model, chips, out["pruned"])
            top = out["ranked"][0]
            assert top["predicted"]["mfu_pct"] > 0, top
            assert top["plan"]["flags"], top
            assert "--batch-size" in top["plan"]["cli"], top
            print(f"  [selftest] {model}@{chips}: top "
                  f"{top['plan']['key']} "
                  f"mfu={top['predicted']['mfu_pct']:.1f}%")
    # tiny LM must rank the fenced plain-DP recipe first (the tie-break
    # contract the validation fences depend on)
    out = autoplan("lm-tiny", 4, top_k=1)
    assert out["ranked"][0]["plan"]["key"] == "c4/dp4", out["ranked"][0]
    print("autoplan selftest OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("model", nargs="?", default=None,
                    help="model to plan for (resnet50 | lm | lm-tiny)")
    ap.add_argument("--chips", default="4,8,32",
                    help="comma-separated world sizes (default: 4,8,32)")
    ap.add_argument("--chip", default=None,
                    help="chip generation for the capability tables "
                         "(v4, v5e, v5p, v6e, ...; default: CPU-nominal)")
    ap.add_argument("--top-k", type=int, default=5)
    ap.add_argument("--hbm-budget", type=float, default=None,
                    help="override the per-chip HBM byte budget")
    ap.add_argument("--overlap-from", default=None, metavar="TIMELINE_JSON",
                    help="replace the assumed backward-overlap fraction "
                         "with the measured overlap_pct_mean from an "
                         "obs_timeline.py report")
    ap.add_argument("--overlap-schedule", nargs="?", const=4.0, type=float,
                    default=None, metavar="BUCKET_MB",
                    help="replace the assumed backward-overlap fraction "
                         "with the bucketed scheduler's schedule-derived "
                         "one (cost.bucketed_overlap over the model's "
                         "gradient bytes at BUCKET_MB-MiB buckets, "
                         "default 4) — use when the recipe runs "
                         "--overlap bucketed; payload records "
                         "overlap_source=schedule")
    ap.add_argument("--no-elastic", action="store_true",
                    help="skip pre-planning the shrunk elastic worlds")
    ap.add_argument("--validate", action="store_true",
                    help="lower the top-k candidates' recipe twins on the "
                         "simulated mesh and fence predictions vs ledgers")
    ap.add_argument("--validate-k", type=int, default=3)
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write the ranked plan.json to PATH")
    ap.add_argument("--format", choices=("table", "json"), default="table")
    ap.add_argument("--selftest", action="store_true",
                    help="run the zero-compile acceptance sweep and exit")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.model is None:
        ap.error("model is required (or --selftest)")
    if args.validate:
        _setup_mesh_backend()

    from pytorch_distributed_tpu.plan import MODELS, autoplan

    if args.model not in MODELS:
        ap.error(f"unknown model {args.model!r}; known: {sorted(MODELS)}")

    overlap = None
    overlap_source = None
    if args.overlap_from and args.overlap_schedule is not None:
        ap.error("--overlap-from and --overlap-schedule are exclusive "
                 "(measured vs schedule-derived provenance)")
    if args.overlap_from:
        overlap = overlap_from_timeline(args.overlap_from)
        print(f"measured overlap {100.0 * overlap:.1f}% from "
              f"'{args.overlap_from}' (assumed default was 60%)")
    elif args.overlap_schedule is not None:
        from pytorch_distributed_tpu.plan import cost as cost_mod

        overlap = cost_mod.spec_bucketed_overlap(
            MODELS[args.model](), bucket_mb=args.overlap_schedule)
        overlap_source = "schedule"
        print(f"schedule-derived overlap {100.0 * overlap:.1f}% "
              f"(bucketed model, {args.overlap_schedule:g} MiB buckets)")

    sweeps = []
    rc = 0
    for chips in [int(c) for c in args.chips.split(",") if c]:
        payload = autoplan(
            args.model, chips, chip=args.chip, top_k=args.top_k,
            elastic=not args.no_elastic, validate=args.validate,
            validate_k=args.validate_k, hbm_budget=args.hbm_budget,
            overlap=overlap, overlap_source=overlap_source)
        sweeps.append(payload)
        if args.format == "table":
            print(_render(payload))
        if args.validate and not payload.get("validation_ok", True):
            rc = 1
    doc = sweeps[0] if len(sweeps) == 1 else {
        "schema_version": sweeps[0]["schema_version"],
        "model": args.model, "sweeps": sweeps}
    if args.format == "json":
        print(json.dumps(doc, indent=2))
    if args.out:
        with open(args.out, "w") as f:
            json.dump(doc, f, indent=2)
            f.write("\n")
        print(f"wrote {args.out}")
    if rc:
        print("autoplan: top-k validation failed its fences",
              file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
