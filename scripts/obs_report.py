#!/usr/bin/env python
"""Fold one run's observability artifacts into a human-readable summary,
or fence two runs against each other (``--diff A B``).

Inputs (any subset):
- ``--metrics-jsonl``  per-step records from ``obs.MetricsLogger``
  (``--metrics-jsonl`` on any recipe / ``LMTrainer``);
- ``--hb-dir``         per-process heartbeats from ``obs.HeartbeatWriter``
  (``--hb-dir``), with straggler flagging by step lag / beat age;
- ``--telemetry-csv``  the 500 ms device-memory CSV from
  ``utils.telemetry.TelemetrySampler`` (``--telemetry-csv``);
- ``--flight-dir``     flight-recorder ring dumps (``--flight-rec`` on
  either trainer), folded in as the ``== postmortem ==`` cross-rank
  root-cause section (scripts/postmortem.py);
- ``--synclint-json``  a synclint/shardlint ``--json`` capture, folded
  in as the ``== synclint ==`` cross-rank congruence section — the
  pre-launch twin of the postmortem fold.  With ``--strict``, any
  error-severity sync finding fails the report.

Output: step-time percentiles + throughput + MFU + loss/grad-norm
trajectory, the goodput/badput ledger (ft_event + recompile records),
bench staleness events, per-device peak HBM, and a straggler table —
with malformed JSONL lines *counted*, not silently skipped (the torn
final line after a SIGKILL is the common case).

``--diff A B`` compares two metrics JSONL files — step-time p50/p95,
throughput, MFU, goodput — and prints a thresholded PASS/REGRESS verdict
per metric (exit code 1 on overall REGRESS): the perf-regression fence a
CI job can gate on.  ``--strict`` additionally promotes the
bench-staleness WARN (``--bench-max-stale-days``) from a note to a
failing fence on both the report and the diff.

``--selftest`` synthesizes the artifacts in a temp dir, runs the report
and both diff verdicts on them, and asserts the output — the fast tier-1
CI hook.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _mib(n: float) -> str:
    return f"{n / (1024 * 1024):.1f}"


def load_metrics(path: str) -> Tuple[List[dict], int]:
    """Parse a metrics JSONL; returns ``(records, malformed_line_count)``.

    Malformed/truncated lines (the torn tail after a kill — routine since
    the FT subsystem made kill-and-resume a supported flow) are *counted*
    so the report can say how much of the stream was lost."""
    records, malformed = [], 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                malformed += 1
                continue
            if isinstance(rec, dict):
                records.append(rec)
            else:
                malformed += 1  # parseable but not a record object
    return records, malformed


def summarize_metrics(records: List[dict], malformed: int = 0) -> List[str]:
    if not records:
        return ["  (no records)"] + (
            [f"  malformed lines   {malformed}"] if malformed else [])
    records = sorted(records, key=lambda r: (r.get("step", 0), r.get("t", 0)))
    times = sorted(r["step_time"] for r in records if "step_time" in r)
    lines = [
        f"  steps logged      {len(records)} "
        f"(step {records[0].get('step')}..{records[-1].get('step')})",
        f"  wall span         {records[-1].get('t', 0) - records[0].get('t', 0):.1f}s",
        f"  step time         p50 {_pct(times, .5) * 1e3:.1f}ms  "
        f"p95 {_pct(times, .95) * 1e3:.1f}ms  "
        f"max {(times[-1] if times else 0) * 1e3:.1f}ms",
    ]
    if malformed:
        lines.append(f"  malformed lines   {malformed} "
                     "(torn tail from a killed writer?)")
    thr = [r["throughput"] for r in records if "throughput" in r]
    if thr:
        lines.append(f"  throughput        mean {sum(thr) / len(thr):.1f}/s  "
                     f"last {thr[-1]:.1f}/s")
    mfu = [r["mfu"] for r in records if "mfu" in r]
    if mfu:
        hfu = [r.get("hfu", 0.0) for r in records if "mfu" in r]
        lines.append(f"  mfu               mean {sum(mfu) / len(mfu):.1f}%  "
                     f"last {mfu[-1]:.1f}%  "
                     f"(hfu mean {sum(hfu) / len(hfu):.1f}%)")
    loss = [r["loss"] for r in records if "loss" in r]
    if loss:
        lines.append(f"  loss              first {loss[0]:.4f}  "
                     f"last {loss[-1]:.4f}")
    gn = [r["grad_norm"] for r in records if "grad_norm" in r]
    if gn:
        lines.append(f"  grad_norm         last {gn[-1]:.4f}  "
                     f"max {max(gn):.4f}")
    lr = [r["lr"] for r in records if "lr" in r]
    if lr:
        lines.append(f"  lr                last {lr[-1]:.6g}")
    return lines


def summarize_ft_events(records: List[dict]) -> List[str]:
    """Fold the FT subsystem's structured ``ft_event`` records (skips,
    rollbacks, preemptions — ft/divergence.py and the trainers) into the
    summary: per-kind counts with the steps involved, plus the final LR
    backoff scale after the last rollback."""
    events = [r for r in records if "ft_event" in r]
    if not events:
        return []
    by_kind: Dict[str, List[dict]] = {}
    for e in events:
        by_kind.setdefault(str(e["ft_event"]), []).append(e)
    lines = ["== ft events =="]
    for kind in sorted(by_kind):
        evs = by_kind[kind]
        steps = [e["step"] for e in evs if "step" in e]
        shown = ",".join(str(s) for s in steps[:8])
        if len(steps) > 8:
            shown += ",…"
        lines.append(f"  {kind:<16}  {len(evs)}x"
                     + (f"  steps {shown}" if steps else ""))
    rollbacks = by_kind.get("rollback", [])
    scales = [e["lr_scale"] for e in rollbacks if "lr_scale" in e]
    if scales:
        lines.append(f"  lr scale          {scales[-1]:g} after "
                     f"{len(rollbacks)} rollback(s)")
    return lines


def bench_staleness_info(args) -> Optional[Dict]:
    """Days-since-last-good from BENCH_LKG.json + bench_events.jsonl
    (scripts/benchlib.py ``bench_staleness``), honoring the report's fixed
    ``--now`` clock.  None when neither artifact yields a timestamp or
    staleness reporting is disabled (``--bench-max-stale-days 0``)."""
    max_days = getattr(args, "bench_max_stale_days", None)
    if max_days is not None and max_days <= 0:
        return None
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    from benchlib import bench_staleness

    info = bench_staleness(lkg_path=getattr(args, "bench_lkg", None),
                           events_path=getattr(args, "bench_events", None),
                           now=getattr(args, "now", None))
    if info is not None and max_days is not None:
        info["max_stale_days"] = max_days
        info["warn"] = info["days_stale"] > max_days
    return info


def summarize_bench(records: List[dict],
                    staleness: Optional[Dict] = None) -> List[str]:
    """Fold ``bench_event`` records (scripts/benchlib.py — e.g. a stale
    benchmark probe replaying its last-known-good number) into the
    summary, so a dashboard reading this report can't mistake a replayed
    benchmark for a fresh one.  ``staleness`` (``bench_staleness_info``)
    adds the days-since-last-good aging line, with a WARN past
    ``--bench-max-stale-days``."""
    events = [r for r in records if "bench_event" in r]
    if not events and staleness is None:
        return []
    lines = ["== bench =="]
    for e in events:
        kind = str(e["bench_event"])
        detail = []
        if e.get("metric"):
            detail.append(str(e["metric"]))
        if e.get("last_good"):
            detail.append(f"last good {e['last_good']}")
        if e.get("reason"):
            detail.append(str(e["reason"]))
        lines.append(f"  {kind:<16}  " + "; ".join(detail))
    if staleness is not None:
        ev = (f", {staleness['stale_events']} stale event(s)"
              if staleness.get("stale_events") else "")
        lines.append(f"  last good         {staleness['days_stale']:.1f} "
                     f"days ago ({staleness.get('last_good')}){ev}")
        if staleness.get("predicted_mfu") is not None:
            meas = staleness.get("measured_mfu")
            drift = staleness.get("prediction_drift_pct")
            tail = (f"  measured {meas:.1f}%  drift {drift:+.1f}%"
                    if meas is not None and drift is not None else "")
            lines.append(f"  plan mfu          predicted "
                         f"{staleness['predicted_mfu']:.1f}%{tail}")
        if staleness.get("warn"):
            lines.append(f"  WARN              benchmark stale "
                         f"> {staleness['max_stale_days']:g} days — "
                         f"re-run bench.py for a fresh capture")
    return lines


# ------------------------------------------------------------- plan.json
def load_plan(path: str) -> Dict:
    """One autoplan sweep payload (scripts/autoplan.py).  A multi-chip
    file ({"sweeps": [...]}) folds to its first sweep — the primary world
    size; pass a single-sweep file to report on another."""
    with open(path) as f:
        obj = json.load(f)
    if "sweeps" in obj:
        sweeps = obj["sweeps"]
        if not sweeps:
            raise ValueError(f"{path}: empty sweeps list")
        return sweeps[0]
    return obj


def plan_stats(payload: Dict) -> Optional[Dict]:
    """The chosen (top-ranked) plan's identity + predictions, or None for
    a sweep where nothing was feasible."""
    ranked = payload.get("ranked") or []
    if not ranked:
        return None
    top = ranked[0]
    pred = top.get("predicted", {})
    return {
        "model": payload.get("model"),
        "chips": payload.get("chips"),
        "hw": (payload.get("hw") or {}).get("name"),
        "key": top.get("plan", {}).get("key"),
        "cli": top.get("plan", {}).get("cli"),
        "predicted_mfu_pct": pred.get("mfu_pct"),
        "predicted_step_time_ms": pred.get("step_time_ms"),
        "predicted_wire_bytes": pred.get("wire_bytes"),
        "predicted_peak_hbm_bytes": pred.get("peak_hbm_bytes"),
        "validation_ok": payload.get("validation_ok"),
    }


def _residual(predicted: Optional[float],
              measured: Optional[float]) -> Optional[float]:
    if predicted is None or measured is None or not predicted:
        return None
    return 100.0 * (measured - predicted) / predicted


def summarize_plan(payload: Dict, records: List[dict]) -> List[str]:
    """The ``== plan ==`` section: the chosen plan + its predicted
    MFU/wire-bytes/peak-HBM, and — when a metrics stream is on hand —
    the measured values next to each prediction with the drift residual.
    Drift here is informational (the hard fences live in the validation
    pass autoplan --validate already ran against the lowered ledgers)."""
    ps = plan_stats(payload)
    lines = ["== plan =="]
    if ps is None:
        lines.append(f"  (no feasible plan for {payload.get('model')} "
                     f"at {payload.get('chips')} chips)")
        return lines
    lines.append(f"  chosen            {ps['key']}  "
                 f"({ps['model']} @ {ps['chips']} chips, {ps['hw']})")
    if ps["cli"]:
        lines.append(f"  cli               {ps['cli']}")
    cs = comm_stats(records)
    mfu = [r["mfu"] for r in records
           if "mfu" in r and "ft_event" not in r and "bench_event" not in r]
    measured_mfu = sum(mfu) / len(mfu) if mfu else None
    for label, pred, meas, fmt in (
            ("mfu", ps["predicted_mfu_pct"], measured_mfu,
             lambda v: f"{v:.1f}%"),
            ("wire bytes", ps["predicted_wire_bytes"],
             cs["comm_wire_bytes"], lambda v: f"{v:.0f} B"),
            ("peak hbm", ps["predicted_peak_hbm_bytes"],
             cs["peak_hbm_bytes"], lambda v: f"{_mib(v)} MiB")):
        if pred is None:
            continue
        res = _residual(pred, meas)
        tail = (f"  measured {fmt(meas)}  drift {res:+.1f}%"
                if res is not None else
                ("  measured --" if meas is None else ""))
        lines.append(f"  {label:<16}  predicted {fmt(pred)}{tail}")
    if ps["validation_ok"] is not None:
        lines.append("  validation        "
                     + ("ok (lowered-ledger fences hold)"
                        if ps["validation_ok"]
                        else "FAILED (predicted vs ledger fence exceeded)"))
    return lines


def plan_json_section(payload: Dict, records: List[dict]) -> Dict:
    """Machine-readable twin of ``summarize_plan``."""
    ps = plan_stats(payload)
    if ps is None:
        return {"model": payload.get("model"),
                "chips": payload.get("chips"), "chosen": None}
    cs = comm_stats(records)
    mfu = [r["mfu"] for r in records
           if "mfu" in r and "ft_event" not in r and "bench_event" not in r]
    measured_mfu = sum(mfu) / len(mfu) if mfu else None
    ps["measured_mfu_pct"] = measured_mfu
    ps["measured_wire_bytes"] = cs["comm_wire_bytes"]
    ps["measured_peak_hbm_bytes"] = cs["peak_hbm_bytes"]
    ps["mfu_drift_pct"] = _residual(ps["predicted_mfu_pct"], measured_mfu)
    ps["wire_drift_pct"] = _residual(ps["predicted_wire_bytes"],
                                     cs["comm_wire_bytes"])
    ps["peak_hbm_drift_pct"] = _residual(ps["predicted_peak_hbm_bytes"],
                                         cs["peak_hbm_bytes"])
    return ps


_COMM_FIELDS = ("model_comm_bytes", "comm_wire_bytes", "collective_count",
                "exposed_comm_ms", "overlap_pct", "peak_hbm_bytes")


def comm_stats(records: List[dict]) -> Dict[str, Optional[float]]:
    """Per-run means of the comm fields the trainers stamp from the static
    ledger (``model_comm_bytes``/``comm_wire_bytes``/``collective_count``,
    obs/comms.py) and the timeline analyzer measures
    (``exposed_comm_ms``/``overlap_pct``, obs/timeline.py)."""
    steps = [r for r in records
             if "ft_event" not in r and "bench_event" not in r]
    out: Dict[str, Optional[float]] = {}
    for key in _COMM_FIELDS:
        vals = [float(r[key]) for r in steps if key in r]
        out[key] = sum(vals) / len(vals) if vals else None
    return out


def _comm_residual(predicted: Optional[float],
                   measured: Optional[float]) -> Optional[float]:
    from pytorch_distributed_tpu.obs.flops import comm_residual_pct

    if predicted is None or measured is None or not predicted:
        return None
    return comm_residual_pct(predicted, measured)


def summarize_comms(records: List[dict], ledger_path: Optional[str] = None,
                    predicted_bytes: Optional[float] = None) -> List[str]:
    """The ``== comms ==`` section: per-step collective traffic from the
    metrics stream, the itemized ledger breakdown when one is on disk, and
    the predicted-vs-measured residual fence (obs/flops.py analytic comm
    model vs the compiled ledger; >15% means the model and the lowering
    disagree about what the step communicates)."""
    cs = comm_stats(records)
    if not any(v is not None for v in cs.values()) and not ledger_path:
        return []
    lines = ["== comms =="]
    if cs["model_comm_bytes"] is not None:
        wire = (f", {cs['comm_wire_bytes']:.0f} B wire"
                if cs["comm_wire_bytes"] is not None else "")
        cnt = (f", {cs['collective_count']:.0f} collectives"
               if cs["collective_count"] is not None else "")
        lines.append(f"  per-step payload  {cs['model_comm_bytes']:.0f} B"
                     f"{wire}{cnt}")
    if cs["exposed_comm_ms"] is not None:
        ov = (f"  (overlap {cs['overlap_pct']:.1f}%)"
              if cs["overlap_pct"] is not None else "")
        lines.append(f"  exposed comm      "
                     f"{cs['exposed_comm_ms']:.3f} ms/step mean{ov}")
    residual = _comm_residual(predicted_bytes, cs["model_comm_bytes"])
    if residual is not None:
        verdict = "ok" if abs(residual) <= 15.0 else "EXCEEDS ±15%"
        lines.append(f"  predicted model   {predicted_bytes:.0f} B -> "
                     f"residual {residual:+.1f}% [{verdict}]")
    if ledger_path:
        from pytorch_distributed_tpu.obs.comms import load_ledgers

        for step, lg in sorted(load_ledgers(ledger_path).items()):
            kinds = ", ".join(
                f"{k}×{v['count']} {v['bytes']:.0f}B"
                for k, v in sorted(lg.by_kind().items()))
            lines.append(f"  ledger {step}: {kinds or 'no collectives'}")
            phases = ", ".join(
                f"{p} {v['bytes']:.0f}B"
                for p, v in sorted(lg.by_phase().items(),
                                   key=lambda kv: -kv[1]["bytes"]))
            if phases:
                lines.append(f"    by phase: {phases}")
            # grad_sync wire encodings: label compressed-collective traffic
            # by payload dtype (ops/qcomm.py modes) so an accidental f32
            # fallback is visible in the report, not just in shardlint.
            enc = lg.phase_wire_encodings("grad_sync")
            if enc and (len(enc) > 1 or "f32" not in enc):
                encs = ", ".join(f"{k} {v:.0f}B"
                                 for k, v in sorted(enc.items(),
                                                    key=lambda kv: -kv[1]))
                lines.append(f"    grad_sync encoding: {encs}")
    if len(lines) == 1:
        return []
    return lines


_MEM_FIELDS = ("mem_peak_bytes", "mem_temp_peak_bytes", "mem_residual_pct")


def mem_stats(records: List[dict]) -> Dict[str, Optional[float]]:
    """Per-run means of the memory-ledger fields the trainers stamp
    (``mem_peak_bytes``/``mem_temp_peak_bytes``/``mem_residual_pct``,
    obs/memory.py)."""
    steps = [r for r in records
             if "ft_event" not in r and "bench_event" not in r]
    out: Dict[str, Optional[float]] = {}
    for key in _MEM_FIELDS:
        vals = [float(r[key]) for r in steps if key in r]
        out[key] = sum(vals) / len(vals) if vals else None
    return out


def _load_mem_ledger_json(path: str) -> Dict[str, Dict]:
    """The raw ``mem_ledger.json`` dicts: unlike ``memory.load_ledgers``,
    the serialized ``class_peaks``/``phase_peaks`` stay authoritative —
    recomputing them from the truncated top-k buffer list would lie."""
    with open(path) as f:
        return json.load(f)


def summarize_memory(records: List[dict], ledger_path: Optional[str] = None,
                     top_k: int = 5) -> List[str]:
    """The ``== memory ==`` section: per-step peak HBM from the metrics
    stream, and — when a mem_ledger.json is on disk — the per-step
    watermark peak vs the compiled ``memory_analysis()`` ground truth
    (±10%% fence), the class/phase breakdown, and the top live buffers at
    the high-water mark."""
    ms = mem_stats(records)
    if not any(v is not None for v in ms.values()) and not ledger_path:
        return []
    lines = ["== memory =="]
    if ms["mem_peak_bytes"] is not None:
        temp = (f"  (temps {_mib(ms['mem_temp_peak_bytes'])} MiB)"
                if ms["mem_temp_peak_bytes"] is not None else "")
        lines.append(f"  per-step peak     {_mib(ms['mem_peak_bytes'])} MiB"
                     f"{temp}")
    if ms["mem_residual_pct"] is not None:
        verdict = ("ok" if ms["mem_residual_pct"] <= 10.0
                   else "EXCEEDS ±10%")
        lines.append(f"  vs memory_analysis residual "
                     f"{ms['mem_residual_pct']:.1f}% [{verdict}]")
    if ledger_path:
        for step, d in sorted(_load_mem_ledger_json(ledger_path).items()):
            peak = float(d.get("peak_bytes", 0))
            measured = float(d.get("measured_peak_bytes", 0.0))
            resid = float(d.get("residual_pct", 0.0))
            fence = ""
            if measured:
                verdict = "ok" if resid <= 10.0 else "EXCEEDS ±10%"
                fence = (f" (measured {_mib(measured)} MiB, residual "
                         f"{resid:.1f}% [{verdict}])")
            lines.append(f"  ledger {step}: peak {_mib(peak)} MiB at instr "
                         f"{d.get('peak_index')}/{d.get('n_instructions')}"
                         f"{fence}")
            classes = ", ".join(
                f"{k} {_mib(float(v))}"
                for k, v in sorted(d.get("class_peaks", {}).items(),
                                   key=lambda kv: -kv[1]) if v)
            if classes:
                lines.append(f"    by class (MiB): {classes}")
            phases = ", ".join(
                f"{p} {_mib(float(v))}"
                for p, v in sorted(d.get("phase_peaks", {}).items(),
                                   key=lambda kv: -kv[1]) if v)
            if phases:
                lines.append(f"    by phase (MiB): {phases}")
            for b in d.get("top", [])[:top_k]:
                dims = "x".join(str(x) for x in b.get("dims", [])) or "scalar"
                lines.append(
                    f"    top: {b.get('name'):<28} {_mib(b.get('bytes', 0))} "
                    f"MiB {b.get('dtype')}[{dims}] {b.get('klass')}"
                    + (f" ({b.get('phase')})" if b.get("phase") else ""))
    if len(lines) == 1:
        return []
    return lines


def telemetry_stats(path: str) -> Tuple[int, Dict[int, float], Dict[int, float]]:
    """``(n_rows, peak_by_device, limit_by_device)`` from the ``timestamp,
    index,bytes_limit,bytes_in_use,peak_bytes`` CSV (no header in the
    statistics.sh contract)."""
    peak: Dict[int, float] = {}
    limit: Dict[int, float] = {}
    n_rows = 0
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) < 5:
                continue
            try:
                idx = int(row[1])
                lim, pk = float(row[2]), float(row[4])
            except ValueError:
                continue  # header or torn row
            n_rows += 1
            peak[idx] = max(peak.get(idx, 0.0), pk)
            limit[idx] = max(limit.get(idx, 0.0), lim)
    return n_rows, peak, limit


def summarize_telemetry(path: str) -> List[str]:
    n_rows, peak, limit = telemetry_stats(path)
    if not peak:
        return ["  (no samples)"]
    lines = [f"  samples           {n_rows}"]
    for idx in sorted(peak):
        cap = f" / {_mib(limit[idx])} MiB" if limit[idx] else ""
        lines.append(f"  device {idx:<2}         peak {_mib(peak[idx])} MiB{cap}")
    return lines


def heartbeat_stats(hb_dir: str, now: Optional[float], max_step_lag: int,
                    max_age_s: float) -> Tuple[Dict, Dict, float]:
    """``(beats, flagged, now)`` — the parsed heartbeat state the text and
    JSON renderings share."""
    from pytorch_distributed_tpu.obs.heartbeat import (
        find_stragglers,
        read_heartbeats,
    )

    beats = read_heartbeats(hb_dir)
    if now is None:
        now = time.time()
    flagged = find_stragglers(beats, now=now, max_step_lag=max_step_lag,
                              max_age_s=max_age_s) if beats else {}
    return beats, flagged, now


def read_membership(hb_dir: str) -> Optional[Dict]:
    """The elastic coordinator's membership.json, if this run is elastic
    (ft/elastic.py) — {"epoch": int, "ranks": [...]} or None."""
    path = os.path.join(hb_dir, "membership.json")
    try:
        with open(path) as f:
            obj = json.load(f)
        return {"epoch": int(obj["epoch"]),
                "ranks": [int(r) for r in obj["ranks"]]}
    except (OSError, ValueError, KeyError, TypeError):
        return None


def summarize_heartbeats(hb_dir: str, now: Optional[float],
                         max_step_lag: int, max_age_s: float) -> List[str]:
    beats, flagged, now = heartbeat_stats(hb_dir, now, max_step_lag,
                                          max_age_s)
    if not beats:
        return ["  (no heartbeats)"]
    lines = []
    member = read_membership(hb_dir)
    if member is not None:
        lines.append(f"  membership epoch {member['epoch']}: "
                     f"world {len(member['ranks'])} "
                     f"ranks {member['ranks']}")
    for pid in sorted(beats):
        b = beats[pid]
        mark = f"  ** STRAGGLER: {flagged[pid]}" if pid in flagged else ""
        # hardened beats stamp their membership epoch (+ world) so a
        # stale incarnation is visibly from a pre-re-mesh world
        ep = f" epoch {b['epoch']}" if "epoch" in b else ""
        lines.append(f"  process {pid:<3}       step {b['step']:<8} "
                     f"beat age {now - b['t']:.1f}s{ep}{mark}")
    if not flagged:
        lines.append("  no stragglers")
    return lines


def _postmortem_mod():
    """scripts/postmortem.py as a module (same dir as this file)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import postmortem

    return postmortem


def postmortem_section(flight_dir: str,
                       hb_dir: Optional[str] = None) -> List[str]:
    """The ``== postmortem ==`` fold (ISSUE 13): merge per-rank flight-
    recorder dumps into the cross-rank root-cause report, clock-aligned
    against the heartbeats when available."""
    pm = _postmortem_mod()
    try:
        rep = pm.postmortem(flight_dir, hb_dir=hb_dir)
    except Exception as e:  # a torn dump must not kill the report
        return ["== postmortem ==", f"  (unreadable: {e})"]
    if not rep.get("n_ranks"):
        return ["== postmortem ==",
                f"  (no flightrec_rank*.json in '{flight_dir}')"]
    return pm.render_text(rep).splitlines()


def serving_stats(records: List[dict]) -> Optional[Dict]:
    """Scalar summary of the serving SLO fields (serving/engine.py):
    TTFT / inter-token-latency percentiles, queue/pool pressure,
    preemption and defrag counts.  None when the run logged no serving
    steps (training runs keep their report unchanged)."""
    steps = [r for r in records
             if r.get("serving") and "ft_event" not in r
             and "bench_event" not in r]
    if not steps:
        return None

    def last(field):
        # percentiles and counters are cumulative over the run — the
        # last stamped value IS the run summary
        for r in reversed(steps):
            v = r.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
        return None

    def peak(field):
        vals = [float(r[field]) for r in steps
                if isinstance(r.get(field), (int, float))]
        return max(vals) if vals else None

    out: Dict = {"steps": float(len(steps))}
    for f in ("ttft_p50_ms", "ttft_p95_ms", "ttft_p99_ms",
              "itl_p50_ms", "itl_p95_ms", "itl_p99_ms",
              "tokens_per_s", "requests_completed", "preemptions"):
        out[f] = last(f)
    out["queue_depth_peak"] = peak("queue_depth")
    out["kv_occupancy_peak_pct"] = peak("kv_occupancy_pct")
    out["kv_frag_peak_pct"] = peak("kv_frag_pct")
    out["defrags"] = float(sum(1 for r in records
                               if r.get("ft_event") == "serve_defrag"))
    # per-request attribution quantiles (obs/reqtrace.py step_fields):
    # stamped on serving step records when --req-trace is on; None keeps
    # untraced serving runs unchanged
    out["queue_wait_share_p99"] = last("queue_wait_share_p99")
    out["preempt_redo_ms_p99"] = last("preempt_redo_ms_p99")
    return out


def summarize_serving(records: List[dict]) -> List[str]:
    s = serving_stats(records)
    if s is None:
        return []

    def fmt(v, unit=""):
        return "--" if v is None else f"{v:.1f}{unit}"

    return [
        "== serving ==",
        f"  {s['steps']:.0f} serving step(s); "
        f"{fmt(s['requests_completed'])} request(s) completed; "
        f"{fmt(s['tokens_per_s'])} tok/s",
        f"  TTFT p50/p95/p99  {fmt(s['ttft_p50_ms'], 'ms')} / "
        f"{fmt(s['ttft_p95_ms'], 'ms')} / {fmt(s['ttft_p99_ms'], 'ms')}",
        f"  ITL p50/p95/p99   {fmt(s['itl_p50_ms'], 'ms')} / "
        f"{fmt(s['itl_p95_ms'], 'ms')} / {fmt(s['itl_p99_ms'], 'ms')}",
        f"  queue depth peak  {fmt(s['queue_depth_peak'])};  "
        f"KV occupancy peak {fmt(s['kv_occupancy_peak_pct'], '%')};  "
        f"frag peak {fmt(s['kv_frag_peak_pct'], '%')}",
        f"  preemptions       {fmt(s['preemptions'])};  "
        f"defrags {s['defrags']:.0f}",
    ]


def trace_stats(records: List[dict]) -> Optional[Dict]:
    """Attribution summary over the run's per-request ``reqtrace``
    events (obs/reqtrace.py); None when tracing was off."""
    from pytorch_distributed_tpu.obs.reqtrace import (
        attribution_summary,
        trace_records,
    )

    return attribution_summary(trace_records(records))


def summarize_traces(records: List[dict]) -> List[str]:
    """The ``== traces ==`` fold (ISSUE 17): per-request TTFT/e2e
    critical-path attribution + the tail rollup that names the dominant
    component behind the p99."""
    s = trace_stats(records)
    if s is None:
        return []
    from pytorch_distributed_tpu.obs.reqtrace import format_tail_line

    lines = [
        "== traces ==",
        f"  {s['requests']} request trace(s); {s['violations']} SLO "
        f"violation(s); {s['preemptions']} preemption(s); "
        f"spans kept {s['sampled_kept']}, dropped {s['spans_dropped']}",
        f"  TTFT p50/p99      {s['ttft_p50_ms']:.1f}ms / "
        f"{s['ttft_p99_ms']:.1f}ms;  e2e p99 {s['e2e_p99_ms']:.1f}ms;  "
        f"recon err max {s['recon_err_ms_max']:.3f}ms",
        f"  queue-wait share p99 {s['queue_wait_share_p99']:.1f}% of "
        f"TTFT;  preempt-redo p99 {s['preempt_redo_ms_p99']:.1f}ms",
    ]
    tail = s.get("tail")
    if tail:
        lines.append("  tail attribution: " + format_tail_line(tail))
        lines.append(f"  dominant tail component: {tail['dominant']}")
    return lines


def attr_stats(records: List[dict]) -> Optional[Dict]:
    """Step-time attribution summary over a ``--step-attr`` run's
    ``attr_*`` record fields (obs/stepattr.py), with the roofline bolted
    on when the run booked its ``stepattr_phases`` event.  None when
    attribution was off (every other run keeps its report unchanged)."""
    from pytorch_distributed_tpu.obs import stepattr

    summ = stepattr.summarize(records)
    if summ is None:
        return None
    summ = dict(summ)
    ev = stepattr.phase_event(records)
    if ev is not None:
        summ["roofline"] = stepattr.roofline(summ, ev)
    return summ


def summarize_attribution(records: List[dict]) -> List[str]:
    """The ``== attribution ==`` fold (ISSUE 20): the exact identity
    step_time == compute + exposed_comm + host_sync + data_wait + other,
    the two diff-fenced tails, and the roofline's fix-first ranking."""
    s = attr_stats(records)
    if s is None:
        return []
    from pytorch_distributed_tpu.obs.stepattr import format_summary_line

    lines = [
        "== attribution ==",
        "  " + format_summary_line(s),
        f"  identity recon    err max {s['recon_err_ms_max']:.3f}ms "
        f"({s['recon_err_pct_p50']:.2f}% of step p50) over "
        f"{s['steps']} step(s)",
        f"  data_wait_share   p50 {s['data_wait_share_p50']:.1f}%  "
        f"p95 {s['data_wait_share_p95']:.1f}%",
        f"  host_sync         p50 {s['host_sync_ms_p50']:.2f}ms  "
        f"p95 {s['host_sync_ms_p95']:.2f}ms",
    ]
    if s.get("overlap_measured") is not None:
        lines.append(f"  comm overlap      measured "
                     f"{s['overlap_measured']:.2f} "
                     f"(exposure source: {s['exposure_source']})")
    roof = s.get("roofline")
    if roof:
        lines.append("  fix first: " + ", ".join(
            f"{p['phase']} {p['headroom_ms']:.1f}ms ({p['label']})"
            for p in roof["fix_first"][:3]))
    return lines


_FLEET_COUNTERS = ("requests_routed", "requests_completed",
                   "requests_failed", "retries", "hedges", "hedges_won",
                   "hedges_lost", "duplicates_suppressed",
                   "replica_down_events", "drain_events",
                   "scale_up_events", "scale_down_events")

_FLEET_EVENT_KINDS = ("replica_down", "replica_evict", "scale_up",
                      "scale_down", "drain")


def fleet_stats(records: List[dict]) -> Optional[Dict]:
    """Scalar summary of the fleet router plane (serving/router.py,
    ISSUE 19): the ``fleet``-stamped cycle records carry the cumulative
    counters, the per-request ``fleettrace`` ft_events carry router-side
    latency attribution, and the ``replica_down`` / scale / drain
    ft_events carry the membership churn.  None when the run had no
    router (single-replica serving and training runs are untouched)."""
    steps = [r for r in records
             if r.get("fleet") and "ft_event" not in r
             and "bench_event" not in r]
    traces = [r for r in records if r.get("ft_event") == "fleettrace"]
    churn = [r for r in records
             if r.get("ft_event") in _FLEET_EVENT_KINDS]
    if not steps and not traces and not churn:
        return None

    def last(field):
        # counters are cumulative over the run — the last cycle record
        # stamped IS the run summary
        for r in reversed(steps):
            v = r.get(field)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                return float(v)
        return None

    out: Dict = {"cycles": float(len(steps))}
    for f in ("replicas_up", "replicas_quarantined", "replicas_total",
              "retry_rate_pct", "hedge_win_rate_pct"):
        out[f] = last(f)
    for f in _FLEET_COUNTERS:
        out[f] = last("fleet_" + f)
    out["traced_requests"] = float(len(traces))
    per: Dict[str, float] = {}
    for t in traces:
        k = str(t.get("replica"))
        per[k] = per.get(k, 0.0) + 1.0
    out["requests_by_replica"] = per
    if traces:
        def q99(field):
            vals = sorted(float(t.get(field, 0.0)) for t in traces)
            return _pct(vals, .99)

        out["router_ttft_p50_ms"] = _pct(
            sorted(float(t.get("router_ttft_ms", 0.0)) for t in traces), .5)
        out["router_ttft_p99_ms"] = q99("router_ttft_ms")
        out["router_wait_p99_ms"] = q99("router_wait_ms")
        out["redispatch_p99_ms"] = q99("redispatch_ms")
        out["hedge_wait_p99_ms"] = q99("hedge_wait_ms")
        out["engine_ttft_p99_ms"] = q99("engine_ttft_ms")
        out["retried_requests"] = float(
            sum(1 for t in traces if t.get("attempts", 1) > 1))
        out["hedged_requests"] = float(
            sum(1 for t in traces if t.get("hedged")))
    out["events"] = [
        {"kind": r.get("ft_event"), "replica": r.get("replica"),
         "reason": r.get("reason") or r.get("scope")}
        for r in churn]
    return out


def summarize_fleet(records: List[dict]) -> List[str]:
    """The ``== fleet ==`` fold (ISSUE 19): per-replica request counts,
    retries, hedges won/lost, drain/scale events, and the router-side
    tail attribution (router-wait vs redispatch vs engine)."""
    s = fleet_stats(records)
    if s is None:
        return []

    def fmt(v, unit=""):
        return "--" if v is None else f"{v:.1f}{unit}"

    def cnt(field):
        v = s.get(field)
        return "--" if v is None else f"{v:.0f}"

    lines = [
        "== fleet ==",
        f"  {s['cycles']:.0f} router cycle(s); replicas "
        f"{cnt('replicas_up')} up / {cnt('replicas_quarantined')} "
        f"quarantined / {cnt('replicas_total')} total",
        f"  routed {cnt('requests_routed')}; completed "
        f"{cnt('requests_completed')}; failed {cnt('requests_failed')}; "
        f"duplicates suppressed {cnt('duplicates_suppressed')}",
        f"  retries {cnt('retries')} (retry_rate "
        f"{fmt(s['retry_rate_pct'], '%')});  hedges {cnt('hedges')} "
        f"(won {cnt('hedges_won')} / lost {cnt('hedges_lost')}, win_rate "
        f"{fmt(s['hedge_win_rate_pct'], '%')})",
        f"  replica_down {cnt('replica_down_events')};  drain "
        f"{cnt('drain_events')};  scale up/down "
        f"{cnt('scale_up_events')}/{cnt('scale_down_events')}",
    ]
    if s["requests_by_replica"]:
        lines.append("  requests by replica: " + ", ".join(
            f"replica{k}×{v:.0f}"
            for k, v in sorted(s["requests_by_replica"].items())))
    if s.get("router_ttft_p99_ms") is not None:
        lines.append(
            f"  router TTFT p50/p99  "
            f"{fmt(s['router_ttft_p50_ms'], 'ms')} / "
            f"{fmt(s['router_ttft_p99_ms'], 'ms')};  "
            f"{s['traced_requests']:.0f} fleet trace(s), "
            f"{cnt('retried_requests')} retried, "
            f"{cnt('hedged_requests')} hedged")
        lines.append(
            f"  tail attribution p99: router_wait "
            f"{fmt(s['router_wait_p99_ms'], 'ms')}, redispatch "
            f"{fmt(s['redispatch_p99_ms'], 'ms')}, hedge_wait "
            f"{fmt(s['hedge_wait_p99_ms'], 'ms')}, engine "
            f"{fmt(s['engine_ttft_p99_ms'], 'ms')}")
    for e in s["events"]:
        what = f"  [{e['kind']}] replica={e['replica']}"
        if e.get("reason"):
            what += f" ({e['reason']})"
        lines.append(what)
    return lines


_SYNC_KINDS = ("collective-incongruence", "sync-digest-drift",
               "collective-desync", "protocol-desync")


def synclint_stats(path: str) -> Dict:
    """Roll up a synclint/shardlint ``--json`` report list: digest-pinned
    schedules, protocol verdicts, and every surviving sync finding."""
    try:
        with open(path) as f:
            reports = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        return {"error": str(e)}
    digests = 0
    protocols_verified = 0
    by_kind: Dict[str, int] = {}
    findings: List[dict] = []
    for r in reports:
        if r.get("sync_digest"):
            digests += 1
        for f in r.get("findings", []):
            if f.get("kind") not in _SYNC_KINDS:
                continue
            if (f["kind"] == "protocol-desync"
                    and f.get("severity") == "info"):
                protocols_verified += 1
                continue
            by_kind[f["kind"]] = by_kind.get(f["kind"], 0) + 1
            findings.append(f)
    return {
        "schedules_pinned": digests,
        "protocols_verified": protocols_verified,
        "errors": sum(1 for f in findings if f.get("severity") == "error"),
        "warnings": sum(1 for f in findings if f.get("severity") == "warn"),
        "by_kind": by_kind,
        "findings": findings,
    }


def summarize_synclint(path: str) -> List[str]:
    """The ``== synclint ==`` fold: cross-rank congruence verdicts from a
    synclint/shardlint --json capture.  Errors here are the pre-launch
    twin of the postmortem section's hang diagnosis."""
    s = synclint_stats(path)
    lines = ["== synclint =="]
    if "error" in s:
        lines.append(f"  (unreadable: {s['error']})")
        return lines
    lines.append(f"  {s['schedules_pinned']} collective schedule(s) "
                 f"digest-verified; {s['protocols_verified']} protocol(s) "
                 "model-checked desync-free")
    if not s["findings"]:
        lines.append("  congruence clean: no desync findings")
    else:
        lines.append(f"  {s['errors']} error(s), {s['warnings']} warn(s): "
                     + ", ".join(f"{k}×{v}"
                                 for k, v in sorted(s["by_kind"].items())))
        for f in s["findings"]:
            lines.append(f"  [{f.get('severity')}] {f.get('kind')} @ "
                         f"{f.get('where')}: {f.get('message')}")
    return lines


def report(args) -> str:
    sections = []
    records: List[dict] = []
    if args.metrics_jsonl:
        records, malformed = load_metrics(args.metrics_jsonl)
        sections.append("== steps ==")
        sections += summarize_metrics(
            [r for r in records
             if "ft_event" not in r and "bench_event" not in r], malformed)
        sections += summarize_ft_events(records)
        from pytorch_distributed_tpu.obs.alerts import summarize_alerts
        from pytorch_distributed_tpu.obs.goodput import summarize_goodput

        sections += summarize_goodput(records)
        sections += summarize_alerts(records)
        sections += summarize_comms(records, getattr(args, "comm_ledger", None),
                                    getattr(args, "comm_predicted", None))
        sections += summarize_memory(records,
                                     getattr(args, "mem_ledger", None))
        sections += summarize_bench(records, bench_staleness_info(args))
        sections += summarize_serving(records)
        sections += summarize_traces(records)
        sections += summarize_fleet(records)
        sections += summarize_attribution(records)
    else:
        if getattr(args, "comm_ledger", None):
            sections += summarize_comms([], args.comm_ledger,
                                        getattr(args, "comm_predicted", None))
        if getattr(args, "mem_ledger", None):
            sections += summarize_memory([], args.mem_ledger)
    if getattr(args, "plan", None):
        sections += summarize_plan(load_plan(args.plan), records)
    if args.telemetry_csv:
        sections.append("== devices ==")
        sections += summarize_telemetry(args.telemetry_csv)
    if args.hb_dir:
        sections.append("== heartbeats ==")
        sections += summarize_heartbeats(args.hb_dir, args.now,
                                         args.max_step_lag, args.max_beat_age)
    if getattr(args, "synclint_json", None):
        sections += summarize_synclint(args.synclint_json)
    if getattr(args, "flight_dir", None):
        sections += postmortem_section(args.flight_dir,
                                       getattr(args, "hb_dir", None))
    if not sections:
        sections.append("nothing to report: pass --metrics-jsonl, "
                        "--hb-dir, and/or --telemetry-csv")
    return "\n".join(sections)


def report_json(args) -> Dict:
    """Machine-readable twin of ``report()``: every section as structured
    data (``--format json``)."""
    out: Dict = {}
    records: List[dict] = []
    if args.metrics_jsonl:
        records, malformed = load_metrics(args.metrics_jsonl)
        steps = [r for r in records
                 if "ft_event" not in r and "bench_event" not in r]
        stats = run_stats(records)
        stats["malformed_lines"] = malformed
        loss = [r["loss"] for r in steps if "loss" in r]
        if loss:
            stats["loss_first"], stats["loss_last"] = loss[0], loss[-1]
        out["steps"] = stats
        events: Dict[str, Dict] = {}
        for e in (r for r in records if "ft_event" in r):
            slot = events.setdefault(str(e["ft_event"]),
                                     {"count": 0, "steps": []})
            slot["count"] += 1
            if "step" in e:
                slot["steps"].append(e["step"])
        out["ft_events"] = events
        from pytorch_distributed_tpu.obs.goodput import compute_goodput

        gp = compute_goodput(records)
        out["goodput"] = {
            "wall_s": gp.wall_s, "productive_s": gp.productive_s,
            "badput_s": dict(gp.badput_s), "counts": dict(gp.counts),
            "steps": gp.steps, "goodput_pct": gp.goodput_pct,
            "untracked_s": gp.untracked_s, "alerts": gp.alerts,
        }
        from pytorch_distributed_tpu.obs.alerts import alerts_data

        out["alerts"] = alerts_data(records)
        out["bench"] = [r for r in records if "bench_event" in r]
        comms = comm_stats(records)
        comms["residual_pct"] = _comm_residual(
            getattr(args, "comm_predicted", None),
            comms["model_comm_bytes"])
        comms["predicted_bytes"] = getattr(args, "comm_predicted", None)
        out["comms"] = comms
        out["memory"] = mem_stats(records)
        srv = serving_stats(records)
        if srv is not None:
            out["serving"] = srv
        trc = trace_stats(records)
        if trc is not None:
            out["traces"] = trc
        flt = fleet_stats(records)
        if flt is not None:
            out["fleet"] = flt
        att = attr_stats(records)
        if att is not None:
            out["attribution"] = att
    staleness = bench_staleness_info(args)
    if staleness is not None:
        out["bench_staleness"] = staleness
    if getattr(args, "comm_ledger", None):
        from pytorch_distributed_tpu.obs.comms import load_ledgers

        out.setdefault("comms", {})["ledger"] = {
            step: {"total_bytes": lg.total_bytes,
                   "total_wire_bytes": lg.total_wire_bytes,
                   "count": lg.count, "by_kind": lg.by_kind(),
                   "by_phase": lg.by_phase()}
            for step, lg in load_ledgers(args.comm_ledger).items()}
    if getattr(args, "mem_ledger", None):
        out.setdefault("memory", {})["ledger"] = _load_mem_ledger_json(
            args.mem_ledger)
    if getattr(args, "plan", None):
        out["plan"] = plan_json_section(load_plan(args.plan), records)
    if args.telemetry_csv:
        n_rows, peak, limit = telemetry_stats(args.telemetry_csv)
        out["devices"] = {
            "samples": n_rows,
            "per_device": {str(i): {"peak_bytes": peak[i],
                                    "limit_bytes": limit.get(i, 0.0)}
                           for i in sorted(peak)},
        }
    if args.hb_dir:
        beats, flagged, now = heartbeat_stats(
            args.hb_dir, args.now, args.max_step_lag, args.max_beat_age)
        out["heartbeats"] = {
            str(pid): {"step": b.get("step"), "beat_age_s": now - b["t"],
                       "epoch": b.get("epoch"),
                       "straggler": flagged.get(pid)}
            for pid, b in sorted(beats.items())}
        member = read_membership(args.hb_dir)
        if member is not None:
            out["membership"] = member
    if getattr(args, "synclint_json", None):
        out["synclint"] = synclint_stats(args.synclint_json)
    if getattr(args, "flight_dir", None):
        try:
            out["postmortem"] = _postmortem_mod().postmortem(
                args.flight_dir, hb_dir=getattr(args, "hb_dir", None))
        except Exception as e:
            out["postmortem"] = {"error": str(e)}
    return out


# ------------------------------------------------------------------ run diff
def run_stats(records: List[dict]) -> Dict[str, Optional[float]]:
    """Scalar per-run summary for the diff fence."""
    from pytorch_distributed_tpu.obs.goodput import compute_goodput

    steps = [r for r in records
             if "step_time" in r and "ft_event" not in r
             and "bench_event" not in r]
    times = sorted(r["step_time"] for r in steps)
    thr = [r["throughput"] for r in steps if "throughput" in r]
    mfu = [r["mfu"] for r in steps if "mfu" in r]
    from pytorch_distributed_tpu.obs import stepattr as stepattr_mod

    gp = compute_goodput(records)
    cs = comm_stats(records)
    srv = serving_stats(records)
    trc = trace_stats(records)
    flt = fleet_stats(records)
    att_s = stepattr_mod.summarize(records)

    def attr(field):
        # prefer the step-record stamp (windowed, what the run saw live);
        # fall back to the reqtrace events so a trace-only JSONL still
        # fences — None when neither plane was on
        v = srv.get(field) if srv else None
        if v is None and trc is not None:
            v = trc.get(field)
        return v

    return {
        "steps": float(len(steps)),
        "step_time_p50": _pct(times, .5) if times else None,
        "step_time_p95": _pct(times, .95) if times else None,
        "throughput": sum(thr) / len(thr) if thr else None,
        "mfu": sum(mfu) / len(mfu) if mfu else None,
        "goodput": gp.goodput_pct if gp.steps else None,
        "badput_remesh_s": gp.badput_s["remesh"] if gp.steps else None,
        "model_comm_bytes": cs["model_comm_bytes"],
        "comm_wire_bytes": cs["comm_wire_bytes"],
        "exposed_comm_ms": cs["exposed_comm_ms"],
        "peak_hbm_bytes": cs["peak_hbm_bytes"],
        "alerts": float(gp.alerts) if gp.steps else None,
        # serving SLO fences (None for training runs -> rows skip)
        "ttft_p99_ms": srv["ttft_p99_ms"] if srv else None,
        "tokens_per_s": srv["tokens_per_s"] if srv else None,
        # per-request attribution fences (--req-trace runs only)
        "queue_wait_share_p99": attr("queue_wait_share_p99"),
        "preempt_redo_ms_p99": attr("preempt_redo_ms_p99"),
        # fleet router fences (serving/router.py) — None without a
        # router, so single-replica and training diffs are untouched
        "retry_rate": flt["retry_rate_pct"] if flt else None,
        "hedge_win_rate": flt["hedge_win_rate_pct"] if flt else None,
        # step-attribution fences (obs/stepattr.py) — None without
        # --step-attr, so unattributed diffs are untouched
        "data_wait_share_p95": (att_s["data_wait_share_p95"]
                                if att_s else None),
        "host_sync_ms_p95": (att_s["host_sync_ms_p95"]
                             if att_s else None),
    }


# (name, lower_is_better, absolute) — goodput diffs in absolute
# percentage points and badput_remesh_s in absolute seconds (both use
# goodput_threshold_pp: a remesh storm is seconds of lost wall clock,
# not a ratio — an elastic drill vs its uninterrupted baseline divides
# by zero otherwise); the rest diff in relative percent.
# exposed_comm_ms fences the overlap win (more un-overlapped collective
# time per step); wire bytes fence the traffic itself (a sharding change
# that moves more data); peak_hbm_bytes fences the compiled per-device
# footprint (the --zero wus / fused-CE memory wins, stamped from the
# ledger's memory_analysis).
_DIFF_METRICS = (
    ("step_time_p50", True, False),
    ("step_time_p95", True, False),
    ("throughput", False, False),
    ("mfu", False, False),
    ("goodput", False, True),
    ("badput_remesh_s", True, True),
    ("exposed_comm_ms", True, False),
    ("comm_wire_bytes", True, False),
    ("peak_hbm_bytes", True, False),
    # `alert` ft_event count (obs/alerts.py): absolute delta — any NEW
    # alert in the candidate regresses (threshold 0.5 below), and a
    # clean baseline (0 alerts) must not divide-by-zero.
    ("alerts", True, True),
    # serving SLO fences (serving/engine.py): time-to-first-token p99
    # and end-to-end token throughput.  Missing from training runs ->
    # both rows skip, so training diffs are untouched.
    ("ttft_p99_ms", True, False),
    ("tokens_per_s", False, False),
    # per-request attribution fences (obs/reqtrace.py): both absolute —
    # the share is percentage points, and a clean baseline books
    # preempt_redo_ms_p99 == 0 so a relative row would hide a planted
    # preemption storm behind the zero-baseline guard.
    ("queue_wait_share_p99", True, True),
    ("preempt_redo_ms_p99", True, True),
    # fleet router fences (serving/router.py): both absolute percentage
    # points — retry_rate climbing means replicas are flapping under the
    # candidate; hedge_win_rate falling means the hedge delay stopped
    # tracking the real p95 (hedges fire but never win).  A clean
    # baseline books 0% retries, so relative rows would divide by zero.
    ("retry_rate", True, True),
    ("hedge_win_rate", False, True),
    # step-attribution fences (obs/stepattr.py, --step-attr): both
    # absolute — the share is percentage points, and a clean baseline
    # books host_sync_ms_p95 near zero so a relative row would hide a
    # planted host-sync regression behind the zero-baseline guard.
    # These catch composition regressions that the aggregate step-time
    # row can mask: a loader that got slower while compute got faster.
    ("data_wait_share_p95", True, True),
    ("host_sync_ms_p95", True, True),
)


def diff_data(a_records: List[dict], b_records: List[dict],
              threshold_pct: float = 10.0,
              goodput_threshold_pp: float = 5.0,
              label_a: str = "A", label_b: str = "B") -> Dict:
    """Compare run B against baseline run A -> structured verdicts.

    A metric REGRESSes when B is worse than A by more than
    ``threshold_pct`` percent (relative), or ``goodput_threshold_pp``
    percentage points for the absolute-pp metrics.  Metrics missing from
    either run are skipped — a run without ``--mfu`` must not fail the
    fence on MFU."""
    sa, sb = run_stats(a_records), run_stats(b_records)
    rows: List[Dict] = []
    regressed = False
    for name, lower_better, absolute_pp in _DIFF_METRICS:
        va, vb = sa[name], sb[name]
        row: Dict = {"metric": name, "a": va, "b": vb}
        if va is None or vb is None:
            row["verdict"] = "missing"
        elif absolute_pp:
            delta = vb - va
            row["delta_pp"] = delta
            # alerts: any new firing is a regression, not a ±5pp band
            thr = 0.5 if name == "alerts" else goodput_threshold_pp
            worse = (delta > thr if lower_better else -delta > thr)
            row["verdict"] = "REGRESS" if worse else "PASS"
            regressed = regressed or worse
        elif va == 0:
            row["verdict"] = "zero-baseline"
        else:
            row["delta_pct"] = 100.0 * (vb - va) / va
            worse = (row["delta_pct"] > threshold_pct if lower_better
                     else row["delta_pct"] < -threshold_pct)
            row["verdict"] = "REGRESS" if worse else "PASS"
            regressed = regressed or worse
        rows.append(row)
    return {
        "baseline": label_a, "candidate": label_b,
        "steps_a": sa["steps"], "steps_b": sb["steps"],
        "metrics": rows,
        "overall": "REGRESS" if regressed else "PASS",
        "regressed": regressed,
    }


def diff_report(a_records: List[dict], b_records: List[dict],
                threshold_pct: float = 10.0,
                goodput_threshold_pp: float = 5.0,
                label_a: str = "A", label_b: str = "B") -> Tuple[str, bool]:
    """Text rendering of ``diff_data`` → (report text, regressed)."""
    d = diff_data(a_records, b_records, threshold_pct=threshold_pct,
                  goodput_threshold_pp=goodput_threshold_pp,
                  label_a=label_a, label_b=label_b)
    w = 20
    lines = [
        "== diff ==",
        f"  baseline {d['baseline']}: {d['steps_a']:.0f} steps;  "
        f"candidate {d['candidate']}: {d['steps_b']:.0f} steps",
        f"  {'metric':<{w}} {'A':>10} {'B':>10} {'delta':>9}  verdict",
    ]
    for row in d["metrics"]:
        name, va, vb = row["metric"], row["a"], row["b"]
        if row["verdict"] == "missing":
            lines.append(f"  {name:<{w}} {'--':>10} {'--':>10} {'--':>9}  "
                         "(missing)")
            continue
        if row["verdict"] == "zero-baseline":
            lines.append(f"  {name:<{w}} {va:>10.4g} {vb:>10.4g} "
                         f"{'--':>9}  (zero baseline)")
            continue
        if "delta_pp" in row:
            if name == "alerts":  # a count, not a percentage
                dtxt = f"{row['delta_pp']:+.0f}"
                fa, fb = f"{va:.0f}", f"{vb:.0f}"
            elif name.endswith(("_ms", "_ms_p99", "_ms_p95")):
                # absolute but milliseconds (preempt_redo_ms_p99,
                # host_sync_ms_p95)
                dtxt = f"{row['delta_pp']:+.1f}ms"
                fa, fb = f"{va:.1f}ms", f"{vb:.1f}ms"
            else:
                dtxt = f"{row['delta_pp']:+.1f}pp"
                fa, fb = f"{va:.1f}%", f"{vb:.1f}%"
        else:
            dtxt = f"{row['delta_pct']:+.1f}%"
            if name.startswith("step_time"):
                fa, fb = f"{va * 1e3:.1f}ms", f"{vb * 1e3:.1f}ms"
            else:
                fa, fb = f"{va:.4g}", f"{vb:.4g}"
        lines.append(f"  {name:<{w}} {fa:>10} {fb:>10} {dtxt:>9}  "
                     f"{row['verdict']}")
    lines.append(f"overall: {d['overall']}")
    return "\n".join(lines), d["regressed"]


def plan_diff_rows(plan: Optional[Dict], a_records: List[dict],
                   b_records: List[dict]) -> Tuple[List[str], Dict]:
    """The predicted-vs-measured residual rows a ``--plan`` adds to the
    diff: how far each run's measured MFU sits from the planner's
    prediction.  Like bench staleness, a note — prediction drift means
    the cost model needs recalibrating, not that run B regressed."""
    if plan is None:
        return [], {}
    ps = plan_stats(plan)
    if ps is None or ps.get("predicted_mfu_pct") is None:
        return [], {}
    sa, sb = run_stats(a_records), run_stats(b_records)
    pred = ps["predicted_mfu_pct"]
    drift = {"predicted_mfu_pct": pred, "plan_key": ps["key"],
             "mfu_drift_a_pct": _residual(pred, sa["mfu"]),
             "mfu_drift_b_pct": _residual(pred, sb["mfu"])}
    fa = (f"{drift['mfu_drift_a_pct']:+.1f}%"
          if drift["mfu_drift_a_pct"] is not None else "--")
    fb = (f"{drift['mfu_drift_b_pct']:+.1f}%"
          if drift["mfu_drift_b_pct"] is not None else "--")
    lines = [f"  {'plan_mfu_drift':<16} {fa:>10} {fb:>10} "
             f"{'--':>9}  (vs predicted {pred:.1f}%, plan {ps['key']}; "
             "note, not a fence)"]
    return lines, drift


def run_diff(path_a: str, path_b: str, threshold_pct: float,
             goodput_threshold_pp: float, fmt: str = "text",
             staleness: Optional[Dict] = None,
             plan: Optional[Dict] = None,
             strict: bool = False) -> int:
    a, mal_a = load_metrics(path_a)
    b, mal_b = load_metrics(path_b)
    kw = dict(threshold_pct=threshold_pct,
              goodput_threshold_pp=goodput_threshold_pp,
              label_a=os.path.basename(path_a),
              label_b=os.path.basename(path_b))
    plan_lines, plan_drift = plan_diff_rows(plan, a, b)
    stale_fail = bool(strict and staleness is not None
                      and staleness.get("warn"))
    if fmt == "json":
        d = diff_data(a, b, **kw)
        d["malformed_lines"] = {"a": mal_a, "b": mal_b}
        if staleness is not None:
            d["bench_staleness"] = staleness
        if stale_fail:
            d["stale_fence_failed"] = True
        if plan_drift:
            d["plan"] = plan_drift
        print(json.dumps(d, indent=2))
        return 1 if (d["regressed"] or stale_fail) else 0
    text, regressed = diff_report(a, b, **kw)
    if plan_lines:
        # splice the drift row above the overall verdict line
        body = text.splitlines()
        text = "\n".join(body[:-1] + plan_lines + body[-1:])
    if mal_a or mal_b:
        text += f"\n(malformed lines: A {mal_a}, B {mal_b})"
    if staleness is not None and staleness.get("warn"):
        # By default a note, never a verdict: a stale benchmark capture
        # makes the comparison context-poor but does not make run B a
        # regression.  --strict promotes it to a failing fence (the CI
        # posture: refuse to certify a diff against unrefreshed numbers).
        kind = "STRICT" if strict else "note"
        text += (f"\n{kind}: benchmark baseline stale "
                 f"{staleness['days_stale']:.1f} days "
                 f"(> {staleness['max_stale_days']:g}) — re-run bench.py")
    print(text)
    return 1 if (regressed or stale_fail) else 0


def _selftest() -> int:
    """Synthesize the artifacts, run the report + diff fences, assert."""
    import tempfile

    from pytorch_distributed_tpu.obs import HeartbeatWriter, MetricsLogger

    with tempfile.TemporaryDirectory() as d:
        now = time.time()
        # per-step metrics via the real logger
        mpath = os.path.join(d, "metrics.jsonl")
        with MetricsLogger(mpath, flush_every=7) as log:
            for i in range(20):
                log.log_step(i, step_time=0.01 + 0.001 * (i % 5),
                             n_items=128, lr=0.1,
                             scalars={"loss": 2.0 - 0.05 * i,
                                      "grad_norm": 1.0 + 0.1 * i},
                             extra={"mfu": 40.0 + 0.1 * i,
                                    "hfu": 45.0 + 0.1 * i,
                                    "model_comm_bytes": 66952.0,
                                    "comm_wire_bytes": 100428.0,
                                    "collective_count": 16.0,
                                    "exposed_comm_ms": 0.40,
                                    "overlap_pct": 33.3,
                                    "mem_peak_bytes": 820.0,
                                    "mem_temp_peak_bytes": 120.0,
                                    "mem_residual_pct": 2.5})
            # ft_event records interleave in the same JSONL (ft/)
            log.log_event("skip", step=7, consecutive=1)
            log.log_event("skip", step=8, consecutive=2)
            log.log_event("rollback", step=9, restored_step=5, lr_scale=0.5)
            log.log_event("remesh", step=12, change="shrink", old_world=4,
                          new_world=3, epoch=1, reason="drill")
            log.log_event("preempt", step=19)
            # live alert plane (obs/alerts.py): firings booked as
            # `alert` ft_events fold into their own report section
            log.log_event("alert", step=15, alert="step_time_p95",
                          rule="step_time_p95", severity="warn",
                          value=22.0, threshold=15.0, rank=0,
                          detail="step time p95 22.0ms > 15ms")
            log.log_event("alert", step=18, alert="dead_rank",
                          rule="dead_rank", severity="page", rank=1,
                          detail="rank 1: beat age 120.0s > 60s "
                                 "(dead or hung)")
        with open(mpath, "a") as f:
            # torn tail (a killed writer) + a bench staleness event
            f.write(json.dumps({
                "bench_event": "stale", "t": now,
                "metric": "resnet50_train_images_per_sec_per_chip",
                "last_good": "2026-07-31T06:32:08+0000",
                "reason": "device discovery hung (tunnel unreachable)",
            }) + "\n")
            f.write('{"step": 20, "step_time": 0.0')
        # heartbeats: pid 0 current (elastic, epoch-stamped), pid 1
        # lagging AND stale; membership.json as the coordinator leaves it
        hb_dir = os.path.join(d, "hb")
        w0 = HeartbeatWriter(hb_dir, 0, interval_s=0.0, world=3, epoch=1)
        w0.beat(19, step_time_ema=0.011, last_ft="preempt")
        with open(os.path.join(hb_dir, "heartbeat-00001.jsonl"), "w") as f:
            f.write(json.dumps({"pid": 1, "step": 3, "t": now - 120}) + "\n")
        with open(os.path.join(hb_dir, "membership.json"), "w") as f:
            f.write(json.dumps({"epoch": 1, "ranks": [0, 1, 2]}))
        # telemetry CSV (statistics.sh contract)
        tpath = os.path.join(d, "telemetry.csv")
        with open(tpath, "w", newline="") as f:
            wr = csv.writer(f)
            for t in range(4):
                for dev in range(2):
                    wr.writerow([now + t, dev, 8 << 30,
                                 (1 + t) << 20, (2 + t) << 20])

        # a one-entry comm ledger on disk for the comms section
        from pytorch_distributed_tpu.obs import comms as comms_mod

        lpath = os.path.join(d, "comm_ledger.json")
        comms_mod.write_ledgers(lpath, [comms_mod.CommLedger(
            step="lm_train_dp", mesh_shape={"data": 4},
            entries=[comms_mod.CommEntry(
                name="all-reduce.1", kind="all-reduce", bytes=66952,
                wire_bytes=comms_mod.wire_bytes("all-reduce", 66952, 4),
                n_groups=1, group_size=4, phase="backward",
                op_name="jit(step)/transpose(jvp(lm_forward))/add",
                source="lm.py:1")])])

        # a one-entry memory ledger on disk for the memory section
        from pytorch_distributed_tpu.obs import memory as memory_mod

        mlpath = os.path.join(d, "mem_ledger.json")
        memory_mod.write_ledgers(mlpath, [memory_mod.MemLedger(
            step="lm_train_dp", mesh_shape={"data": 4},
            argument_bytes=400, output_bytes=300, donated_bytes=128,
            peak_bytes=820, peak_index=3, n_instructions=9,
            measured_peak_bytes=800.0,
            watermark=[[0, 700], [2, 820], [6, 724]],
            buffers=[
                memory_mod.MemBuffer(
                    name="(params)", bytes=400, dtype="", dims=[],
                    klass="params", phase="", op_name="", source="",
                    defined_at=-1, last_use=8),
                memory_mod.MemBuffer(
                    name="fusion.7", bytes=96, dtype="f32", dims=[4, 6],
                    klass="activations", phase="backward",
                    op_name="transpose(jvp(lm_forward))/dot",
                    source="lm.py:1", defined_at=2, last_use=5)])])

        # a 20-days-stale LKG + events trail for the bench aging line
        bench_lkg = os.path.join(d, "BENCH_LKG.json")
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S%z",
                              time.localtime(now - 20 * 86400))
        with open(bench_lkg, "w") as f:
            json.dump({"metric": "resnet50_train_images_per_sec_per_chip",
                       "value": 2511.3, "vs_baseline": 9.3,
                       "captured_at": stamp,
                       # bench.py stamps the planner prediction on capture
                       "predicted_mfu": 42.0, "measured_mfu": 39.5,
                       "prediction_drift_pct": -6.0}, f)
        bench_events = os.path.join(d, "bench_events.jsonl")
        with open(bench_events, "w") as f:
            f.write(json.dumps({"bench_event": "stale", "t": now - 3600,
                                "reason": "tunnel unreachable"}) + "\n")

        # a real autoplan payload (plan/ is jax-free on this path) for
        # the plan section + the --diff drift row
        from pytorch_distributed_tpu.plan import autoplan

        ppath = os.path.join(d, "plan.json")
        with open(ppath, "w") as f:
            json.dump(autoplan("lm-tiny", 4, top_k=3), f)

        ns = argparse.Namespace(
            metrics_jsonl=mpath, hb_dir=hb_dir, telemetry_csv=tpath,
            now=now, max_step_lag=3, max_beat_age=60.0,
            comm_ledger=lpath, comm_predicted=66000.0,
            mem_ledger=mlpath, bench_lkg=bench_lkg,
            bench_events=bench_events, bench_max_stale_days=14.0,
            plan=ppath)
        out = report(ns)
        for needle in ("== steps ==", "steps logged      20", "p95",
                       "throughput", "loss", "grad_norm",
                       "mfu               mean", "malformed lines   1",
                       "== ft events ==", "skip", "rollback", "preempt",
                       "lr scale          0.5 after 1 rollback",
                       "== goodput ==", "goodput", "badput/nan_skip",
                       "badput/rollback_discard", "badput/remesh",
                       "alerts fired      2",
                       "== alerts ==", "step_time_p95", "[warn]",
                       "dead_rank", "[page]", "ranks 1",
                       "step time p95 22.0ms > 15ms",
                       "membership epoch 1: world 3 ranks [0, 1, 2]",
                       "epoch 1",
                       "== comms ==", "per-step payload  66952 B",
                       "16 collectives", "exposed comm      0.400 ms",
                       "overlap 33.3%", "residual", "[ok]",
                       "ledger lm_train_dp", "all-reduce×1",
                       "by phase: backward",
                       "== memory ==", "per-step peak",
                       "residual 2.5% [ok]", "by class (MiB):",
                       "by phase (MiB):", "top: fusion.7",
                       "== plan ==", "chosen            c4/dp4",
                       "cli               python -m "
                       "pytorch_distributed_tpu.recipes.lm_pretrain",
                       "predicted", "drift",
                       "== bench ==", "stale", "last good",
                       "days ago", "1 stale event(s)",
                       "plan mfu          predicted 42.0%",
                       "drift -6.0%",
                       "WARN", "benchmark stale",
                       "== devices ==", "device 0", "device 1",
                       "== heartbeats ==", "STRAGGLER", "step lag",
                       "beat age"):
            assert needle in out, f"selftest: {needle!r} missing from:\n{out}"

        # json twin: every section present and structurally sane
        js = report_json(ns)
        for key in ("steps", "ft_events", "goodput", "bench", "comms",
                    "memory", "bench_staleness", "devices", "heartbeats",
                    "plan", "alerts"):
            assert key in js, f"selftest: {key!r} missing from json: {js}"
        assert js["alerts"]["total"] == 2, js["alerts"]
        assert js["alerts"]["by_name"]["dead_rank"]["severity"] == "page"
        assert js["alerts"]["by_name"]["step_time_p95"]["steps"] == [15]
        assert js["goodput"]["alerts"] == 2, js["goodput"]
        assert js["steps"]["alerts"] == 2.0, js["steps"]
        assert js["plan"]["key"] == "c4/dp4", js["plan"]
        assert js["plan"]["predicted_mfu_pct"] > 0, js["plan"]
        assert js["plan"]["mfu_drift_pct"] is not None, js["plan"]
        assert js["steps"]["model_comm_bytes"] == 66952.0, js["steps"]
        assert abs(js["comms"]["residual_pct"]) < 15.0, js["comms"]
        assert js["comms"]["ledger"]["lm_train_dp"]["total_bytes"] == 66952
        assert js["memory"]["mem_peak_bytes"] == 820.0, js["memory"]
        mled = js["memory"]["ledger"]["lm_train_dp"]
        assert mled["peak_bytes"] == 820 and mled["residual_pct"] == 2.5
        assert mled["class_peaks"]["params"] == 400, mled
        assert js["bench_staleness"]["warn"], js["bench_staleness"]
        assert 19.5 < js["bench_staleness"]["days_stale"] < 20.5, (
            js["bench_staleness"])
        assert js["bench_staleness"]["prediction_drift_pct"] == -6.0, (
            js["bench_staleness"])
        assert js["heartbeats"]["1"]["straggler"], js["heartbeats"]
        assert not js["heartbeats"]["0"]["straggler"], js["heartbeats"]
        assert js["heartbeats"]["0"]["epoch"] == 1, js["heartbeats"]
        assert js["membership"] == {"epoch": 1, "ranks": [0, 1, 2]}, js
        assert js["goodput"]["counts"]["remesh"] == 1, js["goodput"]
        json.dumps(js)  # must be serializable end-to-end
        # pid 0 must NOT be flagged
        line0 = [ln for ln in out.splitlines() if "process 0" in ln]
        assert line0 and "STRAGGLER" not in line0[0], out

        # ---- diff fences: identical runs PASS, a slowed run REGRESSes ----
        fast = os.path.join(d, "fast.jsonl")
        slow = os.path.join(d, "slow.jsonl")
        for path, st in ((fast, 0.010), (slow, 0.015)):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(30):
                    log.log_step(i, step_time=st, n_items=128, lr=0.1,
                                 extra={"mfu": 40.0 * 0.010 / st,
                                        "hfu": 44.0 * 0.010 / st})
        a_recs, _ = load_metrics(fast)
        b_recs, _ = load_metrics(slow)
        text, regressed = diff_report(a_recs, b_recs)
        assert regressed, f"selftest: slowed run must REGRESS:\n{text}"
        for needle in ("== diff ==", "step_time_p50", "REGRESS",
                       "overall: REGRESS", "throughput", "mfu",
                       "badput_remesh_s"):
            assert needle in text, f"selftest: {needle!r} missing from:\n{text}"
        text2, regressed2 = diff_report(a_recs, a_recs)
        assert not regressed2 and "overall: PASS" in text2, (
            f"selftest: identical runs must PASS:\n{text2}")

        # ---- planted exposed-comm regression: identical step time, but
        # collectives stopped hiding under compute -> the comm fence (and
        # only the comm fence) must REGRESS
        base_c = os.path.join(d, "base_comm.jsonl")
        bad_c = os.path.join(d, "bad_comm.jsonl")
        for path, exposed in ((base_c, 0.20), (bad_c, 0.55)):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(30):
                    log.log_step(i, step_time=0.010, n_items=128, lr=0.1,
                                 extra={"model_comm_bytes": 66952.0,
                                        "comm_wire_bytes": 100428.0,
                                        "exposed_comm_ms": exposed,
                                        "overlap_pct": 60.0})
        c_recs, _ = load_metrics(base_c)
        d_recs, _ = load_metrics(bad_c)
        text3, regressed3 = diff_report(c_recs, d_recs)
        assert regressed3, (
            f"selftest: exposed-comm regression must REGRESS:\n{text3}")
        row = [ln for ln in text3.splitlines() if "exposed_comm_ms" in ln]
        assert row and "REGRESS" in row[0], text3
        step_row = [ln for ln in text3.splitlines() if "step_time_p50" in ln]
        assert step_row and "PASS" in step_row[0], text3
        dd = diff_data(c_recs, d_recs)
        assert dd["overall"] == "REGRESS" and dd["regressed"], dd
        by_name = {r["metric"]: r for r in dd["metrics"]}
        assert by_name["exposed_comm_ms"]["verdict"] == "REGRESS", dd
        assert by_name["comm_wire_bytes"]["verdict"] == "PASS", dd
        json.dumps(dd)

        # ---- planted peak-HBM regression: same timings, compiled peak
        # grew (e.g. a --zero wus run accidentally fell back to replicated
        # optimizer state) -> only the peak_hbm_bytes fence must REGRESS
        base_m = os.path.join(d, "base_mem.jsonl")
        bad_m = os.path.join(d, "bad_mem.jsonl")
        for path, peak in ((base_m, 2.0e8), (bad_m, 3.1e8)):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(30):
                    log.log_step(i, step_time=0.010, n_items=128, lr=0.1,
                                 extra={"model_comm_bytes": 66952.0,
                                        "comm_wire_bytes": 100428.0,
                                        "peak_hbm_bytes": peak})
        m_recs, _ = load_metrics(base_m)
        n_recs, _ = load_metrics(bad_m)
        text4, regressed4 = diff_report(m_recs, n_recs)
        assert regressed4, (
            f"selftest: peak-HBM regression must REGRESS:\n{text4}")
        dm = diff_data(m_recs, n_recs)
        by_name4 = {r["metric"]: r for r in dm["metrics"]}
        assert by_name4["peak_hbm_bytes"]["verdict"] == "REGRESS", dm
        assert by_name4["comm_wire_bytes"]["verdict"] == "PASS", dm
        # reverse direction (the memory WIN) must pass the peak fence
        # (row-scoped: the wall-clock goodput metric is timing-noisy here)
        dr = diff_data(n_recs, m_recs)
        by_rev = {r["metric"]: r for r in dr["metrics"]}
        assert by_rev["peak_hbm_bytes"]["verdict"] == "PASS", dr

        # ---- planted alert regression: identical timings, but the
        # candidate run fired an alert -> only the alerts row REGRESSes
        # (any new firing fails the fence; counts render as counts)
        alerted = os.path.join(d, "alerted.jsonl")
        with MetricsLogger(alerted, flush_every=50) as log:
            for i in range(30):
                log.log_step(i, step_time=0.010, n_items=128, lr=0.1,
                             extra={"mfu": 40.0, "hfu": 44.0})
            log.log_event("alert", step=25, alert="goodput_floor",
                          rule="goodput_floor", severity="warn",
                          detail="goodput estimate 41% < 50%")
        al_recs, _ = load_metrics(alerted)
        text5, regressed5 = diff_report(a_recs, al_recs)
        assert regressed5, (
            f"selftest: a new alert must REGRESS the diff:\n{text5}")
        al_row = [ln for ln in text5.splitlines()
                  if ln.strip().startswith("alerts")]
        assert al_row and "REGRESS" in al_row[0], text5
        assert "+1" in al_row[0] and "pp" not in al_row[0], al_row
        da = diff_data(a_recs, al_recs)
        assert {r["metric"]: r for r in da["metrics"]}[
            "alerts"]["verdict"] == "REGRESS", da
        # reverse (alerts cleared in the candidate) passes the row
        dr_a = diff_data(al_recs, a_recs)
        assert {r["metric"]: r for r in dr_a["metrics"]}[
            "alerts"]["verdict"] == "PASS", dr_a

        # ---- bench staleness in --diff: a note, never a failure ----
        import contextlib
        import io

        buf = io.StringIO()
        with contextlib.redirect_stdout(buf):
            rc = run_diff(fast, fast, 10.0, 5.0, staleness={
                "warn": True, "days_stale": 20.0, "max_stale_days": 14.0})
        noted = buf.getvalue()
        assert rc == 0, f"selftest: stale bench must not fail --diff:\n{noted}"
        assert "note: benchmark baseline stale 20.0 days" in noted, noted
        assert "overall: PASS" in noted, noted

        # ---- plan drift row in --diff: also a note, never a failure ----
        buf2 = io.StringIO()
        with contextlib.redirect_stdout(buf2):
            rc2 = run_diff(fast, fast, 10.0, 5.0, plan=load_plan(ppath))
        drifted = buf2.getvalue()
        assert rc2 == 0, (
            f"selftest: plan drift must not fail --diff:\n{drifted}")
        assert "plan_mfu_drift" in drifted, drifted
        assert "not a fence" in drifted, drifted
        assert "overall: PASS" in drifted, drifted

        # ---- --strict: the same stale capture IS a failure (ISSUE 13
        # S4: the CI posture refuses to certify against old numbers) ----
        buf3 = io.StringIO()
        with contextlib.redirect_stdout(buf3):
            rc3 = run_diff(fast, fast, 10.0, 5.0, staleness={
                "warn": True, "days_stale": 20.0, "max_stale_days": 14.0},
                strict=True)
        strict_out = buf3.getvalue()
        assert rc3 == 1, "selftest: --strict must fail a stale diff"
        assert "STRICT: benchmark baseline stale" in strict_out, strict_out
        # report path: same 20-day LKG, strict fails, default stays 0
        buf3b = io.StringIO()
        with contextlib.redirect_stdout(buf3b):
            rc4 = main(["--metrics-jsonl", mpath, "--bench-lkg", bench_lkg,
                        "--bench-events", bench_events, "--strict"])
            rc5 = main(["--metrics-jsonl", mpath, "--bench-lkg", bench_lkg,
                        "--bench-events", bench_events])
        assert rc4 == 1, "selftest: strict report must fail on stale LKG"
        assert rc5 == 0, "selftest: non-strict report must stay exit 0"

        # ---- synclint fold: section, json twin, strict fence ----
        sync_ok = os.path.join(d, "synclint_ok.json")
        sync_bad = os.path.join(d, "synclint_bad.json")
        clean_step = {
            "name": "lm_train_dp", "mesh_shape": {"data": 4},
            "findings": [], "collectives": {}, "memory": {},
            "donation": {}, "sync_digest": "a" * 64}
        proto_step = {
            "name": "sync-protocols", "mesh_shape": {}, "collectives": {},
            "memory": {}, "donation": {}, "sync_digest": "",
            "findings": [{"kind": "protocol-desync", "severity": "info",
                          "where": "proto:preempt-stop",
                          "message": "verified desync-free"}]}
        with open(sync_ok, "w") as f:
            json.dump([clean_step, proto_step], f)
        desync_step = {
            "name": "sync-scopes", "mesh_shape": {}, "collectives": {},
            "memory": {}, "donation": {}, "sync_digest": "",
            "findings": [{"kind": "collective-desync", "severity": "error",
                          "where": "train/lm.py:1500",
                          "message": "collective call step_fn() is "
                                     "reachable under a rank-dependent "
                                     "branch"}]}
        with open(sync_bad, "w") as f:
            json.dump([clean_step, proto_step, desync_step], f)
        ns_sync = argparse.Namespace(
            metrics_jsonl=None, hb_dir=None, telemetry_csv=None, now=now,
            max_step_lag=3, max_beat_age=60.0, bench_lkg=None,
            bench_events=None, bench_max_stale_days=14.0, plan=None,
            flight_dir=None, synclint_json=sync_ok)
        sync_out = report(ns_sync)
        for needle in ("== synclint ==",
                       "1 collective schedule(s) digest-verified",
                       "1 protocol(s) model-checked desync-free",
                       "congruence clean: no desync findings"):
            assert needle in sync_out, (
                f"selftest: {needle!r} missing from:\n{sync_out}")
        js_sync = report_json(ns_sync)
        assert js_sync["synclint"]["errors"] == 0, js_sync["synclint"]
        assert js_sync["synclint"]["schedules_pinned"] == 1, (
            js_sync["synclint"])
        ns_sync.synclint_json = sync_bad
        bad_out = report(ns_sync)
        assert "[error] collective-desync @ train/lm.py:1500" in bad_out, (
            bad_out)
        js_bad = report_json(ns_sync)
        assert js_bad["synclint"]["errors"] == 1, js_bad["synclint"]
        assert js_bad["synclint"]["by_kind"] == {
            "collective-desync": 1}, js_bad["synclint"]
        buf_sync = io.StringIO()
        with contextlib.redirect_stdout(buf_sync):
            rc_s_ok = main(["--synclint-json", sync_ok, "--strict"])
            rc_s_note = main(["--synclint-json", sync_bad])
            rc_s_bad = main(["--synclint-json", sync_bad, "--strict"])
        assert rc_s_ok == 0, "selftest: strict clean synclint must pass"
        assert rc_s_note == 0, (
            "selftest: non-strict synclint errors stay exit 0 (a note)")
        assert rc_s_bad == 1, (
            "selftest: --strict must fail on synclint error findings")

        # ---- serving plane: section, json twin, planted TTFT fence ----
        # a training-shaped run must not grow a serving section
        assert "== serving ==" not in out, out
        spath = os.path.join(d, "serving.jsonl")
        with MetricsLogger(spath, flush_every=50) as log:
            for i in range(10):
                log.log_step(i, step_time=0.005, n_items=32,
                             extra={"serving": 1.0,
                                    "queue_depth": float(max(0, 5 - i)),
                                    "active_seqs": 4.0,
                                    "kv_occupancy_pct": 55.0 + i,
                                    "kv_frag_pct": 12.5,
                                    "preemptions": 1.0,
                                    "requests_completed": float(i),
                                    "tokens_per_s": 512.0,
                                    "ttft_p50_ms": 40.0,
                                    "ttft_p95_ms": 75.0,
                                    "ttft_p99_ms": 80.0,
                                    "itl_p50_ms": 4.0, "itl_p95_ms": 9.0,
                                    "itl_p99_ms": 12.0})
            log.log_event("serve_preempt", step=4, rid=3)
            log.log_event("serve_defrag", step=7, defrags=1)
        ns_s = argparse.Namespace(
            metrics_jsonl=spath, hb_dir=None, telemetry_csv=None, now=now,
            max_step_lag=3, max_beat_age=60.0, bench_lkg=None,
            bench_events=None, bench_max_stale_days=14.0, plan=None,
            flight_dir=None)
        srv_out = report(ns_s)
        for needle in ("== serving ==", "512.0 tok/s",
                       "TTFT p50/p95/p99  40.0ms / 75.0ms / 80.0ms",
                       "ITL p50/p95/p99   4.0ms / 9.0ms / 12.0ms",
                       "queue depth peak  5.0",
                       "KV occupancy peak 64.0%",
                       "preemptions       1.0;  defrags 1"):
            assert needle in srv_out, (
                f"selftest: {needle!r} missing from:\n{srv_out}")
        js_s = report_json(ns_s)
        assert js_s["serving"]["ttft_p99_ms"] == 80.0, js_s["serving"]
        assert js_s["serving"]["kv_occupancy_peak_pct"] == 64.0, (
            js_s["serving"])
        assert js_s["steps"]["ttft_p99_ms"] == 80.0, js_s["steps"]
        assert js_s["steps"]["tokens_per_s"] == 512.0, js_s["steps"]
        json.dumps(js_s)

        # planted TTFT regression: same step times and throughput, but
        # first tokens land 2.5x later -> the ttft_p99_ms fence (and only
        # it) must REGRESS, and the --diff CLI must exit 1
        base_s = os.path.join(d, "serve_base.jsonl")
        bad_s = os.path.join(d, "serve_slow_ttft.jsonl")
        for path, ttft in ((base_s, 80.0), (bad_s, 200.0)):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(10):
                    log.log_step(i, step_time=0.005, n_items=32,
                                 extra={"serving": 1.0,
                                        "tokens_per_s": 512.0,
                                        "ttft_p99_ms": ttft})
        sa_recs, _ = load_metrics(base_s)
        sb_recs, _ = load_metrics(bad_s)
        text6, regressed6 = diff_report(sa_recs, sb_recs)
        assert regressed6, (
            f"selftest: planted TTFT regression must REGRESS:\n{text6}")
        ds = diff_data(sa_recs, sb_recs)
        by_srv = {r["metric"]: r for r in ds["metrics"]}
        assert by_srv["ttft_p99_ms"]["verdict"] == "REGRESS", ds
        assert by_srv["step_time_p50"]["verdict"] == "PASS", ds
        assert by_srv["tokens_per_s"]["verdict"] == "PASS", ds
        # reverse direction (TTFT improved) passes the row
        dr_s = diff_data(sb_recs, sa_recs)
        assert {r["metric"]: r for r in dr_s["metrics"]}[
            "ttft_p99_ms"]["verdict"] == "PASS", dr_s
        buf_s = io.StringIO()
        with contextlib.redirect_stdout(buf_s):
            rc_s = run_diff(base_s, bad_s, 10.0, 5.0)
        assert rc_s == 1, "selftest: planted TTFT regression must exit 1"
        assert "ttft_p99_ms" in buf_s.getvalue(), buf_s.getvalue()
        # training-only diffs skip the serving rows (missing, not a fail)
        assert {r["metric"]: r for r in diff_data(a_recs, b_recs)[
            "metrics"]}["ttft_p99_ms"]["verdict"] == "missing"
        # ...and untraced serving runs skip the attribution rows
        assert by_srv["queue_wait_share_p99"]["verdict"] == "missing", ds
        assert by_srv["preempt_redo_ms_p99"]["verdict"] == "missing", ds

        # ---- traces plane (ISSUE 17): section, json twin, tail rollup ----
        tpath = os.path.join(d, "traces.jsonl")
        with MetricsLogger(tpath, flush_every=50) as log:
            for i in range(8):
                storm = i >= 6  # two tail requests dominated by redo
                ttft = 300.0 if storm else 50.0
                redo = 240.0 if storm else 0.0
                queue = 40.0 if storm else 35.0
                log.log_event(
                    "reqtrace", step=i, rid=i,
                    trace_id=f"ptd-engine:0-{i:08x}",
                    ttft_ms=ttft, e2e_ms=ttft + 20.0, tokens=8,
                    preemptions=3 if storm else 0,
                    queue_wait_ms=queue, prefill_ms=10.0,
                    redo_wait_ms=redo, defrag_wait_ms=0.0,
                    other_wait_ms=ttft - queue - 10.0 - redo,
                    decode_ms=18.0, redo_own_ms=0.0, defrag_run_ms=0.0,
                    other_run_ms=2.0, preempt_redo_ms=redo,
                    queue_wait_share_pct=100.0 * queue / ttft,
                    violated=1 if storm else 0, n_spans=12,
                    spans_dropped=0, sampled=1)
        ns_t = argparse.Namespace(
            metrics_jsonl=tpath, hb_dir=None, telemetry_csv=None, now=now,
            max_step_lag=3, max_beat_age=60.0, bench_lkg=None,
            bench_events=None, bench_max_stale_days=14.0, plan=None,
            flight_dir=None)
        trc_out = report(ns_t)
        for needle in ("== traces ==", "8 request trace(s)",
                       "2 SLO violation(s)", "6 preemption(s)",
                       "tail attribution:",
                       "dominant tail component: preempt_redo"):
            assert needle in trc_out, (
                f"selftest: {needle!r} missing from:\n{trc_out}")
        js_t = report_json(ns_t)
        assert js_t["traces"]["requests"] == 8, js_t["traces"]
        assert js_t["traces"]["tail"]["dominant"] == "preempt_redo", (
            js_t["traces"])
        json.dumps(js_t)
        # an untraced run must not grow the section
        assert "== traces ==" not in srv_out, srv_out

        # planted preemption storm: identical step times / throughput /
        # TTFT fence inputs -- the NEW attribution rows (and only they)
        # must flip the diff to REGRESS and the CLI to exit 1
        base_t = os.path.join(d, "attr_base.jsonl")
        bad_t = os.path.join(d, "attr_storm.jsonl")
        for path, (share, redo_ms) in ((base_t, (12.0, 0.0)),
                                       (bad_t, (55.0, 210.0))):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(10):
                    log.log_step(i, step_time=0.005, n_items=32,
                                 extra={"serving": 1.0,
                                        "tokens_per_s": 512.0,
                                        "ttft_p99_ms": 80.0,
                                        "queue_wait_share_p99": share,
                                        "preempt_redo_ms_p99": redo_ms})
        ta_recs, _ = load_metrics(base_t)
        tb_recs, _ = load_metrics(bad_t)
        dt = diff_data(ta_recs, tb_recs)
        by_t = {r["metric"]: r for r in dt["metrics"]}
        assert by_t["queue_wait_share_p99"]["verdict"] == "REGRESS", dt
        assert by_t["preempt_redo_ms_p99"]["verdict"] == "REGRESS", dt
        assert by_t["ttft_p99_ms"]["verdict"] == "PASS", dt
        # the improvement direction passes both rows
        by_rt = {r["metric"]: r
                 for r in diff_data(tb_recs, ta_recs)["metrics"]}
        assert by_rt["queue_wait_share_p99"]["verdict"] == "PASS", by_rt
        assert by_rt["preempt_redo_ms_p99"]["verdict"] == "PASS", by_rt
        buf_t = io.StringIO()
        with contextlib.redirect_stdout(buf_t):
            rc_t = run_diff(base_t, bad_t, 10.0, 5.0)
        assert rc_t == 1, (
            "selftest: planted preemption storm must exit 1")
        assert "preempt_redo_ms_p99" in buf_t.getvalue(), buf_t.getvalue()

        # ---- attribution plane (ISSUE 20): section, json twin, diff ----
        from pytorch_distributed_tpu.obs import stepattr as sa_mod

        def write_attr_run(path, comp, sync_ms, data_ms, other):
            # identical 100ms step times: only the composition differs,
            # so the NEW attribution rows (and only they) may flip
            with MetricsLogger(path, flush_every=50) as log:
                prof = sa_mod.phase_profile(
                    {"forward": 1e9, "backward": 2e9, "update": 1e7},
                    {"forward": 1e7, "backward": 2e7, "update": 1e8},
                    comm_bytes=1e6, peak_flops=1e12, hbm_bw=1e11,
                    link_bw=1e10, n_devices=1)
                log.log_event("stepattr_phases",
                              **sa_mod.phase_event_fields(prof))
                for i in range(10):
                    log.log_step(i, step_time=0.100, n_items=32, extra={
                        "attr_compute_ms": comp,
                        "attr_exposed_comm_ms": 8.0,
                        "attr_host_sync_ms": sync_ms,
                        "attr_data_wait_ms": data_ms,
                        "attr_other_ms": other,
                        "attr_device_ms": comp + 8.0,
                        "attr_comm_ms": 20.0,
                        "attr_recon_err_ms": 0.02,
                        "data_wait_share": data_ms})
        attr_base = os.path.join(d, "sa_base.jsonl")
        attr_bad = os.path.join(d, "sa_starved.jsonl")
        write_attr_run(attr_base, comp=62.0, sync_ms=3.0, data_ms=8.0,
                       other=19.0)
        write_attr_run(attr_bad, comp=42.0, sync_ms=12.0, data_ms=30.0,
                       other=8.0)
        ns_at = argparse.Namespace(
            metrics_jsonl=attr_base, hb_dir=None, telemetry_csv=None,
            now=now, max_step_lag=3, max_beat_age=60.0, bench_lkg=None,
            bench_events=None, bench_max_stale_days=14.0, plan=None,
            flight_dir=None)
        at_out = report(ns_at)
        for needle in ("== attribution ==", "dominant: compute",
                       "identity recon", "% of step p50",
                       "data_wait_share   p50 8.0%  p95 8.0%",
                       "host_sync         p50 3.00ms  p95 3.00ms",
                       "comm overlap      measured 0.60",
                       "fix first: backward"):
            assert needle in at_out, (
                f"selftest: {needle!r} missing from:\n{at_out}")
        js_at = report_json(ns_at)
        assert js_at["attribution"]["dominant"] == "compute", js_at
        assert js_at["attribution"]["recon_err_pct_p50"] <= 0.5, js_at
        roofl = js_at["attribution"]["roofline"]
        at_labels = {p["phase"]: p["label"] for p in roofl["phases"]}
        assert at_labels["update"] == "hbm-bound", at_labels
        assert at_labels["grad_sync"] == "comm-bound", at_labels
        json.dumps(js_at)
        # runs without --step-attr must not grow the section or rows
        assert "== attribution ==" not in srv_out, srv_out
        assert by_srv["data_wait_share_p95"]["verdict"] == "missing", ds
        assert by_srv["host_sync_ms_p95"]["verdict"] == "missing", ds
        # planted input starvation: identical step times, but data-wait
        # share climbs 22pp and host-sync p95 climbs 9ms -> both new
        # rows (and only they) REGRESS, in both text and exit code
        aa_recs, _ = load_metrics(attr_base)
        ab_recs, _ = load_metrics(attr_bad)
        dat = diff_data(aa_recs, ab_recs)
        by_at = {r["metric"]: r for r in dat["metrics"]}
        assert by_at["data_wait_share_p95"]["verdict"] == "REGRESS", dat
        assert by_at["host_sync_ms_p95"]["verdict"] == "REGRESS", dat
        assert by_at["step_time_p50"]["verdict"] == "PASS", dat
        # the improvement direction passes both rows
        by_rat = {r["metric"]: r
                  for r in diff_data(ab_recs, aa_recs)["metrics"]}
        assert by_rat["data_wait_share_p95"]["verdict"] == "PASS", by_rat
        assert by_rat["host_sync_ms_p95"]["verdict"] == "PASS", by_rat
        buf_at = io.StringIO()
        with contextlib.redirect_stdout(buf_at):
            rc_at = run_diff(attr_base, attr_bad, 10.0, 5.0)
        assert rc_at == 1, (
            "selftest: planted input starvation must exit 1")
        assert "data_wait_share_p95" in buf_at.getvalue(), buf_at.getvalue()
        assert "host_sync_ms_p95" in buf_at.getvalue(), buf_at.getvalue()

        # ---- fleet plane (ISSUE 19): section, json twin, diff rows ----
        def write_fleet(path, retries, hedges_won):
            with MetricsLogger(path, flush_every=50) as log:
                for i in range(12):
                    rep = i % 2
                    log.log_event(
                        "fleettrace", rid=i,
                        trace_id=f"ptd-fleet-{i:08x}", replica=rep,
                        attempts=2 if i < retries else 1, hedged=0,
                        router_wait_ms=1.0,
                        redispatch_ms=30.0 if i < retries else 0.0,
                        hedge_wait_ms=0.0, engine_ttft_ms=40.0,
                        engine_e2e_ms=60.0,
                        router_ttft_ms=(71.0 if i < retries else 41.0),
                        router_e2e_ms=91.0 if i < retries else 61.0)
                log.log_event("replica_down", replica=1,
                              reason="healthz: connection refused")
                log.log_event("scale_up", replica=2,
                              reason="ttft_p99 91.0% of SLO")
                log.log_event("drain", scope="router", inflight=0)
                log.log_step(1, step_time=1.0, extra={
                    "fleet": 1.0, "replicas_up": 2.0,
                    "replicas_quarantined": 1.0, "replicas_total": 3.0,
                    "fleet_requests_routed": 12.0,
                    "fleet_requests_completed": 12.0,
                    "fleet_requests_failed": 0.0,
                    "fleet_retries": float(retries),
                    "fleet_hedges": 4.0,
                    "fleet_hedges_won": float(hedges_won),
                    "fleet_hedges_lost": 4.0 - hedges_won,
                    "fleet_duplicates_suppressed": 0.0,
                    "fleet_replica_down_events": 1.0,
                    "fleet_drain_events": 1.0,
                    "fleet_scale_up_events": 1.0,
                    "fleet_scale_down_events": 0.0,
                    "retry_rate_pct": 100.0 * retries / 12.0,
                    "hedge_win_rate_pct": 100.0 * hedges_won / 4.0})

        fpath = os.path.join(d, "fleet.jsonl")
        write_fleet(fpath, retries=2, hedges_won=3)
        ns_fl = argparse.Namespace(
            metrics_jsonl=fpath, hb_dir=None, telemetry_csv=None, now=now,
            max_step_lag=3, max_beat_age=60.0, bench_lkg=None,
            bench_events=None, bench_max_stale_days=14.0, plan=None,
            flight_dir=None)
        fl_out = report(ns_fl)
        for needle in ("== fleet ==", "2 up / 1 quarantined / 3 total",
                       "routed 12; completed 12",
                       "retries 2 (retry_rate 16.7%)",
                       "won 3 / lost 1, win_rate 75.0%",
                       "replica_down 1;  drain 1;  scale up/down 1/0",
                       "requests by replica: replica0×6, replica1×6",
                       "tail attribution p99: router_wait",
                       "[replica_down] replica=1 (healthz: connection "
                       "refused)",
                       "[scale_up] replica=2"):
            assert needle in fl_out, (
                f"selftest: {needle!r} missing from:\n{fl_out}")
        js_fl = report_json(ns_fl)
        assert js_fl["fleet"]["retries"] == 2.0, js_fl["fleet"]
        assert js_fl["fleet"]["requests_by_replica"] == {
            "0": 6.0, "1": 6.0}, js_fl["fleet"]
        assert js_fl["fleet"]["router_ttft_p99_ms"] == 71.0, js_fl["fleet"]
        assert js_fl["steps"]["retry_rate"] == 100.0 * 2 / 12, js_fl
        json.dumps(js_fl)
        # routerless runs must not grow the section or the diff rows
        assert "== fleet ==" not in srv_out, srv_out
        assert by_srv["retry_rate"]["verdict"] == "missing", ds
        # planted replica flapping: retry_rate climbs 16.7pp and the
        # hedge win rate collapses -> both new rows (and only they)
        # REGRESS
        fbad = os.path.join(d, "fleet_flap.jsonl")
        write_fleet(fbad, retries=4, hedges_won=0)
        fa_recs, _ = load_metrics(fpath)
        fb_recs, _ = load_metrics(fbad)
        dfl = diff_data(fa_recs, fb_recs)
        by_fl = {r["metric"]: r for r in dfl["metrics"]}
        assert by_fl["retry_rate"]["verdict"] == "REGRESS", dfl
        assert by_fl["hedge_win_rate"]["verdict"] == "REGRESS", dfl
        by_rfl = {r["metric"]: r
                  for r in diff_data(fb_recs, fa_recs)["metrics"]}
        assert by_rfl["retry_rate"]["verdict"] == "PASS", by_rfl
        assert by_rfl["hedge_win_rate"]["verdict"] == "PASS", by_rfl

        # ---- --flight-dir: the postmortem fold (ISSUE 13) ----
        pm = _postmortem_mod()
        fdir = os.path.join(d, "flight")
        pm.make_fixture(fdir)
        buf4 = io.StringIO()
        with contextlib.redirect_stdout(buf4):
            rc6 = main(["--flight-dir", fdir])
        fold = buf4.getvalue()
        assert rc6 == 0, fold
        for needle in ("== postmortem ==", "stalled first", "hang"):
            assert needle in fold, f"selftest: {needle!r} missing:\n{fold}"
        js_f = report_json(argparse.Namespace(
            metrics_jsonl=None, hb_dir=None, telemetry_csv=None,
            flight_dir=fdir, now=now))
        assert js_f["postmortem"]["n_ranks"] == 2, js_f
        assert js_f["postmortem"]["stalled_rank"] == 1, js_f
        json.dumps(js_f["postmortem"])
        # an empty dir degrades to a note, never a crash
        empty_f = os.path.join(d, "noflight")
        os.makedirs(empty_f)
        sec = postmortem_section(empty_f)
        assert any("no flightrec_rank" in ln for ln in sec), sec
    print("obs_report selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a run's observability artifacts")
    ap.add_argument("--metrics-jsonl", type=str, default=None,
                    dest="metrics_jsonl")
    ap.add_argument("--hb-dir", type=str, default=None, dest="hb_dir")
    ap.add_argument("--telemetry-csv", type=str, default=None,
                    dest="telemetry_csv")
    ap.add_argument("--comm-ledger", type=str, default=None,
                    dest="comm_ledger",
                    help="comm_ledger.json (scripts/shardlint.py "
                    "--comm-ledger) to itemize in the comms section")
    ap.add_argument("--comm-predicted", type=float, default=None,
                    dest="comm_predicted", metavar="BYTES",
                    help="analytic per-step comm bytes (obs.flops."
                    "lm_comm_bytes/image_comm_bytes) to fence the measured "
                    "ledger against (±15%% residual)")
    ap.add_argument("--mem-ledger", type=str, default=None,
                    dest="mem_ledger",
                    help="mem_ledger.json (scripts/shardlint.py "
                    "--mem-ledger or a trainer's --mem-ledger) to itemize "
                    "in the memory section: watermark peak vs "
                    "memory_analysis, class/phase breakdown, top buffers")
    ap.add_argument("--plan", type=str, default=None, metavar="PLAN_JSON",
                    help="autoplan payload (scripts/autoplan.py --out) to "
                    "fold in: the chosen plan + predicted vs measured "
                    "MFU/wire-bytes/peak-HBM drift; in --diff, adds the "
                    "predicted-vs-measured MFU residual row (a note, "
                    "never a verdict)")
    ap.add_argument("--bench-lkg", type=str, default=None, dest="bench_lkg",
                    help="BENCH_LKG.json for staleness aging (default: the "
                    "checked-in repo-root file)")
    ap.add_argument("--bench-events", type=str, default=None,
                    dest="bench_events",
                    help="bench_events.jsonl for staleness aging (default: "
                    "$BENCH_EVENTS_JSONL or the repo-root file; missing is "
                    "fine)")
    ap.add_argument("--bench-max-stale-days", type=float, default=14.0,
                    dest="bench_max_stale_days", metavar="DAYS",
                    help="WARN in the bench section (and note in --diff) "
                    "when the last good benchmark capture is older than "
                    "DAYS (default 14; 0 disables); with --strict the "
                    "WARN is a failing fence")
    ap.add_argument("--strict", action="store_true",
                    help="promote the bench-staleness WARN to a failure: "
                    "exit 1 from the report and from --diff when the last "
                    "good benchmark is older than --bench-max-stale-days")
    ap.add_argument("--synclint-json", type=str, default=None,
                    dest="synclint_json", metavar="PATH",
                    help="synclint/shardlint --json capture to fold in as "
                    "the '== synclint ==' cross-rank congruence section; "
                    "with --strict, any error-severity sync finding "
                    "(incongruent schedule, digest drift, host desync, "
                    "protocol counterexample) fails the report")
    ap.add_argument("--flight-dir", type=str, default=None,
                    dest="flight_dir", metavar="DIR",
                    help="directory with flight-recorder dumps "
                    "(flightrec_rank*.json) to fold in as the "
                    "'== postmortem ==' cross-rank root-cause section")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="output format; json emits every section (and "
                    "--diff verdicts) as one machine-readable object")
    ap.add_argument("--max-step-lag", type=int, default=3, dest="max_step_lag",
                    help="flag processes more than N steps behind the lead")
    ap.add_argument("--max-beat-age", type=float, default=60.0,
                    dest="max_beat_age",
                    help="flag processes whose newest beat is older (seconds)")
    ap.add_argument("--now", type=float, default=None,
                    help=argparse.SUPPRESS)  # fixed clock for tests
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="compare two metrics JSONL runs (A = baseline, "
                    "B = candidate): step-time p50/p95, throughput, MFU, "
                    "goodput with PASS/REGRESS verdicts; exit 1 on REGRESS")
    ap.add_argument("--threshold-pct", type=float, default=10.0,
                    dest="threshold_pct",
                    help="relative regression threshold for --diff "
                    "(default 10%%)")
    ap.add_argument("--goodput-threshold-pp", type=float, default=5.0,
                    dest="goodput_threshold_pp",
                    help="absolute goodput regression threshold for --diff "
                    "in percentage points (default 5)")
    ap.add_argument("--selftest", action="store_true",
                    help="synthesize artifacts, run the report, verify it")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.diff:
        return run_diff(args.diff[0], args.diff[1], args.threshold_pct,
                        args.goodput_threshold_pp, fmt=args.format,
                        staleness=bench_staleness_info(args),
                        plan=(load_plan(args.plan) if args.plan else None),
                        strict=getattr(args, "strict", False))
    if args.format == "json":
        print(json.dumps(report_json(args), indent=2))
    else:
        print(report(args))
    rc = 0
    staleness = bench_staleness_info(args)
    if (getattr(args, "strict", False) and staleness is not None
            and staleness.get("warn")):
        print(f"STRICT: benchmark baseline stale "
              f"{staleness['days_stale']:.1f} days "
              f"(> {staleness['max_stale_days']:g}) — failing",
              file=sys.stderr)
        rc = 1
    if getattr(args, "strict", False) and getattr(
            args, "synclint_json", None):
        sstats = synclint_stats(args.synclint_json)
        n_sync_err = sstats.get("errors", 0)
        if "error" in sstats or n_sync_err:
            what = (sstats.get("error")
                    or f"{n_sync_err} error-severity sync finding(s)")
            print(f"STRICT: synclint fold failing — {what}",
                  file=sys.stderr)
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
