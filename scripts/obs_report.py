#!/usr/bin/env python
"""Fold one run's observability artifacts into a human-readable summary.

Inputs (any subset):
- ``--metrics-jsonl``  per-step records from ``obs.MetricsLogger``
  (``--metrics-jsonl`` on any recipe / ``LMTrainer``);
- ``--hb-dir``         per-process heartbeats from ``obs.HeartbeatWriter``
  (``--hb-dir``), with straggler flagging by step lag / beat age;
- ``--telemetry-csv``  the 500 ms device-memory CSV from
  ``utils.telemetry.TelemetrySampler`` (``--telemetry-csv``).

Output: step-time percentiles + throughput + loss/grad-norm trajectory,
per-device peak HBM, and a straggler table — the per-stage, per-device
measurements the reference's per-node nvidia-smi CSVs never aggregated.

``--selftest`` synthesizes all three artifacts in a temp dir, runs the
report on them, and asserts the summary — the fast tier-1 CI hook.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
import time
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _pct(sorted_vals, q):
    if not sorted_vals:
        return 0.0
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def _mib(n: float) -> str:
    return f"{n / (1024 * 1024):.1f}"


def load_metrics(path: str) -> List[dict]:
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except ValueError:
                continue  # torn tail line from a killed writer
    return records


def summarize_metrics(records: List[dict]) -> List[str]:
    if not records:
        return ["  (no records)"]
    records = sorted(records, key=lambda r: (r.get("step", 0), r.get("t", 0)))
    times = sorted(r["step_time"] for r in records if "step_time" in r)
    lines = [
        f"  steps logged      {len(records)} "
        f"(step {records[0].get('step')}..{records[-1].get('step')})",
        f"  wall span         {records[-1].get('t', 0) - records[0].get('t', 0):.1f}s",
        f"  step time         p50 {_pct(times, .5) * 1e3:.1f}ms  "
        f"p95 {_pct(times, .95) * 1e3:.1f}ms  "
        f"max {(times[-1] if times else 0) * 1e3:.1f}ms",
    ]
    thr = [r["throughput"] for r in records if "throughput" in r]
    if thr:
        lines.append(f"  throughput        mean {sum(thr) / len(thr):.1f}/s  "
                     f"last {thr[-1]:.1f}/s")
    loss = [r["loss"] for r in records if "loss" in r]
    if loss:
        lines.append(f"  loss              first {loss[0]:.4f}  "
                     f"last {loss[-1]:.4f}")
    gn = [r["grad_norm"] for r in records if "grad_norm" in r]
    if gn:
        lines.append(f"  grad_norm         last {gn[-1]:.4f}  "
                     f"max {max(gn):.4f}")
    lr = [r["lr"] for r in records if "lr" in r]
    if lr:
        lines.append(f"  lr                last {lr[-1]:.6g}")
    return lines


def summarize_ft_events(records: List[dict]) -> List[str]:
    """Fold the FT subsystem's structured ``ft_event`` records (skips,
    rollbacks, preemptions — ft/divergence.py and the trainers) into the
    summary: per-kind counts with the steps involved, plus the final LR
    backoff scale after the last rollback."""
    events = [r for r in records if "ft_event" in r]
    if not events:
        return []
    by_kind: Dict[str, List[dict]] = {}
    for e in events:
        by_kind.setdefault(str(e["ft_event"]), []).append(e)
    lines = ["== ft events =="]
    for kind in sorted(by_kind):
        evs = by_kind[kind]
        steps = [e["step"] for e in evs if "step" in e]
        shown = ",".join(str(s) for s in steps[:8])
        if len(steps) > 8:
            shown += ",…"
        lines.append(f"  {kind:<16}  {len(evs)}x"
                     + (f"  steps {shown}" if steps else ""))
    rollbacks = by_kind.get("rollback", [])
    scales = [e["lr_scale"] for e in rollbacks if "lr_scale" in e]
    if scales:
        lines.append(f"  lr scale          {scales[-1]:g} after "
                     f"{len(rollbacks)} rollback(s)")
    return lines


def summarize_telemetry(path: str) -> List[str]:
    """Per-device peak/limit from the ``timestamp,index,bytes_limit,
    bytes_in_use,peak_bytes`` CSV (no header in the statistics.sh contract)."""
    peak: Dict[int, float] = {}
    limit: Dict[int, float] = {}
    n_rows = 0
    with open(path, newline="") as f:
        for row in csv.reader(f):
            if len(row) < 5:
                continue
            try:
                idx = int(row[1])
                lim, pk = float(row[2]), float(row[4])
            except ValueError:
                continue  # header or torn row
            n_rows += 1
            peak[idx] = max(peak.get(idx, 0.0), pk)
            limit[idx] = max(limit.get(idx, 0.0), lim)
    if not peak:
        return ["  (no samples)"]
    lines = [f"  samples           {n_rows}"]
    for idx in sorted(peak):
        cap = f" / {_mib(limit[idx])} MiB" if limit[idx] else ""
        lines.append(f"  device {idx:<2}         peak {_mib(peak[idx])} MiB{cap}")
    return lines


def summarize_heartbeats(hb_dir: str, now: Optional[float],
                         max_step_lag: int, max_age_s: float) -> List[str]:
    from pytorch_distributed_tpu.obs.heartbeat import (
        find_stragglers,
        read_heartbeats,
    )

    beats = read_heartbeats(hb_dir)
    if not beats:
        return ["  (no heartbeats)"]
    if now is None:
        now = time.time()
    flagged = find_stragglers(beats, now=now, max_step_lag=max_step_lag,
                              max_age_s=max_age_s)
    lines = []
    for pid in sorted(beats):
        b = beats[pid]
        mark = f"  ** STRAGGLER: {flagged[pid]}" if pid in flagged else ""
        lines.append(f"  process {pid:<3}       step {b['step']:<8} "
                     f"beat age {now - b['t']:.1f}s{mark}")
    if not flagged:
        lines.append("  no stragglers")
    return lines


def report(args) -> str:
    sections = []
    if args.metrics_jsonl:
        records = load_metrics(args.metrics_jsonl)
        sections.append("== steps ==")
        sections += summarize_metrics(
            [r for r in records if "ft_event" not in r])
        sections += summarize_ft_events(records)
    if args.telemetry_csv:
        sections.append("== devices ==")
        sections += summarize_telemetry(args.telemetry_csv)
    if args.hb_dir:
        sections.append("== heartbeats ==")
        sections += summarize_heartbeats(args.hb_dir, args.now,
                                         args.max_step_lag, args.max_beat_age)
    if not sections:
        sections.append("nothing to report: pass --metrics-jsonl, "
                        "--hb-dir, and/or --telemetry-csv")
    return "\n".join(sections)


def _selftest() -> int:
    """Synthesize all three artifacts, run the report, assert the summary."""
    import tempfile

    from pytorch_distributed_tpu.obs import HeartbeatWriter, MetricsLogger

    with tempfile.TemporaryDirectory() as d:
        now = time.time()
        # per-step metrics via the real logger
        mpath = os.path.join(d, "metrics.jsonl")
        with MetricsLogger(mpath, flush_every=7) as log:
            for i in range(20):
                log.log_step(i, step_time=0.01 + 0.001 * (i % 5),
                             n_items=128, lr=0.1,
                             scalars={"loss": 2.0 - 0.05 * i,
                                      "grad_norm": 1.0 + 0.1 * i})
            # ft_event records interleave in the same JSONL (ft/)
            log.log_event("skip", step=7, consecutive=1)
            log.log_event("skip", step=8, consecutive=2)
            log.log_event("rollback", step=9, restored_step=5, lr_scale=0.5)
            log.log_event("preempt", step=19)
        # heartbeats: pid 0 current, pid 1 lagging AND stale
        hb_dir = os.path.join(d, "hb")
        w0 = HeartbeatWriter(hb_dir, 0, interval_s=0.0)
        w0.beat(19)
        with open(os.path.join(hb_dir, "heartbeat-00001.jsonl"), "w") as f:
            f.write(json.dumps({"pid": 1, "step": 3, "t": now - 120}) + "\n")
        # telemetry CSV (statistics.sh contract)
        tpath = os.path.join(d, "telemetry.csv")
        with open(tpath, "w", newline="") as f:
            wr = csv.writer(f)
            for t in range(4):
                for dev in range(2):
                    wr.writerow([now + t, dev, 8 << 30,
                                 (1 + t) << 20, (2 + t) << 20])

        out = report(argparse.Namespace(
            metrics_jsonl=mpath, hb_dir=hb_dir, telemetry_csv=tpath,
            now=now, max_step_lag=3, max_beat_age=60.0))
        for needle in ("== steps ==", "steps logged      20", "p95",
                       "throughput", "loss", "grad_norm",
                       "== ft events ==", "skip", "rollback", "preempt",
                       "lr scale          0.5 after 1 rollback",
                       "== devices ==", "device 0", "device 1",
                       "== heartbeats ==", "STRAGGLER", "step lag",
                       "beat age"):
            assert needle in out, f"selftest: {needle!r} missing from:\n{out}"
        # pid 0 must NOT be flagged
        line0 = [ln for ln in out.splitlines() if "process 0" in ln]
        assert line0 and "STRAGGLER" not in line0[0], out
    print("obs_report selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Summarize a run's observability artifacts")
    ap.add_argument("--metrics-jsonl", type=str, default=None,
                    dest="metrics_jsonl")
    ap.add_argument("--hb-dir", type=str, default=None, dest="hb_dir")
    ap.add_argument("--telemetry-csv", type=str, default=None,
                    dest="telemetry_csv")
    ap.add_argument("--max-step-lag", type=int, default=3, dest="max_step_lag",
                    help="flag processes more than N steps behind the lead")
    ap.add_argument("--max-beat-age", type=float, default=60.0,
                    dest="max_beat_age",
                    help="flag processes whose newest beat is older (seconds)")
    ap.add_argument("--now", type=float, default=None,
                    help=argparse.SUPPRESS)  # fixed clock for tests
    ap.add_argument("--selftest", action="store_true",
                    help="synthesize artifacts, run the report, verify it")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    print(report(args))
    return 0


if __name__ == "__main__":
    sys.exit(main())
