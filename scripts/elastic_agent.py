#!/usr/bin/env python
"""elastic_agent — membership-epoch coordination CLI for elastic runs.

The file-based half of ISSUE 10: ``ElasticCoordinator`` (ft/elastic.py)
maintains ``membership.json`` in the run's heartbeat directory; this CLI
is how operators (and restarted ranks) talk to it.  No devices, no mesh —
pure file coordination, safe on a login node beside a live run.

Commands:

- ``status --hb-dir D``   one-shot report: current membership epoch +
  ranks, per-rank liveness (live / slow / dead, from the same
  ``find_stragglers`` thresholds the trainers use), pending join
  requests.  Exit 0 when every member is live, 1 otherwise — cronnable.
- ``watch --hb-dir D``    the coordinator loop: every ``--interval``
  seconds run one ``decide()`` round — evict dead members, admit pending
  joins, commit the next epoch atomically.  ``--once`` for a single
  round (the cron idiom).  ``--min-ranks`` is the shrink floor below
  which eviction is refused.  ``--alerts-from metrics.jsonl``
  additionally consumes ``dead_rank`` ``alert`` ft_events (the live
  alert plane, obs/alerts.py) into the same eviction round — no second
  liveness policy.
- ``join --hb-dir D --rank R``  file an admission request for a
  restarted/new rank; the next ``decide()`` folds it in.
- ``--selftest``          the fast no-mesh CI path (like
  ``chaoskit.py --selftest``): membership round-trip, join protocol,
  dead-eviction + epoch fencing of stale beats, min-ranks refusal.

Decisions only move ``membership.json``; the training processes observe
the epoch bump via their own elastic pollers and re-mesh themselves
(train/trainer.py, train/lm.py ``remesh``).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.ft.elastic import (  # noqa: E402
    ElasticCoordinator,
    Membership,
    split_liveness,
)
from pytorch_distributed_tpu.obs.heartbeat import (  # noqa: E402
    find_stragglers,
    read_heartbeats,
)


def _coordinator(args) -> ElasticCoordinator:
    return ElasticCoordinator(
        args.hb_dir, world=args.world, min_ranks=args.min_ranks,
        max_step_lag=args.max_step_lag, max_age_s=args.max_age_s)


def cmd_status(args) -> int:
    co = _coordinator(args)
    cur = co.membership()
    beats = read_heartbeats(args.hb_dir, min_epoch=cur.epoch)
    flagged = find_stragglers(beats, max_step_lag=args.max_step_lag,
                              max_age_s=args.max_age_s)
    dead, slow = split_liveness(flagged)
    print(f"membership epoch {cur.epoch}: world {cur.world} "
          f"ranks {list(cur.ranks)}")
    unhealthy = 0
    for r in cur.ranks:
        beat = beats.get(r)
        if r in dead:
            state, unhealthy = f"DEAD ({flagged[r]})", unhealthy + 1
        elif r in slow:
            state, unhealthy = f"slow ({flagged[r]})", unhealthy + 1
        elif beat is None:
            # no beat at this epoch yet: in flight (just re-meshed)
            state = "no beat at this epoch (in flight)"
        else:
            state = f"live (step {beat.get('step')})"
            if beat.get("mem") is not None:
                state += f", mem {beat['mem'] / 2**20:.0f} MiB"
        print(f"  rank {r}: {state}")
    joins = sorted(co.pending_joins())
    if joins:
        print(f"pending joins: {joins}")
    _print_flight_dumps(args)
    return 1 if unhealthy else 0


def _print_flight_dumps(args) -> None:
    """Point the operator at fresh flight-recorder dumps (ISSUE 13):
    when any rank died or hung with ``--flight-rec`` on, its ring dump
    sits next to the heartbeats — surface it plus the one command that
    merges them, instead of making the operator ls around."""
    from pytorch_distributed_tpu.obs.flightrec import find_dumps

    flight_dir = getattr(args, "flight_dir", None) or args.hb_dir
    try:
        dumps = find_dumps(flight_dir)
    except OSError:
        return
    if not dumps:
        return
    print(f"flight-recorder dumps in '{flight_dir}':")
    for r in sorted(dumps):
        path = dumps[r]
        reason, age = "?", "?"
        try:
            with open(path) as f:
                reason = json.load(f).get("reason", "?")
            age = f"{time.time() - os.path.getmtime(path):.0f}s ago"
        except (OSError, ValueError):
            pass
        print(f"  rank {r}: {os.path.basename(path)} "
              f"(reason={reason}, {age})")
    print(f"merge them: python scripts/postmortem.py {flight_dir} "
          f"--hb-dir {args.hb_dir}")


def _alert_dead_ranks(path, since_t: float):
    """Ranks declared dead by `alert` ft_events in a metrics JSONL that
    are newer than ``since_t`` → {rank: newest event t}.  Tolerant of a
    missing/partial file (the run may still be writing it)."""
    from pytorch_distributed_tpu.obs.alerts import dead_ranks_from_events
    from pytorch_distributed_tpu.obs.metrics import read_metrics

    try:
        records = read_metrics(path)
    except (OSError, ValueError):
        return {}
    return dead_ranks_from_events(records, since_t=since_t)


def cmd_watch(args) -> int:
    co = _coordinator(args)
    # Alert-driven eviction (ISSUE 14): dead_rank alerts booked into the
    # metrics JSONL by obs/alerts.py (trainer-side) or obs_live
    # (aggregator-side) merge into the SAME decide() round the heartbeat
    # evidence feeds — one liveness policy, one commit path.  Events are
    # consumed once by timestamp so a re-admitted rank is not re-evicted
    # by its own old alert.
    alerts_from = getattr(args, "alerts_from", None)
    seen_t = 0.0
    while True:
        extra_dead = None
        if alerts_from:
            flagged = _alert_dead_ranks(alerts_from, seen_t)
            if flagged:
                seen_t = max(seen_t, *flagged.values())
                extra_dead = set(flagged)
                print(f"alert-driven eviction candidates from "
                      f"'{alerts_from}': {sorted(extra_dead)}", flush=True)
        chg = co.decide(extra_dead=extra_dead)
        if chg is not None:
            print(f"epoch {chg.old.epoch} -> {chg.new.epoch} "
                  f"({chg.kind}): world {chg.old.world} -> "
                  f"{chg.new.world}; {chg.reason}", flush=True)
        if args.once:
            return 0
        time.sleep(args.interval)


def cmd_join(args) -> int:
    co = _coordinator(args)
    co.request_join(args.rank)
    print(f"filed join request for rank {args.rank} "
          f"({co.join_path(args.rank)})")
    return 0


def _selftest() -> int:
    """No-mesh coordination fast path: membership round-trip, the join
    protocol, epoch fencing of stale beats, and the min-ranks floor."""
    import tempfile

    from pytorch_distributed_tpu.ft.elastic import atomic_write_json

    with tempfile.TemporaryDirectory() as d:
        hb = os.path.join(d, "hb")
        co = ElasticCoordinator(hb, world=4, min_ranks=2, max_age_s=5.0)

        # 1. Fresh membership: epoch 0, all ranks; json round-trips.
        cur = co.membership()
        assert (cur.epoch, cur.ranks) == (0, (0, 1, 2, 3)), cur
        assert Membership.from_json(cur.to_json()) == cur

        # 2. Atomic write discipline: no tmp litter after a commit.
        atomic_write_json(co.path, cur.to_json())
        assert not [n for n in os.listdir(hb) if ".tmp." in n]

        # 3. All live → no decision, epoch stays put.
        now = time.time()
        beats = {r: {"pid": r, "step": 10, "t": now, "epoch": 0}
                 for r in range(4)}
        assert co.decide(now=now, beats=beats) is None
        assert co.membership().epoch == 0

        # 4. Dead beat → evicted, epoch bumps, survivors committed.
        beats[3]["t"] = now - 3600.0
        chg = co.decide(now=now, beats=beats)
        assert chg is not None and chg.kind == "shrink"
        assert chg.new.ranks == (0, 1, 2) and chg.new.epoch == 1
        assert co.membership() == chg.new

        # 5. Stale-incarnation fencing: a beat from epoch 0 never reads
        #    as live at epoch 1 (read path drops it) — the hardened
        #    heartbeat writer stamps epoch into every record.
        hb_live = read_heartbeats(hb, min_epoch=co.membership().epoch)
        assert 3 not in hb_live

        # 6. Join protocol: request → pending → admitted → request file
        #    consumed; grow bumps the epoch again.
        co.request_join(3)
        assert co.pending_joins() == {3}
        fresh = {r: {"pid": r, "step": 12, "t": now, "epoch": 1}
                 for r in (0, 1, 2)}
        chg2 = co.decide(now=now, beats=fresh)
        assert chg2 is not None and chg2.kind == "grow"
        assert chg2.new.ranks == (0, 1, 2, 3) and chg2.new.epoch == 2
        assert co.pending_joins() == set()

        # 7. Min-ranks floor: losing 3 of 4 would leave 1 < 2 — refused,
        #    membership and epoch unmoved.
        dead3 = {r: {"pid": r, "step": 12,
                     "t": now - (3600.0 if r else 0.0), "epoch": 2}
                 for r in range(4)}
        assert co.decide(now=now, beats=dead3) is None
        assert co.membership().epoch == 2

        # 8. A member with NO beat at the current epoch is in flight,
        #    not dead — must not be evicted.
        assert co.decide(now=now, beats={0: {"pid": 0, "step": 1,
                                             "t": now, "epoch": 2}}) is None

        # 9. CLI surface: status exits 0 on a live fleet, 1 with a dead
        #    member; join files the request where decide() finds it.
        ns = argparse.Namespace(hb_dir=hb, world=4, min_ranks=2,
                                max_step_lag=3, max_age_s=5.0, rank=9)

        def beat_file(r, t):
            path = os.path.join(hb, f"heartbeat-{r:05d}.jsonl")
            with open(path, "w") as f:
                f.write(json.dumps({"pid": r, "step": 5, "t": t,
                                    "epoch": 2}) + "\n")

        for r in range(4):
            beat_file(r, time.time())
        assert cmd_status(ns) == 0
        beat_file(3, time.time() - 3600.0)  # rank 3 goes dead
        assert cmd_status(ns) == 1
        # a flight dump next to the beats is surfaced; the pointer path
        # must survive the bare Namespace above (no flight_dir attr)
        with open(os.path.join(hb, "flightrec_rank3.json"), "w") as f:
            f.write(json.dumps({"rank": 3, "reason": "hang"}))
        assert cmd_status(ns) == 1
        assert cmd_join(ns) == 0
        assert co.pending_joins() == {9}

        # 10. Alert-driven eviction (ISSUE 14): a dead_rank `alert`
        #     ft_event routes into the SAME decide() path as heartbeat
        #     evidence — here the beats are all fresh (the heartbeat
        #     monitor alone would keep everyone), the alert evicts.
        hb2 = os.path.join(d, "hb2")
        co2 = ElasticCoordinator(hb2, world=4, min_ranks=2, max_age_s=5.0)
        now = time.time()
        fresh4 = {r: {"pid": r, "step": 20, "t": now, "epoch": 0}
                  for r in range(4)}
        chg3 = co2.decide(now=now, beats=fresh4, extra_dead={2})
        assert chg3 is not None and chg3.kind == "shrink"
        assert chg3.new.ranks == (0, 1, 3) and "alert" in chg3.reason

        #     The floor still rules: alerts for 2 of the 3 survivors
        #     would leave 1 < min_ranks — refused, epoch unmoved.
        fresh3 = {r: {"pid": r, "step": 21, "t": now, "epoch": 1}
                  for r in (0, 1, 3)}
        assert co2.decide(now=now, beats=fresh3, extra_dead={1, 3}) is None
        assert co2.membership().epoch == 1

        #     CLI surface: `watch --once --alerts-from` reads the booked
        #     event from a metrics JSONL and commits the eviction.
        mpath = os.path.join(d, "metrics.jsonl")
        with open(mpath, "w") as f:
            f.write(json.dumps({"ft_event": "alert", "t": now,
                                "alert": "dead_rank", "rule": "dead_rank",
                                "severity": "page", "rank": 3,
                                "detail": "rank 3: beat age 120s"}) + "\n")
        for r in (0, 1, 3):
            path = os.path.join(hb2, f"heartbeat-{r:05d}.jsonl")
            with open(path, "w") as f:
                f.write(json.dumps({"pid": r, "step": 22,
                                    "t": time.time(), "epoch": 1}) + "\n")
        ns2 = argparse.Namespace(hb_dir=hb2, world=4, min_ranks=2,
                                 max_step_lag=3, max_age_s=5.0,
                                 interval=0.0, once=True,
                                 alerts_from=mpath)
        assert cmd_watch(ns2) == 0
        assert co2.membership().ranks == (0, 1)
    print("elastic_agent selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Membership-epoch coordination for elastic runs")
    ap.add_argument("--selftest", action="store_true",
                    help="run the fast no-mesh coordination checks")
    sub = ap.add_subparsers(dest="cmd")

    def common(p):
        p.add_argument("--hb-dir", required=True,
                       help="the run's heartbeat directory")
        p.add_argument("--world", type=int, default=1,
                       help="initial world size if membership.json is new")
        p.add_argument("--min-ranks", type=int, default=1,
                       help="shrink floor: never evict below this world")
        p.add_argument("--max-step-lag", type=int, default=3)
        p.add_argument("--max-age-s", type=float, default=60.0,
                       help="beat age beyond which a rank reads as dead")

    s = sub.add_parser("status", help="one-shot membership + liveness report")
    common(s)
    s.add_argument("--flight-dir", default=None,
                   help="where --flight-rec dumps land (default: the "
                        "heartbeat dir); fresh dumps are surfaced with "
                        "the postmortem merge command")
    w = sub.add_parser("watch", help="run the coordinator decision loop")
    common(w)
    w.add_argument("--interval", type=float, default=10.0,
                   help="seconds between decide() rounds")
    w.add_argument("--once", action="store_true",
                   help="one decision round and exit (cron idiom)")
    w.add_argument("--alerts-from", default=None, dest="alerts_from",
                   metavar="JSONL",
                   help="also consume `alert` ft_events from this metrics "
                        "JSONL: dead_rank alerts (obs/alerts.py, booked "
                        "by the trainer or obs_live) feed the same "
                        "decide() eviction round as heartbeat evidence")
    j = sub.add_parser("join", help="file a join request for a rank")
    common(j)
    j.add_argument("--rank", type=int, required=True)

    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.cmd == "status":
        return cmd_status(args)
    if args.cmd == "watch":
        return cmd_watch(args)
    if args.cmd == "join":
        return cmd_join(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
