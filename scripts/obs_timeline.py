#!/usr/bin/env python
"""obs_timeline: comm/compute timeline analysis of profiler captures.

Parses ``*.xplane.pb`` captures (jax.profiler / scripts/profile_trace.py
output) with the pure-python decoder in obs/timeline.py — no TF, no jax —
and reports per-step collective time, overlap with compute, and exposed
(un-overlapped) communication per device stream.  Optionally marries the
measured spans to a static comm ledger (scripts/shardlint.py
--comm-ledger) to turn bytes into effective bus bandwidth, aligns
multi-process captures on a common clock using heartbeat files, and
exports the merged timeline as Chrome-trace JSON for Perfetto
(https://ui.perfetto.dev) or chrome://tracing.

Usage:
  python scripts/obs_timeline.py TRACE_DIR            # text report
  python scripts/obs_timeline.py a.xplane.pb b.xplane.pb \\
      --hb-dir runs/hb --out merged.trace.json        # cross-rank merge
  python scripts/obs_timeline.py TRACE_DIR \\
      --ledger comm_ledger.json --step lm_train_dp    # bytes -> GB/s
  python scripts/obs_timeline.py TRACE_DIR \\
      --mem-ledger mem_ledger.json --out m.trace.json # + HBM counter track
  python scripts/obs_timeline.py TRACE_DIR --json report.json
  python scripts/obs_timeline.py --selftest           # fixture round-trip
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Deliberately no jax import: timeline analysis must run anywhere,
# including on a login host that only has the capture files.
from pytorch_distributed_tpu.obs import timeline as tlmod  # noqa: E402

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "data", "synthetic.xplane.pb")


def _collect_captures(paths):
    files = []
    for p in paths:
        if os.path.isdir(p):
            found = tlmod.find_xplane_files(p)
            if not found:
                raise SystemExit(f"no *.xplane.pb under {p}")
            files.extend(found)
        else:
            files.append(p)
    return files


def _report_text(rank, tl, stats, agg, marriage, step_name):
    lines = [f"rank {rank}: {tl.source}"
             f"  host={tl.hostname or '?'}  spans={len(tl.spans)}"
             f"  streams={agg.get('streams', 0)}"]
    if not stats:
        lines.append("  no device op spans found")
        return lines
    lines.append(
        f"  steps={agg['steps']}  comm {agg['comm_ms_mean']:.3f} ms/step"
        f"  exposed {agg['exposed_ms_mean']:.3f} ms/step"
        f"  overlap {agg['overlap_pct_mean']:.1f}%")
    for kind, slot in sorted(agg.get("by_kind", {}).items()):
        lines.append(f"    {kind:<22} ×{slot['count']:<4}"
                     f" {slot['time_ns'] / 1e6:.3f} ms total")
    if marriage:
        lines.append(f"  vs ledger step '{step_name}':")
        for kind, m in sorted(marriage.items()):
            match = "ok" if m["count_match"] else "MISMATCH"
            lines.append(
                f"    {kind:<22} ledger {m['ledger_count']} ops /"
                f" {m['wire_bytes']:.0f} wire B; measured"
                f" {m['measured_count_per_step']:.1f} ops/step"
                f" {m['measured_ms_per_step']:.3f} ms/step"
                f" -> {m['bus_gbps']:.2f} GB/s  [count {match}]")
    return lines


def make_fixture(path: str) -> None:
    """Deterministic 2-stream synthetic capture: two 100 us step windows,
    each with 60 us of fusion compute and a 30 us all-reduce that overlaps
    compute for 10 us (-> exposed 20 us, overlap 33.3%)."""
    US = 1_000_000  # ps per microsecond
    base = 1_000_000  # ns

    def device_line(idx):
        events = []
        for step in range(2):
            t0_ps = step * 100 * US
            events.append({"name": "fusion.1", "offset_ps": t0_ps + 5 * US,
                           "duration_ps": 60 * US,
                           "stats": {"hlo_op": "fusion.1", "program_id": 7}})
            # all-reduce starts 10 us before compute ends: 10 us overlap
            events.append({"name": "all-reduce.3",
                           "offset_ps": t0_ps + 55 * US,
                           "duration_ps": 30 * US,
                           "stats": {"hlo_op": "all-reduce.3",
                                     "program_id": 7}})
        return {"name": f"tf_XLATfrtCpuClient/{idx}",
                "timestamp_ns": base, "events": events}

    python_line = {
        "name": "python", "timestamp_ns": base,
        "events": [
            {"name": "train_step", "offset_ps": 0, "duration_ps": 100 * US},
            {"name": "train_step", "offset_ps": 100 * US,
             "duration_ps": 100 * US},
        ],
    }
    data = tlmod.encode_xspace(
        [{"name": "/host:CPU",
          "lines": [python_line, device_line(0), device_line(1)]}],
        hostname="fixture")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(data)


def selftest() -> int:
    """Round-trip the checked-in fixture and check every derived number.
    Pure python + a tmp-dir heartbeat pair for the clock-offset path; no
    jax, no profiler — the tier-1 fast fence for the whole decode/analyze
    stack."""
    import tempfile

    path = FIXTURE
    if not os.path.exists(path):  # regenerate if the fixture went missing
        make_fixture(path)
    tl = tlmod.parse_xspace(path)
    assert tl.hostname == "fixture", tl.hostname
    assert len(tl.spans) == 10, len(tl.spans)
    assert len(tl.device_lines()) == 2, tl.device_lines()
    assert len(tl.annotations("train_step")) == 2

    stats = tlmod.analyze_steps(tl)
    # 2 steps x 2 streams
    assert len(stats) == 4, [s.to_dict() for s in stats]
    for s in stats:
        assert abs(s.comm_ns - 30_000) < 1, s
        assert abs(s.overlap_ns - 10_000) < 1, s
        assert abs(s.exposed_ns - 20_000) < 1, s
        assert abs(s.overlap_pct - 100.0 / 3) < 0.1, s
    agg = tlmod.aggregate_steps(stats)
    assert agg["steps"] == 2 and agg["streams"] == 2, agg
    assert abs(agg["comm_ms_mean"] - 0.03) < 1e-6, agg
    assert abs(agg["exposed_ms_mean"] - 0.02) < 1e-6, agg
    assert agg["by_kind"]["all-reduce"]["count"] == 4, agg

    # ledger marriage: a synthetic 1-op ledger must report a count match
    # (1 all-reduce per step per stream) and a finite bandwidth
    from pytorch_distributed_tpu.obs import comms
    ledger = comms.CommLedger(step="fixture", entries=[comms.CommEntry(
        name="all-reduce.3", kind="all-reduce", bytes=4096,
        wire_bytes=comms.wire_bytes("all-reduce", 4096, 2),
        n_groups=1, group_size=2, phase="grad_sync",
        op_name="jit(step)/grad_sync/add", source="steps.py:1")])
    marriage = tlmod.marry_ledger(stats, ledger)
    m = marriage["all-reduce"]
    assert m["count_match"], marriage
    assert m["bus_gbps"] > 0, marriage

    # clock alignment: rank 1's beats written 2.5 ms late -> offset ~2.5 ms
    with tempfile.TemporaryDirectory() as d:
        for pid, skew in ((100, 0.0), (200, 0.0025)):
            with open(os.path.join(d, f"heartbeat-{pid}.jsonl"), "w") as f:
                for step in range(4):
                    f.write(json.dumps(
                        {"pid": pid, "step": step,
                         "t": 1000.0 + step + skew}) + "\n")
        offs = tlmod.clock_offsets_from_heartbeats(d)
        assert abs(offs[100]) < 1e-9 and abs(offs[200] - 0.0025) < 1e-9, offs

        trace = tlmod.to_chrome_trace([(0, tl), (1, tl)],
                                      {0: offs[100], 1: offs[200]})
    evs = trace["traceEvents"]
    coll = [e for e in evs if e.get("cat") == "collective"]
    assert len(coll) == 8, len(coll)  # 4 all-reduces x 2 ranks
    r0 = [e for e in coll if e["pid"] == 0][0]
    r1 = [e for e in coll if e["pid"] == 1][0]
    # rank 1's identical span lands 2500 us earlier once the skew is removed
    assert abs((r0["ts"] - r1["ts"]) - 2500.0) < 1e-6, (r0["ts"], r1["ts"])

    # HBM watermark merge: a 3-point ledger curve becomes a per-rank
    # counter track spanning exactly the rank's capture window
    from pytorch_distributed_tpu.obs import memory
    mled = memory.MemLedger(
        step="fixture", mesh_shape={"data": 2}, argument_bytes=512,
        output_bytes=256, donated_bytes=0, peak_bytes=1024, peak_index=2,
        n_instructions=5, measured_peak_bytes=1024.0,
        watermark=[[0, 768], [2, 1024], [4, 800]], buffers=[])
    trace = tlmod.to_chrome_trace([(0, tl), (1, tl)], mem_ledgers=[mled])
    ctr = [e for e in trace["traceEvents"] if e.get("ph") == "C"]
    assert len(ctr) == 6, len(ctr)  # 3 change points x 2 ranks
    for e in ctr:
        assert e["name"] == "hbm_watermark · fixture", e
    r0 = sorted((e for e in ctr if e["pid"] == 0), key=lambda e: e["ts"])
    t0 = min(s.start_ns for s in tl.spans) / 1e3
    t1 = max(s.end_ns for s in tl.spans) / 1e3
    assert abs(r0[0]["ts"] - t0) < 1e-6 and abs(r0[-1]["ts"] - t1) < 1e-6
    assert [e["args"]["bytes"] for e in r0] == [768, 1024, 800], r0

    print("obs_timeline selftest OK: parse/analyze/marry/align/export all "
          "verified on the checked-in fixture")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("captures", nargs="*",
                    help="trace dirs and/or *.xplane.pb files; each file "
                         "becomes one rank (in argument order)")
    ap.add_argument("--annotation", default=None,
                    help="step-marker annotation name (default: first of "
                         "lm_step/train_step/profile_step present)")
    ap.add_argument("--ledger", default=None, metavar="PATH",
                    help="comm_ledger.json to marry measured spans against")
    ap.add_argument("--step", default=None,
                    help="ledger step name (default: sole entry, else "
                         "required)")
    ap.add_argument("--mem-ledger", default=None, metavar="PATH",
                    help="mem_ledger.json (scripts/shardlint.py "
                         "--mem-ledger); merges each step's HBM watermark "
                         "into --out as a Perfetto counter track")
    ap.add_argument("--hb-dir", default=None, metavar="DIR",
                    help="heartbeat dir for cross-rank clock alignment")
    ap.add_argument("--out", default=None, metavar="PATH",
                    help="write merged Chrome-trace JSON (Perfetto)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the analysis report as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the decoder/analyzer on the checked-in "
                         "fixture and exit (no jax, no captures needed)")
    ap.add_argument("--make-fixture", default=None, metavar="PATH",
                    help="write the deterministic synthetic capture used "
                         "by --selftest and the tests, then exit")
    args = ap.parse_args(argv)

    if args.make_fixture:
        make_fixture(args.make_fixture)
        print(f"wrote synthetic capture to {args.make_fixture}")
        return 0
    if args.selftest:
        return selftest()
    if not args.captures:
        ap.error("no captures given (pass a trace dir or *.xplane.pb files)")

    ledger = None
    if args.ledger:
        from pytorch_distributed_tpu.obs import comms
        ledgers = comms.load_ledgers(args.ledger)
        if args.step:
            if args.step not in ledgers:
                raise SystemExit(f"step {args.step!r} not in {args.ledger}; "
                                 f"has: {sorted(ledgers)}")
            ledger = ledgers[args.step]
        elif len(ledgers) == 1:
            ledger = next(iter(ledgers.values()))
        else:
            raise SystemExit(f"--ledger has {len(ledgers)} steps; pick one "
                             f"with --step (has: {sorted(ledgers)})")

    mem_ledgers = None
    if args.mem_ledger:
        from pytorch_distributed_tpu.obs import memory
        by_step = memory.load_ledgers(args.mem_ledger)
        if args.step:
            if args.step not in by_step:
                raise SystemExit(f"step {args.step!r} not in "
                                 f"{args.mem_ledger}; has: {sorted(by_step)}")
            mem_ledgers = [by_step[args.step]]
        else:
            mem_ledgers = [by_step[k] for k in sorted(by_step)]

    files = _collect_captures(args.captures)
    timelines = [(rank, tlmod.parse_xspace(f)) for rank, f in
                 enumerate(files)]

    offsets = {}
    if args.hb_dir:
        by_pid = tlmod.clock_offsets_from_heartbeats(args.hb_dir)
        # heartbeat pids map to capture ranks in sorted order
        for rank, pid in enumerate(sorted(by_pid)):
            if rank < len(timelines):
                offsets[rank] = by_pid[pid]
        if by_pid:
            print(f"clock offsets from {args.hb_dir}: " + ", ".join(
                f"rank{r}={offsets.get(r, 0.0) * 1e3:+.3f}ms"
                for r, _ in enumerate(timelines)))

    report = {"captures": [], "ledger": args.ledger,
              "ledger_step": ledger.step if ledger else None}
    for rank, tl in timelines:
        stats = tlmod.analyze_steps(tl, annotation=args.annotation)
        agg = tlmod.aggregate_steps(stats)
        marriage = tlmod.marry_ledger(stats, ledger) if (
            ledger and stats) else {}
        print("\n".join(_report_text(
            rank, tl, stats, agg, marriage,
            ledger.step if ledger else "")))
        report["captures"].append({
            "rank": rank, "source": tl.source, "hostname": tl.hostname,
            "clock_offset_s": offsets.get(rank, 0.0),
            "aggregate": agg,
            "per_step": [s.to_dict() for s in stats],
            "ledger_marriage": marriage,
        })

    if args.out:
        trace = tlmod.to_chrome_trace(timelines, offsets,
                                      mem_ledgers=mem_ledgers)
        with open(args.out, "w") as f:
            json.dump(trace, f)
            f.write("\n")
        print(f"wrote Chrome-trace JSON ({len(trace['traceEvents'])} events)"
              f" to {args.out} — open in https://ui.perfetto.dev")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
