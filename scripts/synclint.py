#!/usr/bin/env python
"""synclint: cross-rank collective-congruence verifier.

Three layers, all riding the shared lowering service (zero extra
compiles beyond the shardlint sweep):

- HLO congruence       every recipe's ordered collective schedule (kind,
                       channel id, replica groups, shapes) is extracted
                       from the compiled module text and checked for
                       replica-group partition validity (disjoint,
                       in-range, uniform, covering) plus permute
                       well-formedness; the canonical schedule is pinned
                       as a sha256 digest in analysis/baseline.json and
                       drift is an error (a reordered schedule deadlocks
                       a multi-process mesh even when every count/bytes
                       budget holds).
- host desync          inter-procedural AST pass over the registered hot
                       loops (synclint.SYNC_SCOPES) flagging jitted-step
                       / collective calls reachable under rank-dependent
                       or locally-data-dependent branches that are not
                       routed through a '# synclint: agreement' point.
                       '# synclint: allow' suppresses a single call.
- protocol model check explicit-state exploration of the repo's
                       multi-step protocols (divergence rollback,
                       elastic shrink/grow, checkpoint fallback,
                       preemption stop) for reachable states where ranks
                       disagree on the next collective — the static twin
                       of the PR 13 flight-recorder hang post-mortem.

Exit status 1 when any error-severity finding survives.

Usage:
  python scripts/synclint.py                     # all three layers
  python scripts/synclint.py --steps lm_train_dp # HLO layer subset
  python scripts/synclint.py --hlo-cache hlo/    # jax-free: congruence
                                                 # off persisted lowering
                                                 # artifacts instead of a
                                                 # live sweep
  python scripts/synclint.py --no-hlo --no-proto # AST layer only
  python scripts/synclint.py --update-baseline   # patch the current
                                                 # schedule digests into
                                                 # analysis/baseline.json
  python scripts/synclint.py --selftest          # jax-free planted
                                                 # fixture checks
"""

import argparse
import json
import os
import sys

# Must precede the first jax import: the live sweep needs >= 4 simulated
# devices (mirrors tests/conftest.py so schedule digests match the test
# sweep).  Pure env-var setup — the jax import itself stays in main().
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

_DEFAULT_BASELINE = os.path.join(
    _REPO, "pytorch_distributed_tpu", "analysis", "baseline.json")
_FIXTURE_DIR = os.path.join(_REPO, "tests", "data", "synclint")


def build_parser() -> argparse.ArgumentParser:
    """Argparse-only parser factory (lint-checked by test_recipe_flags)."""
    ap = argparse.ArgumentParser(
        prog="synclint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--steps", default=None,
                    help="comma-separated subset of recipes for the HLO "
                         "layer (see --list)")
    ap.add_argument("--list", action="store_true",
                    help="list known recipe names and exit")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the full reports as JSON")
    ap.add_argument("--baseline", default=_DEFAULT_BASELINE,
                    help="baseline with pinned sync digests (default: the "
                         "checked-in analysis/baseline.json)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="skip the schedule-digest diff")
    ap.add_argument("--update-baseline", action="store_true",
                    help="patch the current collective-schedule digests "
                         "into --baseline (preserving the collective/"
                         "memory budgets shardlint pinned) instead of "
                         "diffing")
    ap.add_argument("--hlo-cache", default=None, metavar="DIR",
                    help="run the HLO congruence layer jax-free off "
                         "persisted lowering artifacts (<name>.hlo + "
                         "<name>.json, written by shardlint --hlo-cache) "
                         "instead of a live sweep")
    ap.add_argument("--no-hlo", action="store_true",
                    help="skip the HLO congruence layer")
    ap.add_argument("--no-ast", action="store_true",
                    help="skip the host control-flow desync layer")
    ap.add_argument("--no-proto", action="store_true",
                    help="skip the protocol model check layer")
    ap.add_argument("--selftest", action="store_true",
                    help="run the jax-free planted-fixture checks and exit")
    return ap


def _selftest() -> int:
    """Jax-free detector checks on the checked-in fixtures.

    Every layer must both fire on its planted hazard and stay quiet on
    the clean twin — a lint that can't find its own plant is noise."""
    from pytorch_distributed_tpu.analysis import astlint, synclint, syncproto

    def _read(fname):
        with open(os.path.join(_FIXTURE_DIR, fname)) as f:
            return f.read()

    # layer 1: congruent fixture is clean and digest-stable
    good = _read("good.hlo")
    assert synclint.verify_congruence(good, "good", n_devices=4) == [], \
        "good.hlo must verify congruent"
    sched = synclint.extract_schedule(good)
    assert len(sched) == 4, f"good.hlo schedule has {len(sched)} entries"
    d1 = synclint.schedule_digest(sched)
    d2 = synclint.schedule_digest(synclint.extract_schedule(good))
    assert d1 == d2 and len(d1) == 64, "schedule digest must be stable"

    # layer 1: every planted incongruence fires with the right diagnosis
    planted_hlo = {
        "bad_dup.hlo": "more than one replica group",
        "bad_oob.hlo": "out of range",
        "bad_sizes.hlo": "mismatched sizes",
        "bad_missing.hlo": "participate in no replica group",
        "bad_permute.hlo": "not a permutation",
    }
    for fname, needle in planted_hlo.items():
        fs = synclint.verify_congruence(_read(fname), fname, n_devices=4)
        assert fs and all(f.kind == "collective-incongruence" for f in fs), \
            f"{fname}: expected collective-incongruence, got {fs}"
        assert any(needle in f.message for f in fs), \
            f"{fname}: no finding mentions {needle!r}: {fs}"

    # layer 2: planted desync fires at the documented lines, anchored
    # twin is clean, and the in-module plant agrees
    fs = astlint.lint_desync_source(
        _read("desync_planted.py"),
        path="desync_planted.py", hot_functions=("T.fit",))
    got = sorted(f.where for f in fs)
    assert got == ["desync_planted.py:16", "desync_planted.py:19"], \
        f"planted desync fired at {got}"
    assert any("rank-dependent" in f.message for f in fs)
    assert any("locally-data-dependent" in f.message for f in fs)
    fs = astlint.lint_desync_source(
        _read("agreement_ok.py"),
        path="agreement_ok.py", hot_functions=("T.fit",))
    assert fs == [], f"agreement_ok.py must lint clean, got {fs}"
    assert len(synclint.planted_desync_findings()) == 2

    # layer 3: shipped protocols verify; buggy local variants desync
    proto = syncproto.check_protocols()
    assert proto and all(f.severity == "info" for f in proto), \
        f"shipped protocols must verify desync-free, got {proto}"
    planted = syncproto.planted_counterexamples()
    assert len(planted) == len(syncproto.MODELS) and \
        all(f.severity == "error" for f in planted), \
        f"planted protocol variants must desync, got {planted}"

    print(f"synclint selftest OK: {len(planted_hlo)} planted HLO "
          f"incongruences, 2 planted desync sites, "
          f"{len(planted)} planted protocol counterexamples all caught; "
          "clean twins quiet")
    return 0


def main() -> int:
    args = build_parser().parse_args()

    if args.selftest:
        return _selftest()

    from pytorch_distributed_tpu.analysis import synclint
    from pytorch_distributed_tpu.analysis import (
        load_baseline,
        render_table,
    )

    if args.list:
        import jax  # noqa: F401
        jax.config.update("jax_platforms", "cpu")
        from pytorch_distributed_tpu.analysis import core
        for name in core.RECIPES:
            print(name)
        print("sync-scopes")
        print("sync-protocols")
        return 0

    names = args.steps.split(",") if args.steps else None
    reports = []

    if not args.no_hlo:
        if args.hlo_cache:
            reports.extend(synclint.sweep_cached(args.hlo_cache, names))
        else:
            import jax  # noqa: F401
            jax.config.update("jax_platforms", "cpu")
            jax.config.update("jax_threefry_partitionable", True)
            reports.extend(synclint.sweep(names))

    if not args.no_ast:
        reports.append(synclint.lint_sync_scopes())
    if not args.no_proto:
        reports.append(synclint.check_protocols())

    hlo_reports = [r for r in reports if r.sync_digest]

    if args.update_baseline:
        # JSON-level patch: only the sync_digest keys change, so the
        # collective/memory budgets shardlint pinned stay byte-identical.
        baseline = (load_baseline(args.baseline)
                    if os.path.exists(args.baseline) else {})
        patched = 0
        for r in hlo_reports:
            baseline.setdefault(r.name, {})["sync_digest"] = r.sync_digest
            patched += 1
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"patched {patched} schedule digest(s) into {args.baseline}")
    elif not args.no_baseline and hlo_reports:
        baseline = (load_baseline(args.baseline)
                    if os.path.exists(args.baseline) else {})
        if not baseline:
            print(f"note: no baseline at {args.baseline}; run "
                  "--update-baseline to pin schedule digests")
        for r in hlo_reports:
            entry = baseline.get(r.name)
            for f in synclint.diff_digest(r, entry):
                r.add(f)

    print(render_table(reports))
    if args.json:
        with open(args.json, "w") as f:
            json.dump([r.to_dict() for r in reports], f, indent=2)
            f.write("\n")
        print(f"wrote {args.json}")

    n_err = sum(len(r.errors()) for r in reports)
    if n_err:
        print(f"synclint: {n_err} error finding(s)", file=sys.stderr)
        return 1
    print("synclint: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
