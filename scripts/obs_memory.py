#!/usr/bin/env python
"""obs_memory: render per-step HBM memory ledgers (obs/memory.py).

Reads either ``mem_ledger.json`` files (scripts/shardlint.py --mem-ledger,
or a trainer's ``--mem-ledger`` emission) or raw post-optimization HLO
text dumps (``*.hlo``/``*.txt`` — anything else is treated as a ledger
JSON), and prints the watermark peak, the measured-vs-static residual,
the class/phase breakdown, and the top-k live buffers at the high-water
mark.  Pure text parsing end to end — no jax import — so it runs on a
login host with only the dump files, same contract as obs_timeline.py.

Usage:
  python scripts/obs_memory.py mem_ledger.json                # text report
  python scripts/obs_memory.py dump.hlo --top-k 20            # from raw HLO
  python scripts/obs_memory.py mem_ledger.json --step lm_train_dp \\
      --json report.json
  python scripts/obs_memory.py --selftest        # fixture ledger, no jax
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.obs import memory  # noqa: E402

FIXTURE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "tests", "data", "mem_fixture.hlo")

# Deterministic 10-instruction module: 3 args (params/opt_state/data), a
# forward dot, a backward grad fusion, a grad_sync all-reduce, an
# optimizer fusion written straight into the donated output, and a scalar
# loss reduce.  Every ledger number it produces is hand-computable — see
# selftest() for the full derivation.
_FIXTURE_HLO = """\
HloModule jit_step, is_scheduled=true, entry_computation_layout={(f32[64,64]{1,0}, f32[64,64]{1,0}, f32[16,64]{1,0})->(f32[64,64]{1,0}, f32[])}, input_output_alias={ {0}: (0, {}, may-alias) }, num_partitions=4

%region_0.20 (Arg_0.21: f32[], Arg_1.22: f32[]) -> f32[] {
  %Arg_0.21 = f32[] parameter(0)
  %Arg_1.22 = f32[] parameter(1)
  ROOT %add.23 = f32[] add(f32[] %Arg_0.21, f32[] %Arg_1.22)
}

%fused_computation (param_0.1: f32[16,64], param_1.1: f32[16,64]) -> f32[64,64] {
  %param_0.1 = f32[16,64]{1,0} parameter(0)
  %param_1.1 = f32[16,64]{1,0} parameter(1)
  ROOT %dot.11 = f32[64,64]{1,0} dot(f32[16,64]{1,0} %param_1.1, f32[16,64]{1,0} %param_0.1), lhs_contracting_dims={0}, rhs_contracting_dims={0}
}

%fused_computation.1 (param_0.2: f32[64,64], param_1.2: f32[64,64], param_2.2: f32[64,64]) -> f32[64,64] {
  %param_0.2 = f32[64,64]{1,0} parameter(0)
  %param_1.2 = f32[64,64]{1,0} parameter(1)
  %param_2.2 = f32[64,64]{1,0} parameter(2)
  %multiply.12 = f32[64,64]{1,0} multiply(f32[64,64]{1,0} %param_0.2, f32[64,64]{1,0} %param_2.2)
  ROOT %subtract.13 = f32[64,64]{1,0} subtract(f32[64,64]{1,0} %param_1.2, f32[64,64]{1,0} %multiply.12)
}

ENTRY %main.10 (p0.1: f32[64,64], p1.2: f32[64,64], p2.3: f32[16,64]) -> (f32[64,64], f32[]) {
  %p0.1 = f32[64,64]{1,0} parameter(0), metadata={op_name="jit(step)/jit(main)/params"}
  %p1.2 = f32[64,64]{1,0} parameter(1), metadata={op_name="jit(step)/jit(main)/momentum"}
  %p2.3 = f32[16,64]{1,0} parameter(2), metadata={op_name="jit(step)/jit(main)/batch"}
  %constant.4 = f32[] constant(0), metadata={op_name="jit(step)/jit(main)/loss/zero"}
  %dot.5 = f32[16,64]{1,0} dot(f32[16,64]{1,0} %p2.3, f32[64,64]{1,0} %p0.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/jit(main)/jvp(step)/dense" source_file="pytorch_distributed_tpu/train/steps.py" source_line=40}
  %fusion.6 = f32[64,64]{1,0} fusion(f32[16,64]{1,0} %dot.5, f32[16,64]{1,0} %p2.3), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/jit(main)/transpose(jvp(step))/dense" source_file="pytorch_distributed_tpu/train/steps.py" source_line=40}
  %all-reduce.7 = f32[64,64]{1,0} all-reduce(f32[64,64]{1,0} %fusion.6), channel_id=1, replica_groups=[1,4]<=[4], use_global_device_ids=true, to_apply=%region_0.20, metadata={op_name="jit(step)/jit(main)/grad_sync/psum" source_file="pytorch_distributed_tpu/train/steps.py" source_line=55}
  %reduce.8 = f32[] reduce(f32[16,64]{1,0} %dot.5, f32[] %constant.4), dimensions={0,1}, to_apply=%region_0.20, metadata={op_name="jit(step)/jit(main)/loss/reduce_sum" source_file="pytorch_distributed_tpu/train/steps.py" source_line=47}
  %fusion.9 = f32[64,64]{1,0} fusion(f32[64,64]{1,0} %all-reduce.7, f32[64,64]{1,0} %p0.1, f32[64,64]{1,0} %p1.2), kind=kLoop, calls=%fused_computation.1, metadata={op_name="jit(step)/jit(main)/optimizer/sgd" source_file="pytorch_distributed_tpu/train/steps.py" source_line=60}
  ROOT %tuple.10 = (f32[64,64]{1,0}, f32[]) tuple(f32[64,64]{1,0} %fusion.9, f32[] %reduce.8)
}
"""


def _mib(b) -> str:
    return f"{float(b) / 2**20:.3f}"


def _ledger_dicts(paths, top_k):
    """``{step: ledger_dict}`` across the inputs.  HLO dumps are ledgered
    on the spot; JSON files contribute their serialized dicts verbatim
    (the stored class/phase breakdowns are authoritative — recomputing
    them from a truncated top-k buffer list would under-report)."""
    out = {}
    for p in paths:
        if p.endswith((".hlo", ".txt")):
            step = os.path.splitext(os.path.basename(p))[0]
            with open(p) as f:
                led = memory.ledger_from_hlo_text(f.read(), step=step)
            out[step] = led.to_dict(top_k=top_k)
        else:
            with open(p) as f:
                data = json.load(f)
            for step, d in data.items():
                out[step] = d
    return out


def _report_text(step, d, top_k):
    measured = d.get("measured_peak_bytes", 0.0)
    lines = [f"ledger {step}: peak {_mib(d['peak_bytes'])} MiB at instr "
             f"{d['peak_index']}/{d['n_instructions']}"
             + (f"  (measured {_mib(measured)} MiB, residual "
                f"{d.get('residual_pct', 0.0):.2f}%)" if measured else "")]
    lines.append(
        f"  argument {_mib(d['argument_bytes'])} MiB"
        f" + output {_mib(d['output_bytes'])} MiB"
        f" + temps {_mib(d['peak_bytes'] - d['argument_bytes'] - d['output_bytes'])} MiB"
        f"  (donated {_mib(d['donated_bytes'])} MiB)")
    cp = d.get("class_peaks", {})
    if cp:
        lines.append("  by class (MiB): " + "  ".join(
            f"{k}={_mib(v)}" for k, v in sorted(
                cp.items(), key=lambda kv: -kv[1])))
    pp = d.get("phase_peaks", {})
    if pp:
        lines.append("  by phase (MiB): " + "  ".join(
            f"{k}={_mib(v)}" for k, v in sorted(
                pp.items(), key=lambda kv: -kv[1])))
    for b in d.get("top", [])[:top_k]:
        dims = ",".join(str(x) for x in b.get("dims", []))
        lines.append(
            f"  top: {b['name']:<24} {_mib(b['bytes']):>10} MiB"
            f"  {b.get('dtype', '')}[{dims}]  {b.get('klass', '')}"
            + (f" ({b['phase']})" if b.get("phase") else ""))
    wm = d.get("watermark", [])
    if wm:
        lines.append(f"  watermark: {len(wm)} change points "
                     f"(low {_mib(min(v for _, v in wm))} MiB, "
                     f"high {_mib(max(v for _, v in wm))} MiB)")
    return lines


def make_fixture(path: str) -> None:
    """Write the deterministic HLO module used by --selftest and the
    tests."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        f.write(_FIXTURE_HLO)


def selftest() -> int:
    """Ledger the checked-in fixture and check every number against the
    hand derivation.  Schedule (entry computation, 10 instructions):

      idx 0-2  parameters: params 16384 B, opt_state 16384 B, data 4096 B
      idx 3    constant.4      4 B temp, live [3, 7] (reduce reads it)
      idx 4    dot.5        4096 B temp, live [4, 7]   (forward)
      idx 5    fusion.6    16384 B temp, live [5, 6]   (backward grad)
      idx 6    all-reduce.7 16384 B temp, live [6, 8]  (grad_sync scratch)
      idx 7    reduce.8    -> written into the output allocation
      idx 8    fusion.9    -> written into the output allocation
      idx 9    ROOT tuple  -> the output allocation itself

    Constant terms: argument 36864 B, output 16388 B (donation: param 0).
    Temp curve peaks at idx 6 (4 + 4096 + 16384 + 16384 = 36868 B), so
    peak = 36864 + 16388 + 36868 = 90120 B."""
    path = FIXTURE
    if not os.path.exists(path):  # regenerate if the fixture went missing
        make_fixture(path)
    with open(path) as f:
        led = memory.ledger_from_hlo_text(
            f.read(), step="fixture", mesh_shape={"data": 4},
            arg_classes=["params", "opt_state", "data"])

    assert led.n_instructions == 10, led.n_instructions
    assert led.argument_bytes == 36864, led.argument_bytes
    assert led.output_bytes == 16388, led.output_bytes
    assert led.donated_bytes == 16384, led.donated_bytes
    assert led.peak_bytes == 90120, led.peak_bytes
    assert led.peak_index == 6, led.peak_index
    assert led.temp_peak_bytes == 36868, led.temp_peak_bytes
    base = 53252  # argument + output
    assert led.watermark == [
        [0, base], [3, base + 4], [4, base + 4100], [5, base + 20484],
        [6, base + 36868], [7, base + 20484], [8, base + 16384],
        [9, base]], led.watermark

    cls = led.class_peaks()
    assert cls == {"params": 16384, "opt_state": 16384, "data": 4096,
                   "activations": 20484, "collective": 16384,
                   "output": 16388}, cls
    ph = led.phase_peaks()
    assert ph == {"resident": base, "forward": 4100, "backward": 16384,
                  "grad_sync": 16384}, ph

    top = led.top_buffers(3)
    assert [b.name for b in top] == \
        ["(outputs)", "all-reduce.7", "fusion.6"], [b.name for b in top]
    ar = top[1]
    assert ar.klass == "collective" and ar.phase == "grad_sync", ar
    assert ar.source.endswith("steps.py:55"), ar.source

    # serialization round-trips the scalar fences
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "mem_ledger.json")
        memory.write_ledgers(p, [led])
        back = memory.load_ledgers(p)["fixture"]
        assert back.peak_bytes == led.peak_bytes
        assert back.watermark == led.watermark
        assert back.mesh_shape == {"data": 4}

    # the counter-track export spans [t0, t1] and ends at the last point
    evs = memory.watermark_counter_events(led, 100.0, 1000.0, pid=3)
    assert len(evs) == len(led.watermark), evs
    assert evs[0]["ts"] == 100.0 and evs[-1]["ts"] == 1000.0, evs
    assert evs[0]["args"]["bytes"] == base, evs[0]
    assert max(e["args"]["bytes"] for e in evs) == 90120, evs

    print("obs_memory selftest OK: watermark/classes/phases/top/round-trip"
          " all verified on the checked-in fixture")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("inputs", nargs="*",
                    help="mem_ledger.json files and/or raw HLO text dumps "
                         "(*.hlo / *.txt)")
    ap.add_argument("--step", default=None,
                    help="only report this step name")
    ap.add_argument("--top-k", type=int, default=10,
                    help="live buffers to list at the peak (default 10)")
    ap.add_argument("--json", default=None, metavar="PATH",
                    help="write the merged {step: ledger} dict as JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="verify the ledger math on the checked-in HLO "
                         "fixture and exit (no jax, no inputs needed)")
    ap.add_argument("--make-fixture", default=None, metavar="PATH",
                    help="write the deterministic HLO module used by "
                         "--selftest and the tests, then exit")
    args = ap.parse_args(argv)

    if args.make_fixture:
        make_fixture(args.make_fixture)
        print(f"wrote HLO fixture to {args.make_fixture}")
        return 0
    if args.selftest:
        return selftest()
    if not args.inputs:
        ap.error("no inputs given (pass mem_ledger.json or *.hlo dumps)")

    ledgers = _ledger_dicts(args.inputs, args.top_k)
    if args.step:
        if args.step not in ledgers:
            raise SystemExit(f"step {args.step!r} not found; "
                             f"has: {sorted(ledgers)}")
        ledgers = {args.step: ledgers[args.step]}

    for step in sorted(ledgers):
        print("\n".join(_report_text(step, ledgers[step], args.top_k)))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(ledgers, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
