#!/usr/bin/env python
"""chaoskit — fault-injection toolkit for the FT subsystem (ft/).

Commands:

- ``corrupt PATH``   deterministic byte-level corruption (``--mode flip``
  flips seed-chosen bits; ``--mode truncate`` cuts the file) — the storage
  half of a chaos drill: corrupt the latest checkpoint, re-run ``--resume``,
  and watch the loader fall back to ``checkpoint.prev.msgpack``;
- ``verify PATH``    sha256 sidecar check (exit 0 = intact, 1 = corrupt,
  also 0 with a note when no sidecar exists — legacy file);
- ``seal PATH``      write/refresh the sidecar for an existing file (adopt
  a pre-FT checkpoint into the verified world);
- ``drill shrink|grow|hang|alert|serve``  run an end-to-end drill on a tiny
  synthetic LM: ``shrink`` loses a rank at a seed-deterministic step and
  continues at world N−1; ``grow`` re-admits it later and finishes back
  at world N (exit 0 iff every expected ``remesh`` event was committed);
  ``hang`` (ISSUE 13) stalls a rank inside the collective region and
  passes iff the hang watchdog flags it, the flight recorder dumps
  pre-mortem, and ``postmortem.py`` names the stalled rank; ``alert``
  (ISSUE 14) injects a ``DelayRank`` slowdown under a step-time rule, a
  silent phantom rank under a dead-rank rule, and a 20-day-stale bench
  LKG under a staleness rule, and passes iff every one raises its
  matching alert *live* (scraped off the rank's ``/metrics`` exporter or
  booked by ``obs_live --once``) and lands as an ``alert`` ft_event that
  goodput and ``obs_report`` fold; ``serve`` (ISSUE 15) drags the
  continuous-batching serving engine with a ``DelayRank`` straggler
  mid-soak so first-token latency blows through a ``ttft_p99`` rule's
  ceiling, and passes iff the alert is booked live as an ``alert``
  ft_event in the serving JSONL and ``obs_report`` folds the serving
  section; ``desync`` (ISSUE 18) plants one rank-divergent branch and
  demands BOTH detectors catch it: synclint's host desync pass + protocol
  model check statically (pre-launch), and — because a rank that diverges
  away from a collective looks exactly like a stalled rank to its peers —
  the hang watchdog / flight recorder / postmortem live; ``replica-kill``
  (ISSUE 19) SIGKILLs a serving replica mid-decode behind the fleet
  router and passes iff every admitted request completes exactly once
  with tokens bit-exact vs an unkilled baseline, ttft_p99 holds, and the
  ``replica_down`` ft_event + alert land in the router JSONL;
  ``router-restart`` (ISSUE 19) SIGKILLs the router itself mid-run,
  restarts it, and passes iff client replays complete exactly once —
  the replicas' idempotent rid caches (or deterministic recompute)
  absorb the lost ledger; ``slow-loader`` (ISSUE 20) injects a loader
  stall under ``--step-attr`` and passes iff the attribution plane
  names ``data_wait`` dominant (the stall must not be blamed on the
  device), the ``data_wait_share`` alert fires live on ``/metrics``
  and books as an ``alert`` ft_event, and the jax-free
  ``obs_roofline.py`` + ``obs_report.py`` fold the same verdict from
  the JSONL alone.  Mesh drills import jax lazily inside them;
  the fleet drills never touch jax at all (subprocess sim replicas).
  Every drill kind shares the ``--seed`` contract: the injection step
  comes from ``drill_plan(seed, steps)``, so the same seed reproduces
  the same schedule across kinds and runs;
- ``--selftest``     the fast no-mesh CI path (tier-1, like
  ``shardlint.py --selftest`` / ``obs_report.py --selftest``): sidecar
  round-trip, flip/truncate detection, corruption determinism, retry
  backoff, drill-plan determinism, membership-injector latching — no
  devices.

Signal/NaN/delay injectors live in ``pytorch_distributed_tpu.ft.chaos`` and
are installed programmatically (``chaos=`` on either trainer); this CLI
covers the parts that act on files from outside a run, plus the drill
runner above.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.ft.chaos import corrupt_file  # noqa: E402
from pytorch_distributed_tpu.ft.integrity import (  # noqa: E402
    retrying,
    sidecar_path,
    verify_sidecar,
    write_sidecar,
)


def cmd_corrupt(args) -> int:
    info = corrupt_file(args.path, mode=args.mode, seed=args.seed,
                        nbytes=args.nbytes)
    print(f"corrupted '{args.path}': {info}")
    if verify_sidecar(args.path) is None:
        print("note: no sha256 sidecar — a loader cannot detect this "
              "corruption before deserialization")
    return 0


def cmd_verify(args) -> int:
    ok = verify_sidecar(args.path)
    if ok is None:
        print(f"'{args.path}': no sidecar ({sidecar_path(args.path)} "
              "missing) — legacy/unverified file")
        return 0
    if ok:
        print(f"'{args.path}': sha256 OK")
        return 0
    print(f"'{args.path}': CORRUPT (sha256 mismatch vs sidecar)")
    return 1


def cmd_seal(args) -> int:
    side = write_sidecar(args.path)
    print(f"wrote '{side}'")
    return 0


def drill_plan(seed: int, steps: int):
    """Seed-deterministic (lose_step, join_step) for the elastic drill —
    same seed, same schedule, every time (the chaoskit contract)."""
    import random

    rng = random.Random(int(seed))
    if steps < 8:
        raise ValueError(f"drill needs >= 8 steps, got {steps}")
    lose = rng.randrange(2, steps // 2)
    join = rng.randrange(lose + 2, steps - 1)
    return lose, join


def cmd_drill(args) -> int:
    """End-to-end elastic drill on the tiny synthetic LM (the only
    chaoskit command that touches devices; jax imported here, lazily)."""
    # fleet drills run on subprocess sim replicas — no mesh, no devices;
    # dispatch before the jax/trainer imports below.
    if args.kind == "replica-kill":
        return _drill_replica_kill(args)
    if args.kind == "router-restart":
        return _drill_router_restart(args)
    import jax

    from pytorch_distributed_tpu.ft import (
        ChaosSchedule,
        ElasticSim,
        JoinRankAt,
        LoseRankAt,
    )
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    if args.kind == "hang":
        return _drill_hang(args)
    if args.kind == "desync":
        return _drill_desync(args)
    if args.kind == "alert":
        return _drill_alert(args)
    if args.kind == "serve":
        return _drill_serve(args)
    if args.kind == "trace":
        return _drill_trace(args)
    if args.kind == "slow-loader":
        return _drill_slow_loader(args)
    world = args.world
    if world < 2 or world > len(jax.devices()):
        print(f"need 2 <= --world <= {len(jax.devices())} devices, "
              f"got {world}")
        return 2
    lose_step, join_step = drill_plan(args.seed, args.steps)
    victim = world - 1
    injectors = [LoseRankAt(lose_step, rank=victim, reason="drill")]
    want = [("shrink", world, world - 1)]
    if args.kind == "grow":
        injectors.append(JoinRankAt(join_step, rank=victim, reason="drill"))
        want.append(("grow", world - 1, world))
    print(f"drill {args.kind}: world {world}, lose rank {victim} at step "
          f"{lose_step}" + (f", re-admit at step {join_step}"
                            if args.kind == "grow" else ""))

    mesh = build_mesh(MeshSpec(("data",), (world,)),
                      devices=jax.devices()[:world])
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(length=256, seq_len=16, vocab=64,
                               seed=args.seed)
    # global batch divisible by both worlds N and N-1
    batch = world * (world - 1)
    sim = ElasticSim(world, min_ranks=1)
    t = LMTrainer(model, mesh, ds, batch_size=batch, lr=1e-2,
                  seed=args.seed, save_steps=2, prefetch=0,
                  elastic=sim, chaos=ChaosSchedule(*injectors))
    loss = t.fit(args.steps, print_freq=max(1, args.steps // 4))
    got = [(c.kind, c.old.world, c.new.world) for c in sim.history]
    print(f"final loss {loss:.4f}; remesh events {got}")
    if got != want:
        print(f"FAIL: expected {want}")
        return 1
    print(f"drill {args.kind}: OK")
    return 0


def _drill_hang(args) -> int:
    """Stalled-collective drill (ISSUE 13): ``HangAt`` stalls rank 0
    inside the collective region for several watchdog windows; the hang
    watchdog must emit a ``hang`` ft_event, dump the flight ring
    pre-mortem, and ``postmortem.py`` must name the rank with its
    last-entered collective."""
    import tempfile

    import jax

    from pytorch_distributed_tpu.ft import ChaosSchedule
    from pytorch_distributed_tpu.ft.chaos import HangAt
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs import flightrec
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    world = min(args.world, len(jax.devices()))
    # reuse the seeded elastic plan: the lose step doubles as the stall
    # step, so `--seed` drives every drill kind the same way
    hang_step, _ = drill_plan(args.seed, args.steps)
    timeout = args.hang_timeout
    stall = max(4.0 * timeout, 0.5)  # several watchdog windows
    out = args.out or tempfile.mkdtemp(prefix="hang-drill-")
    print(f"drill hang: world {world}, stall rank 0 at step {hang_step} "
          f"for {stall:.1f}s (watchdog timeout {timeout:.1f}s), dumps in "
          f"'{out}'")

    mesh = build_mesh(MeshSpec(("data",), (world,)),
                      devices=jax.devices()[:world])
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(length=256, seq_len=16, vocab=64,
                               seed=args.seed)
    t = LMTrainer(model, mesh, ds, batch_size=world, lr=1e-2,
                  seed=args.seed, prefetch=0, hb_dir=out,
                  chaos=ChaosSchedule(HangAt(hang_step, stall, rank=0)),
                  # the comm ledger labels the ring's collective region,
                  # so the verdict can name the dominant collective
                  comm_ledger=os.path.join(out, "comm_ledger.json"),
                  flight_rec=out, hang_timeout=timeout)
    loss = t.fit(args.steps, print_freq=max(1, args.steps // 4))

    ok = True
    if t._hang_wd is None or t._hang_wd.hangs < 1:
        print("FAIL: hang watchdog never fired")
        ok = False
    dumps = flightrec.find_dumps(out)
    if 0 not in dumps:
        print(f"FAIL: no flight dump for rank 0 in '{out}'")
        ok = False
    if ok:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import postmortem as pm

        report = pm.postmortem(out)
        print(pm.render_text(report))
        if report.get("hang_ranks") != [0]:
            print(f"FAIL: expected hang_ranks [0], got "
                  f"{report.get('hang_ranks')}")
            ok = False
        if "rank 0" not in (report.get("verdict") or ""):
            print(f"FAIL: verdict does not name rank 0: "
                  f"{report.get('verdict')!r}")
            ok = False
    if not ok:
        return 1
    print(f"final loss {loss:.4f}; hang flagged at step {hang_step}, "
          f"{len(dumps)} rank dump(s)")
    print("drill hang: OK")
    return 0


def _drill_desync(args) -> int:
    """Desync drill (ISSUE 18): one planted rank-divergent branch, two
    detectors.  Statically, synclint's host desync pass must flag the
    branch pre-launch (the collective guarded by a rank-/data-dependent
    predicate with no agreement point), and the protocol model check
    must produce the matching counterexample.  Live, the divergence is
    executed at the same seed-chosen step (``drill_plan`` — the shared
    ``--seed`` contract): the divergent rank never enters the collective
    its peers are blocked in, which to those peers is indistinguishable
    from a stall — so the live verdict is exactly the hang drill's
    watchdog + flight-recorder + postmortem signature."""
    from pytorch_distributed_tpu.analysis import synclint, syncproto

    findings = synclint.planted_desync_findings()
    errs = [f for f in findings if f.severity == "error"]
    print(f"desync static: synclint flags {len(errs)} planted branch(es)")
    for f in errs:
        print(f"  {f}")
    if len(errs) != 2:
        print("FAIL: synclint must flag both planted divergent branches")
        return 1
    if not any("rank-dependent" in f.message for f in errs) or \
            not any("locally-data-dependent" in f.message for f in errs):
        print("FAIL: expected one rank-dependent and one "
              "locally-data-dependent finding")
        return 1
    planted = syncproto.planted_counterexamples()
    cex = [f for f in planted if "preempt" in f.where]
    print(f"desync static: protocol explorer reproduces the hang: "
          f"{cex[0].message if cex else 'MISSING'}")
    if not cex:
        print("FAIL: protocol model check lost the preempt counterexample")
        return 1

    print("desync live: executing the divergence — the divergent rank "
          "skips the collective its peers are blocked in; the watchdog "
          "+ flight recorder must name it")
    rc = _drill_hang(args)
    if rc != 0:
        return rc
    print("drill desync: OK (static synclint + live flight recorder "
          "both caught the divergent branch)")
    return 0


def _drill_alert(args) -> int:
    """Live telemetry-plane drill (ISSUE 14): three injected faults, each
    of which must raise its matching declarative alert *while the run is
    live*, not in a post-hoc report:

    - ``DelayRank`` drags every step past a ``step_time_p95`` rule's
      p50 ceiling → the alert must appear on the rank's ``/metrics``
      exporter (``ptd_alert_firing``) mid-run and as an ``alert``
      ft_event in the JSONL;
    - a planted 20-day-stale ``BENCH_LKG.json`` under a ``bench_stale``
      rule → booked by the trainer-side engine's lazy bench check;
    - a phantom rank whose heartbeat went silent 120 s ago under a
      ``dead_rank`` rule → a killed rank can never book its own death,
      so ``obs_live --once`` must book it (exit 1) into the same JSONL.

    Passes iff all three land and goodput + ``obs_report`` fold them.
    """
    import json as _json
    import socket
    import subprocess
    import tempfile
    import threading
    import time as _time
    import urllib.request
    from datetime import datetime, timedelta, timezone

    import jax

    from pytorch_distributed_tpu.ft import ChaosSchedule
    from pytorch_distributed_tpu.ft.chaos import DelayRank
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs.alerts import (
        dead_ranks_from_events,
        summarize_alerts,
    )
    from pytorch_distributed_tpu.obs.export import parse_prometheus
    from pytorch_distributed_tpu.obs.goodput import compute_goodput
    from pytorch_distributed_tpu.obs.metrics import read_metrics
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    world = min(args.world, len(jax.devices()))
    out = args.out or tempfile.mkdtemp(prefix="alert-drill-")
    os.makedirs(out, exist_ok=True)

    # fault 1 of 3: a benchmark LKG captured 20 days ago (events file
    # deliberately absent so nothing can refresh it)
    stamp = (datetime.now(timezone.utc)
             - timedelta(days=20)).strftime("%Y-%m-%dT%H:%M:%S%z")
    lkg = os.path.join(out, "BENCH_LKG.json")
    with open(lkg, "w") as f:
        _json.dump({"metric": "drill_tokens_per_s", "value": 1.0,
                    "captured_at": stamp}, f)

    delay = 0.15  # fault 2 of 3: DelayRank, lands in every measured step
    rules_path = os.path.join(out, "rules.json")
    with open(rules_path, "w") as f:
        _json.dump({"rules": [
            # p50 quantile + warmup: robust against the compile-step
            # outlier; 60 ms ceiling vs a 150 ms injected floor
            {"kind": "step_time_p95", "name": "step_time",
             "severity": "warn", "quantile": "p50", "max_ms": 60.0,
             "warmup_steps": 4},
            {"kind": "dead_rank", "severity": "page", "max_age_s": 30.0},
            {"kind": "bench_stale", "severity": "warn", "max_days": 14.0,
             "lkg_path": lkg,
             "events_path": os.path.join(out, "bench_events.jsonl")},
        ]}, f, indent=2)

    with socket.socket() as s:  # free localhost port for the exporter
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    mpath = os.path.join(out, "metrics.jsonl")
    print(f"drill alert: world {world}, DelayRank({delay:.2f}s) vs 60ms "
          f"p50 ceiling, exporter on :{port}, artifacts in '{out}'")

    mesh = build_mesh(MeshSpec(("data",), (world,)),
                      devices=jax.devices()[:world])
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(length=256, seq_len=16, vocab=64,
                               seed=args.seed)
    t = LMTrainer(model, mesh, ds, batch_size=world, lr=1e-2,
                  seed=args.seed, prefetch=0, hb_dir=out,
                  metrics_jsonl=mpath, metrics_port=port,
                  alerts=rules_path,
                  chaos=ChaosSchedule(DelayRank(delay)))
    t.obs.flush_every = 1  # short run: sinks must see every step live

    # scrape the rank-0 exporter concurrently with fit(): the step-time
    # alert must be visible on /metrics while the run is still going
    seen = {"firing": set(), "scrapes": 0}
    stop = threading.Event()

    def _scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1.0) as r:
                    samples = parse_prometheus(
                        r.read().decode("utf-8", "replace"))
                seen["scrapes"] += 1
                for name, lab, v in samples:
                    if name == "ptd_alert_firing" and v:
                        seen["firing"].add(lab.get("rule"))
            except Exception:
                pass
            stop.wait(0.2)

    th = threading.Thread(target=_scrape, daemon=True)
    th.start()
    loss = t.fit(args.steps, print_freq=max(1, args.steps // 4))
    stop.set()
    th.join(timeout=2.0)

    ok = True
    if "step_time" not in seen["firing"]:
        print(f"FAIL: live scrape never saw ptd_alert_firing{{rule="
              f"\"step_time\"}} ({seen['scrapes']} scrape(s), saw "
              f"{sorted(seen['firing'])})")
        ok = False
    booked = {str(e.get("alert")) for e in read_metrics(mpath)
              if e.get("ft_event") == "alert"}
    for want in ("step_time", "bench_stale"):
        if want not in booked:
            print(f"FAIL: no '{want}' alert ft_event in '{mpath}' "
                  f"(booked: {sorted(booked)})")
            ok = False

    # fault 3 of 3: a phantom rank that stopped beating 120 s ago — only
    # the aggregator can book its death
    phantom = world
    with open(os.path.join(out, f"heartbeat-{phantom:05d}.jsonl"),
              "w") as f:
        f.write(_json.dumps({"pid": phantom, "step": 0,
                             "t": _time.time() - 120.0,
                             "world": world + 1}) + "\n")
    live = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "obs_live.py")
    proc = subprocess.run(
        [sys.executable, live, "--hb-dir", out, "--rules", rules_path,
         "--alerts-jsonl", mpath, "--once"],
        capture_output=True, text=True)
    print(proc.stdout, end="")
    if proc.returncode != 1:
        print(f"FAIL: obs_live --once exited {proc.returncode} (want 1 "
              f"= alert firing); stderr: {proc.stderr.strip()}")
        ok = False

    records = read_metrics(mpath)
    dead = dead_ranks_from_events(records)
    if phantom not in dead:
        print(f"FAIL: obs_live did not book a dead_rank alert for rank "
              f"{phantom} (got {sorted(dead)})")
        ok = False
    gp = compute_goodput(records)
    if gp.alerts < 3:
        print(f"FAIL: goodput folded {gp.alerts} alert(s), want >= 3")
        ok = False
    summary = "\n".join(summarize_alerts(records))
    if "== alerts ==" not in summary or "dead_rank" not in summary:
        print(f"FAIL: alerts summary incomplete:\n{summary}")
        ok = False
    rep = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "obs_report.py"), "--metrics-jsonl", mpath],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if "== alerts ==" not in rep.stdout:
        print(f"FAIL: obs_report did not fold the alerts section "
              f"(rc {rep.returncode})")
        ok = False
    if not ok:
        return 1
    print(f"final loss {loss:.4f}; alerts live-scraped "
          f"{sorted(seen['firing'])}, booked {sorted(booked | {'dead_rank'})}, "
          f"goodput folded {gp.alerts}")
    print("drill alert: OK")
    return 0


def _drill_slow_loader(args) -> int:
    """Input-starvation drill (ISSUE 20): a ``SlowLoader`` injector
    sleeps in the batch path — inside the step-attribution ``data_wait``
    window — so a ``--step-attr`` run must *measure* the stall as data
    wait, not blame the device.  Passes iff:

    - the attribution plane names ``data_wait`` the dominant component
      and the identity still reconciles (recon err <= 0.5% of step p50);
    - the ``data_wait_share`` alert fires live on the rank's ``/metrics``
      exporter (``ptd_alert_firing``) and lands as an ``alert`` ft_event
      in the JSONL;
    - the jax-free ``obs_roofline.py`` names the same bottleneck from
      the JSONL alone, and ``obs_report`` folds the attribution section.
    """
    import json as _json
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import jax

    from pytorch_distributed_tpu.ft import ChaosSchedule
    from pytorch_distributed_tpu.ft.chaos import SlowLoader
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs import stepattr as stepattr_mod
    from pytorch_distributed_tpu.obs.export import parse_prometheus
    from pytorch_distributed_tpu.obs.metrics import read_metrics
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    world = min(args.world, len(jax.devices()))
    out = args.out or tempfile.mkdtemp(prefix="slow-loader-drill-")
    os.makedirs(out, exist_ok=True)

    delay = 0.05  # injected per-step loader stall, dwarfs the tiny LM step
    rules_path = os.path.join(out, "rules.json")
    with open(rules_path, "w") as f:
        _json.dump({"rules": [
            {"kind": "data_wait_share", "name": "data_wait_share",
             "severity": "warn", "max_pct": 30.0, "warmup_steps": 2},
        ]}, f, indent=2)
    with socket.socket() as s:  # free localhost port for the exporter
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    mpath = os.path.join(out, "metrics.jsonl")
    print(f"drill slow-loader: world {world}, SlowLoader({delay:.2f}s) vs "
          f"30% data-wait ceiling, exporter on :{port}, artifacts in "
          f"'{out}'")

    mesh = build_mesh(MeshSpec(("data",), (world,)),
                      devices=jax.devices()[:world])
    model = TransformerLM(vocab_size=64, d_model=32, n_heads=2, n_layers=1)
    ds = SyntheticTokenDataset(length=256, seq_len=16, vocab=64,
                               seed=args.seed)
    t = LMTrainer(model, mesh, ds, batch_size=world, lr=1e-2,
                  seed=args.seed, prefetch=0, hb_dir=out,
                  metrics_jsonl=mpath, metrics_port=port,
                  alerts=rules_path, step_attr=True,
                  chaos=ChaosSchedule(SlowLoader(delay)))
    t.obs.flush_every = 1  # short run: sinks must see every step live

    # scrape the exporter concurrently with fit(): the alert AND the
    # ptd_attr_* gauges must be visible on /metrics while the run lives
    seen = {"firing": set(), "share": None, "scrapes": 0}
    stop = threading.Event()

    def _scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1.0) as r:
                    samples = parse_prometheus(
                        r.read().decode("utf-8", "replace"))
                seen["scrapes"] += 1
                for name, lab, v in samples:
                    if name == "ptd_alert_firing" and v:
                        seen["firing"].add(lab.get("rule"))
                    elif name == "ptd_attr_data_wait_share_pct":
                        seen["share"] = max(seen["share"] or 0.0, v)
            except Exception:
                pass
            stop.wait(0.2)

    th = threading.Thread(target=_scrape, daemon=True)
    th.start()
    loss = t.fit(args.steps, print_freq=max(1, args.steps // 4))
    stop.set()
    th.join(timeout=2.0)

    ok = True
    if "data_wait_share" not in seen["firing"]:
        print(f"FAIL: live scrape never saw ptd_alert_firing{{rule="
              f"\"data_wait_share\"}} ({seen['scrapes']} scrape(s), saw "
              f"{sorted(seen['firing'])})")
        ok = False
    if not seen["share"] or seen["share"] <= 30.0:
        print(f"FAIL: ptd_attr_data_wait_share_pct never exceeded the "
              f"30% ceiling on /metrics (max seen: {seen['share']})")
        ok = False
    records = read_metrics(mpath)
    booked = {str(e.get("alert")) for e in records
              if e.get("ft_event") == "alert"}
    if "data_wait_share" not in booked:
        print(f"FAIL: no 'data_wait_share' alert ft_event in '{mpath}' "
              f"(booked: {sorted(booked)})")
        ok = False
    summ = stepattr_mod.summarize(records)
    if summ is None or summ["dominant"] != "data_wait":
        print(f"FAIL: attribution must name data_wait dominant, got "
              f"{summ and summ['dominant']} (shares: "
              f"{summ and summ['shares_pct']})")
        ok = False
    elif summ["recon_err_pct_p50"] > 0.5:
        print(f"FAIL: identity recon err {summ['recon_err_pct_p50']:.3f}% "
              f"of step p50 breaches the 0.5% fence")
        ok = False

    # the jax-free CLI names the same bottleneck from the JSONL alone
    scripts_dir = os.path.dirname(os.path.abspath(__file__))
    roof = subprocess.run(
        [sys.executable, os.path.join(scripts_dir, "obs_roofline.py"),
         "--metrics-jsonl", mpath, "--json"],
        capture_output=True, text=True)
    try:
        doc = _json.loads(roof.stdout)
    except ValueError:
        doc = {}
    if roof.returncode != 0 or doc.get("dominant") != "data_wait":
        print(f"FAIL: obs_roofline --json rc {roof.returncode}, dominant "
              f"{doc.get('dominant')}; stderr: {roof.stderr.strip()}")
        ok = False
    rep = subprocess.run(
        [sys.executable, os.path.join(scripts_dir, "obs_report.py"),
         "--metrics-jsonl", mpath],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    if ("== attribution ==" not in rep.stdout
            or "dominant: data_wait" not in rep.stdout):
        print(f"FAIL: obs_report did not fold the attribution section "
              f"(rc {rep.returncode})")
        ok = False
    if not ok:
        return 1
    print(f"final loss {loss:.4f}; data-wait share p95 "
          f"{summ['data_wait_share_p95']:.1f}% (max scraped "
          f"{seen['share']:.1f}%), recon err "
          f"{summ['recon_err_pct_p50']:.3f}% of step p50, alert booked "
          f"live")
    print("drill slow-loader: OK")
    return 0


def _drill_serve(args) -> int:
    """Serving-plane drill (ISSUE 15): a ``DelayRank`` straggler drags
    every engine iteration of a continuous-batching soak, so queued
    requests' first tokens land far past a ``ttft_p99`` rule's ceiling.
    Passes iff the alert engine books a live ``ttft_p99`` alert ft_event
    into the serving JSONL, the run still completes every request, and
    ``obs_report`` folds the ``== serving ==`` section from the same
    file."""
    import json as _json
    import subprocess
    import tempfile

    from pytorch_distributed_tpu.ft import ChaosSchedule
    from pytorch_distributed_tpu.ft.chaos import DelayRank
    from pytorch_distributed_tpu.obs.alerts import AlertEngine, Rule
    from pytorch_distributed_tpu.obs.metrics import (
        MetricsLogger,
        read_metrics,
    )
    from pytorch_distributed_tpu.serving.engine import (
        ServingEngine,
        init_lm_params,
    )
    from pytorch_distributed_tpu.serving.loadgen import (
        LoadConfig,
        generate_load,
    )

    out = args.out or tempfile.mkdtemp(prefix="serve-drill-")
    os.makedirs(out, exist_ok=True)
    mpath = os.path.join(out, "serving.jsonl")
    delay = 0.05  # per-iteration straggler stall
    ceiling_ms = 25.0  # vs a >= 50ms injected TTFT floor
    n_requests = 12
    print(f"drill serve: DelayRank({delay:.2f}s/step) vs "
          f"{ceiling_ms:.0f}ms ttft_p99 ceiling, {n_requests} requests, "
          f"artifacts in '{out}'")

    params = init_lm_params(64, 32, 4, 1, block_size=8, seed=args.seed)
    obs = MetricsLogger(mpath, flush_every=1)
    alert_engine = AlertEngine(
        [Rule("ttft_p99", "ttft_p99", "page", {"max_ms": ceiling_ms})],
        emit=lambda **f: obs.log_event("alert", **f))
    obs.register(alert_engine.observe)

    eng = ServingEngine(
        params, vocab_size=64, d_model=32, n_heads=4, n_layers=1,
        max_batch=4, kv_blocks=32, block_size=8, blocks_per_seq=6,
        chunk_size=8, max_new_tokens=8, obs=obs,
        chaos=ChaosSchedule(DelayRank(delay)), seed=args.seed)
    load = generate_load(LoadConfig(n_requests=n_requests, rate_rps=200.0,
                                    seed=args.seed))
    for _, req in load:
        req.max_new_tokens = min(req.max_new_tokens, 8)
    try:
        summary = eng.run(load)
    finally:
        obs.close()

    ok = True
    if summary["completed"] != n_requests:
        print(f"FAIL: {summary['completed']}/{n_requests} requests "
              f"completed under the straggler")
        ok = False
    ttft = summary.get("ttft_p99_ms")
    if ttft is None or ttft <= ceiling_ms:
        print(f"FAIL: injected straggler did not breach the ceiling "
              f"(ttft_p99 {ttft} vs {ceiling_ms}ms)")
        ok = False
    booked = {str(e.get("alert")) for e in read_metrics(mpath)
              if e.get("ft_event") == "alert"}
    if "ttft_p99" not in booked:
        print(f"FAIL: no 'ttft_p99' alert ft_event in '{mpath}' "
              f"(booked: {sorted(booked)})")
        ok = False
    rep = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "obs_report.py"), "--metrics-jsonl", mpath],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    for needle in ("== serving ==", "== alerts =="):
        if needle not in rep.stdout:
            print(f"FAIL: obs_report did not fold {needle!r} "
                  f"(rc {rep.returncode})")
            ok = False
    if not ok:
        return 1
    print(_json.dumps({k: summary[k] for k in
                       ("completed", "tokens", "ttft_p99_ms",
                        "tokens_per_s")}, sort_keys=True))
    print(f"drill serve: ttft_p99 {ttft:.1f}ms > {ceiling_ms:.0f}ms "
          f"ceiling, alert booked live")
    print("drill serve: OK")
    return 0


class _PreemptStorm:
    """Chaos injector for ``drill trace``: from ``start`` on, evict the
    scheduler's preferred victim every ``every`` steps (duck-typed into
    the engine's ``chaos.on_step`` hook).  The guard keeps at least one
    lane live so the run always terminates."""

    def __init__(self, every: int = 1, start: int = 3):
        self.every, self.start = every, start

    def on_step(self, eng, step: int) -> None:
        if step < self.start or step % self.every:
            return
        if len(eng.sched.active) > 1:
            victim = eng.sched.pick_victim()
            if victim is not None:
                eng._preempt(victim)


def _drill_trace(args) -> int:
    """Request-tracing drill (ISSUE 17): a seeded preemption storm — a
    tiny KV pool under priority scheduling plus a ``_PreemptStorm``
    injector evicting a victim every step — thrashes every request
    through preempt/requeue/recompute.  Passes iff:

    - every request still completes (bit-exact recompute contract);
    - the per-request tail attribution (obs/reqtrace.py via
      ``obs_trace.py --json``) names **preempt_redo** as the dominant
      TTFT component — the storm is visible as *recompute thrash*, not
      mis-filed as queue wait;
    - the ``preempt_redo`` alert fires live on the rank's ``/metrics``
      exporter (``ptd_alert_firing`` + ``ptd_serving_attr_*`` gauges)
      and is booked as an ``alert`` ft_event in the JSONL;
    - ``obs_report`` folds the ``== traces ==`` section from the file.
    """
    import json as _json
    import socket
    import subprocess
    import tempfile
    import threading
    import urllib.request

    import numpy as np

    from pytorch_distributed_tpu.obs.alerts import AlertEngine, Rule
    from pytorch_distributed_tpu.obs.export import (
        MetricsExporter,
        parse_prometheus,
    )
    from pytorch_distributed_tpu.obs.metrics import (
        MetricsLogger,
        read_metrics,
    )
    from pytorch_distributed_tpu.obs.reqtrace import ReqTracer
    from pytorch_distributed_tpu.serving.engine import (
        ServingEngine,
        init_lm_params,
    )
    from pytorch_distributed_tpu.serving.scheduler import Request

    out = args.out or tempfile.mkdtemp(prefix="trace-drill-")
    os.makedirs(out, exist_ok=True)
    mpath = os.path.join(out, "serving.jsonl")
    n_requests, slo_ms = 24, 40.0
    with socket.socket() as s:  # free localhost port for the exporter
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    print(f"drill trace: preemption storm over a 24-block pool, "
          f"{n_requests} requests vs {slo_ms:.0f}ms TTFT SLO, exporter "
          f"on :{port}, artifacts in '{out}'")

    cfg = dict(vocab_size=64, d_model=64, n_heads=4, n_layers=2,
               max_batch=4, kv_blocks=24, block_size=4, blocks_per_seq=8,
               chunk_size=4, max_new_tokens=6, policy="priority",
               defrag_threshold_pct=200.0)  # never defrag: isolate redo
    params = init_lm_params(cfg["vocab_size"], cfg["d_model"],
                            cfg["n_heads"], cfg["n_layers"],
                            block_size=cfg["block_size"], seed=args.seed)

    # warmup engine: same jit cache (lru_cached step fns), so the
    # measured run's first prefill doesn't carry compile time into its
    # attribution
    warm = ServingEngine(params, seed=args.seed, **cfg)
    warm.run([(0.0, Request(rid=0, prompt=[1] * 8, max_new_tokens=2))])

    obs = MetricsLogger(mpath, flush_every=1)
    alert_engine = AlertEngine(
        [Rule("preempt_redo", "preempt_redo", "page", {"max_ms": 50.0}),
         Rule("queue_wait_share", "queue_wait_share", "warn",
              {"max_pct": 15.0})],
        emit=lambda **f: obs.log_event("alert", **f))
    exporter = MetricsExporter(port, rank=0, engine=alert_engine)
    exporter.start()
    obs.register(alert_engine.observe)
    obs.register(exporter.update)
    tracer = ReqTracer(slo_ms=slo_ms, sample=1.0)

    # scrape /metrics concurrently: the preempt_redo alert and the
    # ptd_serving_attr_* gauges must be visible while the run is live
    seen = {"firing": set(), "gauges": set(), "scrapes": 0}
    stop = threading.Event()

    def _scrape():
        url = f"http://127.0.0.1:{port}/metrics"
        while not stop.is_set():
            try:
                with urllib.request.urlopen(url, timeout=1.0) as r:
                    samples = parse_prometheus(
                        r.read().decode("utf-8", "replace"))
                seen["scrapes"] += 1
                for name, lab, v in samples:
                    if name == "ptd_alert_firing" and v:
                        seen["firing"].add(lab.get("rule"))
                    if name.startswith("ptd_serving_attr_"):
                        seen["gauges"].add(name)
            except Exception:
                pass
            stop.wait(0.05)

    th = threading.Thread(target=_scrape, daemon=True)
    th.start()

    eng = ServingEngine(params, obs=obs, chaos=_PreemptStorm(every=1,
                                                             start=3),
                        trace=tracer, seed=args.seed, **cfg)
    rng = np.random.RandomState(7)
    load = []
    for i in range(n_requests):
        prompt = [int(x) for x in
                  rng.randint(1, cfg["vocab_size"],
                              size=int(rng.randint(20, 29)))]
        load.append((i * 0.002, Request(
            rid=i, prompt=prompt, max_new_tokens=cfg["max_new_tokens"],
            priority=2 if i % 3 == 0 else 0)))
    try:
        summary = eng.run(load)
    finally:
        stop.set()
        th.join(timeout=2.0)
        exporter.stop()
        obs.close()

    ok = True
    if summary["completed"] != n_requests:
        print(f"FAIL: {summary['completed']}/{n_requests} requests "
              f"completed under the storm")
        ok = False
    if summary.get("preemptions", 0) < n_requests:
        print(f"FAIL: storm too weak — {summary.get('preemptions')} "
              f"preemption(s)")
        ok = False

    scripts = os.path.dirname(os.path.abspath(__file__))
    probe = subprocess.run(
        [sys.executable, os.path.join(scripts, "obs_trace.py"),
         "--metrics-jsonl", mpath, "--json"],
        capture_output=True, text=True)
    attr = _json.loads(probe.stdout) if probe.returncode == 0 else {}
    dominant = (attr.get("tail") or {}).get("dominant")
    if dominant != "preempt_redo":
        print(f"FAIL: tail attribution names {dominant!r}, want "
              f"'preempt_redo' (obs_trace rc {probe.returncode})")
        ok = False
    if attr and attr.get("recon_err_ms_max", 1e9) >= 0.05:
        print(f"FAIL: component sums drifted from TTFT by "
              f"{attr['recon_err_ms_max']:.3f}ms")
        ok = False
    if attr.get("violations", 0) < 1:
        print("FAIL: storm produced no SLO violations to attribute")
        ok = False

    if "preempt_redo" not in seen["firing"]:
        print(f"FAIL: live scrape never saw ptd_alert_firing{{rule="
              f"\"preempt_redo\"}} ({seen['scrapes']} scrape(s), saw "
              f"{sorted(seen['firing'])})")
        ok = False
    if "ptd_serving_attr_preempt_redo_ms" not in seen["gauges"]:
        print(f"FAIL: live scrape never saw the ptd_serving_attr_* "
              f"gauges (saw {sorted(seen['gauges'])})")
        ok = False
    booked = {str(e.get("alert")) for e in read_metrics(mpath)
              if e.get("ft_event") == "alert"}
    if "preempt_redo" not in booked:
        print(f"FAIL: no 'preempt_redo' alert ft_event in '{mpath}' "
              f"(booked: {sorted(booked)})")
        ok = False

    rep = subprocess.run(
        [sys.executable, os.path.join(scripts, "obs_report.py"),
         "--metrics-jsonl", mpath],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    for needle in ("== traces ==", "dominant tail component: preempt_redo",
                   "== alerts =="):
        if needle not in rep.stdout:
            print(f"FAIL: obs_report did not fold {needle!r} "
                  f"(rc {rep.returncode})")
            ok = False
    if not ok:
        return 1
    shares = (attr["tail"]["shares_pct"] if attr else {})
    print(_json.dumps({"completed": summary["completed"],
                       "preemptions": summary.get("preemptions"),
                       "violations": attr.get("violations"),
                       "preempt_redo_ms_p99":
                           attr.get("preempt_redo_ms_p99"),
                       "redo_share_pct":
                           round(shares.get("preempt_redo", 0.0), 1)},
                      sort_keys=True))
    print(f"drill trace: preempt_redo owns "
          f"{shares.get('preempt_redo', 0.0):.0f}% of the p99 TTFT, "
          f"alert booked live")
    print("drill trace: OK")
    return 0


def _fleet_boot_replica(out: str, tag: str, rid: int, seed: int,
                        itl_ms: float = 6.0):
    """Boot one jax-free sim replica subprocess; returns (proc, url)."""
    import subprocess
    import time as _time

    scripts = os.path.dirname(os.path.abspath(__file__))
    pf = os.path.join(out, f"{tag}-replica{rid}.port")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(scripts, "serve_fleet.py"), "replica",
         "--replica-id", str(rid), "--port-file", pf, "--seed", str(seed),
         "--sim-itl-ms", str(itl_ms), "--sim-prefill-ms", "0.5",
         "--max-batch", "2", "--hb-dir", os.path.join(out, f"hb-{tag}")],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
    t_end = _time.monotonic() + 20.0
    while _time.monotonic() < t_end and not os.path.exists(pf):
        _time.sleep(0.02)
    if not os.path.exists(pf):
        proc.kill()
        raise RuntimeError(f"replica {rid} never wrote its port file")
    with open(pf) as f:
        return proc, f"http://127.0.0.1:{int(f.read().strip())}"


def _fleet_report_needles(jsonl: str, needles) -> bool:
    """Run obs_report over the router JSONL and check the fold landed."""
    import subprocess

    rep = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "obs_report.py"), "--metrics-jsonl", jsonl],
        capture_output=True, text=True)
    ok = True
    for needle in needles:
        if needle not in rep.stdout:
            print(f"FAIL: obs_report did not fold {needle!r} "
                  f"(rc {rep.returncode})")
            ok = False
    return ok


def _drill_replica_kill(args) -> int:
    """ISSUE 19: SIGKILL a replica mid-decode under live load.

    Two subprocess sim replicas behind an in-process fleet router; the
    seeded plan picks the completion count after which replica 1 dies
    (while it provably has requests in flight).  Passes iff every
    admitted request completes exactly once with tokens bit-exact vs an
    unkilled baseline run, ttft_p99 holds inside a 3x+250 ms ceiling,
    the router books the ``replica_down`` ft_event + alert, and
    ``obs_report`` folds the ``== fleet ==`` section from the JSONL.
    """
    import json as _json
    import random as _random
    import signal as _sig
    import tempfile
    import threading
    import time as _time

    from pytorch_distributed_tpu.obs import alerts as _alerts
    from pytorch_distributed_tpu.obs.metrics import (
        MetricsLogger,
        read_metrics,
    )
    from pytorch_distributed_tpu.serving import router as _router

    out = args.out or tempfile.mkdtemp(prefix="ptd-drill-fleet-")
    os.makedirs(out, exist_ok=True)
    n_req = max(args.steps, 8)
    kill_after, _ = drill_plan(args.seed, n_req)
    rng = _random.Random(args.seed)
    prompts = [[rng.randrange(64) for _ in range(8)] for _ in range(n_req)]

    def run(tag: str, kill_victim: bool):
        procs, urls = {}, {}
        for rid in (0, 1):
            procs[rid], urls[rid] = _fleet_boot_replica(
                out, tag, rid, args.seed)
        jsonl = os.path.join(out, f"router-{tag}.jsonl")
        obs = MetricsLogger(jsonl, process_index=-2, flush_every=1)
        engine = _alerts.AlertEngine(
            [_alerts.Rule(kind="replica_down", name="replica_down",
                          severity="page", params={})],
            emit=lambda **f: obs.log_event("alert", **f), process_index=-2)
        registry = _router.ReplicaRegistry(
            urls, hb_dir=os.path.join(out, f"hb-{tag}"),
            backoff_initial_s=0.05, probe_timeout=1.0)
        rt = _router.FleetRouter(
            registry,
            _router.RouterPolicy(deadline_s=30.0, max_retries=3,
                                 retry_backoff_s=0.01, seed=args.seed),
            obs=obs, alert_engine=engine)
        registry.probe()
        results = [None] * n_req
        lock = threading.Lock()

        def fire(i: int):
            _time.sleep(i * 0.004)
            code, res = rt.submit({"rid": i, "prompt": prompts[i],
                                   "max_new_tokens": 8})
            with lock:
                results[i] = (code, res)

        killed = {"t": None}

        def killer():
            victim = registry.replicas[1]
            t_end = _time.monotonic() + 20.0
            while _time.monotonic() < t_end:
                done = rt.stats.as_dict()["requests_completed"]
                if done >= n_req:
                    return  # run finished before the plan's kill point
                if done >= kill_after and victim.inflight > 0:
                    break
                _time.sleep(0.002)
            procs[1].send_signal(_sig.SIGKILL)
            killed["t"] = _time.monotonic()

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(n_req)]
        kt = threading.Thread(target=killer) if kill_victim else None
        t0 = _time.monotonic()
        for t in threads:
            t.start()
        if kt is not None:
            kt.start()
        for t in threads:
            t.join(timeout=60.0)
        if kt is not None:
            kt.join(timeout=30.0)
        wall = _time.monotonic() - t0
        rt.log_cycle(wall)
        obs.close()
        for p in procs.values():
            p.kill()
            p.wait(timeout=5.0)
        ttfts = sorted(r[1]["router_ttft_ms"] for r in results
                       if r and r[0] == 200 and r[1].get("ok"))
        p99 = (ttfts[min(len(ttfts) - 1, int(0.99 * len(ttfts)))]
               if ttfts else None)
        return {"results": results, "stats": rt.stats.as_dict(),
                "ledger": len(rt.ledger), "ttft_p99_ms": p99,
                "jsonl": jsonl, "killed_at": killed["t"]}

    print(f"drill replica-kill: {n_req} requests over 2 replicas, SIGKILL "
          f"replica 1 after completion #{kill_after} (seed {args.seed})")
    base = run("base", kill_victim=False)
    kill = run("kill", kill_victim=True)

    ok = True
    for tag, r in (("base", base), ("kill", kill)):
        lost = [i for i, res in enumerate(r["results"])
                if not (res and res[0] == 200 and res[1].get("ok"))]
        if lost:
            print(f"FAIL: {tag} run lost request(s) {lost}")
            ok = False
        if r["ledger"] != n_req:
            print(f"FAIL: {tag} ledger holds {r['ledger']} completions, "
                  f"want {n_req}")
            ok = False
        if r["stats"]["duplicates_suppressed"] != 0:
            print(f"FAIL: {tag} run double-completed "
                  f"{r['stats']['duplicates_suppressed']} request(s)")
            ok = False
    if kill["killed_at"] is None:
        print("FAIL: the killer never fired — the fault was not injected")
        ok = False
    if kill["stats"]["retries"] < 1:
        print("FAIL: no redispatch despite a killed replica")
        ok = False
    if kill["stats"]["replica_down_events"] < 1:
        print("FAIL: router never saw the UP -> QUARANTINED transition")
        ok = False
    if ok:
        for i in range(n_req):
            if base["results"][i][1]["tokens"] != kill["results"][i][1]["tokens"]:
                print(f"FAIL: rid {i} tokens diverge after redispatch")
                ok = False
                break
    if base["ttft_p99_ms"] and kill["ttft_p99_ms"]:
        ceiling = base["ttft_p99_ms"] * 3.0 + 250.0
        if kill["ttft_p99_ms"] > ceiling:
            print(f"FAIL: ttft_p99 {kill['ttft_p99_ms']:.1f} ms blew the "
                  f"ceiling {ceiling:.1f} ms (baseline "
                  f"{base['ttft_p99_ms']:.1f} ms)")
            ok = False
    recs = read_metrics(kill["jsonl"])
    if "replica_down" not in {r.get("ft_event") for r in recs}:
        print("FAIL: no replica_down ft_event in the router JSONL")
        ok = False
    if not [r for r in recs if r.get("ft_event") == "alert"
            and r.get("rule") == "replica_down"]:
        print("FAIL: no replica_down alert booked")
        ok = False
    if not _fleet_report_needles(kill["jsonl"],
                                 ("== fleet ==", "replica_down")):
        ok = False
    if not ok:
        return 1
    print(_json.dumps(
        {"requests": n_req, "kill_after": kill_after,
         "base_ttft_p99_ms": round(base["ttft_p99_ms"], 2),
         "kill_ttft_p99_ms": round(kill["ttft_p99_ms"], 2),
         "retries": kill["stats"]["retries"],
         "replica_down_events": kill["stats"]["replica_down_events"],
         "lost": 0, "double_completed": 0}, sort_keys=True))
    print("drill replica-kill: zero lost, zero double-completed, tokens "
          "bit-exact across the redispatch")
    print("drill replica-kill: OK")
    return 0


def _drill_router_restart(args) -> int:
    """ISSUE 19 variant: SIGKILL the *router* mid-run.

    Clients retry against a restarted router process; the restarted
    router has an empty ledger, so re-dispatched rids hit the replicas'
    idempotent rid caches (or recompute deterministically).  Passes iff
    every client receives exactly one successful completion with the
    expected tokens bit-exact, and ``obs_report`` folds the fleet
    section from the shared (append-mode) router JSONL.
    """
    import itertools
    import json as _json
    import random as _random
    import signal as _sig
    import subprocess
    import tempfile
    import threading
    import time as _time

    from pytorch_distributed_tpu.serving import replica as _replica
    from pytorch_distributed_tpu.serving import router as _router

    out = args.out or tempfile.mkdtemp(prefix="ptd-drill-fleet-")
    os.makedirs(out, exist_ok=True)
    n_req = max(args.steps, 8)
    kill_after, _ = drill_plan(args.seed, n_req)
    scripts = os.path.dirname(os.path.abspath(__file__))
    procs, urls = {}, {}
    for rid in (0, 1):
        procs[rid], urls[rid] = _fleet_boot_replica(out, "rr", rid,
                                                    args.seed)
    jsonl = os.path.join(out, "router-rr.jsonl")
    counter = itertools.count()

    def boot_router():
        i = next(counter)
        pf = os.path.join(out, f"router{i}.port")
        proc = subprocess.Popen(
            [sys.executable, os.path.join(scripts, "serve_fleet.py"),
             "router", "--replicas", f"0={urls[0]},1={urls[1]}",
             "--port-file", pf, "--metrics-jsonl", jsonl,
             "--retry-backoff-ms", "10", "--deadline-s", "30",
             "--probe-interval", "0.2", "--quarantine-backoff-ms", "50",
             "--hb-dir", os.path.join(out, "hb-rr")],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        t_end = _time.monotonic() + 20.0
        while _time.monotonic() < t_end and not os.path.exists(pf):
            _time.sleep(0.02)
        if not os.path.exists(pf):
            proc.kill()
            raise RuntimeError("router never wrote its port file")
        with open(pf) as f:
            return proc, f"http://127.0.0.1:{int(f.read().strip())}"

    rproc, rurl = boot_router()
    holder = {"url": rurl, "proc": rproc}
    rng = _random.Random(args.seed)
    prompts = [[rng.randrange(64) for _ in range(8)] for _ in range(n_req)]
    expected = [_replica.sim_tokens(p, 8, 64, args.seed) for p in prompts]
    successes = [0] * n_req
    tokens_out = [None] * n_req
    lock = threading.Lock()

    def client(i: int):
        _time.sleep(i * 0.004)
        t_end = _time.monotonic() + 45.0
        while _time.monotonic() < t_end:
            url = holder["url"]
            try:
                res = _router.http_json(
                    "POST", url + "/generate",
                    {"rid": i, "prompt": prompts[i], "max_new_tokens": 8},
                    30.0)
            except _router.TRANSPORT_ERRORS:
                _time.sleep(0.05)  # router down: wait for the restart
                continue
            if res.get("ok"):
                with lock:
                    successes[i] += 1
                    tokens_out[i] = res["tokens"]
                return
            _time.sleep(0.05)

    killed = {"t": None}

    def killer():
        t_end = _time.monotonic() + 20.0
        while _time.monotonic() < t_end:
            try:
                stats = _router.http_json(
                    "GET", holder["url"] + "/stats", None, 1.0)
                done = stats["stats"]["requests_completed"]
            except _router.TRANSPORT_ERRORS:
                done = 0
            if done >= kill_after:
                break
            _time.sleep(0.01)
        holder["proc"].send_signal(_sig.SIGKILL)
        killed["t"] = _time.monotonic()
        nproc, nurl = boot_router()
        holder.update(url=nurl, proc=nproc)

    print(f"drill router-restart: {n_req} requests, SIGKILL the router "
          f"after completion #{kill_after}, restart, clients replay "
          f"(seed {args.seed})")
    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_req)]
    kt = threading.Thread(target=killer)
    for t in threads:
        t.start()
    kt.start()
    for t in threads:
        t.join(timeout=90.0)
    kt.join(timeout=60.0)

    ok = True
    if killed["t"] is None:
        print("FAIL: the router was never killed")
        ok = False
    lost = [i for i in range(n_req) if successes[i] != 1]
    if lost:
        print(f"FAIL: request(s) {lost} did not complete exactly once "
              f"(counts {[successes[i] for i in lost]})")
        ok = False
    for i in range(n_req):
        if tokens_out[i] is not None and tokens_out[i] != expected[i]:
            print(f"FAIL: rid {i} tokens diverge across the restart")
            ok = False
            break
    computed = cache_hits = 0
    for rid in (0, 1):
        try:
            s = _router.http_json("GET", urls[rid] + "/stats", None, 2.0)
            computed += int(s["computed"])
            cache_hits += int(s["cache_hits"])
        except _router.TRANSPORT_ERRORS:
            print(f"FAIL: replica {rid} unreachable post-drill")
            ok = False
    if computed < n_req:
        print(f"FAIL: replicas computed {computed} < {n_req} requests")
        ok = False
    if not _fleet_report_needles(jsonl, ("== fleet ==",)):
        ok = False
    try:
        holder["proc"].kill()
    except OSError:
        pass
    for p in procs.values():
        p.kill()
        p.wait(timeout=5.0)
    if not ok:
        return 1
    print(_json.dumps(
        {"requests": n_req, "kill_after": kill_after,
         "computed": computed, "replay_cache_hits": cache_hits,
         "recompute_duplicates": computed - n_req,
         "lost": 0, "double_completed": 0}, sort_keys=True))
    print("drill router-restart: every request completed exactly once "
          "across the crash, tokens bit-exact")
    print("drill router-restart: OK")
    return 0


def _selftest() -> int:
    """No-mesh FT fast path: every assertion here runs in well under a
    second with zero jax involvement."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        # 1. Sidecar round-trip: seal → verify OK.
        p = os.path.join(d, "blob.bin")
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 16)  # 4 KiB, content irrelevant
        write_sidecar(p)
        assert verify_sidecar(p) is True, "fresh sidecar must verify"

        # 2. Bit-flip detection + determinism: identical copies corrupted
        #    with the same seed flip the identical byte offsets.
        c1, c2 = os.path.join(d, "c1"), os.path.join(d, "c2")
        shutil.copyfile(p, c1)
        shutil.copyfile(p, c2)
        shutil.copyfile(sidecar_path(p), sidecar_path(c1))
        i1 = corrupt_file(c1, mode="flip", seed=7, nbytes=3)
        i2 = corrupt_file(c2, mode="flip", seed=7, nbytes=3)
        assert i1 == i2, f"flip corruption must be seed-deterministic: " \
                         f"{i1} != {i2}"
        with open(c1, "rb") as f1, open(c2, "rb") as f2:
            assert f1.read() == f2.read(), "corrupted bytes must match"
        assert verify_sidecar(c1) is False, "flip must fail verification"
        i3 = corrupt_file(c2, mode="flip", seed=8, nbytes=3)
        assert i3 != i2, "different seeds must corrupt differently"

        # 3. Truncation detection.
        t = os.path.join(d, "t")
        shutil.copyfile(p, t)
        shutil.copyfile(sidecar_path(p), sidecar_path(t))
        info = corrupt_file(t, mode="truncate", seed=3)
        assert info["new_size"] < info["old_size"]
        assert verify_sidecar(t) is False, "truncation must fail verification"

        # 4. Untouched original still verifies (corruption didn't leak).
        assert verify_sidecar(p) is True

        # 5. Bounded-backoff retry: two transient OSErrors then success;
        #    exhausted attempts re-raise.
        calls = {"n": 0}
        delays = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert retrying(flaky, attempts=3, base_delay=0.01,
                        sleep=delays.append) == "ok"
        assert calls["n"] == 3 and delays == [0.01, 0.02], delays
        try:
            retrying(lambda: (_ for _ in ()).throw(OSError("always")),
                     attempts=2, base_delay=0.0, sleep=lambda _s: None)
        except OSError:
            pass
        else:
            raise AssertionError("exhausted retries must re-raise")

        # 6. CLI surface: verify exit codes match the file state.
        assert cmd_verify(argparse.Namespace(path=p)) == 0
        assert cmd_verify(argparse.Namespace(path=c1)) == 1

        # 7. Drill-plan determinism: same seed → same schedule; schedules
        #    are ordered with re-admission strictly after the loss.
        assert drill_plan(0, 12) == drill_plan(0, 12)
        assert drill_plan(0, 12) != drill_plan(1, 12) or \
            drill_plan(0, 16) != drill_plan(1, 16)
        for seed in range(8):
            lose, join = drill_plan(seed, 12)
            assert 2 <= lose < join < 11, (seed, lose, join)

        # 8. Membership injectors latch once and drive the trainer's
        #    elastic controller — no jax needed, a stub trainer suffices.
        from pytorch_distributed_tpu.ft.elastic import (
            JoinRankAt,
            LoseRankAt,
        )

        class _Ctl:
            def __init__(self):
                self.calls = []

            def force_lose(self, rank, reason="chaos"):
                self.calls.append(("lose", rank, reason))

            def force_join(self, rank, reason="chaos"):
                self.calls.append(("join", rank, reason))

        class _Trainer:
            elastic = _Ctl()

        tr = _Trainer()
        lose = LoseRankAt(3, rank=2, reason="drill")
        join = JoinRankAt(5, rank=2, reason="drill")
        for s in range(8):
            lose.on_step(tr, s)
            join.on_step(tr, s)
        assert tr.elastic.calls == [("lose", 2, "drill"),
                                    ("join", 2, "drill")]
        assert lose.fired and join.fired
        # a trainer without an elastic controller ignores the injection
        LoseRankAt(0, rank=0).on_step(object(), 0)

        # 9. HangAt latches once, stalls only via the collective hook,
        #    and only at its step — no jax needed with rank=None.
        from pytorch_distributed_tpu.ft.chaos import HangAt

        h = HangAt(3, seconds=0.0)
        h.on_step(None, 3)          # wrong hook: must not fire
        assert not h.fired
        h.on_collective(None, 2)    # wrong step: must not fire
        assert not h.fired
        h.on_collective(None, 3)
        assert h.fired, "HangAt must fire at its step"
        h.on_collective(None, 3)    # latched: second visit is a no-op
        assert h.fired

        # 10. SlowLoader stalls only via the batch hook (inside the
        #     step-attribution data_wait window), honors --every, and
        #     passes the batch through untouched — no jax with ranks=None.
        from pytorch_distributed_tpu.ft.chaos import SlowLoader

        sl = SlowLoader(0.0, every=2)
        sl.on_step(None, 0)         # wrong hook: must not count
        assert sl.injected == 0
        sentinel = object()
        assert sl.on_batch(0, sentinel) is sentinel
        assert sl.on_batch(1, sentinel) is sentinel  # skipped by every=2
        assert sl.on_batch(2, sentinel) is sentinel
        assert sl.injected == 2, sl.injected
    print("chaoskit selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Deterministic fault injection for FT drills")
    ap.add_argument("--selftest", action="store_true",
                    help="run the fast no-mesh integrity/injector checks")
    sub = ap.add_subparsers(dest="cmd")
    c = sub.add_parser("corrupt", help="corrupt a file (deterministic)")
    c.add_argument("path")
    c.add_argument("--mode", choices=("flip", "truncate"), default="flip")
    c.add_argument("--seed", type=int, default=0)
    c.add_argument("--nbytes", type=int, default=1,
                   help="bytes to bit-flip (flip mode)")
    v = sub.add_parser("verify", help="check a file against its sidecar")
    v.add_argument("path")
    s = sub.add_parser("seal", help="write the sha256 sidecar for a file")
    s.add_argument("path")
    d = sub.add_parser("drill",
                       help="run an end-to-end elastic membership drill")
    d.add_argument("kind",
                   choices=("shrink", "grow", "hang", "alert", "serve",
                            "trace", "desync", "replica-kill",
                            "router-restart", "slow-loader"),
                   help="shrink: lose a rank and continue; grow: lose "
                        "then re-admit it; hang: stall a rank inside a "
                        "collective and let the watchdog catch it; "
                        "alert: slow/dead/stale injections must each "
                        "raise their matching live alert; serve: a "
                        "straggler under the serving engine must fire "
                        "the ttft_p99 SLO alert live; trace: a "
                        "preemption storm whose request-trace tail "
                        "attribution must name preempt_redo and fire "
                        "the preempt_redo alert live; desync: a planted "
                        "rank-divergent branch must be caught statically "
                        "by synclint AND live by the hang watchdog + "
                        "flight recorder; replica-kill: SIGKILL a serving "
                        "replica mid-decode — every in-flight request "
                        "must complete exactly once via redispatch, "
                        "bit-exact vs an unkilled run; router-restart: "
                        "SIGKILL the fleet router itself — client "
                        "replays against the restarted router must land "
                        "exactly once via the replicas' rid caches; "
                        "slow-loader: an injected loader stall under "
                        "--step-attr must be attributed to data_wait "
                        "(not the device) and fire the data_wait_share "
                        "alert live")
    d.add_argument("--world", type=int, default=4,
                   help="starting data-parallel world size")
    d.add_argument("--steps", type=int, default=12)
    d.add_argument("--seed", type=int, default=0,
                   help="drives the injection schedule for EVERY drill "
                        "kind (the shared chaoskit contract): the same "
                        "seed yields the same drill_plan() step — the "
                        "lose/re-admit steps for shrink/grow, and the "
                        "stall/divergence step for hang/desync — so any "
                        "drill reproduces byte-for-byte from its seed")
    d.add_argument("--hang-timeout", type=float, default=1.0,
                   help="hang-drill watchdog timeout in seconds (the "
                        "injected stall is 4x this)")
    d.add_argument("--out", metavar="DIR", default=None,
                   help="hang-drill flight-recorder dump dir (default: "
                        "a fresh temp dir, printed)")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.cmd == "corrupt":
        return cmd_corrupt(args)
    if args.cmd == "verify":
        return cmd_verify(args)
    if args.cmd == "seal":
        return cmd_seal(args)
    if args.cmd == "drill":
        return cmd_drill(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
