#!/usr/bin/env python
"""chaoskit — fault-injection toolkit for the FT subsystem (ft/).

Commands:

- ``corrupt PATH``   deterministic byte-level corruption (``--mode flip``
  flips seed-chosen bits; ``--mode truncate`` cuts the file) — the storage
  half of a chaos drill: corrupt the latest checkpoint, re-run ``--resume``,
  and watch the loader fall back to ``checkpoint.prev.msgpack``;
- ``verify PATH``    sha256 sidecar check (exit 0 = intact, 1 = corrupt,
  also 0 with a note when no sidecar exists — legacy file);
- ``seal PATH``      write/refresh the sidecar for an existing file (adopt
  a pre-FT checkpoint into the verified world);
- ``--selftest``     the fast no-mesh CI path (tier-1, like
  ``shardlint.py --selftest`` / ``obs_report.py --selftest``): sidecar
  round-trip, flip/truncate detection, corruption determinism, retry
  backoff — no jax import, no devices.

Signal/NaN/delay injectors live in ``pytorch_distributed_tpu.ft.chaos`` and
are installed programmatically (``chaos=`` on either trainer); this CLI
covers the parts that act on files from outside a run.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.ft.chaos import corrupt_file  # noqa: E402
from pytorch_distributed_tpu.ft.integrity import (  # noqa: E402
    retrying,
    sidecar_path,
    verify_sidecar,
    write_sidecar,
)


def cmd_corrupt(args) -> int:
    info = corrupt_file(args.path, mode=args.mode, seed=args.seed,
                        nbytes=args.nbytes)
    print(f"corrupted '{args.path}': {info}")
    if verify_sidecar(args.path) is None:
        print("note: no sha256 sidecar — a loader cannot detect this "
              "corruption before deserialization")
    return 0


def cmd_verify(args) -> int:
    ok = verify_sidecar(args.path)
    if ok is None:
        print(f"'{args.path}': no sidecar ({sidecar_path(args.path)} "
              "missing) — legacy/unverified file")
        return 0
    if ok:
        print(f"'{args.path}': sha256 OK")
        return 0
    print(f"'{args.path}': CORRUPT (sha256 mismatch vs sidecar)")
    return 1


def cmd_seal(args) -> int:
    side = write_sidecar(args.path)
    print(f"wrote '{side}'")
    return 0


def _selftest() -> int:
    """No-mesh FT fast path: every assertion here runs in well under a
    second with zero jax involvement."""
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        # 1. Sidecar round-trip: seal → verify OK.
        p = os.path.join(d, "blob.bin")
        with open(p, "wb") as f:
            f.write(bytes(range(256)) * 16)  # 4 KiB, content irrelevant
        write_sidecar(p)
        assert verify_sidecar(p) is True, "fresh sidecar must verify"

        # 2. Bit-flip detection + determinism: identical copies corrupted
        #    with the same seed flip the identical byte offsets.
        c1, c2 = os.path.join(d, "c1"), os.path.join(d, "c2")
        shutil.copyfile(p, c1)
        shutil.copyfile(p, c2)
        shutil.copyfile(sidecar_path(p), sidecar_path(c1))
        i1 = corrupt_file(c1, mode="flip", seed=7, nbytes=3)
        i2 = corrupt_file(c2, mode="flip", seed=7, nbytes=3)
        assert i1 == i2, f"flip corruption must be seed-deterministic: " \
                         f"{i1} != {i2}"
        with open(c1, "rb") as f1, open(c2, "rb") as f2:
            assert f1.read() == f2.read(), "corrupted bytes must match"
        assert verify_sidecar(c1) is False, "flip must fail verification"
        i3 = corrupt_file(c2, mode="flip", seed=8, nbytes=3)
        assert i3 != i2, "different seeds must corrupt differently"

        # 3. Truncation detection.
        t = os.path.join(d, "t")
        shutil.copyfile(p, t)
        shutil.copyfile(sidecar_path(p), sidecar_path(t))
        info = corrupt_file(t, mode="truncate", seed=3)
        assert info["new_size"] < info["old_size"]
        assert verify_sidecar(t) is False, "truncation must fail verification"

        # 4. Untouched original still verifies (corruption didn't leak).
        assert verify_sidecar(p) is True

        # 5. Bounded-backoff retry: two transient OSErrors then success;
        #    exhausted attempts re-raise.
        calls = {"n": 0}
        delays = []

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise OSError("transient")
            return "ok"

        assert retrying(flaky, attempts=3, base_delay=0.01,
                        sleep=delays.append) == "ok"
        assert calls["n"] == 3 and delays == [0.01, 0.02], delays
        try:
            retrying(lambda: (_ for _ in ()).throw(OSError("always")),
                     attempts=2, base_delay=0.0, sleep=lambda _s: None)
        except OSError:
            pass
        else:
            raise AssertionError("exhausted retries must re-raise")

        # 6. CLI surface: verify exit codes match the file state.
        assert cmd_verify(argparse.Namespace(path=p)) == 0
        assert cmd_verify(argparse.Namespace(path=c1)) == 1
    print("chaoskit selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Deterministic fault injection for FT drills")
    ap.add_argument("--selftest", action="store_true",
                    help="run the fast no-mesh integrity/injector checks")
    sub = ap.add_subparsers(dest="cmd")
    c = sub.add_parser("corrupt", help="corrupt a file (deterministic)")
    c.add_argument("path")
    c.add_argument("--mode", choices=("flip", "truncate"), default="flip")
    c.add_argument("--seed", type=int, default=0)
    c.add_argument("--nbytes", type=int, default=1,
                   help="bytes to bit-flip (flip mode)")
    v = sub.add_parser("verify", help="check a file against its sidecar")
    v.add_argument("path")
    s = sub.add_parser("seal", help="write the sha256 sidecar for a file")
    s.add_argument("path")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if args.cmd == "corrupt":
        return cmd_corrupt(args)
    if args.cmd == "verify":
        return cmd_verify(args)
    if args.cmd == "seal":
        return cmd_seal(args)
    ap.print_help()
    return 2


if __name__ == "__main__":
    sys.exit(main())
