#!/usr/bin/env python
"""Cross-rank postmortem analyzer: merge per-rank flight-recorder dumps
(+ heartbeats) into a root-cause report for a hung or dead job.

Input is a directory of ``flightrec_rank<k>.json`` dumps written by
``obs/flightrec.py`` on any death path (signal, rollback, checkpoint
corruption, unhandled exception, or the collective-hang watchdog), plus —
when available — the run's heartbeat files, whose per-step wall-clock
history aligns the ranks' clocks (``obs/timeline.py`` machinery, the same
alignment the cross-rank timeline uses).

The report answers the questions that dominate multi-node debugging time:

- **which rank stalled first** (earliest aligned last-progress time —
  the rank that stopped completing steps before everyone else)
- **the desync frontier**: the last collective each rank entered, with
  kind/bytes/step — a rank sitting a step behind the others' frontier is
  the one everyone else is blocked waiting for
- **step skew** across ranks at death, and membership epoch agreement
- **per-rank memory at death** (an OOM-killed rank shows up as the one
  with the fat RSS and no hang event)

Usage:
    python scripts/postmortem.py RUN_DIR [--hb-dir DIR] [--json]
    python scripts/postmortem.py --selftest
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_tpu.obs.flightrec import find_dumps  # noqa: E402
from pytorch_distributed_tpu.obs.timeline import (  # noqa: E402
    clock_offsets_from_heartbeats,
)


# --------------------------------------------------------------- loading --

def load_dumps(flight_dir: str) -> Dict[int, Dict[str, Any]]:
    """``{rank: dump}`` for every parseable flightrec_rank<k>.json."""
    out: Dict[int, Dict[str, Any]] = {}
    for rank, path in find_dumps(flight_dir).items():
        try:
            with open(path) as f:
                out[rank] = json.load(f)
        except (OSError, ValueError):
            continue  # a torn/corrupt dump must not sink the others
    return out


# -------------------------------------------------------------- analysis --

def _last_event(events: List[Dict[str, Any]], kind: str,
                ) -> Optional[Dict[str, Any]]:
    for ev in reversed(events):
        if ev.get("kind") == kind:
            return ev
    return None


def analyze(dumps: Dict[int, Dict[str, Any]],
            offsets: Optional[Dict[int, float]] = None) -> Dict[str, Any]:
    """The merged root-cause report (pure function of the dumps).

    ``offsets`` maps *pid* → clock offset seconds (the heartbeat-derived
    alignment); each rank's timestamps are shifted by its pid's offset
    before any cross-rank comparison."""
    offsets = offsets or {}
    ranks: Dict[int, Dict[str, Any]] = {}
    for rank, d in sorted(dumps.items()):
        pid = d.get("pid")
        off = float(offsets.get(pid, 0.0))
        events = d.get("events") or []
        last_end = _last_event(events, "step_end")
        last_coll = _last_event(events, "coll_enter")
        hang = _last_event(events, "hang")
        in_step = d.get("in_step")
        # Last completed step: the final step_end wins; a rank mid-step
        # has progressed *through* step-1 only.
        last_step = last_end.get("step") if last_end else None
        if last_step is None and in_step:
            last_step = (in_step.get("step") or 0) - 1
        # Aligned time of the rank's last forward progress.
        progress_t = (last_end.get("t") if last_end
                      else (events[0].get("t") if events else None))
        frontier = None
        if last_coll is not None:
            frontier = {"step": last_coll.get("step"),
                        "kind": last_coll.get("collective"),
                        "bytes": last_coll.get("bytes")}
        elif d.get("last_collective"):
            lc = d["last_collective"]
            frontier = {"step": lc.get("step"), "kind": lc.get("kind"),
                        "bytes": lc.get("bytes")}
        membership = d.get("membership") or {}
        ranks[rank] = {
            "pid": pid,
            "reason": d.get("reason"),
            "clock_offset_s": off,
            "last_step": last_step,
            "last_progress_t": (None if progress_t is None
                                else progress_t - off),
            "in_step": in_step,
            "frontier": frontier,
            "hang": (None if hang is None else {
                "step": hang.get("step"),
                "t": (hang.get("t") or 0.0) - off,
                "elapsed_s": hang.get("elapsed_s"),
                "collective": hang.get("collective"),
            }),
            "epoch": membership.get("epoch"),
            "world": membership.get("world"),
            "mem_bytes": d.get("mem_bytes"),
            "events_dropped": d.get("events_dropped", 0),
        }

    report: Dict[str, Any] = {"ranks": ranks, "n_ranks": len(ranks)}
    if not ranks:
        report["verdict"] = "no flight dumps found"
        return report

    # Which rank stalled first: earliest aligned last-progress time.  In a
    # collective hang every rank eventually stops, but the culprit stops
    # completing steps first — the survivors block one collective later.
    with_t = {r: v["last_progress_t"] for r, v in ranks.items()
              if v["last_progress_t"] is not None}
    stalled = (min(with_t, key=with_t.get) if with_t
               else min(ranks))
    report["stalled_rank"] = stalled

    steps = [v["last_step"] for v in ranks.values()
             if v["last_step"] is not None]
    report["step_skew"] = (max(steps) - min(steps)) if steps else None

    fr_steps = {r: v["frontier"]["step"] for r, v in ranks.items()
                if v["frontier"] and v["frontier"].get("step") is not None}
    report["frontier_desync"] = (len(set(fr_steps.values())) > 1
                                 if fr_steps else False)
    # Behind-the-frontier beats raw progress time when the frontier itself
    # disagrees: the rank that never entered the collective everyone else
    # is blocked in is the root cause even if clocks are misaligned.
    if report["frontier_desync"]:
        report["stalled_rank"] = min(fr_steps, key=fr_steps.get)

    epochs = {v["epoch"] for v in ranks.values() if v["epoch"] is not None}
    report["epoch_skew"] = len(epochs) > 1
    report["epochs"] = sorted(epochs)

    hang_ranks = [r for r, v in ranks.items() if v["hang"] is not None]
    report["hang_ranks"] = hang_ranks

    culprit = ranks[report["stalled_rank"]]
    coll = culprit["frontier"] or {}
    report["verdict"] = (
        f"rank {report['stalled_rank']} stalled first "
        f"(last completed step {culprit['last_step']}, "
        f"last-entered collective "
        f"{coll.get('kind') or 'unknown'}@step {coll.get('step')})"
    )
    return report


def postmortem(flight_dir: str,
               hb_dir: Optional[str] = None) -> Dict[str, Any]:
    """Load dumps + heartbeat clock offsets and analyze.  ``hb_dir``
    defaults to the flight dir (trainers usually point both at the run
    directory); missing heartbeats degrade to zero offsets."""
    dumps = load_dumps(flight_dir)
    offsets: Dict[int, float] = {}
    try:
        offsets = clock_offsets_from_heartbeats(hb_dir or flight_dir)
    except Exception:
        pass
    return analyze(dumps, offsets)


# ------------------------------------------------------------- rendering --

def _fmt_mem(n: Optional[float]) -> str:
    if not n:
        return "-"
    return f"{n / (1 << 20):.0f}MiB"


def render_text(report: Dict[str, Any]) -> str:
    lines = ["== postmortem =="]
    if not report.get("ranks"):
        lines.append("  no flight dumps found")
        return "\n".join(lines)
    lines.append(f"  verdict: {report['verdict']}")
    if report.get("hang_ranks"):
        lines.append(f"  hang ft_events on ranks: "
                     f"{sorted(report['hang_ranks'])}")
    lines.append(
        f"  step skew {report.get('step_skew')}  "
        f"frontier desync {'YES' if report.get('frontier_desync') else 'no'}"
        f"  epoch skew "
        f"{'YES ' + str(report.get('epochs')) if report.get('epoch_skew') else 'no'}")
    for rank, v in sorted(report["ranks"].items()):
        fr = v.get("frontier") or {}
        hang = v.get("hang")
        mark = " <-- stalled first" if rank == report.get("stalled_rank") \
            else ""
        lines.append(
            f"  rank {rank} pid {v.get('pid')}: reason={v.get('reason')} "
            f"last_step={v.get('last_step')} "
            f"frontier={fr.get('kind') or '?'}@{fr.get('step')} "
            f"epoch={v.get('epoch')} mem={_fmt_mem(v.get('mem_bytes'))}"
            f"{' hang@step ' + str(hang['step']) if hang else ''}{mark}")
        if v.get("events_dropped"):
            lines.append(f"    ({v['events_dropped']} older events dropped "
                         f"from the ring)")
    return "\n".join(lines)


# --------------------------------------------------------------- fixture --

def make_fixture(out_dir: str) -> str:
    """Deterministic 2-rank hang fixture with a known desync frontier.

    Story: rank 1's clock runs 2 s ahead.  Both ranks complete steps 0-4;
    rank 0 enters the step-5 grad allreduce and blocks (its watchdog
    fires a hang); rank 1 stalled *before* entering step 5 — its frontier
    is the step-4 collective, one behind.  The analyzer must name rank 1
    via the frontier (and the aligned progress times agree).  Used by
    ``--selftest`` and checked in under ``tests/data/postmortem/``."""
    os.makedirs(out_dir, exist_ok=True)
    base = 1700000000.0
    skew = 2.0  # rank 1 wall clock = true time + 2 s

    def clean_events(rank: int, off: float):
        evs = []
        for s in range(5):  # steps 0..4 complete on both ranks
            t0 = base + 1.0 * s + off
            evs.append({"t": t0, "kind": "step_begin", "step": s})
            evs.append({"t": t0 + 0.1, "kind": "coll_enter", "step": s,
                        "collective": "all-reduce", "bytes": 4096.0})
            evs.append({"t": t0 + 0.8, "kind": "coll_exit", "step": s})
            evs.append({"t": t0 + 0.9, "kind": "step_end", "step": s,
                        "dt": 0.9})
        t5 = base + 5.0 + off
        if rank == 0:
            # enters the step-5 collective, never exits; watchdog fires
            evs.append({"t": t5, "kind": "step_begin", "step": 5})
            evs.append({"t": t5 + 0.1, "kind": "coll_enter", "step": 5,
                        "collective": "all-reduce", "bytes": 4096.0})
            evs.append({"t": t5 + 40.0, "kind": "hang", "step": 5,
                        "elapsed_s": 40.0, "threshold_s": 30.0,
                        "collective": "all-reduce"})
        else:
            # stalls before entering step 5: begins the step, no coll
            evs.append({"t": t5, "kind": "step_begin", "step": 5})
        return evs

    pids = {0: 11111, 1: 22222}
    for rank in (0, 1):
        off = skew if rank == 1 else 0.0
        events = clean_events(rank, off)
        last_coll_step = 5 if rank == 0 else 4
        dump = {
            "schema": 1,
            "rank": rank,
            "pid": pids[rank],
            "reason": "hang" if rank == 0 else "signal:15",
            "t_dump": base + 46.0 + off,
            "capacity": 2048,
            "events_total": len(events),
            "events_dropped": 0,
            "last_collective": {"step": last_coll_step,
                                "kind": "all-reduce", "bytes": 4096.0,
                                "name": "all-reduce.1",
                                "t": base + last_coll_step + 0.1 + off},
            "last_heartbeat": {"pid": pids[rank], "step": 4,
                               "t": base + 4.9 + off},
            "membership": {"world": 2, "epoch": 0},
            "in_step": {"step": 5,
                        "elapsed_s": 41.0 if rank == 0 else 43.0},
            "step_times": {"count": 5, "p50": 0.9, "p95": 0.9},
            "mem_bytes": (512 << 20) if rank == 0 else (768 << 20),
            "events": events,
        }
        with open(os.path.join(out_dir, f"flightrec_rank{rank}.json"),
                  "w") as f:
            json.dump(dump, f, indent=1)
            f.write("\n")
    # Heartbeat history for clock alignment: common steps 0..4, rank 1's
    # wall clock +2 s — clock_offsets_from_heartbeats recovers {22222: 2.0}.
    for rank in (0, 1):
        off = skew if rank == 1 else 0.0
        path = os.path.join(out_dir, f"heartbeat-{pids[rank]:05d}.jsonl")
        with open(path, "w") as f:
            for s in range(5):
                rec = {"pid": pids[rank], "step": s,
                       "t": base + 1.0 * s + 0.9 + off, "epoch": 0,
                       "world": 2}
                f.write(json.dumps(rec) + "\n")
    return out_dir


# -------------------------------------------------------------- selftest --

def _selftest() -> int:
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        make_fixture(td)
        report = postmortem(td)

        # 1. both ranks load
        assert report["n_ranks"] == 2, report

        # 2. clock alignment recovered rank 1's +2 s skew from heartbeats
        r1 = report["ranks"][1]
        assert abs(r1["clock_offset_s"] - 2.0) < 0.25, r1

        # 3. desync frontier: rank 0 entered all-reduce@5, rank 1 stopped
        #    at all-reduce@4 → frontier desync, rank 1 is the culprit
        assert report["frontier_desync"] is True, report
        assert report["ranks"][0]["frontier"]["step"] == 5
        assert report["ranks"][1]["frontier"]["step"] == 4
        assert report["stalled_rank"] == 1, report

        # 4. hang ft_event attributed (rank 0's watchdog fired while
        #    blocked waiting on rank 1)
        assert report["hang_ranks"] == [0], report
        assert report["ranks"][0]["hang"]["collective"] == "all-reduce"

        # 5. skew/epoch/memory forensics
        assert report["step_skew"] == 0, report  # both completed step 4
        assert report["epoch_skew"] is False and report["epochs"] == [0]
        assert report["ranks"][1]["mem_bytes"] == 768 << 20

        # 6. verdict names the rank and the collective; text render folds
        assert "rank 1 stalled first" in report["verdict"], report
        text = render_text(report)
        assert "== postmortem ==" in text and "<-- stalled first" in text

        # 7. empty dir degrades, not crashes
        with tempfile.TemporaryDirectory() as empty:
            r = postmortem(empty)
            assert r["n_ranks"] == 0 and "no flight dumps" in r["verdict"]

        # 8. json round-trip
        json.loads(json.dumps(report))

    print("postmortem selftest: OK (8 blocks)")
    return 0


# ------------------------------------------------------------------ main --

def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="merge per-rank flight-recorder dumps into a "
                    "cross-rank root-cause report")
    p.add_argument("flight_dir", nargs="?", default=None,
                   help="directory holding flightrec_rank<k>.json dumps "
                        "(the trainers' --flight-rec dir)")
    p.add_argument("--hb-dir", default=None,
                   help="heartbeat directory for cross-rank clock "
                        "alignment (default: the flight dir)")
    p.add_argument("--json", action="store_true",
                   help="emit the report as JSON instead of text")
    p.add_argument("--selftest", action="store_true",
                   help="run the no-mesh fixture selftest and exit")
    args = p.parse_args(argv)

    if args.selftest:
        return _selftest()
    if not args.flight_dir:
        p.error("flight_dir is required (or --selftest)")

    report = postmortem(args.flight_dir, hb_dir=args.hb_dir)
    if args.json:
        print(json.dumps(report, indent=1, sort_keys=True))
    else:
        print(render_text(report))
    # A found root cause exits 1 (forensic alarm, mirrors elastic_agent
    # status); an empty dir exits 2 so automation can tell them apart.
    if not report.get("ranks"):
        return 2
    return 1 if (report.get("hang_ranks")
                 or report.get("frontier_desync")) else 0


if __name__ == "__main__":
    sys.exit(main())
