#!/usr/bin/env python
"""A/B the ResNet-50 train-step variants on the real chip.

Variants: conv7 vs space_to_depth stem, batch 256 vs 512.  Run on TPU:
    python scripts/profile_variants.py [b256,b512,s2d256,s2d512]
Prints ms/step and img/s for each; use to decide what bench.py should run.
"""

import sys

import jax
import jax.numpy as jnp
import numpy as np

from benchlib import timed_step_loop  # noqa: E402


def bench(name, batch, stem):
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    image = 224
    mesh = data_parallel_mesh()
    model = models.create_model(
        "resnet50", num_classes=1000, dtype=jnp.bfloat16, stem=stem
    )
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False
    )
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    b = {
        "images": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)).astype(np.float32)
        ),
        "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    dt, _ = timed_step_loop(step, state, b, lr, iters=20, warmup=3)
    print(f"{name}: {dt*1e3:.1f} ms/step -> {batch/dt:.0f} img/s", flush=True)


VARIANTS = {
    "b256": (256, "conv7"),
    "b512": (512, "conv7"),
    "s2d256": (256, "space_to_depth"),
    "s2d512": (512, "space_to_depth"),
}

if __name__ == "__main__":
    names = sys.argv[1].split(",") if len(sys.argv) > 1 else list(VARIANTS)
    for n in names:
        bench(n, *VARIANTS[n])
