#!/usr/bin/env python
"""Convert a framework checkpoint back to the reference's torch format.

Usage:
    python scripts/export_torch_checkpoint.py \
        --input ckpts/checkpoint.msgpack --output checkpoint.pth.tar

Reads a msgpack checkpoint (or a ``--pretrained`` ``<arch>.msgpack``),
converts the ResNet tree to a torchvision-shaped ``state_dict`` (OIHW convs,
[out,in] linear, BN running stats) and writes the reference's payload
``{'epoch', 'arch', 'state_dict', 'best_acc1'}`` via ``torch.save`` —
loadable by the reference's recipes and by plain torchvision
``model.load_state_dict`` (reference distributed.py:219-225,327-330).

The migration path therefore runs both ways:
import_torch_checkpoint.py (reference → here) and this (here → reference).
"""

import argparse
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True,
                    help="msgpack checkpoint / pretrained file")
    ap.add_argument("--output", required=True, help=".pth/.pth.tar to write")
    ap.add_argument("--arch", default=None,
                    help="arch name (defaults to the checkpoint's own field)")
    args = ap.parse_args()

    from flax import serialization

    with open(args.input, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    arch = args.arch or payload.get("arch")
    if not arch:
        sys.exit("--arch required: checkpoint has no 'arch' field")

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.utils.torch_import import (
        export_resnet_state_dict,
    )

    ctor = models._REGISTRY.get(arch)
    stage_sizes = getattr(ctor, "keywords", {}).get("stage_sizes")
    if stage_sizes is None:
        sys.exit(f"export supports the ResNet family; {arch!r} has no "
                 "stage_sizes")
    state = payload["state"]
    variables = {"params": state["params"],
                 "batch_stats": state["batch_stats"]}
    sd = export_resnet_state_dict(variables, stage_sizes)

    import torch

    out = {
        "epoch": int(payload.get("epoch", 0)),
        "arch": arch,
        "best_acc1": float(payload.get("best_acc1", 0.0)),
        "state_dict": {k: torch.from_numpy(v.copy()) for k, v in sd.items()},
    }
    torch.save(out, args.output)
    print(f"wrote {args.output} ({arch}, epoch={out['epoch']}, "
          f"best_acc1={out['best_acc1']})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
