#!/usr/bin/env python
"""Conv throughput with in-program repetition (fori_loop) so the ~2 ms
per-launch tunnel overhead doesn't pollute kernel timing."""

import time

import jax
import jax.numpy as jnp


from benchlib import timed_scalar  # noqa: E402


REPS = 20


def main():
    shapes = [
        (256, 56, 56, 64, 64, 3, 1),
        (256, 28, 28, 128, 128, 3, 1),
        (256, 14, 14, 256, 256, 3, 1),
        (256, 7, 7, 512, 512, 3, 1),
        (256, 56, 56, 256, 64, 1, 1),   # 1x1 reduce
        (256, 14, 14, 1024, 256, 1, 1),
    ]
    for (b, h, w, cin, cout, k, stride) in shapes:
        x0 = jnp.ones((b, h, w, cin), jnp.bfloat16)
        wgt = jnp.ones((k, k, cin, cout), jnp.bfloat16) * 0.01
        flops = 2 * b * (h // stride) * (w // stride) * cin * cout * k * k

        @jax.jit
        def fwd_loop(x0, wgt):
            def body(i, acc):
                y = jax.lax.conv_general_dilated(
                    x0, wgt, (stride, stride), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                return acc + y.astype(jnp.float32).mean() * (i + 1)

            return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

        t = timed_scalar(fwd_loop, x0, wgt) / REPS
        print(f"conv fwd b{b} {h}x{w} {cin}->{cout} k{k}: {t*1e3:.3f} ms -> "
              f"{flops/t/1e12:.1f} TFLOP/s")

        @jax.jit
        def bwd_loop(x0, wgt):
            def f(xw):
                x, wg = xw
                y = jax.lax.conv_general_dilated(
                    x, wg, (stride, stride), "SAME",
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                )
                return y.astype(jnp.float32).mean()

            def body(i, acc):
                gx, gw = jax.grad(f)((x0, wgt))
                return (acc + gx.astype(jnp.float32).mean() * (i + 1)
                        + gw.astype(jnp.float32).mean())

            return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

        t = timed_scalar(bwd_loop, x0, wgt) / REPS
        print(f"  fwd+bwd: {t*1e3:.3f} ms -> {3*flops/t/1e12:.1f} TFLOP/s eq")


if __name__ == "__main__":
    main()
