#!/usr/bin/env python
"""Conv throughput via carry-chained in-program repetition (defeats LICM/CSE:
each iteration's conv consumes the previous result)."""

import time

import jax
import jax.numpy as jnp


from benchlib import timed_scalar  # noqa: E402


REPS = 20


def main():
    shapes = [
        (256, 56, 56, 64, 3),
        (256, 28, 28, 128, 3),
        (256, 14, 14, 256, 3),
        (256, 7, 7, 512, 3),
        (256, 56, 56, 64, 1),
        (256, 14, 14, 256, 1),
    ]
    for (b, h, w, c, k) in shapes:
        x0 = jnp.ones((b, h, w, c), jnp.bfloat16)
        wgt = (jnp.ones((k, k, c, c), jnp.bfloat16) / (k * k * c))
        flops = 2 * b * h * w * c * c * k * k

        def conv(x, wg):
            return jax.lax.conv_general_dilated(
                x, wg, (1, 1), "SAME",
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )

        @jax.jit
        def fwd_chain(x0, wgt):
            def body(i, x):
                return conv(x, wgt)

            return jax.lax.fori_loop(0, REPS, body, x0).astype(jnp.float32).mean()

        t = timed_scalar(fwd_chain, x0, wgt) / REPS
        print(f"conv fwd b{b} {h}x{w} c{c} k{k}: {t*1e3:.3f} ms -> "
              f"{flops/t/1e12:.1f} TFLOP/s")

        @jax.jit
        def bwd_chain(x0, wgt):
            def f(x, wg):
                return conv(x, wg).astype(jnp.float32).mean()

            def body(i, carry):
                x, gw_acc = carry
                gx, gw = jax.grad(f, argnums=(0, 1))(x, wgt)
                return gx.astype(jnp.bfloat16), gw_acc + gw.astype(jnp.float32).mean()

            x, acc = jax.lax.fori_loop(0, REPS, body, (x0, jnp.float32(0)))
            return x.astype(jnp.float32).mean() + acc

        t = timed_scalar(bwd_chain, x0, wgt) / REPS
        print(f"  fwd+bwd chained: {t*1e3:.3f} ms -> {3*flops/t/1e12:.1f} TFLOP/s eq")


if __name__ == "__main__":
    main()
