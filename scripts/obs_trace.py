#!/usr/bin/env python
"""obs_trace — per-request serving-trace analyzer (ISSUE 17).

Reads the ``reqtrace`` ft_events the serving engine books into the
metrics JSONL (``serve_lm.py --req-trace --metrics-jsonl ...``, recorder
in obs/reqtrace.py) and answers the question the aggregate quantiles
can't: *where did the TTFT tail come from?*

    # human report: per-component attribution + tail rollup + slowest
    obs_trace.py --metrics-jsonl /tmp/serve.jsonl

    # machine form; recount SLO violations against a different target
    obs_trace.py --metrics-jsonl /tmp/serve.jsonl --json --slo-ms 250

    # standalone Perfetto file of the per-request tracks
    obs_trace.py --metrics-jsonl /tmp/serve.jsonl --perfetto /tmp/req.json

The Perfetto output holds the request tracks alone; to read them against
the engine's step timeline, pass the same records to
``obs.timeline.to_chrome_trace(..., req_traces=...)`` (the
``scripts/obs_timeline.py`` merge path).

Runs with **no jax in the process** — obs/reqtrace.py is loaded by file
path, never through the package ``__init__`` (which imports jax for the
shard_map bridge); ``--selftest`` asserts it, like obs_live.py, and
round-trips the checked-in fixture ``tests/data/reqtrace_fixture.jsonl``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS = os.path.join(_REPO, "pytorch_distributed_tpu", "obs")
FIXTURE = os.path.join(_REPO, "tests", "data", "reqtrace_fixture.jsonl")


def _load_obs(name: str):
    """Load ``pytorch_distributed_tpu/obs/<name>.py`` by path under the
    same ``_ptd_obs_<name>`` alias obs/alerts.py uses, so the sibling
    modules share one instance and jax never enters the process."""
    import importlib.util

    full = f"pytorch_distributed_tpu.obs.{name}"
    if full in sys.modules:
        return sys.modules[full]
    alias = f"_ptd_obs_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(
        alias, os.path.join(_OBS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


reqtrace = _load_obs("reqtrace")
metrics = _load_obs("metrics")


# ------------------------------------------------------------------ analysis

def analyze(path: str, slo_ms=None):
    """Parse the JSONL, optionally re-judge violations against
    ``slo_ms``, and return (records, attribution summary dict)."""
    records = metrics.read_metrics(path)
    trs = reqtrace.trace_records(records)
    if slo_ms is not None:
        for r in trs:
            r["violated"] = 1 if float(r.get("ttft_ms", 0)) > slo_ms else 0
    return trs, reqtrace.attribution_summary(trs)


def analyze_fleet(path: str):
    """Fleet-router reconciliation (ISSUE 19): the per-request
    ``fleettrace`` events checked against the decomposition identity and
    — when the same JSONL holds the replicas' ``reqtrace`` events — the
    engine's own TTFT.  None for routerless runs."""
    records = metrics.read_metrics(path)
    ftrs = reqtrace.fleet_trace_records(records)
    return ftrs, reqtrace.fleet_reconciliation(
        ftrs, reqtrace.trace_records(records))


def render_fleet(frec) -> str:
    lines = ["== fleet routing =="]
    if frec is None:
        return ""
    lines.append(
        f"requests {frec['requests']}  retried {frec['retried']}  "
        f"hedged {frec['hedged']}  router ttft p99 "
        f"{frec['router_ttft_p99_ms']:.1f}ms  router wait p99 "
        f"{frec['router_wait_p99_ms']:.1f}ms")
    lines.append(
        f"decomposition err max {frec['decomp_err_ms_max']:.4f}ms "
        "(router_ttft == router_wait + redispatch + hedge_wait "
        "+ engine_ttft)")
    if frec["engine_matched"]:
        lines.append(
            f"engine echo: {frec['engine_matched']} request(s) matched "
            f"to reqtrace; err max "
            f"{frec['engine_echo_err_ms_max']:.4f}ms")
    else:
        lines.append("engine echo: no matching reqtrace events in this "
                     "JSONL (replicas log to their own files)")
    return "\n".join(lines)


def render(summ, trs, slo_ms=None) -> str:
    lines = ["== request traces =="]
    if summ is None:
        lines.append("no reqtrace events (run serve_lm.py --req-trace)")
        return "\n".join(lines)
    lines.append(
        f"requests {summ['requests']}  violations {summ['violations']}"
        + (f" (slo {slo_ms:g}ms)" if slo_ms is not None else "")
        + f"  spans kept {summ['sampled_kept']}"
          f"  spans dropped {summ['spans_dropped']}"
          f"  preemptions {summ['preemptions']}")
    lines.append(
        f"ttft p50 {summ['ttft_p50_ms']:.1f}ms  "
        f"p99 {summ['ttft_p99_ms']:.1f}ms  "
        f"e2e p99 {summ['e2e_p99_ms']:.1f}ms  "
        f"recon err max {summ['recon_err_ms_max']:.3f}ms")
    lines.append(
        f"queue_wait_share_p99 {summ['queue_wait_share_p99']:.1f}%  "
        f"preempt_redo_ms_p99 {summ['preempt_redo_ms_p99']:.1f}ms")
    tail = summ.get("tail")
    if tail:
        lines.append("tail attribution: " + reqtrace.format_tail_line(tail))
        lines.append(f"dominant tail component: {tail['dominant']}")
    slow = sorted(trs, key=lambda r: -float(r.get("ttft_ms", 0)))[:5]
    if slow:
        lines.append("slowest requests (ttft | queue/prefill/redo/defrag):")
        for r in slow:
            lines.append(
                f"  {r.get('trace_id', '?'):<24} {r['ttft_ms']:8.1f}ms | "
                f"{r['queue_wait_ms']:.1f} / {r['prefill_ms']:.1f} / "
                f"{r['redo_wait_ms']:.1f} / {r['defrag_wait_ms']:.1f}"
                f"  (preempts {r.get('preemptions', 0)},"
                f" hops {len(json.loads(r['ctx'])['hops'])})")
    return "\n".join(lines)


# ------------------------------------------------------------------ selftest

def _selftest() -> int:
    assert "jax" not in sys.modules, \
        "obs_trace selftest must run jax-free (import-time hygiene)"
    assert os.path.exists(FIXTURE), f"missing fixture {FIXTURE}"

    trs, summ = analyze(FIXTURE)
    assert summ is not None and summ["requests"] >= 4, summ
    # every record reconciles: component sum == ttft (the recorder's
    # exactness contract, re-checked on the checked-in artifact)
    assert summ["recon_err_ms_max"] < 0.05, summ["recon_err_ms_max"]
    # the fixture is a preemption storm: redo must dominate the tail
    assert summ["tail"]["dominant"] == "preempt_redo", summ["tail"]
    # tail sampling kept every violator's spans
    for r in trs:
        if r.get("violated"):
            assert r.get("spans"), f"violator {r['rid']} lost its spans"
    out = render(summ, trs)
    for needle in ("== request traces ==", "tail attribution:",
                   "preempt_redo", "slowest requests"):
        assert needle in out, f"missing {needle!r} in:\n{out}"

    # --slo-ms re-judging: an absurdly high SLO clears all violations
    _, relaxed = analyze(FIXTURE, slo_ms=1e9)
    assert relaxed["violations"] == 0, relaxed["violations"]

    # round-trip: records -> chrome events -> a request track exists
    evs = reqtrace.chrome_events(trs)
    names = {e["args"]["name"] for e in evs
             if e.get("ph") == "M" and e.get("name") == "thread_name"}
    assert any(n.startswith("req ") for n in names), names
    kinds = {e["name"] for e in evs if e.get("ph") == "X"}
    assert {"queue", "prefill", "decode"} <= kinds, kinds
    assert "redo_prefill" in kinds, kinds

    # context wire round-trip (the router-propagation contract)
    ctx = reqtrace.TraceContext.from_wire(json.loads(trs[0]["ctx"]))
    assert ctx.to_wire() == json.loads(trs[0]["ctx"])
    assert ctx.hops and ctx.hops[0].startswith("engine"), ctx.hops

    # fleet reconciliation (ISSUE 19): one JSONL holding both the
    # router's fleettrace events and the replica's reqtrace events —
    # the decomposition identity and the engine-TTFT echo must both
    # reconcile exactly, and the section must render
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        fpath = os.path.join(d, "fleet.jsonl")
        with metrics.MetricsLogger(fpath, process_index=-2) as log:
            for i in range(4):
                retried = i == 3
                engine_ttft = 40.0 + i
                log.log_event(
                    "reqtrace", rid=i, trace_id=f"ptd-fleet-{i:08x}",
                    ttft_ms=engine_ttft, e2e_ms=engine_ttft + 20.0,
                    queue_wait_ms=5.0, prefill_ms=30.0,
                    redo_wait_ms=0.0, defrag_wait_ms=0.0,
                    other_wait_ms=engine_ttft - 35.0, tokens=8,
                    preemptions=0, violated=0, n_spans=4,
                    spans_dropped=0, sampled=1)
                log.log_event(
                    "fleettrace", rid=i, trace_id=f"ptd-fleet-{i:08x}",
                    replica=i % 2, attempts=2 if retried else 1,
                    hedged=0, router_wait_ms=1.25,
                    redispatch_ms=30.0 if retried else 0.0,
                    hedge_wait_ms=0.0, engine_ttft_ms=engine_ttft,
                    engine_e2e_ms=engine_ttft + 20.0,
                    router_ttft_ms=(1.25 + (30.0 if retried else 0.0)
                                    + engine_ttft),
                    router_e2e_ms=(1.25 + (30.0 if retried else 0.0)
                                   + engine_ttft + 20.0))
        ftrs, frec = analyze_fleet(fpath)
        assert frec is not None and frec["requests"] == 4, frec
        assert frec["retried"] == 1 and frec["hedged"] == 0, frec
        assert frec["decomp_err_ms_max"] < 1e-9, frec
        assert frec["engine_matched"] == 4, frec
        assert frec["engine_echo_err_ms_max"] < 1e-9, frec
        fout = render_fleet(frec)
        for needle in ("== fleet routing ==", "requests 4  retried 1",
                       "decomposition err max 0.0000ms",
                       "engine echo: 4 request(s) matched"):
            assert needle in fout, f"missing {needle!r} in:\n{fout}"
        # a routerless JSONL keeps the section (and --json key) out
        _t, none_rec = analyze_fleet(FIXTURE)
        assert none_rec is None, none_rec
        assert render_fleet(None) == ""

    assert "jax" not in sys.modules
    print("obs_trace selftest: OK")
    return 0


# ---------------------------------------------------------------------- main

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-request serving-trace attribution")
    ap.add_argument("--metrics-jsonl", default=None,
                    help="metrics JSONL holding reqtrace events")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable attribution summary")
    ap.add_argument("--slo-ms", type=float, default=None,
                    help="re-judge SLO violations against this TTFT target")
    ap.add_argument("--perfetto", default=None, metavar="OUT",
                    help="write per-request tracks as a Chrome-trace JSON")
    ap.add_argument("--selftest", action="store_true",
                    help="fixture round-trip + jax-free assertion")
    args = ap.parse_args(argv)

    if args.selftest:
        return _selftest()
    if not args.metrics_jsonl:
        ap.error("--metrics-jsonl is required (or --selftest)")
    trs, summ = analyze(args.metrics_jsonl, slo_ms=args.slo_ms)
    _ftrs, frec = analyze_fleet(args.metrics_jsonl)
    if args.perfetto:
        trace = {"traceEvents": reqtrace.chrome_events(trs),
                 "displayTimeUnit": "ms"}
        with open(args.perfetto, "w") as f:
            json.dump(trace, f)
        print(f"wrote {args.perfetto} "
              f"({len(trace['traceEvents'])} events)")
    if args.as_json:
        out = dict(summ) if summ else {}
        if frec is not None:
            out["fleet"] = frec
        print(json.dumps(out, indent=2, sort_keys=True))
    else:
        print(render(summ, trs, slo_ms=args.slo_ms))
        if frec is not None:
            print(render_fleet(frec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
