#!/bin/bash
# Probe the axon tunnel in fresh subprocesses (a wedged jax.devices()
# poisons its interpreter — only a clean process can retry); whenever the
# tunnel answers and the host is not running the test suite, run the
# on-chip capture queue in priority order until every target artifact is
# complete.  Round-5 queue (VERDICT r4 "Next round" #2/#4/#5):
#   1. arch_bench      -> RESULTS_archs.json       (13-arch fig1 table)
#   2. decode_bench    -> int8 + speculative + b32-breakdown + long-prefill
#   3. bench.py        -> fresh BENCH_LKG (non-stale BENCH_r05 source)
#   4. lm_bench        -> fused-CE MFU rows (the declared perf axis)
cd /root/repo || exit 1
mkdir -p runs
LOG=runs/tunnel_watch.log
want=${ARCH_WATCH_WANT:-13}
# Fresh retry budget per watcher launch: the cap separates deterministic
# failures within ONE session from transient tunnel deaths; it must not
# outlive the session that observed them.
rm -f runs/decode_bench.tries runs/lm_bench.tries runs/bench_lkg.tries
for i in $(seq 1 300); do
  # Count every recorded row, error rows included: a deterministically
  # failing arch is a final answer, not a reason to re-run forever.
  have=$(python - <<'PY' 2>/dev/null
import json
try:
    print(len(json.load(open("RESULTS_archs.json"))["configs"]))
except Exception:
    print(0)
PY
)
  decode_done=$(python - <<'PY' 2>/dev/null
import json
try:
    d = json.load(open("RESULTS_decode.json"))["configs"]
    need = {"b1_p512_greedy_int8w", "b8_p512_greedy_int8w",
            "b1_spec_t1.0", "b32_breakdown", "b1_p4096_prefill_flash"}
    print(1 if need <= set(d) else 0)
except Exception:
    print(0)
PY
)
  lm_done=$(python - <<'PY' 2>/dev/null
import json
try:
    d = json.load(open("RESULTS_lm.json"))["configs"]
    print(1 if "L1024_b4_flash_fusedce8" in d else 0)
except Exception:
    print(0)
PY
)
  [ "${decode_done:-0}" = "1" ] && rm -f runs/decode_bench.tries
  [ "${lm_done:-0}" = "1" ] && rm -f runs/lm_bench.tries
  d_tries=$(cat runs/decode_bench.tries 2>/dev/null || echo 0)
  l_tries=$(cat runs/lm_bench.tries 2>/dev/null || echo 0)
  b_tries=$(cat runs/bench_lkg.tries 2>/dev/null || echo 0)
  if [ "${have:-0}" -ge "$want" ] \
     && { [ "${decode_done:-0}" = "1" ] || [ "$d_tries" -ge 3 ]; } \
     && { [ "${lm_done:-0}" = "1" ] || [ "$l_tries" -ge 3 ]; } \
     && [ "$b_tries" -ge 1 ]; then
    echo "$(date -u +%H:%M:%S) captures finished (decode=$decode_done lm=$lm_done)" >> "$LOG"
    exit 0
  fi
  if ! pgrep -f "pytest tests/" >/dev/null 2>&1; then
    if timeout 60 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "$(date -u +%H:%M:%S) tunnel up (archs $have/$want decode $decode_done lm $lm_done bench $b_tries) -> captures" >> "$LOG"
      if [ "${have:-0}" -lt "$want" ]; then
        timeout 2700 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u experiments/arch_bench.py >> "$LOG" 2>&1
      fi
      # Cap per-bench retries: a deterministic failure is a final answer,
      # not a reason to re-run a 20-min bench forever.
      if [ "${decode_done:-0}" != "1" ] && [ "$d_tries" -lt 3 ]; then
        echo $((d_tries + 1)) > runs/decode_bench.tries
        timeout 1800 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u experiments/decode_bench.py >> "$LOG" 2>&1
      fi
      if [ "$b_tries" -lt 1 ]; then
        echo $((b_tries + 1)) > runs/bench_lkg.tries
        timeout 1200 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u bench.py >> "$LOG" 2>&1
      fi
      if [ "${lm_done:-0}" != "1" ] && [ "$l_tries" -lt 3 ]; then
        echo $((l_tries + 1)) > runs/lm_bench.tries
        timeout 2400 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u experiments/lm_bench.py >> "$LOG" 2>&1
      fi
      echo "$(date -u +%H:%M:%S) capture attempt ended" >> "$LOG"
    fi
  fi
  sleep 90
done
