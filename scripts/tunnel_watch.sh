#!/bin/bash
# Probe the axon tunnel in fresh subprocesses (a wedged jax.devices()
# poisons its interpreter — only a clean process can retry); whenever the
# tunnel answers and the host is not running the test suite, (re)run the
# resumable arch sweep until RESULTS_archs.json holds every arch.
cd /root/repo || exit 1
mkdir -p runs
LOG=runs/tunnel_watch.log
want=${ARCH_WATCH_WANT:-13}
for i in $(seq 1 300); do
  # Count every recorded row, error rows included: a deterministically
  # failing arch is a final answer, not a reason to re-run forever.
  have=$(python - <<'PY' 2>/dev/null
import json
try:
    print(len(json.load(open("RESULTS_archs.json"))["configs"]))
except Exception:
    print(0)
PY
)
  if [ "${have:-0}" -ge "$want" ]; then
    echo "$(date -u +%H:%M:%S) sweep complete ($have archs)" >> "$LOG"
    exit 0
  fi
  if ! pgrep -f "pytest tests/" >/dev/null 2>&1; then
    if timeout 60 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "$(date -u +%H:%M:%S) tunnel up ($have/$want) -> sweep" >> "$LOG"
      timeout 2700 env PYTHONPATH=/root/repo:/root/.axon_site \
        python -u experiments/arch_bench.py >> "$LOG" 2>&1
      echo "$(date -u +%H:%M:%S) sweep attempt ended" >> "$LOG"
    fi
  fi
  sleep 90
done
