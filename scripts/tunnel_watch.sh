#!/bin/bash
# Probe the axon tunnel in fresh subprocesses (a wedged jax.devices()
# poisons its interpreter — only a clean process can retry); whenever the
# tunnel answers and the host is not running the test suite, (re)run the
# resumable arch sweep until RESULTS_archs.json holds every arch.
cd /root/repo || exit 1
mkdir -p runs
LOG=runs/tunnel_watch.log
want=${ARCH_WATCH_WANT:-13}
# Fresh retry budget per watcher launch: the cap separates deterministic
# failures within ONE session from transient tunnel deaths; it must not
# outlive the session that observed them.
rm -f runs/decode_bench.tries
for i in $(seq 1 300); do
  # Count every recorded row, error rows included: a deterministically
  # failing arch is a final answer, not a reason to re-run forever.
  have=$(python - <<'PY' 2>/dev/null
import json
try:
    print(len(json.load(open("RESULTS_archs.json"))["configs"]))
except Exception:
    print(0)
PY
)
  quant_done=$(python - <<'PY' 2>/dev/null
import json
try:
    d = json.load(open("RESULTS_decode.json"))["configs"]
    # BOTH promised int8 rows (a partial capture is not done).
    keys = {k for k in d if k.endswith("_int8w")}
    print(1 if {"b1_p512_greedy_int8w", "b8_p512_greedy_int8w"} <= keys
          else 0)
except Exception:
    print(0)
PY
)
  [ "${quant_done:-0}" = "1" ] && rm -f runs/decode_bench.tries
  tries_now=$(cat runs/decode_bench.tries 2>/dev/null || echo 0)
  if [ "${have:-0}" -ge "$want" ] && { [ "${quant_done:-0}" = "1" ] || [ "$tries_now" -ge 3 ]; }; then
    echo "$(date -u +%H:%M:%S) captures finished (int8 ok=$quant_done tries=$tries_now)" >> "$LOG"
    exit 0
  fi
  if ! pgrep -f "pytest tests/" >/dev/null 2>&1; then
    if timeout 60 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
      echo "$(date -u +%H:%M:%S) tunnel up ($have/$want archs, int8 $quant_done) -> captures" >> "$LOG"
      if [ "${have:-0}" -lt "$want" ]; then
        timeout 2700 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u experiments/arch_bench.py >> "$LOG" 2>&1
      fi
      # Cap decode-bench retries: a deterministic failure is a final
      # answer here too, not a reason to re-run a 20-min bench forever.
      tries=$(cat runs/decode_bench.tries 2>/dev/null || echo 0)
      if [ "${quant_done:-0}" != "1" ] && [ "$tries" -lt 3 ]; then
        echo $((tries + 1)) > runs/decode_bench.tries
        timeout 1200 env PYTHONPATH=/root/repo:/root/.axon_site \
          python -u experiments/decode_bench.py >> "$LOG" 2>&1
      fi
      echo "$(date -u +%H:%M:%S) capture attempt ended" >> "$LOG"
    fi
  fi
  sleep 90
done
