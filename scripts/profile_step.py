#!/usr/bin/env python
"""Step-phase breakdown on the real chip: fwd, fwd+bwd, full step, raw matmul.

Finds where the ResNet-50 step time goes (VERDICT round-1: backward runs
3.5x forward where ~2x is expected).  Run on TPU: ``python scripts/profile_step.py``.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from benchlib import timed_step_loop, timed_tree  # noqa: E402

timed = partial(timed_tree, iters=20, warmup=3)


def main():
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.ops import cross_entropy
    from pytorch_distributed_tpu.train.optim import sgd_init, sgd_update
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step
    from pytorch_distributed_tpu.parallel import data_parallel_mesh

    batch, image = 256, 224
    mesh = data_parallel_mesh()
    model = models.create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
                          train=False)
    params, stats = variables["params"], variables["batch_stats"]
    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.normal(size=(batch, image, image, 3)).astype(np.float32))
    labels = jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32))

    # --- raw MXU ceiling probe: bf16 matmul ---
    m = 8192
    a = jnp.ones((m, m), jnp.bfloat16)
    mm = jax.jit(lambda x: x @ x)
    t = timed(mm, a)
    print(f"matmul {m}x{m} bf16: {t*1e3:.2f} ms -> {2*m**3/t/1e12:.1f} TFLOP/s")

    # --- forward only (train mode, mutable stats) ---
    def fwd(p, s, x):
        logits, mut = model.apply({"params": p, "batch_stats": s}, x,
                                  train=True, mutable=["batch_stats"])
        return logits.sum()

    f = jax.jit(fwd)
    t_fwd = timed(f, params, stats, images)
    print(f"forward(train): {t_fwd*1e3:.2f} ms")

    # --- forward eval mode ---
    fe = jax.jit(lambda p, s, x: model.apply(
        {"params": p, "batch_stats": s}, x, train=False).sum())
    t_fe = timed(fe, params, stats, images)
    print(f"forward(eval):  {t_fe*1e3:.2f} ms")

    # --- fwd + bwd (loss grad wrt params) ---
    def loss_fn(p, s, x, y):
        logits, mut = model.apply({"params": p, "batch_stats": s}, x,
                                  train=True, mutable=["batch_stats"])
        return cross_entropy(logits, y), mut

    g = jax.jit(jax.grad(loss_fn, has_aux=True))
    t_bwd = timed(g, params, stats, images, labels)
    print(f"fwd+bwd: {t_bwd*1e3:.2f} ms (bwd-only ~{(t_bwd-t_fwd)*1e3:.2f} ms, "
          f"ratio {(t_bwd-t_fwd)/t_fwd:.2f}x fwd)")

    # --- optimizer update alone ---
    mom = sgd_init(params)
    grads = jax.tree_util.tree_map(jnp.zeros_like, params)
    upd = jax.jit(lambda g_, m_, p_: sgd_update(g_, m_, p_, jnp.float32(0.1)))
    t_upd = timed(upd, grads, mom, params)
    print(f"sgd update: {t_upd*1e3:.2f} ms")

    # --- full train step (the bench path) ---
    state = TrainState.create({"params": params, "batch_stats": stats},
                              sgd_init(params))
    step = make_train_step(model, mesh)
    b = {"images": images, "labels": labels,
         "weights": jnp.ones((batch,), jnp.float32)}

    t_step, _ = timed_step_loop(step, state, b, jnp.float32(0.1),
                                iters=20, warmup=3)
    print(f"full step: {t_step*1e3:.2f} ms -> {batch/t_step:.0f} img/s")


if __name__ == "__main__":
    main()
