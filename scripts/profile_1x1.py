#!/usr/bin/env python
"""1x1 conv vs equivalent reshaped matmul at ResNet stage-1 shapes (chained
in-program so LICM can't hoist)."""

import time

import jax
import jax.numpy as jnp

REPS = 20


from benchlib import timed_scalar  # noqa: E402


def bench(b, h, w, cin, cout, label):
    x0 = jnp.ones((b, h, w, cin), jnp.bfloat16)
    w1 = jnp.ones((1, 1, cin, cout), jnp.bfloat16) / cin
    w2 = jnp.ones((1, 1, cout, cin), jnp.bfloat16) / cout
    flops = 2 * b * h * w * cin * cout * 2  # two convs per chain iter

    @jax.jit
    def conv_chain(x0, w1, w2):
        def body(i, x):
            y = jax.lax.conv_general_dilated(
                x, w1, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
            return jax.lax.conv_general_dilated(
                y, w2, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))

        return jax.lax.fori_loop(0, REPS, body, x0).astype(jnp.float32).mean()

    t = timed_scalar(conv_chain, x0, w1, w2) / REPS
    print(f"{label} conv1x1 pair: {t*1e3:.3f} ms -> {flops/t/1e12:.1f} TFLOP/s")

    wa = w1.reshape(cin, cout)
    wb = w2.reshape(cout, cin)

    @jax.jit
    def dot_chain(x0, wa, wb):
        def body(i, x):
            y = x @ wa
            return y @ wb

        return jax.lax.fori_loop(0, REPS, body, x0).astype(jnp.float32).mean()

    t = timed_scalar(dot_chain, x0, wa, wb) / REPS
    print(f"{label} dot pair:     {t*1e3:.3f} ms -> {flops/t/1e12:.1f} TFLOP/s")

    # flattened-spatial dot (one big M dim)
    xf = x0.reshape(b * h * w, cin)

    @jax.jit
    def dotf_chain(xf, wa, wb):
        def body(i, x):
            return (x @ wa) @ wb

        return jax.lax.fori_loop(0, REPS, body, xf).astype(jnp.float32).mean()

    t = timed_scalar(dotf_chain, xf, wa, wb) / REPS
    print(f"{label} flat dot:     {t*1e3:.3f} ms -> {flops/t/1e12:.1f} TFLOP/s")

    # weight-grad shape: [cin, M] @ [M, cout]
    g = jnp.ones((b * h * w, cout), jnp.bfloat16)

    @jax.jit
    def wgrad(xf, g):
        def body(i, acc):
            gw = (xf * (1.0 + acc)).T @ g
            return gw.astype(jnp.float32).mean()

        return jax.lax.fori_loop(0, REPS, body, jnp.float32(0))

    wflops = 2 * b * h * w * cin * cout
    t = timed_scalar(wgrad, xf, g) / REPS
    print(f"{label} wgrad dot:    {t*1e3:.3f} ms -> {wflops/t/1e12:.1f} TFLOP/s")


if __name__ == "__main__":
    bench(256, 56, 56, 64, 256, "56x56  64<->256")
    bench(256, 28, 28, 128, 512, "28x28 128<->512")
    bench(256, 14, 14, 256, 1024, "14x14 256<->1024")
