"""Which fused family is slow? unfused vs 1x1-only vs both, same discipline as bench.py."""
import time, json
import jax, jax.numpy as jnp, numpy as np
from pytorch_distributed_tpu import models
from pytorch_distributed_tpu.ops import fused_conv_bn as fcb
from pytorch_distributed_tpu.parallel import data_parallel_mesh
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState
from pytorch_distributed_tpu.train.steps import make_train_step

mesh = data_parallel_mesh()
batch, image = 256, 224
rng = np.random.default_rng(0)
db = {"images": jnp.asarray(rng.normal(size=(batch, image, image, 3)), dtype=jnp.bfloat16),
      "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
      "weights": jnp.ones((batch,), jnp.float32)}

def measure(fused, allow3):
    orig = fcb.conv3x3_plane_fits_vmem
    if not allow3:
        fcb.conv3x3_plane_fits_vmem = lambda *a, **k: False
    try:
        model = models.create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16,
                                    stem="space_to_depth", fused_convbn=fused)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False)
        state = TrainState.create(variables, sgd_init(variables["params"]))
        step = make_train_step(model, mesh)
        for _ in range(3):
            state, metrics = step(state, db, jnp.float32(0.1))
        float(metrics["loss"])
        iters = 20
        t0 = time.perf_counter()
        for _ in range(iters):
            state, metrics = step(state, db, jnp.float32(0.1))
        assert np.isfinite(float(metrics["loss"]))
        dt = time.perf_counter() - t0
        return batch * iters / dt
    finally:
        fcb.conv3x3_plane_fits_vmem = orig

out = {}
out["unfused"] = round(measure(False, True), 1)
out["fused_1x1_only"] = round(measure(True, False), 1)
out["fused_both"] = round(measure(True, True), 1)
print(json.dumps(out))
