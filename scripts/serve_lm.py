#!/usr/bin/env python
"""serve_lm — continuous-batching LM serving over the paged KV cache.

Front end for ``pytorch_distributed_tpu.serving``: builds a (random-init
or checkpointed) TransformerLM-compatible parameter tree, a
``ServingEngine`` with a paged KV pool, and drives a seeded synthetic
load trace (serving/loadgen.py) through it, emitting the serving SLO
fields (TTFT / inter-token-latency percentiles, queue depth, KV
occupancy, preemptions, tokens/s) into the same MetricsLogger JSONL the
training planes use — so ``obs_report``, the Prometheus exporter, and
the alert engine fold serving runs with zero new plumbing.

``--slo-ttft-ms`` / ``--slo-kv-pct`` arm live ``ttft_p99`` /
``kv_occupancy`` alert rules (obs/alerts.py) over the run's own stream;
breaches are booked as ``alert`` ft_events in the JSONL.

Examples:

    python scripts/serve_lm.py --requests 32 --rate-rps 50 \
        --max-batch 4 --kv-blocks 64 --block-size 16 \
        --metrics-jsonl /tmp/serve.jsonl --slo-ttft-ms 500
    python scripts/serve_lm.py --mode static ...   # naive wave baseline
    python scripts/serve_lm.py --gamma 3 ...       # speculative decode
    python scripts/serve_lm.py --quant int8 ...    # int8 weight-only
    python scripts/serve_lm.py --req-trace --trace-sample 0.25 ...
    python scripts/serve_lm.py --checkpoint pretrained/lm.msgpack ...

``--req-trace`` arms the per-request span recorder (obs/reqtrace.py):
every request's TTFT/e2e decomposes into queue-wait / prefill /
preempt-redo / defrag components, booked as ``reqtrace`` ft_events and
analyzed by ``scripts/obs_trace.py``; ``--checkpoint`` serves real
weights imported from a torch LM (scripts/import_torch_checkpoint.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="serve_lm.py",
        description="continuous-batching LM serving with a paged KV cache")
    m = ap.add_argument_group("model")
    m.add_argument("--vocab-size", type=int, default=64)
    m.add_argument("--d-model", type=int, default=32)
    m.add_argument("--n-heads", type=int, default=4)
    m.add_argument("--n-layers", type=int, default=2)
    m.add_argument("--quant", choices=("", "int8"), default="",
                   help="int8 = weight-only quantized serving "
                        "(models/quant.py)")
    m.add_argument("--checkpoint", default=None,
                   help="serve real weights: an LM msgpack written by "
                        "scripts/import_torch_checkpoint.py (vocab/"
                        "d-model/n-layers come from the tree; --quant "
                        "still composes)")
    m.add_argument("--gamma", type=int, default=0,
                   help="speculative draft length (0 = off; greedy only)")
    m.add_argument("--draft-d-model", type=int, default=16)
    m.add_argument("--draft-layers", type=int, default=1)

    e = ap.add_argument_group("engine")
    e.add_argument("--max-batch", type=int, default=4,
                   help="decode slot count (the static [B] batch)")
    e.add_argument("--kv-blocks", type=int, default=64,
                   help="paged KV pool size in blocks (block 0 reserved)")
    e.add_argument("--block-size", type=int, default=16,
                   help="tokens per KV block")
    e.add_argument("--blocks-per-seq", type=int, default=8,
                   help="block-table width = per-sequence token cap / "
                        "block size")
    e.add_argument("--chunk-size", type=int, default=8,
                   help="chunked-prefill chunk length")
    e.add_argument("--max-new-tokens", type=int, default=16,
                   help="cap on generated tokens per request")
    e.add_argument("--mode", choices=("continuous", "static"),
                   default="continuous",
                   help="static = naive wave batching (the A/B baseline)")
    e.add_argument("--policy", choices=("fcfs", "priority"),
                   default="fcfs")
    e.add_argument("--defrag-threshold-pct", type=float, default=50.0)
    e.add_argument("--temperature", type=float, default=0.0)
    e.add_argument("--top-k", type=int, default=0)
    e.add_argument("--top-p", type=float, default=1.0)

    l = ap.add_argument_group("load")
    l.add_argument("--requests", type=int, default=32)
    l.add_argument("--rate-rps", type=float, default=50.0)
    l.add_argument("--profile", choices=("mixed", "uniform"),
                   default="mixed")
    l.add_argument("--seed", type=int, default=0)

    o = ap.add_argument_group("observability")
    o.add_argument("--metrics-jsonl", default=None,
                   help="serving SLO metrics JSONL (obs_report-foldable)")
    o.add_argument("--slo-ttft-ms", type=float, default=None,
                   help="arm a live ttft_p99 alert rule at this ceiling")
    o.add_argument("--slo-kv-pct", type=float, default=None,
                   help="arm a live kv_occupancy alert rule at this pct")
    o.add_argument("--req-trace", action="store_true", dest="req_trace",
                   help="per-request span tracing (obs/reqtrace.py): "
                        "TTFT/e2e critical-path attribution booked as "
                        "reqtrace ft_events; analyze with "
                        "scripts/obs_trace.py")
    o.add_argument("--trace-sample", type=float, default=0.05,
                   dest="trace_sample",
                   help="span retention rate for non-violating requests "
                        "(SLO violators always keep their spans)")
    o.add_argument("--no-watchdog", action="store_true",
                   help="disable the recompile watchdog around the steps")
    o.add_argument("--summary-json", default=None,
                   help="write the run summary dict to this path")
    return ap


def load_checkpoint_params(path: str):
    """Read a ``save_as_pretrained`` LM msgpack (written by
    scripts/import_torch_checkpoint.py) and return
    ``(params, vocab_size, d_model, n_layers)`` with the dims inferred
    from the tree itself (n_heads never shapes it)."""
    from flax import serialization

    with open(path, "rb") as f:
        payload = serialization.msgpack_restore(f.read())
    state = payload.get("state", payload)
    params = state.get("params", state)
    if "embed" not in params:
        raise SystemExit(
            f"--checkpoint {path}: not an LM param tree (missing 'embed');"
            " convert with scripts/import_torch_checkpoint.py")
    vocab, d_model = params["embed"]["embedding"].shape
    n_layers = sum(1 for k in params if k.startswith("block_"))
    return params, int(vocab), int(d_model), n_layers


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from pytorch_distributed_tpu.obs.alerts import AlertEngine, Rule
    from pytorch_distributed_tpu.obs.metrics import MetricsLogger
    from pytorch_distributed_tpu.obs.watchdog import RecompileWatchdog
    from pytorch_distributed_tpu.serving.engine import (
        ServingEngine,
        init_lm_params,
    )
    from pytorch_distributed_tpu.serving.loadgen import (
        LoadConfig,
        generate_load,
    )

    if args.checkpoint:
        (params, args.vocab_size, args.d_model,
         args.n_layers) = load_checkpoint_params(args.checkpoint)
    else:
        params = init_lm_params(args.vocab_size, args.d_model, args.n_heads,
                                args.n_layers, block_size=args.block_size,
                                seed=args.seed)
    if args.quant == "int8":
        from pytorch_distributed_tpu.models.quant import quantize_lm_params

        params = quantize_lm_params(params)
    draft = None
    if args.gamma > 0:
        draft = init_lm_params(args.vocab_size, args.draft_d_model,
                               args.n_heads, args.draft_layers,
                               block_size=args.block_size,
                               seed=args.seed + 1)

    obs = MetricsLogger(args.metrics_jsonl, flush_every=1)
    rules = []
    if args.slo_ttft_ms is not None:
        rules.append(Rule("ttft_p99", "ttft_p99", "page",
                          {"max_ms": float(args.slo_ttft_ms)}))
    if args.slo_kv_pct is not None:
        rules.append(Rule("kv_occupancy", "kv_occupancy", "warn",
                          {"max_pct": float(args.slo_kv_pct)}))
    if rules:
        alert_engine = AlertEngine(
            rules, emit=lambda **f: obs.log_event("alert", **f))
        obs.register(alert_engine.observe)

    wd = None
    if not args.no_watchdog:
        wd = RecompileWatchdog(obs=obs)
        wd.install()

    tracer = None
    if args.req_trace:
        from pytorch_distributed_tpu.obs.reqtrace import ReqTracer

        tracer = ReqTracer(slo_ms=args.slo_ttft_ms,
                           sample=args.trace_sample)

    eng = ServingEngine(
        params, vocab_size=args.vocab_size, d_model=args.d_model,
        n_heads=args.n_heads, n_layers=args.n_layers,
        max_batch=args.max_batch, kv_blocks=args.kv_blocks,
        block_size=args.block_size, blocks_per_seq=args.blocks_per_seq,
        chunk_size=args.chunk_size, max_new_tokens=args.max_new_tokens,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        quant=args.quant, gamma=args.gamma, draft_params=draft,
        policy=args.policy, mode=args.mode,
        defrag_threshold_pct=args.defrag_threshold_pct,
        obs=obs, watchdog=wd, trace=tracer, seed=args.seed)

    load = generate_load(LoadConfig(
        n_requests=args.requests, rate_rps=args.rate_rps,
        profile=args.profile, vocab_size=args.vocab_size, seed=args.seed))
    for _, req in load:
        req.max_new_tokens = min(req.max_new_tokens, args.max_new_tokens)

    try:
        summary = eng.run(load)
    finally:
        if wd is not None:
            wd.uninstall()
        obs.close()

    summary["recompile_anomalies"] = len(wd.anomalies) if wd else None
    if tracer is not None:
        summary["traces_completed"] = tracer.completed
        summary["trace_violations"] = tracer.violations
        summary["trace_spans_dropped"] = tracer.spans_dropped
    print(json.dumps(summary, indent=2, sort_keys=True))
    if args.summary_json:
        with open(args.summary_json, "w") as f:
            json.dump(summary, f, indent=2, sort_keys=True)
            f.write("\n")
    return 0 if summary["completed"] == args.requests else 1


if __name__ == "__main__":
    sys.exit(main())
