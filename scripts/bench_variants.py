#!/usr/bin/env python
"""Time ResNet-50 train-step variants on the real chip.

Variants probe the levers the round-2 profile surfaced (the step is
HBM-roofline-bound at ~690 GB/s effective):
  - batch 256 vs 512           (amortize fixed/latency costs)
  - conv7 vs space_to_depth    (stem MXU packing)
  - f32 vs bf16 input images   (stem read traffic)
  - fused vs unfused conv+BN backward (round 4: the BN-dx fold,
    ops/fused_conv_bn.py — the only identified route past the ceiling)

Select variants by substring — multiple args are OR'd (a variant runs if
ANY substring matches its tag), so ``b256 fused`` = all b256 variants
PLUS all fused variants; use one precise substring for an intersection
(e.g. ``b256-space_to_depth-bfloat16-fusedconvbn``).
"""

import itertools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(step, state, batch, lr, iters=20):
    for _ in range(3):
        state, met = step(state, batch, lr)
    float(met["loss"])
    t0 = time.perf_counter()
    for _ in range(iters):
        state, met = step(state, batch, lr)
    assert np.isfinite(float(met["loss"]))
    return (time.perf_counter() - t0) / iters


def main():
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = data_parallel_mesh()
    image = 224
    rng = np.random.default_rng(0)
    lr = jnp.float32(0.1)

    combos = itertools.product(
        (256, 512), ("conv7", "space_to_depth"), (np.float32, jnp.bfloat16),
        (False, True))
    only = sys.argv[1:] or None
    for batch, stem, in_dtype, fused in combos:
        # Every tag carries a terminal fused-axis token so neither variant's
        # tag is a substring of the other (precise selection stays possible).
        tag = (f"b{batch}-{stem}-{np.dtype(in_dtype).name}"
               + ("-fusedconvbn" if fused else "-unfused"))
        if only and not any(o in tag for o in only):
            continue
        model = models.create_model(
            "resnet50", num_classes=1000, dtype=jnp.bfloat16, stem=stem,
            fused_convbn=fused)
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False)
        state = TrainState.create(variables, sgd_init(variables["params"]))
        step = make_train_step(model, mesh)
        b = {
            "images": jnp.asarray(
                rng.normal(size=(batch, image, image, 3)), dtype=in_dtype),
            "labels": jnp.asarray(
                rng.integers(0, 1000, size=batch).astype(np.int32)),
            "weights": jnp.ones((batch,), jnp.float32),
        }
        try:
            dt = timeit(step, state, b, lr)
        except Exception as e:  # noqa: BLE001 — e.g. Mosaic rejecting the
            # fused kernel on this chip/toolchain: report, keep sweeping.
            print(f"{tag:34s} FAILED {type(e).__name__}: {str(e)[:120]}",
                  flush=True)
            continue
        print(f"{tag:34s} {dt*1e3:8.2f} ms/step  {batch/dt:8.1f} img/s",
              flush=True)


if __name__ == "__main__":
    main()
