#!/usr/bin/env python
"""Does fusing BN statistics into the conv epilogue slow the conv?

Chained conv+BN blocks, with and without an optimization_barrier between the
conv output and the statistics reduction."""

import time
from functools import partial

import jax
import jax.numpy as jnp

REPS = 10


from benchlib import timed_scalar  # noqa: E402


def conv1x1(x, w):
    return jax.lax.conv_general_dilated(
        x, w, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def bn_train(y, barrier):
    if barrier:
        (y,) = jax.lax.optimization_barrier((y,))
    yf = y.astype(jnp.float32)
    mu = yf.mean(axis=(0, 1, 2))
    var = (yf * yf).mean(axis=(0, 1, 2)) - mu * mu
    inv = jax.lax.rsqrt(var + 1e-5)
    return ((yf - mu) * inv).astype(jnp.bfloat16)


def make_block(barrier):
    def block(x, w1, w2):
        y = bn_train(conv1x1(x, w1), barrier)
        y = jax.nn.relu(y)
        y = bn_train(conv1x1(y, w2), barrier)
        return jax.nn.relu(y)

    return block


def bench(b, h, w, cin, cout):
    x0 = jnp.ones((b, h, w, cin), jnp.bfloat16)
    w1 = jnp.ones((1, 1, cin, cout), jnp.bfloat16) / cin
    w2 = jnp.ones((1, 1, cout, cin), jnp.bfloat16) / cout
    flops = 2 * b * h * w * cin * cout * 2

    for barrier in (False, True):
        block = make_block(barrier)

        @jax.jit
        def fwd(x0, w1, w2):
            def body(i, x):
                return block(x, w1, w2)

            return jax.lax.fori_loop(0, REPS, body, x0).astype(jnp.float32).mean()

        t = timed_scalar(fwd, x0, w1, w2) / REPS
        print(f"{h}x{w} {cin}<->{cout} fwd  barrier={barrier}: {t*1e3:.3f} ms "
              f"-> {flops/t/1e12:.1f} conv-TFLOP/s")

        @jax.jit
        def fwdbwd(x0, w1, w2):
            def loss(x, w1, w2):
                return block(x, w1, w2).astype(jnp.float32).mean()

            def body(i, carry):
                x, acc = carry
                gx, g1, g2 = jax.grad(loss, argnums=(0, 1, 2))(x, w1, w2)
                return gx.astype(jnp.bfloat16), acc + g1.astype(jnp.float32).mean()

            x, acc = jax.lax.fori_loop(0, REPS, body, (x0, jnp.float32(0)))
            return x.astype(jnp.float32).mean() + acc

        t = timed_scalar(fwdbwd, x0, w1, w2) / REPS
        print(f"{h}x{w} {cin}<->{cout} f+b  barrier={barrier}: {t*1e3:.3f} ms "
              f"-> {3*flops/t/1e12:.1f} conv-TFLOP/s eq")


if __name__ == "__main__":
    bench(256, 56, 56, 64, 256)
    bench(256, 28, 28, 128, 512)
