#!/usr/bin/env python
"""obs_live — fleet telemetry aggregator + live alert console (ISSUE 14).

The read side of the live telemetry plane: every rank serves its latest
metrics record on ``--metrics-port + rank`` (obs/export.py); this CLI
scrapes those endpoints, tails the heartbeat dir, feeds both into the
*same* declarative ``AlertEngine`` the trainers run (obs/alerts.py), and
renders a terminal dashboard.  Runs on a login node with **no jax in the
process** — the obs modules are loaded by file path, never through the
package ``__init__`` (which imports jax for the shard_map bridge).

Usage:

    # watch a 4-rank local run (ports 9100..9103), 5 s cadence
    obs_live.py --ports 9100 --world 4 --hb-dir /tmp/run/hb

    # one aggregation cycle for cron/CI: exit 1 iff any alert is firing
    obs_live.py --ports 9100 --world 4 --hb-dir /tmp/run/hb --once \\
        --rules rules.json --alerts-jsonl /tmp/run/metrics.jsonl

``--alerts-jsonl`` books each aggregator firing as an ``alert``
ft_event into the shared JSONL (``process: -1`` marks the aggregator) —
crucially ``dead_rank``, which a killed rank can never book for itself;
``elastic_agent watch --alerts-from`` then routes it into the
coordinator's one eviction path, and goodput/obs_report fold it like any
other event.

Default rules are ``alerts.default_rules()`` minus ``goodput_floor``:
a sampled scrape sees only the newest record per interval, so a
wall-span goodput estimate from scrapes would systematically undercount
productive seconds (the trainer-side engine sees every drained record
and owns that rule).

``--selftest`` exercises exposition round-trip, rule parsing, the
pseudo-record synthesis, alert booking, and the exit-code logic — no
sockets beyond localhost, no jax (asserted).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_OBS = os.path.join(_REPO, "pytorch_distributed_tpu", "obs")


def _load_obs(name: str):
    """Load ``pytorch_distributed_tpu/obs/<name>.py`` by path under the
    same ``_ptd_obs_<name>`` alias obs/alerts.py uses, so the sibling
    modules share one instance and jax never enters the process."""
    import importlib.util

    full = f"pytorch_distributed_tpu.obs.{name}"
    if full in sys.modules:
        return sys.modules[full]
    alias = f"_ptd_obs_{name}"
    if alias in sys.modules:
        return sys.modules[alias]
    spec = importlib.util.spec_from_file_location(
        alias, os.path.join(_OBS, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[alias] = mod
    spec.loader.exec_module(mod)
    return mod


alerts = _load_obs("alerts")
export = _load_obs("export")
heartbeat = _load_obs("heartbeat")
metrics = _load_obs("metrics")
stepattr = _load_obs("stepattr")


# --------------------------------------------------------------- aggregation

def aggregator_rules():
    """``default_rules()`` minus ``goodput_floor`` (see module docstring:
    sampled scrapes cannot estimate goodput honestly)."""
    return [r for r in alerts.default_rules() if r.kind != "goodput_floor"]


def endpoint_urls(args) -> list:
    """``--endpoints`` verbatim, or ``--ports BASE --world N`` expanded to
    ``http://HOST:BASE+k/metrics`` — the rank-k port convention both
    trainers use for ``--metrics-port``."""
    urls = []
    for ep in (args.endpoints or "").split(","):
        ep = ep.strip()
        if not ep:
            continue
        if not ep.startswith("http"):
            ep = f"http://{ep}"
        if not ep.rstrip("/").endswith("/metrics"):
            ep = ep.rstrip("/") + "/metrics"
        urls.append(ep)
    if args.ports is not None:
        for k in range(max(1, args.world)):
            urls.append(f"http://{args.host}:{args.ports + k}/metrics")
    return urls


def pseudo_record(samples, rank: int):
    """Synthesize a metrics-record dict from one scrape so the aggregator
    feeds the *same* ``AlertEngine.observe`` the trainers run: step-time
    stats come back from ``ptd_step_time_seconds{stat=...}``, everything
    else from the generic ``ptd_metric{field=...}`` gauges, and ``t`` is
    reconstructed from the record-age gauge."""
    rec = {"process": int(rank)}
    step = export.sample_value(samples, "ptd_step", rank=rank)
    if step is not None:
        rec["step"] = int(step)
    for field, stat in export._STAT_FIELDS.items():
        v = export.sample_value(samples, "ptd_step_time_seconds",
                                rank=rank, stat=stat)
        if v is not None:
            rec[field] = float(v)
    for name, lab, v in samples:
        if name == "ptd_metric" and lab.get("rank") == str(rank):
            rec.setdefault(lab.get("field", "?"), float(v))
    # serving gauges (ptd_serving_*, incl. the ptd_serving_attr_* request-
    # trace attribution) fold back into their record fields, so the
    # aggregator evaluates ttft_p99 / queue_wait_share / preempt_redo
    # rules from a scrape exactly like the engine does from the record
    for field, (gname, labels) in export._SERVING_FIELDS.items():
        v = export.sample_value(samples, gname, rank=rank, **labels)
        if v is not None:
            rec.setdefault(field, float(v))
    # training step-attribution gauges (ptd_attr_*, ISSUE 20) fold back
    # the same way, so the aggregator evaluates data_wait_share rules
    # from a scrape and the dashboard names each rank's bottleneck
    for field, (gname, labels) in export._ATTR_FIELDS.items():
        v = export.sample_value(samples, gname, rank=rank, **labels)
        if v is not None:
            rec.setdefault(field, float(v))
    age = export.sample_value(samples, "ptd_record_age_seconds", rank=rank)
    rec["t"] = time.time() - float(age or 0.0)
    return rec if "step_time" in rec else None


def bottleneck_of(rec):
    """Dominant step-time attribution class of a record — the largest of
    the ``attr_<component>_ms`` fields (None without ``--step-attr``)."""
    comps = {}
    for c in stepattr.COMPONENTS:
        v = rec.get(f"attr_{c}_ms")
        if v is not None:
            comps[c] = float(v)
    if not comps:
        return None
    return max(comps, key=comps.get)


def fleet_from_samples(samples):
    """Parse a fleet-router exposition (``ptd_fleet_*`` gauges,
    serving/router.py ``render_fleet_metrics``) into a dashboard dict;
    None when the endpoint is not a router."""
    if export.sample_value(samples, "ptd_fleet_up") is None:
        return None
    out = {"replicas": {}, "counters": {}, "last_scale": None}
    for name, lab, v in samples:
        if name == "ptd_fleet_replica_state":
            if v == 1.0:
                out["replicas"].setdefault(
                    lab.get("replica", "?"), {})["state"] = lab.get(
                        "state", "?")
        elif name.startswith("ptd_fleet_replica_") and "replica" in lab:
            # label-less ptd_fleet_replica_down_total is a fleet counter,
            # not a per-replica gauge — it falls through to the branch below
            field = name[len("ptd_fleet_replica_"):]
            out["replicas"].setdefault(
                lab["replica"], {})[field] = float(v)
        elif name == "ptd_fleet_last_scale":
            out["last_scale"] = lab.get("decision")
        elif name.startswith("ptd_fleet_"):
            out["counters"][name[len("ptd_fleet_"):]] = float(v)
    return out


def scraped_rank(samples):
    """The rank an exposition claims via ``ptd_up{rank=...}``."""
    for name, lab, _v in samples:
        if name == "ptd_up" and "rank" in lab:
            try:
                return int(lab["rank"])
            except ValueError:
                return None
    return None


class FleetMonitor:
    """One aggregator: scrape endpoints + read heartbeats each cycle,
    evaluate the shared rule set, optionally book firings as ``alert``
    ft_events (``process: -1``), render the dashboard."""

    def __init__(self, urls, hb_dir=None, rules=None, alerts_jsonl=None,
                 timeout: float = 2.0):
        self.urls = list(urls)
        self.hb_dir = hb_dir
        self.timeout = float(timeout)
        self.booker = None
        if alerts_jsonl:
            self.booker = metrics.MetricsLogger(alerts_jsonl,
                                                process_index=-1)
        self.engine = alerts.AlertEngine(
            rules if rules is not None else aggregator_rules(),
            emit=self._book, process_index=-1)
        self.rows = {}        # rank -> dashboard row dict
        self.remote_firing = []   # scraped ptd_alert_firing samples
        self.fleet = None     # fleet-router exposition, when scraped
        self.cycles = 0

    def _book(self, **fields) -> None:
        if self.booker is not None:
            fields = dict(fields)
            step = fields.pop("step", None)
            self.booker.log_event("alert", step=step, **fields)

    def close(self) -> None:
        if self.booker is not None:
            self.booker.close()

    # ----------------------------------------------------------- one cycle
    def cycle(self, now=None):
        """Scrape + evaluate once; returns the alerts fired this cycle."""
        now = time.time() if now is None else now
        self.cycles += 1
        fired = []
        self.remote_firing = []
        self.fleet = None
        seen = set()
        for i, url in enumerate(self.urls):
            try:
                samples = export.scrape(url, timeout=self.timeout)
            except Exception as e:
                self.rows[f"?{i}"] = {"rank": None, "url": url,
                                      "state": "DOWN", "error": str(e)}
                continue
            fl = fleet_from_samples(samples)
            if fl is not None:
                # a router endpoint: feed the fleet block, not a rank row
                self.fleet = fl
                self.rows.pop(f"?{i}", None)
                continue
            rank = scraped_rank(samples)
            rank = i if rank is None else rank
            seen.add(rank)
            self.rows.pop(f"?{i}", None)
            rec = pseudo_record(samples, rank)
            if rec is not None:
                fired += self.engine.observe(rec)
            for name, lab, _v in samples:
                if name == "ptd_alert_firing":
                    self.remote_firing.append((rank, lab.get("rule", "?"),
                                               lab.get("severity", "warn")))
            self.rows[rank] = {
                "rank": rank, "url": url, "state": "UP",
                "step": rec.get("step") if rec else None,
                "p50_ms": (rec.get("step_time_p50", 0.0) * 1e3
                           if rec else None),
                "last_ms": (rec.get("step_time", 0.0) * 1e3
                            if rec else None),
                "throughput": rec.get("throughput") if rec else None,
                "mfu": rec.get("mfu") if rec else None,
                "mem_bytes": export.sample_value(samples,
                                                 "ptd_mem_rss_bytes",
                                                 rank=rank),
                "rec_age_s": export.sample_value(
                    samples, "ptd_record_age_seconds", rank=rank),
                "alerts_total": export.sample_value(samples,
                                                    "ptd_alerts_total",
                                                    rank=rank),
                "q_share_p99": (rec.get("queue_wait_share_p99")
                                if rec else None),
                "redo_p99_ms": (rec.get("preempt_redo_ms_p99")
                                if rec else None),
                "traces": rec.get("trace_completed") if rec else None,
                "bottleneck": bottleneck_of(rec) if rec else None,
                "data_wait_share": (rec.get("data_wait_share")
                                    if rec else None),
                "host_sync_ms": (rec.get("attr_host_sync_ms")
                                 if rec else None),
            }
        beats = {}
        if self.hb_dir:
            beats = heartbeat.read_heartbeats(self.hb_dir)
            fired += self.engine.observe_heartbeats(beats, now=now)
            for pid, b in beats.items():
                row = self.rows.setdefault(pid, {"rank": pid, "url": None,
                                                 "state": "HB"})
                row["beat_age_s"] = max(0.0, now - float(b.get("t", now)))
                row.setdefault("step", b.get("step"))
        self.beats = beats
        return fired

    def quarantined_replicas(self) -> int:
        """Quarantined count off the router's own gauge — ``--once``
        exits 1 on any quarantined replica even with zero alert rules."""
        if self.fleet is None:
            return 0
        return int(self.fleet["counters"].get("quarantined", 0.0))

    def any_firing(self) -> bool:
        return bool(self.engine.active() or self.remote_firing
                    or self.quarantined_replicas())

    # ----------------------------------------------------------- rendering
    def dashboard(self, now=None) -> str:
        now = time.time() if now is None else now
        stamp = time.strftime("%Y-%m-%d %H:%M:%S", time.localtime(now))
        lines = [f"== obs_live @ {stamp} ==  cycle {self.cycles}, "
                 f"{len(self.urls)} endpoint(s)"]
        roll = heartbeat.fleet_rollup(getattr(self, "beats", {}), now=now)
        if roll:
            mem = roll.get("total_mem_bytes")
            lines.append(
                f"fleet: {roll['ranks']} rank(s)  steps "
                f"{roll['min_step']}..{roll['max_step']}  oldest beat "
                f"{roll['oldest_beat_age_s']:.1f}s"
                + (f"  median ema {roll['median_ema_s'] * 1e3:.1f}ms"
                   if roll.get("median_ema_s") is not None else "")
                + (f"  mem {mem / 2**20:.1f} MiB" if mem else ""))
        lines.append(f"{'rank':>4}  {'state':<5}  {'step':>6}  "
                     f"{'p50(ms)':>8}  {'tok/s':>8}  {'mfu':>5}  "
                     f"{'mem(MiB)':>8}  {'rec-age':>7}  {'beat-age':>8}  "
                     f"{'bottleneck':<12}")

        def _fmt(v, spec, dash="-"):
            return format(v, spec) if isinstance(v, (int, float)) else dash

        for key in sorted(self.rows, key=str):
            r = self.rows[key]
            lines.append(
                f"{_fmt(r.get('rank'), 'd', '?'):>4}  {r['state']:<5}  "
                f"{_fmt(r.get('step'), 'd'):>6}  "
                f"{_fmt(r.get('p50_ms'), '.1f'):>8}  "
                f"{_fmt(r.get('throughput'), '.0f'):>8}  "
                f"{_fmt(r.get('mfu'), '.2f'):>5}  "
                f"{_fmt((r.get('mem_bytes') or 0) / 2**20 if r.get('mem_bytes') else None, '.1f'):>8}  "
                f"{_fmt(r.get('rec_age_s'), '.1f'):>7}  "
                f"{_fmt(r.get('beat_age_s'), '.1f'):>8}  "
                f"{(r.get('bottleneck') or '-'):<12}")
        tattr = [r for _k, r in sorted(self.rows.items(), key=lambda kv:
                                       str(kv[0]))
                 if r.get("bottleneck") is not None]
        if tattr:
            lines.append("-- step attribution (where did my step go) --")
            for r in tattr:
                lines.append(
                    f"  rank {_fmt(r.get('rank'), 'd', '?')}: "
                    f"bottleneck {r['bottleneck']};  data-wait "
                    f"{_fmt(r.get('data_wait_share'), '.1f')}% of step;  "
                    f"host-sync {_fmt(r.get('host_sync_ms'), '.2f')}ms")
        attr = [r for _k, r in sorted(self.rows.items(), key=lambda kv:
                                      str(kv[0]))
                if r.get("q_share_p99") is not None
                or r.get("redo_p99_ms") is not None]
        if attr:
            lines.append("-- serving attribution (why TTFT moves) --")
            for r in attr:
                lines.append(
                    f"  rank {_fmt(r.get('rank'), 'd', '?')}: "
                    f"queue-wait share p99 "
                    f"{_fmt(r.get('q_share_p99'), '.1f')}% of TTFT;  "
                    f"preempt-redo p99 "
                    f"{_fmt(r.get('redo_p99_ms'), '.1f')}ms;  "
                    f"traces {_fmt(r.get('traces'), '.0f')}")
        if self.fleet is not None:
            c = self.fleet["counters"]

            def ct(name):
                return f"{c.get(name, 0.0):.0f}"

            lines.append("-- fleet (router) --")
            lines.append(
                f"  routed {ct('requests_total')}  completed "
                f"{ct('completed_total')}  failed {ct('failed_total')}  "
                f"retries {ct('retries_total')}  hedges "
                f"{ct('hedges_total')} (won {ct('hedges_won_total')} / "
                f"lost {ct('hedges_lost_total')})  last scale "
                f"{self.fleet['last_scale'] or 'none'}")
            lines.append(f"  {'replica':>7}  {'state':<11}  {'queue':>5}  "
                         f"{'kv%':>5}  {'ttft_p99':>9}  {'beat-age':>8}  "
                         f"{'dispatched':>10}  {'completed':>9}")
            for rid in sorted(self.fleet["replicas"], key=str):
                r = self.fleet["replicas"][rid]
                lines.append(
                    f"  {rid:>7}  {r.get('state', '?'):<11}  "
                    f"{_fmt(r.get('queue_depth'), '.0f'):>5}  "
                    f"{_fmt(r.get('kv_occupancy_pct'), '.1f'):>5}  "
                    f"{_fmt(r.get('ttft_p99_ms'), '.1f'):>7}ms  "
                    f"{_fmt(r.get('beat_age_seconds'), '.1f'):>7}s  "
                    f"{_fmt(r.get('dispatched_total'), '.0f'):>10}  "
                    f"{_fmt(r.get('completed_total'), '.0f'):>9}")
            nq = self.quarantined_replicas()
            if nq:
                lines.append(f"  {nq} replica(s) QUARANTINED")
        active = self.engine.active()
        if active:
            lines.append("-- alerts firing (aggregator) --")
            for a in sorted(active, key=lambda a: a.name):
                where = f"  rank {a.rank}" if a.rank is not None else ""
                lines.append(f"  {a.name:<16} [{a.severity}]{where}  "
                             f"{a.detail}")
        if self.remote_firing:
            lines.append("-- alerts firing (rank-local) --")
            for rank, rule, sev in sorted(set(self.remote_firing)):
                lines.append(f"  {rule:<16} [{sev}]  rank {rank}")
        if not active and not self.remote_firing:
            lines.append("no alerts firing")
        return "\n".join(lines)


# ----------------------------------------------------------------- CLI glue

def build_rules(spec):
    if spec in (None, "", "default"):
        return aggregator_rules()
    return alerts.load_rules(spec)


def run(args) -> int:
    urls = endpoint_urls(args)
    if not urls and not args.hb_dir:
        print("nothing to watch: pass --endpoints/--ports and/or --hb-dir",
              file=sys.stderr)
        return 2
    mon = FleetMonitor(urls, hb_dir=args.hb_dir,
                       rules=build_rules(args.rules),
                       alerts_jsonl=args.alerts_jsonl,
                       timeout=args.timeout)
    try:
        while True:
            fired = mon.cycle()
            print(mon.dashboard(), flush=True)
            for a in fired:
                print(f"ALERT {a.name} [{a.severity}]: {a.detail}",
                      flush=True)
            if args.once:
                return 1 if mon.any_firing() else 0
            print("", flush=True)
            time.sleep(args.interval)
    except KeyboardInterrupt:
        return 130
    finally:
        mon.close()


# ------------------------------------------------------------------ selftest

def _selftest() -> int:
    """Socket-light, jax-free: exposition round-trip against a real
    exporter on an ephemeral port, pseudo-record synthesis, rule
    loading, alert booking, dashboard needles, exit-code logic."""
    import tempfile
    import urllib.request

    assert "jax" not in sys.modules, \
        "obs_live must never import jax (login-node aggregator)"

    with tempfile.TemporaryDirectory() as d:
        # 1. Live exposition round-trip: exporter on port 0, scraped over
        #    real HTTP, pseudo-record rebuilt from the samples.
        exp = export.MetricsExporter(0, rank=3)
        exp.update({"step": 41, "t": time.time(), "process": 3,
                    "step_time": 0.020, "step_time_ema": 0.021,
                    "step_time_p50": 0.019, "step_time_p95": 0.028,
                    "step_time_max": 0.030, "throughput": 51200.0,
                    "loss": 2.5, "serving": 1.0,
                    "queue_wait_share_p99": 61.5,
                    "preempt_redo_ms_p99": 209.6,
                    "trace_completed": 24.0,
                    "attr_compute_ms": 9.0, "attr_exposed_comm_ms": 1.5,
                    "attr_host_sync_ms": 0.8, "attr_data_wait_ms": 7.7,
                    "attr_other_ms": 1.0, "attr_device_ms": 10.5,
                    "attr_comm_ms": 3.0, "attr_recon_err_ms": 0.01,
                    "data_wait_share": 38.5})
        exp.update({"ft_event": "alert", "t": time.time(), "process": 3,
                    "alert": "x", "rule": "hang", "severity": "page"})
        exp.start()
        try:
            url = f"http://127.0.0.1:{exp.port}/metrics"
            samples = export.scrape(url)
            assert export.sample_value(samples, "ptd_up", rank=3) == 1.0
            rec = pseudo_record(samples, 3)
            assert rec is not None and rec["step"] == 41
            assert abs(rec["step_time_p50"] - 0.019) < 1e-9
            assert abs(rec["throughput"] - 51200.0) < 1e-6
            assert scraped_rank(samples) == 3
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{exp.port}/healthz") as r:
                assert json.loads(r.read())["ok"] is True
            # ptd_serving_attr_* gauges fold back into the pseudo-record
            # so the aggregator can alert on *why* TTFT is breaching,
            # and the dashboard names the attribution per rank
            assert abs(rec["queue_wait_share_p99"] - 61.5) < 1e-9, rec
            assert abs(rec["preempt_redo_ms_p99"] - 209.6) < 1e-9, rec
            # ptd_attr_* training-attribution gauges fold back too, the
            # bottleneck column names the dominant class, and the
            # data_wait_share rule fires from a scrape like from a record
            assert abs(rec["attr_compute_ms"] - 9.0) < 1e-9, rec
            assert abs(rec["data_wait_share"] - 38.5) < 1e-9, rec
            assert bottleneck_of(rec) == "compute", rec
            mon_s = FleetMonitor([url], rules=[
                alerts.Rule("queue_wait_share", "qw", "warn",
                            {"max_pct": 50.0}),
                alerts.Rule("preempt_redo", "redo", "warn",
                            {"max_ms": 100.0}),
                alerts.Rule("data_wait_share", "dw", "warn",
                            {"max_pct": 25.0})])
            fired_s = mon_s.cycle()
            assert {a.name for a in fired_s} == {"qw", "redo", "dw"}, \
                fired_s
            assert mon_s.any_firing()
            dash_s = mon_s.dashboard()
            for needle in ("-- serving attribution", "61.5% of TTFT",
                           "preempt-redo p99 209.6ms", "traces 24",
                           "-- step attribution (where did my step go)",
                           "bottleneck compute",
                           "data-wait 38.5% of step",
                           "host-sync 0.80ms"):
                assert needle in dash_s, \
                    f"dashboard missing {needle!r}\n{dash_s}"
            assert any("UP" in ln and ln.rstrip().endswith("compute")
                       for ln in dash_s.splitlines()), \
                f"bottleneck column missing from the rank row\n{dash_s}"
        finally:
            exp.stop()

        # 2. Rules: default aggregator set drops goodput_floor; a rules
        #    file round-trips; a malformed one raises AlertRuleError.
        kinds = {r.kind for r in aggregator_rules()}
        assert "goodput_floor" not in kinds and "dead_rank" in kinds
        rp = os.path.join(d, "rules.json")
        with open(rp, "w") as f:
            json.dump({"rules": [
                {"kind": "dead_rank", "severity": "page",
                 "max_age_s": 2.0},
                {"kind": "step_time_p95", "max_ms": 15.0,
                 "quantile": "p50"}]}, f)
        loaded = build_rules(rp)
        assert [r.kind for r in loaded] == ["dead_rank", "step_time_p95"]
        bad = os.path.join(d, "bad.json")
        with open(bad, "w") as f:
            json.dump({"rules": [{"kind": "nope"}]}, f)
        try:
            build_rules(bad)
        except alerts.AlertRuleError as e:
            assert "nope" in str(e)
        else:
            raise AssertionError("malformed rules must raise")

        # 3. Heartbeat leg: a fresh rank plus a stale one → dead_rank
        #    fires, is booked to the JSONL (process -1), the dashboard
        #    names it, and --once semantics exit 1.
        hb = os.path.join(d, "hb")
        os.makedirs(hb)
        now = time.time()
        for pid, t in ((0, now), (1, now - 120.0)):
            with open(os.path.join(hb, f"heartbeat-{pid:05d}.jsonl"),
                      "w") as f:
                f.write(json.dumps({"pid": pid, "step": 10, "t": t,
                                    "world": 2, "ema": 0.02}) + "\n")
        booked = os.path.join(d, "metrics.jsonl")
        mon = FleetMonitor([], hb_dir=hb,
                           rules=[alerts.Rule("dead_rank", "dead_rank",
                                              "page",
                                              {"max_age_s": 60.0})],
                           alerts_jsonl=booked)
        fired = mon.cycle(now=now)
        assert [a.rank for a in fired] == [1], fired
        assert mon.any_firing()
        dash = mon.dashboard(now=now)
        for needle in ("== obs_live @", "fleet: 2 rank(s)", "dead_rank",
                       "[page]", "rank 1", "beat age 120.0s"):
            assert needle in dash, f"dashboard missing {needle!r}\n{dash}"
        # second cycle: latched, no re-fire, still firing
        assert mon.cycle(now=now) == []
        assert mon.any_firing()
        mon.close()
        recs = metrics.read_metrics(booked)
        assert len(recs) == 1 and recs[0]["ft_event"] == "alert"
        assert recs[0]["process"] == -1 and recs[0]["rank"] == 1
        dead = alerts.dead_ranks_from_events(recs)
        assert list(dead) == [1], \
            "booked alert must round-trip into elastic_agent's eviction feed"

        # 4. Recovery clears the latch → exit code flips back to 0.
        with open(os.path.join(hb, "heartbeat-00001.jsonl"), "w") as f:
            f.write(json.dumps({"pid": 1, "step": 11, "t": now,
                                "world": 2, "ema": 0.02}) + "\n")
        mon2 = FleetMonitor([], hb_dir=hb,
                            rules=[alerts.Rule("dead_rank", "dead_rank",
                                               "page",
                                               {"max_age_s": 60.0})])
        assert mon2.cycle(now=now) == [] and not mon2.any_firing()
        assert "no alerts firing" in mon2.dashboard(now=now)

        # 5. DOWN endpoint: scrape failure renders, doesn't raise.
        mon3 = FleetMonitor(["http://127.0.0.1:9/metrics"], timeout=0.2)
        mon3.cycle()
        assert "DOWN" in mon3.dashboard()

        # 6. Endpoint expansion: --ports + --world, and bare host:port.
        ns = argparse.Namespace(endpoints="10.0.0.5:9100", ports=9200,
                                world=2, host="127.0.0.1")
        assert endpoint_urls(ns) == [
            "http://10.0.0.5:9100/metrics",
            "http://127.0.0.1:9200/metrics",
            "http://127.0.0.1:9201/metrics"]

        # 7. Fleet router block (ISSUE 19): a ptd_fleet_* exposition is
        #    recognized as a router (not a rank row), the dashboard grows
        #    the replica table, and one quarantined replica flips --once
        #    to exit 1 even with zero alert rules firing.
        import http.server
        import threading

        fleet_text = "\n".join([
            "ptd_fleet_up 1", "ptd_fleet_inflight 2",
            "ptd_fleet_requests_total 30",
            "ptd_fleet_completed_total 28", "ptd_fleet_failed_total 0",
            "ptd_fleet_retries_total 3", "ptd_fleet_hedges_total 4",
            "ptd_fleet_hedges_won_total 3",
            "ptd_fleet_hedges_lost_total 1",
            "ptd_fleet_duplicates_suppressed_total 0",
            "ptd_fleet_replica_down_total 1",
            'ptd_fleet_last_scale{decision="up:replica2"} 1',
            "ptd_fleet_replicas 2", "ptd_fleet_quarantined 1",
            'ptd_fleet_replica_state{replica="0",state="UP"} 1',
            'ptd_fleet_replica_queue_depth{replica="0"} 2',
            'ptd_fleet_replica_kv_occupancy_pct{replica="0"} 50',
            'ptd_fleet_replica_ttft_p99_ms{replica="0"} 88.5',
            'ptd_fleet_replica_beat_age_seconds{replica="0"} 0.4',
            'ptd_fleet_replica_dispatched_total{replica="0"} 20',
            'ptd_fleet_replica_completed_total{replica="0"} 18',
            'ptd_fleet_replica_state{replica="1",state="QUARANTINED"} 1',
            'ptd_fleet_replica_dispatched_total{replica="1"} 10',
            'ptd_fleet_replica_completed_total{replica="1"} 10',
        ]) + "\n"

        class _FleetHandler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                body = fleet_text.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                              _FleetHandler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            mon_f = FleetMonitor(
                [f"http://127.0.0.1:{srv.server_port}/metrics"], rules=[])
            assert mon_f.cycle() == []
            assert mon_f.fleet is not None, "router exposition missed"
            assert not mon_f.rows, \
                "a router endpoint must not masquerade as a rank row"
            assert sorted(mon_f.fleet["replicas"]) == ["0", "1"], \
                "label-less replica_down_total must not fabricate a row"
            assert mon_f.fleet["counters"]["replica_down_total"] == 1.0
            assert mon_f.quarantined_replicas() == 1
            assert mon_f.any_firing(), \
                "--once must exit 1 on a quarantined replica"
            dash_f = mon_f.dashboard()
            for needle in ("-- fleet (router) --",
                           "routed 30  completed 28",
                           "retries 3  hedges 4 (won 3 / lost 1)",
                           "last scale up:replica2",
                           "QUARANTINED", "88.5ms",
                           "1 replica(s) QUARANTINED"):
                assert needle in dash_f, \
                    f"fleet dashboard missing {needle!r}\n{dash_f}"
            # healthy fleet: same shape, nothing quarantined -> exit 0
            fleet_text = fleet_text.replace(
                "ptd_fleet_quarantined 1", "ptd_fleet_quarantined 0")
            mon_ok = FleetMonitor(
                [f"http://127.0.0.1:{srv.server_port}/metrics"], rules=[])
            mon_ok.cycle()
            assert not mon_ok.any_firing()
        finally:
            srv.shutdown()

    assert "jax" not in sys.modules
    print("obs_live selftest: OK")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Fleet telemetry aggregator: scrape per-rank metric "
                    "exporters, tail heartbeats, evaluate alert rules")
    ap.add_argument("--endpoints", default=None,
                    help="comma-separated exporter endpoints "
                         "(host:port or full /metrics URLs)")
    ap.add_argument("--ports", type=int, default=None, metavar="BASE",
                    help="scrape http://HOST:BASE+k/metrics for "
                         "k in [0, --world)")
    ap.add_argument("--world", type=int, default=1,
                    help="rank count for --ports expansion")
    ap.add_argument("--host", default="127.0.0.1",
                    help="host for --ports expansion")
    ap.add_argument("--hb-dir", default=None, dest="hb_dir",
                    help="heartbeat dir (dead/slow-rank rules + fleet "
                         "rollup)")
    ap.add_argument("--rules", default=None, metavar="RULES",
                    help="alert rules JSON, or 'default' (default set "
                         "minus goodput_floor)")
    ap.add_argument("--alerts-jsonl", default=None, dest="alerts_jsonl",
                    metavar="PATH",
                    help="book aggregator firings as alert ft_events "
                         "into this metrics JSONL (process -1)")
    ap.add_argument("--interval", type=float, default=5.0,
                    help="seconds between aggregation cycles")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-endpoint scrape timeout")
    ap.add_argument("--once", action="store_true",
                    help="one cycle for cron/CI: exit 1 iff any alert "
                         "is firing")
    ap.add_argument("--selftest", action="store_true",
                    help="run the jax-free aggregator checks")
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    return run(args)


if __name__ == "__main__":
    sys.exit(main())
