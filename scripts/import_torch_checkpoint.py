#!/usr/bin/env python
"""Convert a reference/torchvision checkpoint to this framework.

Usage:
    python scripts/import_torch_checkpoint.py \
        --input checkpoint.pth.tar --arch resnet50 --out-dir pretrained
    python scripts/import_torch_checkpoint.py \
        --input gpt_mini.pth --arch lm_mini --out-dir pretrained   # LM

Reads the reference's ``checkpoint.pth.tar`` (payload layout of reference
distributed.py:219-225) or a bare ``state_dict`` file, converts layouts
(see utils/torch_import.py), validates the tree against a fresh model
init, and writes ``<out-dir>/<arch>.msgpack``.  The family is detected
from the state_dict itself: ``conv1.weight`` ⇒ torchvision ResNet
(validated against ``create_model(arch)``, ready for ``--pretrained``
with ``PTD_TPU_PRETRAINED_DIR=<out-dir>``); ``embed.weight`` ⇒ GPT-style
LM (validated against ``TransformerLM``, ready for
``serve_lm.py --checkpoint <path>``).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _validate_tree(ref, variables, arch, colls) -> None:
    import flax

    for coll in colls:
        want = flax.traverse_util.flatten_dict(ref[coll])
        got = flax.traverse_util.flatten_dict(variables[coll])
        if set(want) != set(got):
            missing = sorted("/".join(k) for k in set(want) - set(got))[:5]
            extra = sorted("/".join(k) for k in set(got) - set(want))[:5]
            sys.exit(f"{coll} tree mismatch vs {arch}: "
                     f"missing={missing} extra={extra}")
        for k in want:
            if tuple(want[k].shape) != tuple(got[k].shape):
                sys.exit(f"shape mismatch at {'/'.join(k)}: "
                         f"checkpoint {got[k].shape} vs model {want[k].shape}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True, help="torch .pth/.pth.tar file")
    ap.add_argument("--arch", default=None,
                    help="arch name (defaults to the checkpoint's own "
                         "'arch' field; LMs fall back to 'lm')")
    ap.add_argument("--out-dir", default="pretrained")
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args(argv)

    import torch  # CPU build is enough

    payload = torch.load(args.input, map_location="cpu", weights_only=False)
    from pytorch_distributed_tpu.utils.torch_import import (
        import_torch_checkpoint, save_as_pretrained,
    )

    variables, meta = import_torch_checkpoint(payload)
    is_lm = "embed" in variables["params"]
    arch = args.arch or meta.get("arch") or ("lm" if is_lm else None)
    if not arch:
        sys.exit("--arch required: checkpoint has no 'arch' field")

    # Validate against a fresh init of the same shape (structure + dims).
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    if is_lm:
        from pytorch_distributed_tpu.models.transformer import TransformerLM

        vocab, d_model = variables["params"]["embed"]["embedding"].shape
        n_layers = sum(1 for k in variables["params"]
                       if k.startswith("block_"))
        # n_heads never shapes the param tree (qkv is one [D,3D] matmul)
        model = TransformerLM(vocab_size=int(vocab), d_model=int(d_model),
                              n_heads=1, n_layers=n_layers)
        ref = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 8), dtype=jnp.int32)))
        _validate_tree(ref, variables, arch, ("params",))
    else:
        from pytorch_distributed_tpu import models

        model = models.create_model(arch, num_classes=args.num_classes)
        ref = jax.eval_shape(
            lambda: model.init(jax.random.PRNGKey(0),
                               jnp.zeros((1, 64, 64, 3)), train=False)
        )
        _validate_tree(ref, variables, arch, ("params", "batch_stats"))

    path = save_as_pretrained(args.out_dir, arch, variables, meta)
    print(f"wrote {path} (epoch={meta.get('epoch', 0)}, "
          f"best_acc1={meta.get('best_acc1', 0.0)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
