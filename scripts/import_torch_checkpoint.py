#!/usr/bin/env python
"""Convert a reference/torchvision ResNet checkpoint to this framework.

Usage:
    python scripts/import_torch_checkpoint.py \
        --input checkpoint.pth.tar --arch resnet50 --out-dir pretrained

Reads the reference's ``checkpoint.pth.tar`` (payload layout of reference
distributed.py:219-225) or a bare torchvision ``state_dict`` file, converts
layouts (see utils/torch_import.py), validates the tree against a fresh
``create_model(arch)`` init, and writes ``<out-dir>/<arch>.msgpack`` — ready
for ``--pretrained`` (with ``PTD_TPU_PRETRAINED_DIR=<out-dir>``).
"""

import argparse
import sys


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--input", required=True, help="torch .pth/.pth.tar file")
    ap.add_argument("--arch", default=None,
                    help="arch name (defaults to the checkpoint's own "
                         "'arch' field)")
    ap.add_argument("--out-dir", default="pretrained")
    ap.add_argument("--num-classes", type=int, default=1000)
    args = ap.parse_args()

    import torch  # CPU build is enough

    payload = torch.load(args.input, map_location="cpu", weights_only=False)
    from pytorch_distributed_tpu.utils.torch_import import (
        import_torch_checkpoint, save_as_pretrained,
    )

    variables, meta = import_torch_checkpoint(payload)
    arch = args.arch or meta.get("arch")
    if not arch:
        sys.exit("--arch required: checkpoint has no 'arch' field")

    # Validate against a fresh init of the same arch (shape + structure).
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from pytorch_distributed_tpu import models

    model = models.create_model(arch, num_classes=args.num_classes)
    ref = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 64, 64, 3)), train=False)
    )
    for coll in ("params", "batch_stats"):
        import flax

        want = flax.traverse_util.flatten_dict(ref[coll])
        got = flax.traverse_util.flatten_dict(variables[coll])
        if set(want) != set(got):
            missing = sorted("/".join(k) for k in set(want) - set(got))[:5]
            extra = sorted("/".join(k) for k in set(got) - set(want))[:5]
            sys.exit(f"{coll} tree mismatch vs {arch}: "
                     f"missing={missing} extra={extra}")
        for k in want:
            if tuple(want[k].shape) != tuple(got[k].shape):
                sys.exit(f"shape mismatch at {'/'.join(k)}: "
                         f"checkpoint {got[k].shape} vs model {want[k].shape}")

    path = save_as_pretrained(args.out_dir, arch, variables, meta)
    print(f"wrote {path} (epoch={meta.get('epoch', 0)}, "
          f"best_acc1={meta.get('best_acc1', 0.0)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
