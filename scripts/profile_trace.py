#!/usr/bin/env python
"""Capture an XPlane trace of the ResNet-50 train step and summarize it.

Capture goes through ``obs.trace.capture`` (the shared start/stop_trace
path) with the step wrapped in ``obs.trace.scope("profile_step")`` so the
in-repo timeline decoder can window per-step comm/compute/overlap.  The
default summary uses ``obs.timeline``/``scripts/obs_timeline.py`` — pure
stdlib, no tensorboard.  ``analyze <tool>`` keeps the old
tensorboard_plugin_profile converter as an optional fallback for tools
the in-repo decoder doesn't cover (memory_profile, op_profile, ...).

Usage:
    python scripts/profile_trace.py                # capture + timeline report
    python scripts/profile_trace.py analyze [tool] # tensorboard converter
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

TRACE_DIR = "/tmp/ptd_trace"


def capture():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.obs import trace
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    batch, image = 256, 224
    mesh = data_parallel_mesh()
    model = models.create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
                          train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    b = {
        "images": jnp.asarray(rng.normal(size=(batch, image, image, 3)).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    for _ in range(3):
        state, met = step(state, b, lr)
    float(met["loss"])
    with trace.capture(TRACE_DIR):
        for _ in range(5):
            with trace.scope("profile_step"):
                state, met = step(state, b, lr)
        float(met["loss"])
    print(f"trace captured -> {TRACE_DIR}")
    report()


def report():
    """Per-rank comm/compute/overlap summary via the in-repo decoder."""
    import obs_timeline

    rc = obs_timeline.main([TRACE_DIR, "--annotation", "profile_step"])
    if rc:
        print("timeline report failed; try: "
              "python scripts/profile_trace.py analyze", file=sys.stderr)


def analyze(tool="framework_op_stats"):
    from tensorboard_plugin_profile.convert import raw_to_tool_data

    from pytorch_distributed_tpu.obs import timeline

    paths = timeline.find_xplane_files(TRACE_DIR)
    if not paths:
        sys.exit("no xplane.pb found")
    data, _ = raw_to_tool_data.xspace_to_tool_data([paths[-1]], tool + "^", {})
    out = f"/tmp/{tool}.out"
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(out, mode) as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes)")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "analyze":
        analyze(sys.argv[2] if len(sys.argv) > 2 else "framework_op_stats")
    elif len(sys.argv) > 1 and sys.argv[1] == "report":
        report()
    else:
        capture()
