#!/usr/bin/env python
"""Capture an XPlane trace of the ResNet-50 train step on the real chip and
print the self-time op breakdown (tensorboard_plugin_profile converter)."""

import glob
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

TRACE_DIR = "/tmp/ptd_trace"


def capture():
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    batch, image = 256, 224
    mesh = data_parallel_mesh()
    model = models.create_model("resnet50", num_classes=1000, dtype=jnp.bfloat16)
    variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
                          train=False)
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)
    rng = np.random.default_rng(0)
    b = {
        "images": jnp.asarray(rng.normal(size=(batch, image, image, 3)).astype(np.float32)),
        "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    for _ in range(3):
        state, met = step(state, b, lr)
    float(met["loss"])
    jax.profiler.start_trace(TRACE_DIR)
    for _ in range(5):
        state, met = step(state, b, lr)
    float(met["loss"])
    jax.profiler.stop_trace()
    print("trace captured")


def analyze(tool="framework_op_stats"):
    from tensorboard_plugin_profile.convert import raw_to_tool_data

    paths = sorted(glob.glob(TRACE_DIR + "/**/*.xplane.pb", recursive=True))
    if not paths:
        sys.exit("no xplane.pb found")
    data, _ = raw_to_tool_data.xspace_to_tool_data([paths[-1]], tool + "^", {})
    out = f"/tmp/{tool}.out"
    mode = "wb" if isinstance(data, bytes) else "w"
    with open(out, mode) as f:
        f.write(data)
    print(f"wrote {out} ({len(data)} bytes)")


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "analyze":
        analyze(sys.argv[2] if len(sys.argv) > 2 else "framework_op_stats")
    else:
        capture()
