#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's only published experiment (BASELINE.md) — its
fastest recipe (apex AMP+DDP) does an ImageNet epoch (1,281,167 images) in
1186.5 s on 4× V100, i.e. ~270 images/sec/GPU.  ``vs_baseline`` is
our images/sec/chip divided by that per-device number.

Synthetic in-device data (no host IO) so the number isolates the compiled
step: forward + loss + backward + SGD update at global batch 256, bf16
compute policy — the same step the tpu_native recipe runs, with the
space-to-depth stem (mathematically identical to conv7, see models/resnet.py)
and bf16 image feed (what the u8-wire loader path delivers after device-side
normalize).

Roofline note (round-2 profile, scripts/profile_trace.py on the real v5e):
the step moves ~68 GB/step at ~690-750 GB/s effective against a ~819 GB/s
HBM peak — ResNet-50 b256 bf16 is **memory-bound** on this chip (arithmetic
intensity ~29-60 FLOP/byte vs the chip's ~240 balance point), so throughput
is capped near ~3,080 img/s at current traffic; conv fusions alone account
for 55.4 GB/step already running at 699 GB/s.  Batch 512, larger scoped
VMEM, and f32 feeds all measured slower (scripts/bench_variants.py).
"""

import json
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np

REFERENCE_IMGS_PER_SEC_PER_DEVICE = 1281167 / 1186.5 / 4  # ≈ 269.9 (BASELINE.md)


def _require_devices(timeout_s: float = 180.0):
    """Device discovery with a watchdog: on this platform a wedged tunnel
    makes ``jax.devices()`` block forever — fail loudly instead of hanging
    the bench harness.  (Compile slowness is NOT guarded; only discovery.)"""
    result = {}

    def probe():
        try:
            result["devices"] = jax.devices()
        except Exception as e:  # pragma: no cover
            result["error"] = repr(e)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        return result["devices"]
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": 0.0,
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "error": result.get(
            "error", f"device discovery hung >{timeout_s:.0f}s "
                     "(axon tunnel unreachable)"),
    }))
    sys.exit(1)


def main() -> None:
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    batch = 256
    image = 224
    _require_devices()
    mesh = data_parallel_mesh()
    model = models.create_model(
        "resnet50", num_classes=1000, dtype=jnp.bfloat16, stem="space_to_depth"
    )
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False
    )
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)

    rng = np.random.default_rng(0)
    device_batch = {
        "images": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype=jnp.bfloat16
        ),
        "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)

    # Warmup / compile.  Synchronize via a scalar *value fetch*: on tunneled
    # platforms block_until_ready alone can return before the device queue
    # drains, inflating throughput by orders of magnitude.
    for _ in range(3):
        state, metrics = step(state, device_batch, lr)
    float(metrics["loss"])

    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, device_batch, lr)
    assert np.isfinite(float(metrics["loss"]))  # value fetch = pipeline flush
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    imgs_per_sec_per_chip = batch * iters / dt / n_chips
    print(
        json.dumps(
            {
                "metric": "resnet50_train_images_per_sec_per_chip",
                "value": round(imgs_per_sec_per_chip, 1),
                "unit": "images/sec/chip",
                "vs_baseline": round(
                    imgs_per_sec_per_chip / REFERENCE_IMGS_PER_SEC_PER_DEVICE, 3
                ),
            }
        )
    )


if __name__ == "__main__":
    main()
