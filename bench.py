#!/usr/bin/env python
"""Headline benchmark: ResNet-50 ImageNet-shape training throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference's only published experiment (BASELINE.md) — its
fastest recipe (apex AMP+DDP) does an ImageNet epoch (1,281,167 images) in
1186.5 s on 4× V100, i.e. ~270 images/sec/GPU.  ``vs_baseline`` is
our images/sec/chip divided by that per-device number.

Synthetic in-device data (no host IO) so the number isolates the compiled
step: forward + loss + backward + SGD update at global batch 256, bf16
compute policy — the same step the tpu_native recipe runs, with the
space-to-depth stem (mathematically identical to conv7, see models/resnet.py)
and bf16 image feed (what the u8-wire loader path delivers after device-side
normalize).

Tunnel resilience: on this platform the TPU is reached through a tunnel that
can be down at snapshot time, and a wedged ``jax.devices()`` blocks forever
*and cannot be retried in-process* (the backend-init lock stays held).  So
device discovery is probed in fresh subprocesses with retry/backoff for up
to ~10 minutes; if the tunnel never comes up, the last-known-good result
(``BENCH_LKG.json``, refreshed on every successful run) is emitted with
``"stale": true`` rather than 0.0.

Roofline note (round-2 profile, scripts/profile_trace.py on the real v5e):
the step moves ~68 GB/step at ~690-750 GB/s effective against a ~819 GB/s
HBM peak — ResNet-50 b256 bf16 is **memory-bound** on this chip (arithmetic
intensity ~29-60 FLOP/byte vs the chip's ~240 balance point), so throughput
is capped near ~3,080 img/s at current traffic; conv fusions alone account
for 55.4 GB/step already running at 699 GB/s.  Batch 512, larger scoped
VMEM, and f32 feeds all measured slower (scripts/bench_variants.py).
"""

import json
import os
import subprocess
import sys
import time

METRIC = "resnet50_train_images_per_sec_per_chip"
UNIT = "images/sec/chip"
REFERENCE_IMGS_PER_SEC_PER_DEVICE = 1281167 / 1186.5 / 4  # ≈ 269.9 (BASELINE.md)
LKG_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_LKG.json")

PROBE_SNIPPET = "import jax; print(len(jax.devices()))"


def _emit(payload: dict, code: int) -> "NoReturn":
    print(json.dumps(payload))
    sys.exit(code)


def _stale_exit_code() -> int:
    """Exit code for stale (LKG-replay) emissions.  Default 0 keeps the
    driver contract that recorded round 3's stale marker; set
    BENCH_STALE_EXIT_CODE (e.g. 3) so an automated consumer keying on the
    exit code can never mistake a replayed number for a fresh benchmark
    (advisor r3) — the "stale": true field remains the in-band marker."""
    try:
        return int(os.environ.get("BENCH_STALE_EXIT_CODE", "0"))
    except ValueError:
        return 0


def _bench_event(kind: str, **fields) -> None:
    """Structured staleness trail (scripts/benchlib.py): the same JSONL
    record schema the obs layer uses, so ``scripts/obs_report.py`` folds
    the probe's stale reason + last-good timestamp into a run summary.
    Best-effort — the stdout JSON contract must survive regardless."""
    try:
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "scripts"))
        from benchlib import bench_event

        bench_event(kind, metric=METRIC, **fields)
    except Exception:  # noqa: BLE001 — observability never blocks emission
        pass


def _emit_failure(error: str) -> "NoReturn":
    """Last resort: report last-known-good (marked stale) instead of 0.0."""
    try:
        with open(LKG_PATH) as f:
            lkg = json.load(f)
        _bench_event("stale", reason=error,
                     last_good=lkg.get("captured_at"),
                     value=lkg.get("value"))
        _emit({
            "metric": METRIC,
            "value": lkg["value"],
            "unit": UNIT,
            "vs_baseline": lkg["vs_baseline"],
            "stale": True,
            "stale_from": lkg.get("captured_at"),
            "error": error,
        }, _stale_exit_code())
    except (OSError, KeyError, ValueError):
        _bench_event("failed", reason=error)
        _emit({"metric": METRIC, "value": 0.0, "unit": UNIT,
               "vs_baseline": 0.0, "error": error}, 1)


def _probe_devices_with_retry(total_budget_s: float = 600.0,
                              attempt_timeout_s: float = 120.0,
                              sleep_s: float = 20.0) -> None:
    """Retry device discovery in fresh subprocesses until the tunnel answers.

    Each attempt is a new process because a hung ``jax.devices()`` poisons
    the whole process — only a clean interpreter can try again.  Returns on
    success; emits the stale/failure record and exits otherwise.
    """
    deadline = time.monotonic() + total_budget_s
    attempt = 0
    last_err = "no probe attempted"
    while time.monotonic() < deadline:
        attempt += 1
        budget = min(attempt_timeout_s, max(10.0, deadline - time.monotonic()))
        try:
            r = subprocess.run(
                [sys.executable, "-c", PROBE_SNIPPET],
                timeout=budget, capture_output=True, text=True,
            )
            if r.returncode == 0 and r.stdout.strip():
                return
            last_err = (f"probe attempt {attempt}: rc={r.returncode} "
                        f"{r.stderr.strip()[-200:]}")
        except subprocess.TimeoutExpired:
            last_err = (f"probe attempt {attempt}: device discovery hung "
                        f">{budget:.0f}s (axon tunnel unreachable)")
        if time.monotonic() + sleep_s < deadline:
            time.sleep(sleep_s)
        else:
            break
    _emit_failure(last_err)


def _save_lkg(value: float, vs_baseline: float, extra: dict = None) -> str:
    stamp = time.strftime("%Y-%m-%dT%H:%M:%S%z")
    rec = {
        "metric": METRIC,
        "value": value,
        "vs_baseline": vs_baseline,
        "captured_at": stamp,
    }
    rec.update(extra or {})
    tmp = LKG_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f)
        f.write("\n")
    os.replace(tmp, LKG_PATH)
    return stamp


def _plan_prediction(n_chips: int, batch: int, image: int,
                     imgs_per_sec_per_chip: float) -> dict:
    """The autoplan cost model's predicted MFU for the exact benched
    config, next to the MFU the measured rate implies — stamped into the
    payload, the LKG, and a ``captured`` bench_event so the staleness
    report (scripts/obs_report.py) can show prediction drift over time.
    Best-effort: a planner error must never block the headline number."""
    try:
        from pytorch_distributed_tpu.obs.flops import (
            chip_peak_flops,
            image_step_cost,
        )
        from pytorch_distributed_tpu.plan import predicted_mfu, resnet50_spec

        chip = os.environ.get("PTD_BENCH_CHIP") or None
        spec = resnet50_spec(batch=batch, image_size=image)
        predicted = predicted_mfu("resnet50", n_chips, chip=chip, spec=spec)
        if predicted is None:
            return {}
        out = {"predicted_mfu": round(predicted, 2)}
        if imgs_per_sec_per_chip > 0:
            step_s = batch / (imgs_per_sec_per_chip * n_chips)
            cost = image_step_cost("resnet50", batch, image)
            measured = (100.0 * cost.model_flops
                        / (step_s * n_chips * chip_peak_flops(chip)))
            out["measured_mfu"] = round(measured, 2)
            out["prediction_drift_pct"] = round(
                100.0 * (measured - predicted) / predicted, 1)
        return out
    except Exception:  # noqa: BLE001 — prediction is observability only
        return {}


def main() -> None:
    _probe_devices_with_retry()

    # The tunnel answered a moment ago; import jax only now so a wedged
    # discovery above never poisons this interpreter.  The tunnel can still
    # drop between the probe and our own backend init, which would wedge
    # THIS process with no output — a watchdog emits the stale record and
    # hard-exits if init doesn't finish in time (threads can't unblock a
    # hung jax.devices(); only process exit can).
    import threading

    init_done = threading.Event()

    def watchdog():
        if not init_done.wait(240.0):
            try:
                with open(LKG_PATH) as f:
                    lkg = json.load(f)
                _bench_event("stale",
                             reason="backend init hung >240s after probe "
                                    "success",
                             last_good=lkg.get("captured_at"),
                             value=lkg.get("value"))
                print(json.dumps({
                    "metric": METRIC, "value": lkg["value"], "unit": UNIT,
                    "vs_baseline": lkg["vs_baseline"], "stale": True,
                    "stale_from": lkg.get("captured_at"),
                    "error": "backend init hung >240s after probe success",
                }))
                os._exit(_stale_exit_code())
            except (OSError, KeyError, ValueError):
                print(json.dumps({
                    "metric": METRIC, "value": 0.0, "unit": UNIT,
                    "vs_baseline": 0.0,
                    "error": "backend init hung >240s after probe success",
                }))
                os._exit(1)

    threading.Thread(target=watchdog, daemon=True).start()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    batch = 256
    image = 224
    mesh = data_parallel_mesh()  # first jax.devices() call — watchdog scope
    init_done.set()

    rng = np.random.default_rng(0)
    device_batch = {
        "images": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype=jnp.bfloat16
        ),
        "labels": jnp.asarray(rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)

    def measure(fused: bool) -> float:
        model = models.create_model(
            "resnet50", num_classes=1000, dtype=jnp.bfloat16,
            stem="space_to_depth", fused_convbn=fused,
        )
        variables = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False
        )
        state = TrainState.create(variables, sgd_init(variables["params"]))
        step = make_train_step(model, mesh)
        # Warmup + timing with the shared value-fetch sync discipline
        # (utils/benchstep.py): on tunneled platforms block_until_ready
        # alone can return before the device queue drains.
        from pytorch_distributed_tpu.utils.benchstep import measure_train_step

        dt, _ = measure_train_step(step, state, device_batch, lr, iters=20)
        return batch / dt / jax.device_count()

    baseline = measure(fused=False)
    # Round-4 lever: the fused conv+BN backward (ops/fused_conv_bn.py).
    # Guarded — the headline must survive even if Mosaic rejects the
    # kernel on this chip/toolchain; the winner is reported either way.
    fused_rate = None
    try:
        fused_rate = measure(fused=True)
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure
        print(f"# fused_convbn variant failed: {type(e).__name__}: "
              f"{str(e)[:200]}", file=sys.stderr)
    imgs_per_sec_per_chip = max(baseline, fused_rate or 0.0)
    value = round(imgs_per_sec_per_chip, 1)
    vs_baseline = round(
        imgs_per_sec_per_chip / REFERENCE_IMGS_PER_SEC_PER_DEVICE, 3)
    prediction = _plan_prediction(jax.device_count(), batch, image,
                                  imgs_per_sec_per_chip)
    stamp = _save_lkg(value, vs_baseline, extra=prediction)
    _bench_event("captured", value=value, captured_at=stamp, **prediction)
    payload = {
        "metric": METRIC,
        "value": value,
        "unit": UNIT,
        "vs_baseline": vs_baseline,
        "config": ("fused_convbn"
                   if fused_rate and fused_rate > baseline else "baseline"),
        "unfused_img_s": round(baseline, 1),
    }
    payload.update(prediction)
    if fused_rate is not None:
        payload["fused_img_s"] = round(fused_rate, 1)
    print(json.dumps(payload))


if __name__ == "__main__":
    main()
