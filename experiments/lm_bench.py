#!/usr/bin/env python
"""TransformerLM training throughput + MFU on the real chip.

The ResNet headline is HBM-roofline-bound (see ROADMAP); the LM family is
where the MXU earns its keep — large matmuls, high arithmetic intensity.
This bench measures the full compiled LM train step (fwd+bwd+SGD, bf16
compute) and reports tokens/sec and **model FLOPs utilization** against the
chip's advertised bf16 peak, across context lengths and attention
implementations (dense vs the Pallas flash kernel, remat on/off).

MFU counts standard transformer model FLOPs: 6·P_active·T for the matmul
stack plus 12·L·T·d per token... simplified to the PaLM convention:
    flops/token = 6·N + 12·n_layers·d_model·seq_len
(N = non-embedding params; causal attention halves the 12·L·d term, we use
6·L·d.)  Writes RESULTS_lm.json.

Run on the TPU chip:
    PYTHONPATH=/root/repo python experiments/lm_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

PEAK_TFLOPS = float(os.environ.get("LM_BENCH_PEAK_TFLOPS", "197"))  # v5e bf16
D_MODEL = int(os.environ.get("LM_BENCH_D", "1024"))
N_LAYERS = int(os.environ.get("LM_BENCH_LAYERS", "12"))
N_HEADS = int(os.environ.get("LM_BENCH_HEADS", "16"))
VOCAB = int(os.environ.get("LM_BENCH_VOCAB", "32000"))
ITERS = int(os.environ.get("LM_BENCH_ITERS", "10"))


def count_params(params) -> int:
    import jax

    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def bench(L: int, batch: int, attn_impl: str, remat: bool,
          fused_ce: int = 0):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = data_parallel_mesh()
    model = TransformerLM(
        vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, dtype=jnp.bfloat16, attn_impl=attn_impl,
        remat=remat,
    )
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, VOCAB, size=(batch, L)).astype(np.int32))
    variables = model.init(jax.random.PRNGKey(0), tokens[:1, :8])
    params = variables["params"]
    n_params = count_params(params)
    n_embed = params["embed"]["embedding"].size
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_train_step(model, mesh, replicated_like(params),
                              fused_ce_chunks=fused_ce)
    lr = jnp.float32(1e-3)

    for _ in range(3):
        state, met = step(state, tokens, lr)
    float(met["loss"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, met = step(state, tokens, lr)
    assert np.isfinite(float(met["loss"]))
    dt = (time.perf_counter() - t0) / ITERS

    toks = batch * L
    # PaLM-convention model FLOPs (fwd+bwd = 3x fwd matmul FLOPs), causal
    # attention at half the full-L^2 score/value work.
    flops_per_tok = 6 * (n_params - n_embed) + 6 * n_layers_d() * L
    # embedding lookup is a gather (no matmul flops); the tied head IS a
    # matmul over the vocab:
    flops_per_tok += 6 * n_embed
    total_flops = flops_per_tok * toks
    # The step shards over every device in the mesh; normalize peak to match.
    mfu = total_flops / dt / (PEAK_TFLOPS * 1e12 * jax.device_count())
    return {
        "ms_per_step": round(dt * 1e3, 2),
        "tokens_per_sec": round(toks / dt, 0),
        "mfu_pct": round(100 * mfu, 1),
        "params_m": round(n_params / 1e6, 1),
    }


def n_layers_d() -> int:
    return N_LAYERS * D_MODEL


def main() -> int:
    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "..", "RESULTS_lm.json")
    # Resumable: completed rows survive a killed sweep (the watcher runs
    # this under a timeout; without per-row writes a long sweep could
    # burn every retry re-doing the early rows — arch_bench pattern).
    results = {}
    extra = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prior = json.load(f)
            if (prior.get("meta", {}).get("d_model") == D_MODEL
                    and prior["meta"].get("vocab") == VOCAB
                    and prior["meta"].get("n_layers") == N_LAYERS
                    and prior["meta"].get("n_heads") == N_HEADS
                    and prior["meta"].get("peak_tflops") == PEAK_TFLOPS
                    and prior["meta"].get("platform")
                    == jax.default_backend()):
                results = prior.get("configs", {})
                extra = {k: v for k, v in prior.items()
                         if k not in ("meta", "configs")}
        except ValueError:
            pass

    def write():
        out = {
            "meta": {
                "d_model": D_MODEL, "n_layers": N_LAYERS,
                "n_heads": N_HEADS, "vocab": VOCAB,
                "peak_tflops": PEAK_TFLOPS,
                "platform": jax.default_backend(),
                "what": "full LM train step (fwd+bwd+SGD), bf16, "
                        "PaLM-convention MFU vs chip bf16 peak",
            },
            "configs": results,
            **extra,
        }
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
        return out
    # Dense batches are capped by the materialized f32 score tensor
    # (B·H·L² · 4B: 4.3 GB at L=1024 b=4 — b=16 would want 17 GB).
    for L, batch, attn, remat, fused_ce in (
        (1024, 4, "dense", False, 0),
        (1024, 4, "flash", False, 0),
        # fused tied-head+CE (ops/fused_ce.py): the round-5 MFU lever —
        # same step, logits tensor never in HBM; chunks sized so each
        # block's [rows, V] f32 scratch stays O(100 MB).
        (1024, 4, "flash", False, 8),
        (2048, 1, "dense", False, 0),
        (2048, 8, "flash", False, 0),
        (2048, 8, "flash", False, 16),
        (4096, 4, "flash", False, 0),
        (4096, 4, "flash", False, 16),
        # fused CE frees the logits HBM — retry the batch the unfused
        # step could not fit (dense-note above: b16 at L1024 wants 17 GB
        # of score tensor; flash+fused-CE removes both big tensors).
        (1024, 16, "flash", False, 16),
        (4096, 4, "flash", True, 0),
        (8192, 2, "flash", True, 0),
        (8192, 2, "flash", True, 16),
    ):
        tag = (f"L{L}_b{batch}_{attn}{'_remat' if remat else ''}"
               + (f"_fusedce{fused_ce}" if fused_ce else ""))
        if tag in results:
            print(f"{tag}: cached", flush=True)
            continue
        try:
            row = bench(L, batch, attn, remat, fused_ce)
        except Exception as e:
            print(f"{tag}: FAILED {repr(e)[:200]}", flush=True)
            continue
        results[tag] = row
        write()
        print(f"{tag}: {row['ms_per_step']} ms  "
              f"{row['tokens_per_sec']:,.0f} tok/s  MFU {row['mfu_pct']}%",
              flush=True)

    print(json.dumps(write()))
    return 0


if __name__ == "__main__":
    sys.exit(main())
