#!/usr/bin/env python
"""Compiled-peak memory of the LM train step: fused vs unfused loss head.

The fused tied-head+CE (ops/fused_ce.py) exists to keep the [B·L, vocab]
logits tensor out of HBM.  The throughput half of that claim needs the
chip (lm_bench fused rows, armed in tunnel_watch); the MEMORY half is a
compile-time fact XLA will state on any backend: lower + compile the full
train step (fwd+bwd+SGD) both ways and read ``memory_analysis()`` peak
temp bytes — the same compiled-peak methodology as experiments/pp_memory.py
(RESULTS_pp_memory.json).

Writes ``RESULTS_fused_ce_memory.json``.  CPU-safe (compile only):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/fused_ce_memory.py
"""

from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

D_MODEL = int(os.environ.get("FCM_D", "1024"))
N_LAYERS = int(os.environ.get("FCM_LAYERS", "12"))
N_HEADS = int(os.environ.get("FCM_HEADS", "16"))
VOCAB = int(os.environ.get("FCM_VOCAB", "32000"))
SEQ = int(os.environ.get("FCM_SEQ", "1024"))
# Must divide the data-axis device count (8 on the simulated CPU mesh).
BATCH = int(os.environ.get("FCM_BATCH", "8"))
CHUNKS = int(os.environ.get("FCM_CHUNKS", "8"))


def peak_bytes(fused_ce: int) -> dict:
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = data_parallel_mesh()
    model = TransformerLM(
        vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, dtype=jnp.bfloat16, attn_impl="dense",
    )
    toks = jnp.zeros((BATCH, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:1, :8])["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_train_step(model, mesh, replicated_like(params),
                              fused_ce_chunks=fused_ce)
    compiled = step.lower(state, toks, jnp.float32(1e-3)).compile()
    m = compiled.memory_analysis()
    return {
        "temp_bytes_mib": round(m.temp_size_in_bytes / 2**20, 1),
        "peak_mib": round(
            (m.temp_size_in_bytes + m.argument_size_in_bytes
             + m.output_size_in_bytes) / 2**20, 1),
    }


def main() -> int:
    logits_mib = BATCH * (SEQ - 1) * VOCAB * 4 / 2**20
    rows = {}
    for tag, chunks in (("unfused", 0), (f"fused_c{CHUNKS}", CHUNKS)):
        rows[tag] = peak_bytes(chunks)
        print(f"{tag}: temp {rows[tag]['temp_bytes_mib']} MiB "
              f"(peak {rows[tag]['peak_mib']} MiB)", flush=True)
    saved = (rows["unfused"]["temp_bytes_mib"]
             - rows[f"fused_c{CHUNKS}"]["temp_bytes_mib"])
    out = {
        "meta": {
            "d_model": D_MODEL, "n_layers": N_LAYERS, "n_heads": N_HEADS,
            "vocab": VOCAB, "seq": SEQ, "batch": BATCH, "chunks": CHUNKS,
            "platform": jax.default_backend(),
            "analytic_logits_f32_mib": round(logits_mib, 1),
            "what": "XLA compiled-peak temp buffers of the full LM train "
                    "step (fwd+bwd+SGD, bf16, dense attn), unfused logits "
                    "head vs fused tied-head+CE (ops/fused_ce.py) — the "
                    "pp_memory.py compiled-peak methodology",
        },
        "rows": rows,
        "temp_saved_mib": round(saved, 1),
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_fused_ce_memory.json"),
              "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out), flush=True)
    # The claim must be falsifiable: the fused step should save at least
    # half the analytic f32 logits footprint.
    assert saved > 0.5 * logits_mib, (saved, logits_mib)
    return 0


if __name__ == "__main__":
    sys.exit(main())
