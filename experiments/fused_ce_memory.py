#!/usr/bin/env python
"""Compiled-peak memory of the LM train step: fused vs unfused loss head,
single-chip AND 8-way data-sharded.

The fused tied-head+CE (ops/fused_ce.py) exists to keep the [B·L, vocab]
logits tensor out of HBM.  The throughput half of that claim needs the
chip (lm_bench fused rows, armed in tunnel_watch); the MEMORY half is a
compile-time fact XLA will state on any backend: lower + compile the full
train step (fwd+bwd+SGD) both ways and read ``memory_analysis()`` peak
temp bytes — the same compiled-peak methodology as experiments/pp_memory.py
(RESULTS_pp_memory.json).

Round 5 measured the catch: on an 8-way data-sharded mesh the replicated
variant is net-neutral, because its backward carries a fully replicated
[V, D] f32 dE accumulator while the logits it eliminates were already
batch-sharded.  This run therefore A/Bs THREE loss heads on the 8-way mesh
(same per-device batch as the single-chip row): unfused, fused with the
replicated accumulator (the round-5 regression), and fused in DP mode
(ops/fused_ce.py fused_ce_sums_dp — vocab-row-sharded [V/8, D] dE carry,
per-block all_to_all, cotangent left sharded for the existing GSPMD
gradient reduction).

Writes ``RESULTS_fused_ce_memory.json``.  CPU-safe (compile only):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/fused_ce_memory.py
"""

from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

D_MODEL = int(os.environ.get("FCM_D", "1024"))
N_LAYERS = int(os.environ.get("FCM_LAYERS", "12"))
N_HEADS = int(os.environ.get("FCM_HEADS", "16"))
VOCAB = int(os.environ.get("FCM_VOCAB", "32000"))
SEQ = int(os.environ.get("FCM_SEQ", "1024"))
BATCH = int(os.environ.get("FCM_BATCH", "4"))  # single-chip row
CHUNKS = int(os.environ.get("FCM_CHUNKS", "8"))
DP = int(os.environ.get("FCM_DP", "8"))  # sharded-mesh width
# Sharded-mesh global batch: same per-device batch as the single-chip row,
# so the two tables answer the same question (per-device loss-head temps).
BATCH_DP = int(os.environ.get("FCM_BATCH_DP", str(BATCH * DP)))


def peak_bytes(fused_ce: int, n_dev: int = 1, batch: int = BATCH,
               mode: str = "auto") -> dict:
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = build_mesh(MeshSpec(("data",), (n_dev,)), jax.devices()[:n_dev])
    model = TransformerLM(
        vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
        n_layers=N_LAYERS, dtype=jnp.bfloat16, attn_impl="dense",
    )
    toks = jnp.zeros((batch, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:1, :8])["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_train_step(model, mesh, replicated_like(params),
                              fused_ce_chunks=fused_ce, fused_ce_mode=mode)
    compiled = step.lower(state, toks, jnp.float32(1e-3)).compile()
    m = compiled.memory_analysis()
    return {
        "temp_bytes_mib": round(m.temp_size_in_bytes / 2**20, 1),
        "peak_mib": round(
            (m.temp_size_in_bytes + m.argument_size_in_bytes
             + m.output_size_in_bytes) / 2**20, 1),
    }


def main() -> int:
    logits_mib = BATCH * (SEQ - 1) * VOCAB * 4 / 2**20
    rows = {}
    for tag, chunks, mode in (("unfused", 0, "auto"),
                              (f"fused_c{CHUNKS}", CHUNKS, "replicated")):
        rows[tag] = peak_bytes(chunks)
        print(f"{tag}: temp {rows[tag]['temp_bytes_mib']} MiB "
              f"(peak {rows[tag]['peak_mib']} MiB)", flush=True)
    saved = (rows["unfused"]["temp_bytes_mib"]
             - rows[f"fused_c{CHUNKS}"]["temp_bytes_mib"])

    # --- 8-way data-sharded A/B (per-device batch held at BATCH) ---
    rows_dp = {}
    if len(jax.devices()) >= DP:
        for tag, chunks, mode in (
                ("unfused", 0, "auto"),
                (f"fused_c{CHUNKS}_replicated", CHUNKS, "replicated"),
                (f"fused_c{CHUNKS}_dp", CHUNKS, "dp")):
            rows_dp[tag] = peak_bytes(chunks, n_dev=DP, batch=BATCH_DP,
                                      mode=mode)
            print(f"dp{DP} {tag}: temp {rows_dp[tag]['temp_bytes_mib']} MiB "
                  f"(peak {rows_dp[tag]['peak_mib']} MiB)", flush=True)
    else:
        print(f"SKIP dp{DP} table: only {len(jax.devices())} devices "
              f"(need XLA_FLAGS=--xla_force_host_platform_device_count="
              f"{DP})", flush=True)

    out = {
        "meta": {
            "d_model": D_MODEL, "n_layers": N_LAYERS, "n_heads": N_HEADS,
            "vocab": VOCAB, "seq": SEQ, "batch": BATCH, "chunks": CHUNKS,
            "dp": DP, "batch_dp": BATCH_DP,
            "platform": jax.default_backend(),
            "analytic_logits_f32_mib": round(logits_mib, 1),
            "what": "XLA compiled-peak temp buffers of the full LM train "
                    "step (fwd+bwd+SGD, bf16, dense attn), unfused logits "
                    "head vs fused tied-head+CE (ops/fused_ce.py) — the "
                    "pp_memory.py compiled-peak methodology.  rows = one "
                    "chip; rows_dp = 8-way data-sharded mesh at the same "
                    "per-device batch, A/B-ing the replicated-dE fused "
                    "variant (round-5: net-neutral) against DP mode "
                    "(vocab-row-sharded [V/8, D] dE accumulator, "
                    "fused_ce_sums_dp)",
        },
        "rows": rows,
        "temp_saved_mib": round(saved, 1),
    }
    if rows_dp:
        saved_rep = (rows_dp["unfused"]["temp_bytes_mib"]
                     - rows_dp[f"fused_c{CHUNKS}_replicated"]["temp_bytes_mib"])
        saved_dp = (rows_dp["unfused"]["temp_bytes_mib"]
                    - rows_dp[f"fused_c{CHUNKS}_dp"]["temp_bytes_mib"])
        out["rows_dp"] = rows_dp
        out["dp_temp_saved_mib_replicated_accumulator"] = round(saved_rep, 1)
        out["dp_temp_saved_mib_dp_mode"] = round(saved_dp, 1)
        out["meta"]["dp_sharded_note"] = (
            f"measured: at {DP}-way data sharding the replicated-dE fused "
            f"variant saves {round(saved_rep, 1)} MiB of compiled-peak "
            f"temps vs unfused (round 5 measured it net-neutral, -116 MiB "
            f"at global batch {DP}) because its backward carries a "
            f"replicated [V={VOCAB}, D={D_MODEL}] f32 dE accumulator; DP "
            f"mode shards that accumulator to [V/{DP}, D] per device and "
            f"saves {round(saved_dp, 1)} MiB — the fused-head win no "
            f"longer degrades under data sharding")
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_fused_ce_memory.json"),
              "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out), flush=True)
    # The claims must be falsifiable: single-chip, the fused step saves at
    # least half the analytic f32 logits footprint; 8-way, DP mode beats
    # unfused by >= 900 MiB of compiled-peak temps (the ISSUE-1 target the
    # replicated variant missed by construction).
    assert saved > 0.5 * logits_mib, (saved, logits_mib)
    if rows_dp:
        assert saved_dp >= 900.0, (saved_dp, saved_rep)
    return 0


if __name__ == "__main__":
    sys.exit(main())
