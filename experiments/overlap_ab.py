#!/usr/bin/env python
"""Comm-overlap A/B: ``--overlap none`` vs ``--overlap bucketed`` on the
explicit shard_map DP LM step, identical synthetic stream.

Two claims, both fenced here:

1. **Bit-exact numerics** — bucketing is a pure schedule transformation
   (the same per-leaf psum math, grouped differently), so every step's
   loss must match the monolithic run to the last bit, and the compiled
   grad_sync byte totals must be identical (no traffic moved or added —
   the ledger is the oracle, obs/comms.py on real HLO).
2. **Exposed-comm reduction** — with the sync split into K
   reverse-autodiff buckets, bucket k's collective runs concurrently
   with the backward compute that produces buckets k+1..K-1, so only the
   tail bucket's collective is exposed: ``exposed_comm_ms`` must drop
   ≥ 60% vs the monolithic tail-end sync (the ISSUE-16 acceptance
   floor; the schedule-model best is (K-1)/K).

The CPU test backend serializes collectives with compute, so wall-clock
cannot show the overlap.  The fence instead *derives* each variant's
per-step timeline from its REAL compiled ledger (per-bucket payload
bytes from the ``bucket`` field obs/comms.py parses out of the scope
labels) plus fixed deterministic compute/wire rates, encodes it as an
XSpace capture (obs/timeline.py encode_xspace), and runs the production
analyzer over it (``analyze_steps`` / ``aggregate_steps`` — the same
code path obs_timeline.py uses on an accelerator capture).  What is
being tested is the *schedule* — when each collective can start relative
to backward compute — with measured payloads, not a hand-asserted
number.

Writes ``RESULTS_overlap.json`` and two metrics JSONLs whose
``exposed_comm_ms`` / ``overlap_pct`` / ``comm_wire_bytes`` fields fold
into ``scripts/obs_report.py --diff`` (the diff text is embedded in the
results).  CPU-safe:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/overlap_ab.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import numpy as np

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

DP = int(os.environ.get("OAB_DP", "4"))
STEPS = int(os.environ.get("OAB_STEPS", "3"))
BATCH = int(os.environ.get("OAB_BATCH", "8"))
SEQ = int(os.environ.get("OAB_SEQ", "16"))
VOCAB = int(os.environ.get("OAB_VOCAB", "64"))
D_MODEL = int(os.environ.get("OAB_DMODEL", "32"))
BUCKET_MB = float(os.environ.get("OAB_BUCKET_MB", str(1 / 128)))
SEED = int(os.environ.get("OAB_SEED", "0"))

# Deterministic timeline rates: backward compute at 0.5 B/ps, gradient
# wire at 1 B/ps.  Only the *ratio* matters for the overlap fraction —
# per-bucket comm must fit under the remaining backward compute, which
# holds whenever compute-per-byte exceeds wire-per-byte (true on every
# real accelerator this schedule targets).
_COMPUTE_PS_PER_BYTE = 2.0
_WIRE_PS_PER_BYTE = 1.0


def _build(overlap: str, mesh):
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    model = TransformerLM(vocab_size=VOCAB, d_model=D_MODEL, n_heads=4,
                          n_layers=1)
    tokens0 = jnp.zeros((BATCH, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(SEED), tokens0)["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_train_step(
        model, mesh, replicated_like(params), explicit_collectives=True,
        overlap=overlap, bucket_mb=BUCKET_MB)
    return step, state


def _token_stream():
    rng = np.random.default_rng(SEED)
    for _ in range(STEPS):
        yield rng.integers(0, VOCAB, size=(BATCH, SEQ)).astype(np.int32)


def _grad_sync_buckets(ledger) -> dict:
    """{bucket_index: payload_bytes} over the grad_sync phase; the
    monolithic ledger lands everything on -1."""
    out: dict = {}
    for e in ledger.entries:
        if e.phase == "grad_sync":
            out[e.bucket] = out.get(e.bucket, 0) + e.bytes
    return out


def _synth_timeline(buckets: dict) -> bytes:
    """Schedule-derived XSpace for STEPS identical steps.

    Backward runs as one compute segment per bucket (duration ∝ that
    bucket's gradient bytes, reverse-autodiff order: bucket 0's segment
    first).  Bucket k's collective is *issued* when its segment ends and
    *serialized* against the previous bucket's collective (one comm
    channel) — exactly the schedule parallel/overlap.py encodes in HLO.
    The monolithic variant is the same timeline with its single bucket
    (-1): all comm after all backward, fully exposed."""
    from pytorch_distributed_tpu.obs import timeline as tl_mod

    order = sorted(buckets)  # [-1] or [0, 1, ..., K-1]
    seg_ps = {k: max(1.0, buckets[k] * _COMPUTE_PS_PER_BYTE)
              for k in order}
    comm_ps = {k: max(1.0, buckets[k] * _WIRE_PS_PER_BYTE) for k in order}
    step_ps = int(sum(seg_ps.values()) + sum(comm_ps.values())) + 1000

    dev_events = []
    host_events = []
    for s in range(STEPS):
        base = s * step_ps
        host_events.append({"name": "lm_step", "offset_ps": base,
                            "duration_ps": step_ps})
        t = float(base)
        comm_free = float(base)
        for i, k in enumerate(order):
            dev_events.append({
                "name": f"fusion.{s}_{i}",
                "offset_ps": int(t), "duration_ps": int(seg_ps[k]),
                "stats": {"hlo_op": f"fusion.{s}_{i}"}})
            t += seg_ps[k]
            start = max(t, comm_free)
            dev_events.append({
                "name": f"all-reduce.{s}_{i}",
                "offset_ps": int(start), "duration_ps": int(comm_ps[k])})
            comm_free = start + comm_ps[k]

    planes = [
        {"name": "/host:CPU", "lines": [
            {"name": "steps", "timestamp_ns": 0, "events": host_events}]},
        {"name": "/device:CPU:0", "lines": [
            {"name": "stream#0", "timestamp_ns": 0, "events": dev_events}]},
    ]
    return tl_mod.encode_xspace(planes, hostname="overlap_ab")


def _analyze(xspace: bytes) -> dict:
    from pytorch_distributed_tpu.obs import timeline as tl_mod

    tl = tl_mod.parse_xspace_bytes(xspace, source="overlap_ab")
    stats = tl_mod.analyze_steps(tl, annotation="lm_step")
    return tl_mod.aggregate_steps(stats)


def run_variant(overlap: str, mesh, metrics_path: str) -> dict:
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import comms
    from pytorch_distributed_tpu.obs.metrics import MetricsLogger

    step, state = _build(overlap, mesh)
    lr = jnp.float32(0.05)
    losses = []
    first = None
    times = []
    import time

    for toks in _token_stream():
        jt = jnp.asarray(toks)
        if first is None:
            first = jt
        t0 = time.perf_counter()
        state, metrics = step(state, jt, lr)
        jax.block_until_ready(metrics["loss"])
        times.append(time.perf_counter() - t0)
        losses.append(float(metrics["loss"]))

    ledger = comms.ledger_from_jitted(step, (state, first, lr),
                                      step=f"lm_{overlap}", mesh=mesh)
    buckets = _grad_sync_buckets(ledger)
    agg = _analyze(_synth_timeline(buckets))

    logger = MetricsLogger(metrics_path)
    for i, st in enumerate(times):
        logger.log_step(i, step_time=st, n_items=BATCH * SEQ, lr=0.05,
                        extra={
                            **ledger.metrics_fields(),
                            "exposed_comm_ms": agg["exposed_ms_mean"],
                            "overlap_pct": agg["overlap_pct_mean"],
                        })
    logger.flush()

    gs = ledger.by_phase()["grad_sync"]
    return {
        "losses": [round(x, 6) for x in losses],
        "loss_bits": [float(np.float32(x)).hex() for x in losses],
        "grad_sync_bytes": int(gs["bytes"]),
        "grad_sync_wire_bytes": round(float(gs["wire_bytes"]), 1),
        "grad_sync_collectives": int(gs["count"]),
        "n_buckets": len([k for k in buckets if k >= 0]) or 1,
        "bucket_bytes": {str(k): int(v) for k, v in sorted(buckets.items())},
        "exposed_comm_ms": round(agg["exposed_ms_mean"], 6),
        "overlap_pct": round(agg["overlap_pct_mean"], 2),
        "comm_ms": round(agg["comm_ms_mean"], 6),
    }


def _int8_wire_evidence(mesh) -> dict:
    """The GSPMD-migration pin: --grad-compress int8 under the bucketed
    explicit step shows s8 collectives in the compiled HLO ledger."""
    import warnings

    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.obs import comms
    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    model = TransformerLM(vocab_size=VOCAB, d_model=D_MODEL, n_heads=4,
                          n_layers=1)
    tokens = jnp.zeros((BATCH, SEQ), jnp.int32)
    params = model.init(jax.random.PRNGKey(SEED), tokens)["params"]
    residual = qcomm.init_residual(params, "int8", explicit=True,
                                   n_data=DP)
    state = TrainState.create({"params": params}, sgd_init(params),
                              residual=residual)
    state = state.replace(residual=jax.device_put(
        state.residual, NamedSharding(mesh, P("data"))))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        step = make_lm_train_step(
            model, mesh, replicated_like(params), grad_compress="int8",
            overlap="bucketed", bucket_mb=BUCKET_MB)
    ledger = comms.ledger_from_jitted(
        step, (state, tokens, jnp.float32(0.05)), step="lm_int8", mesh=mesh)
    enc = ledger.phase_wire_encodings("grad_sync")
    return {k: int(v) for k, v in enc.items()}


def main() -> int:
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    if len(jax.devices()) < DP:
        print(f"SKIP: need {DP} devices, have {len(jax.devices())} "
              f"(set XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return 0
    mesh = build_mesh(MeshSpec(("data",), (DP,)), jax.devices()[:DP])

    here = os.path.dirname(os.path.abspath(__file__))
    root = os.path.join(here, "..")
    mono_jsonl = os.path.join(root, "metrics_overlap_none.jsonl")
    buck_jsonl = os.path.join(root, "metrics_overlap_bucketed.jsonl")
    for p in (mono_jsonl, buck_jsonl):  # MetricsLogger appends
        if os.path.exists(p):
            os.remove(p)

    mono = run_variant("none", mesh, mono_jsonl)
    print(f"none:     exposed {mono['exposed_comm_ms']:.4f} ms/step "
          f"(overlap {mono['overlap_pct']:.1f}%), grad_sync "
          f"{mono['grad_sync_bytes']} B", flush=True)
    buck = run_variant("bucketed", mesh, buck_jsonl)
    print(f"bucketed: exposed {buck['exposed_comm_ms']:.4f} ms/step "
          f"(overlap {buck['overlap_pct']:.1f}%), {buck['n_buckets']} "
          f"buckets, grad_sync {buck['grad_sync_bytes']} B", flush=True)

    reduction_pct = 100.0 * (1.0 - buck["exposed_comm_ms"]
                             / max(mono["exposed_comm_ms"], 1e-12))
    int8_enc = _int8_wire_evidence(mesh)

    diff = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "obs_report.py"),
         "--diff", mono_jsonl, buck_jsonl],
        capture_output=True, text=True, cwd=root)
    diff_text = (diff.stdout + diff.stderr).strip()
    print(diff_text, flush=True)

    out = {
        "meta": {
            "dp": DP, "steps": STEPS, "batch": BATCH, "seq": SEQ,
            "vocab": VOCAB, "d_model": D_MODEL, "bucket_mb": BUCKET_MB,
            "seed": SEED, "platform": jax.default_backend(),
            "what": "A/B of --overlap none vs bucketed on the explicit "
                    "shard_map DP LM step (train/lm.py), identical "
                    "fixed-seed token stream.  Numerics fenced bit-exact "
                    "from the executed steps; exposed_comm_ms fenced "
                    "from schedule-derived timelines built out of each "
                    "variant's REAL compiled per-bucket ledger bytes "
                    "(obs/comms.py bucket field) and analyzed by the "
                    "production obs/timeline.py analyzer.",
            "rates_ps_per_byte": {"compute": _COMPUTE_PS_PER_BYTE,
                                  "wire": _WIRE_PS_PER_BYTE},
        },
        "none": mono,
        "bucketed": buck,
        "exposed_comm_reduction_pct": round(reduction_pct, 2),
        "loss_bitexact": mono["loss_bits"] == buck["loss_bits"],
        "wire_bytes_equal": (mono["grad_sync_bytes"]
                             == buck["grad_sync_bytes"]),
        "int8_grad_sync_encodings": int8_enc,
        "obs_report_diff": diff_text.splitlines(),
    }
    with open(os.path.join(root, "RESULTS_overlap.json"), "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps({k: out[k] for k in
                      ("exposed_comm_reduction_pct", "loss_bitexact",
                       "wire_bytes_equal", "int8_grad_sync_encodings")}),
          flush=True)

    # Falsifiable claims (the ISSUE-16 acceptance fences).
    assert out["loss_bitexact"], (mono["loss_bits"], buck["loss_bits"])
    assert out["wire_bytes_equal"], (mono["grad_sync_bytes"],
                                     buck["grad_sync_bytes"])
    assert buck["n_buckets"] >= 2, buck["bucket_bytes"]
    assert reduction_pct >= 60.0, reduction_pct
    assert "exposed_comm_ms" in diff_text, diff_text
    assert int8_enc.get("int8", 0) > 10 * int8_enc.get("f32", 0), int8_enc
    return 0


if __name__ == "__main__":
    sys.exit(main())
