#!/usr/bin/env python
"""Input-pipeline throughput: what can the host actually feed?

Round-1 gap (VERDICT "What's missing" #3): the train-step bench excludes
host IO, and nothing measured whether the loader can sustain chip feed
rates (~2,500 img/s for ResNet-50 bf16 on one v5e chip).  This bench
generates an ImageNet-shaped synthetic JPEG ImageFolder (real JPEG decode
work) and measures ``DataLoader`` throughput in every wire mode, both
decode backends.

Writes ``RESULTS_loader.json`` at the repo root and prints one line per
mode.  Pure host work — runs anywhere:

    PYTHONPATH=/root/repo python experiments/loader_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

N_IMAGES = int(os.environ.get("LOADER_BENCH_IMAGES", "512"))
SRC = int(os.environ.get("LOADER_BENCH_SRC", "320"))  # source jpeg size
BATCH = 64
IMAGE = 224


def make_tree(root: str, n: int) -> None:
    from PIL import Image

    rng = np.random.default_rng(0)
    per = n // 4
    for c in range(4):
        d = os.path.join(root, "train", f"c{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per):
            arr = rng.integers(0, 256, size=(SRC, SRC, 3), dtype=np.uint8)
            Image.fromarray(arr).save(os.path.join(d, f"{i:04d}.jpg"),
                                      quality=85)


def bench_native_threads(root: str, n_threads: int) -> float:
    """Raw native decode+crop+resize rate at a fixed thread count: the
    scaling axis for 'can the host feed the chip at N cores'."""
    import glob

    from pytorch_distributed_tpu.data.native import decode_crop_resize_batch

    files = sorted(glob.glob(os.path.join(root, "train", "*", "*.jpg")))
    blobs = [open(f, "rb").read() for f in files[:N_IMAGES]]
    # center-crop params (deterministic: scaling is the variable here)
    decode_crop_resize_batch(blobs[:BATCH], IMAGE, n_threads=n_threads)  # warm
    t0 = time.perf_counter()
    n = 0
    for lo in range(0, len(blobs), BATCH):
        chunk = blobs[lo:lo + BATCH]
        decode_crop_resize_batch(chunk, IMAGE, n_threads=n_threads)
        n += len(chunk)
    return n / (time.perf_counter() - t0)


def bench_mode(root: str, batch_mode: str, transform_kind: str,
               workers: int, worker_type: str = "thread") -> float:
    from pytorch_distributed_tpu.data import DataLoader, ImageFolder
    from pytorch_distributed_tpu.data import transforms as T

    if transform_kind == "f32":
        tf = T.train_transform(IMAGE)
    elif transform_kind == "u8":
        tf = T.train_transform_u8(IMAGE)
    else:
        tf = None  # native decode path supplies its own
    ds = ImageFolder(os.path.join(root, "train"), transform=tf,
                     native_decode=transform_kind == "native",
                     image_size=IMAGE)
    loader = DataLoader(ds, BATCH, num_workers=workers, drop_last=True,
                        batch_mode=batch_mode,
                        random_flip=batch_mode != "f32",
                        worker_type=worker_type)
    # warm one epoch fragment, then time a full pass
    it = iter(loader)
    next(it)
    t0 = time.perf_counter()
    n = 0
    for batch in loader:
        n += int(batch["weights"].sum())
    dt = time.perf_counter() - t0
    return n / dt


def main() -> int:
    import tempfile

    workers = int(os.environ.get("LOADER_BENCH_WORKERS",
                                 str(os.cpu_count() or 2)))
    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        make_tree(tmp, N_IMAGES)
        for name, mode, kind in (
            ("pil_f32", "f32", "f32"),
            ("pil_u8_host_native_norm", "u8_host", "u8"),
            ("pil_u8_wire", "u8_wire", "u8"),
            ("native_decode_u8_host", "u8_host", "native"),
            ("native_decode_u8_wire", "u8_wire", "native"),
        ):
            try:
                rate = bench_mode(tmp, mode, kind, workers)
            except Exception as e:  # modes may be unavailable (no .so)
                print(f"{name}: SKIP ({e})")
                continue
            results[name] = round(rate, 1)
            print(f"{name}: {rate:,.0f} img/s ({workers} workers)", flush=True)

        # Process workers: the GIL-proof mode for the PIL path (reference
        # DataLoader worker processes, reference distributed.py:176-180).
        try:
            rate = bench_mode(tmp, "u8_wire", "u8", max(2, workers),
                              worker_type="process")
            results["pil_u8_wire_proc_workers"] = round(rate, 1)
            print(f"pil_u8_wire_proc_workers: {rate:,.0f} img/s", flush=True)
        except Exception as e:
            print(f"pil_u8_wire_proc_workers: SKIP ({e})")

        # Native decode thread scaling: on an N-core host the decode is
        # embarrassingly parallel (per-image, shared-nothing); the table
        # shows per-thread efficiency on THIS host and the extrapolated
        # core count needed to hit chip feed rate.
        scaling = {}
        try:
            for nt in (1, 2, 4, 8):
                scaling[str(nt)] = round(bench_native_threads(tmp, nt), 1)
                print(f"native_threads={nt}: {scaling[str(nt)]:,.1f} img/s",
                      flush=True)
        except Exception as e:
            print(f"native thread scaling: SKIP ({e})")

    # Per-core rate = the 1-thread rate (aggregate max would over-count on
    # multi-core hosts where threads actually run in parallel).
    per_core = scaling.get("1") if scaling else None
    out = {
        "meta": {
            "images": N_IMAGES, "src_px": SRC, "out_px": IMAGE,
            "batch": BATCH, "workers": workers,
            "cpus": os.cpu_count(),
            "note": "synthetic ImageNet-shaped JPEGs; feed target is "
                    "~2500 img/s/chip (ResNet-50 bf16, BENCH_r01)",
        },
        "img_per_sec": results,
        "native_thread_scaling": {
            "img_per_sec_by_threads": scaling,
            "note": "shared-nothing per-image decode, measured on a "
                    f"{os.cpu_count()}-core host; per_core = the 1-thread "
                    "rate.  Threads beyond the core count only time-slice "
                    "(flat aggregate = zero contention overhead), so N "
                    "physical cores scale the rate ~linearly",
            "per_core_img_per_sec": per_core,
            "cores_needed_for_2500_img_per_sec": (
                int(np.ceil(2500 / per_core)) if per_core else None
            ),
        },
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_loader.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
