#!/usr/bin/env python
"""Real-text LM convergence: held-out perplexity curve on an in-repo corpus.

The round-2 LM evidence was throughput-only (RESULTS_lm.json) and the
convergence oracle synthetic; this is the real-data counterpart the
reference's accuracy story implies (VERDICT r2 "What's missing" #1, LM
side): byte-level LM over the repository's own documentation + source (a
committed, reproducible corpus), 90/10 train/held-out split by corpus
position (TextFileDataset spans), perplexity measured on the held-out tail
at a fixed cadence.

Pass criteria: held-out perplexity falls monotonically-ish (each eval ≤
1.02× the previous) and the final ppl is far below both the initial model's
and the uniform-byte ceiling (256).

Writes ``RESULTS_lm_text.json``.  Short CI version:
tests/test_convergence_short.py.

Run (CPU 8-device mesh, ~10 min):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/lm_text.py
"""

from __future__ import annotations

import glob
import json
import os
import sys

import jax

# The container's sitecustomize presets the tunneled-TPU "axon" platform;
# when the caller asks for a simulated CPU mesh, steer there before
# backends initialize (same dance as __graft_entry__.py).
if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

SEQ = int(os.environ.get("LMTEXT_SEQ", "256"))
D_MODEL = int(os.environ.get("LMTEXT_D", "128"))
STEPS = int(os.environ.get("LMTEXT_STEPS", "300"))
EVAL_EVERY = int(os.environ.get("LMTEXT_EVAL_EVERY", "50"))
BATCH = 16
LR = 0.5


def corpus_paths() -> list:
    pats = ("*.md", "docs/*.md", "pytorch_distributed_tpu/**/*.py",
            "tests/*.py", "experiments/*.py")
    paths = []
    for p in pats:
        paths.extend(sorted(glob.glob(os.path.join(REPO, p), recursive=True)))
    return paths


def main() -> int:
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        TextFileDataset,
        warmup_cosine_lr,
    )

    import jax

    n = jax.device_count()
    mesh = build_mesh(MeshSpec(("data",), (n,)))
    paths = corpus_paths()
    train_ds = TextFileDataset(paths, SEQ, span=(0.0, 0.9))
    eval_ds = TextFileDataset(paths, SEQ, span=(0.9, 1.0))
    corpus_bytes = len(train_ds.data) + len(eval_ds.data)
    print(f"corpus: {len(paths)} files, {corpus_bytes:,} bytes "
          f"({len(train_ds)} train / {len(eval_ds)} eval windows)",
          flush=True)

    model = TransformerLM(vocab_size=256, d_model=D_MODEL, n_heads=4,
                          n_layers=2)
    with mesh:
        trainer = LMTrainer(
            model, mesh, train_ds, BATCH, lr=LR,
            eval_dataset=eval_ds, eval_every=EVAL_EVERY, eval_batches=4,
            lr_schedule=warmup_cosine_lr(LR, max(10, STEPS // 20), STEPS),
            clip_grad_norm=1.0,
        )
        init_loss, init_ppl, _ = trainer.evaluate()  # untrained baseline
        trainer.eval_history.clear()
        trainer.fit(STEPS, print_freq=EVAL_EVERY)

    curve = [
        {"step": (i + 1) * EVAL_EVERY, "loss": round(l, 4),
         "ppl": round(p, 2), "acc_pct": round(a, 2)}
        for i, (l, p, a) in enumerate(trainer.eval_history)
    ]
    out = {
        "meta": {
            "corpus": "in-repo *.md + framework/tests/experiments *.py "
                      "(byte-level, vocab 256)",
            "corpus_bytes": corpus_bytes,
            "split": "90/10 by corpus position (TextFileDataset spans)",
            "model": {"d_model": D_MODEL, "n_heads": 4, "n_layers": 2,
                      "seq": SEQ},
            "steps": STEPS, "batch": BATCH,
            "oracle": "held-out perplexity every "
                      f"{EVAL_EVERY} steps (LM analogue of the reference's "
                      "per-epoch val top-1, distributed.py:212,321-322)",
        },
        "initial": {"loss": round(init_loss, 4), "ppl": round(init_ppl, 2)},
        "curve": curve,
        "best_ppl": round(trainer.best_ppl, 2),
    }
    out_path = os.environ.get("LMTEXT_OUT",
                              os.path.join(REPO, "RESULTS_lm_text.json"))
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))

    ok = True
    if not curve:
        print("FAIL: no eval points recorded")
        ok = False
    else:
        final = curve[-1]["ppl"]
        if final >= init_ppl * 0.5:
            print(f"FAIL: final ppl {final} not well below initial {init_ppl}")
            ok = False
        # Byte-LM short-run eval is noisy; tolerate wobble, catch divergence:
        # no eval may sit above 1.5x the best seen so far.
        best_so_far = float("inf")
        for cur in curve:
            best_so_far = min(best_so_far, cur["ppl"])
            if cur["ppl"] > best_so_far * 1.5:
                print(f"FAIL: ppl {cur['ppl']} diverged from best "
                      f"{best_so_far}")
                ok = False
    print("lm_text:", "OK" if ok else "MISMATCH")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
