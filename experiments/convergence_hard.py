#!/usr/bin/env python
"""Convergence oracle that can FAIL: 100-class low-SNR accuracy curves.

The round-2 oracle (experiments/convergence.py) saturates — 6 easy classes
hit 100% by epoch 2, so fp32/bf16/accum/collective numerics could not be
distinguished beyond gross breakage (VERDICT r2 "What's weak" #2).  This
experiment rebuilds the reference's accuracy oracle (per-epoch val top-1,
reference distributed.py:212,321-322) on a task hard enough to sit well
below the ceiling:

- **a hue wheel** (class c → hue c/CLASSES; 25 classes × 64 imgs/class)
  with per-image hue jitter at 0.45× the class spacing.  Hue is global, so
  the signal survives RandomResizedCrop + flip (position/texture codes do
  not), and the jitter puts an ANALYTIC ceiling on top-1:
  P(correct) = erf(spacing / (2·sqrt(2)·jitter·spacing)) =
  erf(1/(2·sqrt(2)·0.45)) ~= 73% — the curve plateaus mid-range by
  construction, where numerics differences would actually move it;
- configs: fp32, bf16, bf16+accum, explicit-collectives+bf16-wire
  (the Horovod-recipe analogue), and **1-device DP vs 8-device DP**
  (the data-parallel invariance claim, run in a subprocess with a 1-device
  mesh);
- pass criteria: every curve learns (final well above chance), NO curve
  saturates (the oracle keeps its discriminating power), and the final
  top-1 spread across configs stays within the noise window.

Writes ``RESULTS_convergence_hard.json``.  The short CI version lives in
tests/test_convergence_short.py.

Run (CPU 8-device mesh, ~40-60 min on one core):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/convergence_hard.py
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys

import jax
import numpy as np

# sitecustomize presets the tunneled-TPU "axon" platform; steer to the
# simulated CPU mesh when asked (same dance as __graft_entry__.py).
if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

CLASSES = int(os.environ.get("CONVH_CLASSES", "25"))
PER_CLASS_TRAIN = int(os.environ.get("CONVH_PER_CLASS", "64"))
PER_CLASS_VAL = int(os.environ.get("CONVH_PER_CLASS_VAL", "20"))
IMAGE = 32
EPOCHS = int(os.environ.get("CONVH_EPOCHS", "18"))
BATCH = 32
NOISE = float(os.environ.get("CONVH_NOISE", "0.10"))   # per-pixel noise sigma
TINT = float(os.environ.get("CONVH_TINT", "0.45"))     # hue signal strength
# Per-image hue jitter as a fraction of the class spacing (1/CLASSES):
# the irreducible confusion that pins the plateau below the ceiling.
# P(top-1) ~= erf(1 / (2*sqrt(2)*JITTER)) -> 0.34 gives ~86%... 0.5 ~ 68%.
# NOISE/TINT/LR set how FAST the curve rises; only JITTER (relative to the
# class spacing) sets the ceiling — the round-3 run (tint .25, noise .15,
# constant lr .06, 8 epochs) was still mid-rise at 11-14%, so round 4
# strengthens the signal and adds a cosine schedule to reach the plateau,
# where the spread gate has teeth (VERDICT r3).  Class-count note: the first
# round-4 attempt kept 100 classes at 16 imgs/class — train top-1 reached
# ~65% (≈ ceiling) while val pinned at ~25%: pure memorization of the tiny
# per-class sample, not hue reading.  25 classes × 64 imgs/class has the
# SAME epoch cost and the SAME analytic ceiling (jitter is a fraction of
# spacing), but 4× the per-class data — the generalization-gap fix.
JITTER = float(os.environ.get("CONVH_JITTER", "0.45"))
LR = float(os.environ.get("CONVH_LR", "0.12"))
CEILING = (100.0 if JITTER == 0 else
           100.0 * math.erf(1.0 / (2.0 * math.sqrt(2.0) * JITTER)))


def make_dataset(root: str, seed: int = 0) -> None:
    """Hue-wheel classes under per-image hue jitter and pixel noise —
    learnable, but the jitter caps top-1 well below 100% (see module
    docstring for the analytic ceiling)."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    for split, per in (("train", PER_CLASS_TRAIN), ("val", PER_CLASS_VAL)):
        for c in range(CLASSES):
            d = os.path.join(root, split, f"class{c:03d}")
            os.makedirs(d, exist_ok=True)
            for i in range(per):
                # class hue + irreducible per-image jitter (the plateau knob)
                hue = c / CLASSES + rng.normal(0.0, JITTER / CLASSES)
                img = rng.normal(0.45, NOISE, size=(IMAGE, IMAGE, 3))
                tint = np.array([
                    0.5 + 0.5 * np.cos(2 * np.pi * (hue + k / 3.0))
                    for k in range(3)
                ])
                img += TINT * tint
                arr = (np.clip(img, 0, 1) * 255).astype(np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i:03d}.jpg"),
                                          quality=92)


def oracle_estimator_top1(root: str) -> float:
    """Top-1 of the Bayes-style hue reader on the ACTUAL val JPEGs.

    The generator is known (class hue + jitter + pixel noise + JPEG), so
    the best any model could do is read the hue back off the pixels and
    pick the nearest class.  Mean RGB projects the tint template out of
    the noise optimally (noise is iid per pixel); the cos/sin projection
    inverts hue from the three channel means.  The gap between this and
    the analytic ceiling (which assumes PERFECT hue recovery) is
    estimation loss the images themselves impose — quantifying how much
    of the network-vs-ceiling slack is achievable at all (VERDICT r4
    weak 5)."""
    from PIL import Image

    correct = total = 0
    vroot = os.path.join(root, "val")
    for cname in sorted(os.listdir(vroot)):
        c = int(cname.replace("class", ""))
        d = os.path.join(vroot, cname)
        for fn in os.listdir(d):
            v = np.asarray(Image.open(os.path.join(d, fn)),
                           np.float32).mean(axis=(0, 1)) / 255.0
            # v_k ~= base + TINT*(0.5 + 0.5*cos(2pi(hue + k/3)))
            k = np.arange(3) / 3.0
            a = float(np.sum(v * np.cos(2 * np.pi * k)))
            b = float(np.sum(v * np.sin(2 * np.pi * k)))
            # cos(2pi(hue+k/3)) = cos(2pi hue)cos(2pi k/3)
            #                     - sin(2pi hue)sin(2pi k/3)
            # => a = (3/4)TINT cos(2pi hue), b = -(3/4)TINT sin(2pi hue)
            hue = (np.arctan2(-b, a) / (2 * np.pi)) % 1.0
            pred = int(np.round(hue * CLASSES)) % CLASSES
            correct += int(pred == c)
            total += 1
    return 100.0 * correct / max(total, 1)


def run_config(data_root: str, tmpdir: str, name: str, precision: str,
               accum: int, explicit: bool, sync_bn: bool = False):
    import jax.numpy as jnp

    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(
        data=data_root, arch="resnet18", batch_size=BATCH, epochs=EPOCHS,
        # No warmup: LR 0.12 from epoch 0 proved stable (fp32 leg rising
        # cleanly), and the cached-curve fingerprint below predates the
        # warmup-ramp fix in train/lr.py — warmup 0 keeps every config on
        # the identical schedule the first legs ran.
        lr=LR, lr_schedule="cosine", lr_warmup_epochs=0,
        print_freq=1000, seed=0, image_size=IMAGE,
        precision=precision, accum_steps=accum,
        checkpoint_dir=os.path.join(tmpdir, name),
        workers=2, sync_bn=sync_bn,
    )
    t = Trainer(cfg, explicit_collectives=explicit,
                grad_compress="bf16" if explicit else None)
    curve = []
    for epoch in range(EPOCHS):
        t.train_epoch(epoch)
        curve.append(round(float(t.validate()), 3))
        print(f"[{name}] epoch {epoch}: top-1 {curve[-1]}", flush=True)
    return curve


CONFIGS = (
    # name, precision, accum, explicit_collectives, sync_bn
    ("fp32", "fp32", 1, False, False),
    ("bf16", "bf16", 1, False, False),
    # accum=4: BATCH(32)/accum must stay a multiple of the 8-device data
    # axis (the strided-microbatch constraint, train/steps.py) — 32/4 = 8.
    ("bf16_accum4", "bf16", 4, False, False),
    ("explicit_bf16wire", "fp32", 1, True, False),
    # --sync-bn (round 5): psum'd BN moments close the measured 18-point
    # per-shard-BN gap — this leg must rejoin the SyncBN-family spread.
    ("explicit_bf16wire_syncbn", "fp32", 1, True, True),
    # dp1_fp32 runs ONLY in the re-exec'd child (1-device mesh): same
    # global batch, one device — the DP-invariance leg.
    ("dp1_fp32", "fp32", 1, False, False),
)

# The explicit-collectives step deliberately uses PER-SHARD BatchNorm
# statistics (torch-DDP semantics, train/steps.py:103-107) — at this
# matrix's batch 32 / 8 shards that is BN over 4 samples, a genuinely
# different estimator, not a numerics difference.  Its curve is reported
# as a measured SEMANTIC delta vs the SyncBN family (round 4: −18 top-1
# points at plateau), outside the numerics spread gate.  (The reference's
# own regime is ~800 samples/GPU, where local BN is benign — the delta
# here is the small-per-shard-batch worst case, quantified.)
PERSHARD_BN = {"explicit_bf16wire"}


def main() -> int:
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.abspath(os.path.join(here, "..",
                                            "RESULTS_convergence_hard.json"))
    # The trailing tag is an OPAQUE cache key for the schedule; bump it
    # whenever run_config's schedule args change or stale curves get reused.
    fingerprint = [CLASSES, PER_CLASS_TRAIN, PER_CLASS_VAL, IMAGE, EPOCHS,
                   BATCH, NOISE, TINT, JITTER, LR, "cosine_warmup1"]
    only = os.environ.get("CONVH_ONLY", "")
    data_root = os.environ.get("CONVH_DATA", "")

    results = {}
    prior_meta = {}
    if os.path.exists(out_path):  # accumulate across partial runs
        try:
            with open(out_path) as f:
                prior = json.load(f)
            if prior.get("fingerprint") == fingerprint:
                results = prior.get("curves", {})
                prior_meta = prior.get("meta", {})
        except ValueError:
            pass

    def save():
        with open(out_path, "w") as f:
            json.dump({"meta": meta, "fingerprint": fingerprint,
                       "curves": results}, f, indent=1)

    meta = {
        "oracle": "per-epoch val top-1, sharded exact eval "
                  "(reference distributed.py:212,321-322)",
        "dataset": f"{CLASSES}-class low-SNR synthetic ImageFolder (JPEG), "
                   f"{CLASSES * PER_CLASS_TRAIN} train / "
                   f"{CLASSES * PER_CLASS_VAL} val, {IMAGE}px, "
                   f"noise {NOISE} tint {TINT} hue-jitter {JITTER}x spacing",
        "arch": "resnet18",
        "epochs": EPOCHS,
        "batch": BATCH,
        "lr": f"{LR} cosine, no warmup",
        "chance_pct": 100.0 / CLASSES,
        "analytic_ceiling_pct": round(CEILING, 2),
    }

    with tempfile.TemporaryDirectory() as tmp:
        if not data_root:
            data_root = os.path.join(tmp, "data")
            print("=== generating dataset ===", flush=True)
            make_dataset(data_root)
        is_child = bool(os.environ.get("CONVH_CHILD"))
        # Resume-aware: the oracle is a fixed function of the dataset —
        # reuse the recorded value instead of re-decoding every val JPEG
        # each invocation (children inherit it via the merged file).
        for k in ("oracle_estimator_top1", "achievable_pct",
                  "achievable_note", "achievable_conclusion"):
            if k in prior_meta:
                meta[k] = prior_meta[k]
        if "oracle_estimator_top1" not in meta and not is_child:
            meta["oracle_estimator_top1"] = round(
                oracle_estimator_top1(data_root), 2)
            meta["achievable_pct"] = meta["oracle_estimator_top1"]
            meta["achievable_note"] = (
                "top-1 of the known-generator hue-reader applied to the "
                "actual val JPEGs (mean-RGB -> least-squares hue -> nearest "
                "class): the ceiling the IMAGES support after pixel noise + "
                "JPEG, vs the analytic no-estimation-error ceiling "
                f"{round(CEILING, 2)} — network plateaus near the former "
                "mean the slack is estimation loss, not optimization")
        for name, precision, accum, explicit, sync_bn in CONFIGS:
            if only and name not in only.split(","):
                continue
            if name in results:
                print(f"=== {name}: cached ===", flush=True)
                continue
            if name.startswith("dp1_") and not is_child:
                # 1-device DP: same global batch on a 1-device mesh,
                # re-exec'd — the device count is fixed at backend init.
                print(f"=== {name} (subprocess, 1-device mesh) ===",
                      flush=True)
                env = dict(os.environ)
                env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
                env["CONVH_ONLY"] = name
                env["CONVH_DATA"] = data_root
                env["CONVH_CHILD"] = "1"
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__)], env=env)
                if r.returncode not in (0, 1):
                    print(f"{name} subprocess failed rc={r.returncode}")
                with open(out_path) as f:
                    results = json.load(f).get("curves", results)
                continue
            print(f"=== {name} ===", flush=True)
            results[name] = run_config(data_root, tmp, name, precision,
                                       accum, explicit, sync_bn)
            save()

    save()
    if os.environ.get("CONVH_CHILD"):
        return 0  # parent applies the gates over the merged file
    print(json.dumps({"curves": results}, indent=1))
    # Gates are applied AT THE PLATEAU (VERDICT r3): each final is the mean of
    # the last 3 epochs (cosine tail, LR≈0 — epoch noise is smallest there).
    finals = {k: round(float(np.mean(v[-3:])), 3) for k, v in results.items()}
    ok = True
    floor = 0.62 * CEILING  # relative so CONVH_JITTER stays tunable
    for k, curve in results.items():
        v = finals[k]
        # Per-shard-BN runs learn a noisier objective (see PERSHARD_BN
        # note): they must still clearly learn, but their floor is the
        # semantics-delta floor, not the SyncBN-family one.
        k_floor = 8 * meta["chance_pct"] if k in PERSHARD_BN else floor
        if v < k_floor:
            print(f"FAIL: {k} plateau top-1 {v} < {k_floor:.1f} "
                  f"(ceiling {CEILING:.1f})")
            ok = False
        if v > CEILING + 4.0:  # above the analytic ceiling = generator leak
            print(f"FAIL: {k} plateau top-1 {v} exceeds analytic ceiling "
                  f"{CEILING:.1f}+4")
            ok = False
        if len(curve) >= 6:  # plateaued: last-3 mean within 3 of prior-3 mean
            rise = float(np.mean(curve[-3:]) - np.mean(curve[-6:-3]))
            if rise > 3.0:
                print(f"FAIL: {k} still climbing at the end "
                      f"(+{rise:.2f} points over last 3 epochs)")
                ok = False
    sync = {k: v for k, v in finals.items() if k not in PERSHARD_BN}
    spread = max(sync.values()) - min(sync.values()) if sync else 0.0
    if sync:
        # Numerics gate, at plateau where it has teeth: bf16 compute,
        # in-graph accumulation, 1-vs-8-device DP must NOT move the curve.
        if spread > 5.0:
            print(f"FAIL: SyncBN-family plateau spread {spread:.2f} > 5")
            ok = False
    # The semantic delta is only meaningful against the fp32 anchor
    # (partial CONVH_ONLY runs may lack it — report nothing rather than
    # an absolute score mislabeled as a delta).
    deltas = ({k: round(finals[k] - finals["fp32"], 2)
               for k in finals if k in PERSHARD_BN}
              if "fp32" in finals else {})
    print("convergence_hard:", "OK" if ok else "MISMATCH",
          f"plateau_finals={finals} syncbn_spread={spread:.2f} "
          f"pershard_bn_delta={deltas} ceiling={CEILING:.1f}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
