#!/usr/bin/env python
"""Step-attribution acceptance sweep (ISSUE 20): identity + overhead.

Three fences, all measured here and pinned in ``RESULTS_stepattr.json``:

1. **Identity reconciliation** — on every swept recipe (LM data-parallel,
   image GSPMD, image explicit-collectives), a ``--step-attr`` run's
   per-step decomposition ``compute + exposed_comm + host_sync +
   data_wait + other`` must reconcile to the measured ``step_time``
   within **0.5% of the p50 step time** (``recon_err_pct_p50`` from
   ``obs.stepattr.summarize`` — the recorder clamps the residual into
   ``other >= 0`` and reports only the overshoot, so this is a real
   closure check, not a tautology).
2. **Hot-path overhead** — two identical LM runs, ``step_attr`` off vs
   on, compared on the warm-steady step-time p50 (first 10 steps
   dropped) AND through ``scripts/obs_report.py --diff`` at
   ``--threshold-pct 2`` — the flight-recorder A/B methodology
   (RESULTS_flightrec.json / RESULTS_obs_export.json).  The recorder is
   four ``perf_counter`` windows and one dict build per step — the
   delta must sit inside run-to-run noise (< 2%), and final losses must
   be bit-identical (attribution is semantics-neutral).
3. **Slow-loader drill** — ``scripts/chaoskit.py drill slow-loader``
   must pass end to end: injected loader stall named ``data_wait``
   dominant, ``data_wait_share`` alert live on /metrics, identity still
   reconciling under chaos.

CPU-safe:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/stepattr_ab.py
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys

os.environ.setdefault(
    "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

from pytorch_distributed_tpu.obs import stepattr  # noqa: E402
from pytorch_distributed_tpu.obs.metrics import read_metrics  # noqa: E402

STEPS_AB = int(os.environ.get("SAB_STEPS", "200"))
WARMUP = 10
RECON_FENCE_PCT = 0.5
OVERHEAD_FENCE_PCT = 2.0


def _lm_run(path: str, steps: int, step_attr: bool,
            hb_dir: str = None, big: bool = False) -> float:
    """One LM fit; returns the final loss scalar.  ``big`` is the
    RESULTS_obs_export.json A/B model (~180ms steps) — large enough that
    a 2% p50 threshold measures overhead, not timer noise."""
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        SyntheticTokenDataset,
    )

    mesh = build_mesh(MeshSpec(("data",), (2,)), jax.devices()[:2])
    if big:
        model = TransformerLM(vocab_size=256, d_model=128, n_heads=4,
                              n_layers=2)
        ds = SyntheticTokenDataset(4096, 128, 256, seed=0)
        batch = 8
    else:
        model = TransformerLM(vocab_size=64, d_model=32, n_heads=2,
                              n_layers=1)
        ds = SyntheticTokenDataset(512, 16, 64, seed=0)
        batch = 4
    with mesh:
        t = LMTrainer(model, mesh, ds, batch_size=batch, lr=0.05, seed=0,
                      eval_dataset=None, metrics_jsonl=path,
                      hb_dir=hb_dir, hb_interval_s=0.0,
                      step_attr=step_attr)
        t.fit(steps, print_freq=max(1, steps // 4))
    losses = [r["loss"] for r in read_metrics(path)
              if r.get("kind", "step") == "step" and "loss" in r]
    return float(losses[-1])


def _image_run(path: str, tmp: str, explicit: bool) -> None:
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(arch="resnet18", batch_size=8, epochs=1, lr=0.1,
                 print_freq=4, synthetic=True, synthetic_length=64,
                 image_size=32, num_classes=4, seed=0,
                 checkpoint_dir=tmp, workers=0, metrics_jsonl=path,
                 step_attr=True)
    Trainer(cfg, explicit_collectives=explicit).fit()


def _identity(path: str) -> dict:
    summ = stepattr.summarize(read_metrics(path))
    assert summ is not None, path
    return {
        "steps": summ["steps"],
        "step_ms_p50": round(summ["step_ms_p50"], 3),
        "recon_err_pct_p50": round(summ["recon_err_pct_p50"], 4),
        "recon_err_ms_max": round(summ["recon_err_ms_max"], 4),
        "dominant": summ["dominant"],
        "shares_pct": {k: round(v, 2)
                       for k, v in summ["shares_pct"].items()},
    }


def _p50(path: str, warmup: int) -> float:
    ts = [float(r["step_time"]) for r in read_metrics(path)
          if r.get("kind", "step") == "step" and "step_time" in r]
    ts = sorted(ts[warmup:])
    return 1e3 * ts[len(ts) // 2]


def main() -> int:
    import tempfile

    out = {"fence": {"recon_err_pct_p50_max": RECON_FENCE_PCT,
                     "step_time_p50_delta_pct_max": OVERHEAD_FENCE_PCT}}
    with tempfile.TemporaryDirectory(prefix="stepattr-ab-") as tmp:
        # -- 1. identity closure per recipe ---------------------------
        recipes = {}
        lm_path = os.path.join(tmp, "lm_id.jsonl")
        _lm_run(lm_path, 30, step_attr=True,
                hb_dir=os.path.join(tmp, "hb"))
        recipes["lm_dp2"] = _identity(lm_path)
        for name, explicit in (("image_gspmd", False),
                               ("image_explicit", True)):
            p = os.path.join(tmp, f"{name}.jsonl")
            _image_run(p, os.path.join(tmp, name + "_ck"), explicit)
            recipes[name] = _identity(p)
        out["identity"] = recipes
        worst = max(r["recon_err_pct_p50"] for r in recipes.values())
        out["identity"]["worst_recon_err_pct_p50"] = worst
        print(f"=> identity: worst recon err {worst:.4f}% of step p50 "
              f"(fence {RECON_FENCE_PCT}%)", flush=True)
        assert worst <= RECON_FENCE_PCT, recipes

        # -- 2. overhead A/B ------------------------------------------
        off_p = os.path.join(tmp, "off.jsonl")
        on_p = os.path.join(tmp, "on.jsonl")
        loss_off = _lm_run(off_p, STEPS_AB, step_attr=False, big=True)
        loss_on = _lm_run(on_p, STEPS_AB, step_attr=True, big=True)
        p50_off, p50_on = _p50(off_p, WARMUP), _p50(on_p, WARMUP)
        delta = 100.0 * (p50_on - p50_off) / p50_off
        diff = subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts/obs_report.py"),
             "--diff", off_p, on_p, "--threshold-pct", "2"],
            capture_output=True, text=True)
        out["overhead"] = {
            "steps": STEPS_AB,
            "step_time_p50_off_ms": round(p50_off, 3),
            "step_time_p50_on_ms": round(p50_on, 3),
            "step_time_p50_delta_pct": round(delta, 2),
            "final_loss_off": loss_off,
            "final_loss_on": loss_on,
            "loss_bit_identical": loss_off == loss_on,
            "diff_verdict": ("PASS (exit 0)" if diff.returncode == 0
                             else f"REGRESS (exit {diff.returncode})"),
        }
        print(f"=> overhead: p50 {p50_off:.2f} -> {p50_on:.2f}ms "
              f"({delta:+.2f}%), loss identical: "
              f"{loss_off == loss_on}", flush=True)
        assert delta < OVERHEAD_FENCE_PCT, out["overhead"]
        assert loss_off == loss_on, out["overhead"]
        assert diff.returncode == 0, diff.stdout + diff.stderr

    # -- 3. the drill (own subprocess: fresh backend, own mesh) -------
    drill = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/chaoskit.py"),
         "drill", "slow-loader", "--world", "2", "--steps", "12"],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    tail = [ln for ln in drill.stdout.splitlines() if ln.strip()][-4:]
    m = re.search(r"data-wait share p95 ([0-9.]+)%.*?"
                  r"recon err ([0-9.]+)%", drill.stdout, re.S)
    out["drill"] = {
        "ok": drill.returncode == 0,
        "data_wait_share_p95_pct": float(m.group(1)) if m else None,
        "recon_err_pct_p50": float(m.group(2)) if m else None,
        "tail": tail,
    }
    print(f"=> drill slow-loader: rc {drill.returncode}", flush=True)
    assert drill.returncode == 0, drill.stdout + drill.stderr

    res = os.path.join(REPO, "RESULTS_stepattr.json")
    doc = {
        "meta": {
            "what": ("Step-time attribution acceptance (obs/stepattr.py, "
                     "ISSUE 20): (1) the per-step identity step_time == "
                     "compute + exposed_comm + host_sync + data_wait + "
                     "other reconciles to <= 0.5% of the p50 step time "
                     "on every swept recipe (LM dp=2, image GSPMD, image "
                     "explicit-collectives) — the recorder clamps the "
                     "residual into other >= 0 and reports overshoot as "
                     "attr_recon_err_ms, so closure is measured, not "
                     "assumed; (2) hot-path overhead of --step-attr "
                     "(four perf_counter windows + one dict per step, "
                     "zero extra compiles, zero host syncs) fenced < 2% "
                     "step-time p50 via the flightrec A/B methodology "
                     "with bit-identical final losses; (3) the "
                     "chaoskit slow-loader drill passes live: injected "
                     "stall named data_wait dominant, data_wait_share "
                     "alert scraped firing on /metrics, identity still "
                     "closed under chaos."),
            "harness": "experiments/stepattr_ab.py",
            "ab_model": ("TransformerLM vocab=256 d_model=128 heads=4 "
                         "layers=2, seq 128, batch 8, dp=2 (the "
                         "RESULTS_obs_export.json A/B model)"),
            "platform": "cpu (8-device host simulation)",
        },
    }
    doc.update(out)
    with open(res, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    print(f"=> wrote {res}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
