#!/usr/bin/env python
"""KV-cached autoregressive decode throughput on the real chip.

The serving-side benchmark the training results don't cover: prefill
latency and steady-state decode tokens/s for the TransformerLM generate
path (``models/generate.py`` — one compiled program: prefill + lax.scan
over decode steps, cached across calls).

Decode at small batch is memory-bandwidth-bound: every generated token
re-reads the full parameter set (bf16: 2·N_params bytes) plus the growing
KV cache, so the per-token floor is  bytes_read / HBM_BW.  We report that
roofline next to the measurement, per batch size — batch amortizes the
parameter stream, which is the whole serving-throughput story.

Methodology: time generate() at max_new_tokens=1 (prefill + first token)
and at max_new_tokens=N; the difference isolates N-1 steady-state decode
steps.  Reference analogue: the reference's inference story is
``--evaluate`` (distributed.py:197-199); generation is the LM-family
counterpart built on the same harness.

Run on the TPU chip:
    PYTHONPATH=/root/repo python experiments/decode_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

D_MODEL = int(os.environ.get("DECODE_BENCH_D", "1024"))
N_LAYERS = int(os.environ.get("DECODE_BENCH_LAYERS", "12"))
N_HEADS = int(os.environ.get("DECODE_BENCH_HEADS", "16"))
VOCAB = int(os.environ.get("DECODE_BENCH_VOCAB", "32000"))
PROMPT = int(os.environ.get("DECODE_BENCH_PROMPT", "512"))
NEW = int(os.environ.get("DECODE_BENCH_NEW", "257"))
REPS = int(os.environ.get("DECODE_BENCH_REPS", "3"))
HBM_GBPS = float(os.environ.get("DECODE_BENCH_HBM_GBPS", "819"))  # v5e


def _time(fn, reps: int) -> float:
    # Sync discipline (scripts/benchlib.py): on the tunneled axon backend
    # block_until_ready can return before the queue drains — a VALUE fetch
    # is the only reliable barrier, so reduce the tokens to a scalar.
    int(fn().sum())  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        int(fn().sum())
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.generate import generate
    from pytorch_distributed_tpu.models.transformer import TransformerLM

    cfg = dict(vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
               n_layers=N_LAYERS)
    model = TransformerLM(**cfg, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    init_tokens = jnp.asarray(
        rng.integers(0, VOCAB, size=(1, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), init_tokens)["params"]
    params = jax.device_put(params)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    param_bytes = 2 * n_params  # decode streams the bf16 copy

    from pytorch_distributed_tpu.models.quant import quantize_lm_params

    qparams = jax.device_put(quantize_lm_params(params))
    # Streamed bytes: int8 kernels as-is; every fp leaf streams as the
    # bf16 compute copy (the f32->bf16 cast is hoisted out of the scan).
    q_bytes = sum(
        x.size * (1 if x.dtype == jnp.int8 else 2)
        for x in jax.tree_util.tree_leaves(qparams))

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.join(here, "..", "RESULTS_decode.json")
    # Resumable per-row writes (arch_bench pattern): the watcher runs this
    # under a timeout with a capped retry budget — completed rows must
    # survive a killed sweep or retries redo everything and land nothing.
    results = {}
    if os.path.exists(out_path):
        try:
            with open(out_path) as f:
                prior = json.load(f)
            pm = prior.get("meta", {})
            if (pm.get("d_model") == D_MODEL and pm.get("vocab") == VOCAB
                    and pm.get("n_layers") == N_LAYERS
                    and pm.get("n_heads") == N_HEADS
                    and pm.get("prompt") == PROMPT
                    and pm.get("platform") == jax.default_backend()):
                results = prior.get("configs", {})
        except ValueError:
            pass

    def write():
        out = {
            "meta": {
                "d_model": D_MODEL, "n_layers": N_LAYERS,
                "n_heads": N_HEADS, "vocab": VOCAB, "prompt": PROMPT,
                "new_tokens": NEW,
                "params_m": round(n_params / 1e6, 1),
                "hbm_gbps_assumed": HBM_GBPS,
                "platform": jax.default_backend(),
                "what": "KV-cached generate(): prefill latency + "
                        "steady-state decode tok/s vs the params+KV "
                        "HBM-stream floor",
                "topk_nucleus_note": "top-k+top-p samples from the sorted "
                        "k-vector (no full-vocab argsort in the scan): "
                        "6.696 -> 1.761 ms/tok measured at b8/vocab 32k",
            },
            "configs": results,
        }
        with open(out_path, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")

    for batch, sampling, quant in (
            (1, "greedy", ""), (8, "greedy", ""), (32, "greedy", ""),
            (8, "topk50_topp0.9", ""),
            (1, "greedy", "int8"), (8, "greedy", "int8")):
        prompt = jnp.asarray(
            rng.integers(0, VOCAB, size=(batch, PROMPT)).astype(np.int32))
        kw = dict(cfg, dtype=jnp.bfloat16, quant=quant)
        if sampling != "greedy":
            kw.update(temperature=1.0, top_k=50, top_p=0.9)
        tag = f"b{batch}_p{PROMPT}_{sampling}" + ("_int8w" if quant else "")
        if tag in results:
            print(f"{tag}: cached", flush=True)
            continue
        p = qparams if quant else params
        try:
            t1 = _time(lambda: generate(p, prompt, 1, **kw), REPS)
            tn = _time(lambda: generate(p, prompt, NEW, **kw), REPS)
        except Exception as e:  # noqa: BLE001 — record per-config OOM/abort
            print(f"{tag}: FAILED {repr(e)[:200]}", flush=True)
            continue
        per_tok = (tn - t1) / max(NEW - 1, 1)
        toks_per_s = batch / per_tok
        # Per-step HBM floor: the streamed parameter bytes (bf16, or the
        # int8 tree's actual footprint) + the mean-filled KV cache (k and
        # v, bf16) for every sequence in the batch.
        mean_ctx = PROMPT + NEW / 2
        kv_bytes = 2 * N_LAYERS * batch * mean_ctx * D_MODEL * 2
        stream_bytes = q_bytes if quant else param_bytes
        floor_s = (stream_bytes + kv_bytes) / (HBM_GBPS * 1e9)
        results[tag] = {
            "prefill_plus_1tok_ms": round(t1 * 1e3, 2),
            "per_token_ms": round(per_tok * 1e3, 3),
            "decode_tokens_per_sec": round(toks_per_s, 0),
            "hbm_floor_ms": round(floor_s * 1e3, 3),
            "pct_of_bw_roofline": round(100 * floor_s / per_tok, 1),
        }
        write()
        print(f"{tag}: prefill+1 {t1*1e3:.1f} ms  decode "
              f"{per_tok*1e3:.3f} ms/tok  {toks_per_s:,.0f} tok/s  "
              f"({results[tag]['pct_of_bw_roofline']}% of HBM roofline)",
              flush=True)

    # --- b32 roofline-gap breakdown (VERDICT r4 weak 6): where do the
    # extra ms/tok go at batch 32?  Decompose by re-measuring b32 with a
    # tiny KV cache (prompt 64): params stream is batch-invariant, so
    #   per_tok(b32, p512) - per_tok(b32, p64)  ~= attention-over-cache +
    # KV stream for the extra context, and per_tok(b32, p64) ~= params
    # stream + batched-MLP compute + dispatch.  b1@p64 pins the dispatch+
    # params floor.
    b32_tag = f"b32_p{PROMPT}_greedy"
    if b32_tag in results and "b32_breakdown" not in results:
        try:
            gap = {}
            for b in (1, 32):
                pshort = jnp.asarray(
                    rng.integers(0, VOCAB, size=(b, 64)).astype(np.int32))
                kw = dict(cfg, dtype=jnp.bfloat16)
                t1s = _time(lambda: generate(params, pshort, 1, **kw), REPS)
                tns = _time(lambda: generate(params, pshort, NEW, **kw),
                            REPS)
                gap[f"b{b}_p64_per_token_ms"] = round(
                    (tns - t1s) / max(NEW - 1, 1) * 1e3, 3)
            long_ms = results[b32_tag]["per_token_ms"]
            short_ms = gap["b32_p64_per_token_ms"]
            results["b32_breakdown"] = {
                **gap,
                f"b32_p{PROMPT}_per_token_ms": long_ms,
                "attn_over_cache_ms": round(long_ms - short_ms, 3),
                "note": "per_tok(b32,p512)-per_tok(b32,p64) isolates "
                        "attention-over-cache + long-context KV stream; "
                        "b1_p64 is the params+dispatch floor",
            }
            write()
            print(f"b32 breakdown: {results['b32_breakdown']}", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"b32_breakdown: FAILED {repr(e)[:200]}", flush=True)

    # --- long-prompt flash prefill (VERDICT r4: parity-tested, never
    # timed).  P=4096: the dense prefill materializes the O(P·max_len)
    # score tensor; the Pallas kernel streams it.  Rows record prefill+1
    # latency for both paths at b1 (dense may OOM — that row then records
    # the failure, which is itself the result).
    long_p = int(os.environ.get("DECODE_BENCH_LONG_PROMPT", "4096"))
    lp_prompt = jnp.asarray(
        rng.integers(0, VOCAB, size=(1, long_p)).astype(np.int32))
    for fp in (False, True):
        tag = f"b1_p{long_p}_prefill_{'flash' if fp else 'dense'}"
        if tag in results:
            print(f"{tag}: cached", flush=True)
            continue
        try:
            kw = dict(cfg, dtype=jnp.bfloat16, flash_prefill=fp)
            t1 = _time(lambda: generate(params, lp_prompt, 1, **kw), REPS)
        except Exception as e:  # noqa: BLE001
            print(f"{tag}: FAILED {repr(e)[:200]}", flush=True)
            results[tag] = {"failed": repr(e)[:200]}
            write()
            continue
        results[tag] = {"prefill_plus_1tok_ms": round(t1 * 1e3, 2)}
        write()
        print(f"{tag}: prefill+1 {t1*1e3:.1f} ms", flush=True)

    # --- speculative decoding (models/speculative.py): draft proposes
    # gamma tokens, target scores the block in ONE cached pass.  On
    # random-init weights the measured acceptance is the FLOOR (a trained
    # draft tracks its target far better), so alongside the end-to-end
    # rows we record the component times (draft ms/step, target ms/pass)
    # and project tok/s at trained-draft acceptance rates from the
    # rejection-sampling algebra: E[tokens/round] = (1-a^(g+1))/(1-a),
    # round cost = g*t_draft + t_target.
    from pytorch_distributed_tpu.models.speculative import (
        speculative_generate,
    )

    draft_cfg = dict(vocab_size=VOCAB, d_model=D_MODEL // 4,
                     n_heads=max(1, N_HEADS // 4),
                     n_layers=max(1, N_LAYERS // 4))
    draft_model = TransformerLM(**draft_cfg, dtype=jnp.bfloat16)
    draft_params = jax.device_put(draft_model.init(
        jax.random.PRNGKey(1), init_tokens)["params"])
    spec_prompt = jnp.asarray(
        rng.integers(0, VOCAB, size=(1, PROMPT)).astype(np.int32))
    gamma = int(os.environ.get("DECODE_BENCH_GAMMA", "4"))
    spec_new = int(os.environ.get("DECODE_BENCH_SPEC_NEW", "129"))
    for tag, temp in (("b1_spec_greedy", 0.0), ("b1_spec_t1.0", 1.0)):
        if tag in results:
            print(f"{tag}: cached", flush=True)
            continue
        try:
            kw = dict(target_cfg=cfg, draft_cfg=draft_cfg, gamma=gamma,
                      dtype=jnp.bfloat16, temperature=temp, seed=0)
            # Warm at the SAME max_new_tokens: max_len keys the compiled
            # cache shapes, so a shorter warm call would leave the timed
            # run recompiling all four block programs.
            speculative_generate(
                params, draft_params, spec_prompt, spec_new, **kw)
            # Best-of-REPS like every other row (_time discipline — a
            # single post-warmup sample is noise-prone on the tunneled
            # backend); the seeded host RNG makes each repeat replay the
            # identical draft/accept trace, so stats are rep-invariant
            # and the min is a valid latency estimator.
            dt = float("inf")
            for _ in range(max(REPS, 1)):
                t0 = time.perf_counter()
                toks, stats = speculative_generate(
                    params, draft_params, spec_prompt, spec_new, **kw)
                int(toks.sum())  # value fetch = reliable queue barrier
                dt = min(dt, time.perf_counter() - t0)
        except Exception as e:  # noqa: BLE001
            print(f"{tag}: FAILED {repr(e)[:200]}", flush=True)
            continue
        results[tag] = {
            "gamma": gamma,
            "end_to_end_tok_s": round(stats["tokens"] / dt, 1),
            "mean_accepted": round(stats["mean_accepted"], 3),
            "tokens_per_target_pass":
                round(stats["tokens_per_target_pass"], 3),
            "target_passes": stats["target_passes"],
            "note": "random-init draft = acceptance FLOOR; see "
                    "spec_projection for trained-draft projections",
        }
        write()
        print(f"{tag}: {results[tag]['end_to_end_tok_s']} tok/s  "
              f"accepted {stats['mean_accepted']:.2f}/{gamma}  "
              f"{stats['tokens_per_target_pass']:.2f} tok/target-pass",
              flush=True)

    # Component times for the projection: one draft step (L=1) and one
    # target scoring pass (L=gamma+1), both cached-model applies.
    if "spec_projection" in results:
        print("spec_projection: cached", flush=True)
        write()
        print("wrote RESULTS_decode.json", flush=True)
        return 0
    try:
        from pytorch_distributed_tpu.models.speculative import (
            _make_block_apply,
        )

        max_len = PROMPT + spec_new + gamma + 1

        def _component_ms(c, L, p):
            fresh, apply = _make_block_apply(
                L, 1, max_len, c["vocab_size"], c["d_model"], c["n_heads"],
                c["n_layers"], "bfloat16", "")
            cache = fresh()
            toks = jnp.zeros((1, L), jnp.int32)
            _, cache = apply(p, cache, toks)  # compile
            jax.block_until_ready(cache)
            best = float("inf")
            for _ in range(max(REPS, 3)):
                t0 = time.perf_counter()
                lg, c2 = apply(p, cache, toks)
                float(jnp.sum(lg))
                best = min(best, time.perf_counter() - t0)
            return best * 1e3

        t_draft = _component_ms(draft_cfg, 1, draft_params)
        t_target = _component_ms(cfg, gamma + 1, params)
        base_tok_ms = results.get(
            f"b1_p{PROMPT}_greedy", {}).get("per_token_ms")
        proj = {}
        for a in (0.5, 0.7, 0.9):
            exp_toks = (1 - a ** (gamma + 1)) / (1 - a)
            round_ms = gamma * t_draft + t_target
            proj[f"accept_{a}"] = {
                "tokens_per_round": round(exp_toks, 2),
                "proj_tok_s": round(1e3 * exp_toks / round_ms, 1),
            }
        results["spec_projection"] = {
            "draft_step_ms": round(t_draft, 3),
            "target_scorepass_ms": round(t_target, 3),
            "target_only_per_token_ms": base_tok_ms,
            "gamma": gamma,
            "projections": proj,
            "note": "proj_tok_s = E[toks/round]/(gamma*t_draft+t_target); "
                    "host-loop dispatch excluded (measured rows include it)",
        }
        print(f"spec components: draft {t_draft:.2f} ms/step, target "
              f"score {t_target:.2f} ms/pass; projections {proj}",
              flush=True)
    except Exception as e:  # noqa: BLE001
        print(f"spec_projection: FAILED {repr(e)[:200]}", flush=True)

    write()
    print("wrote RESULTS_decode.json", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
