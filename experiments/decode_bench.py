#!/usr/bin/env python
"""KV-cached autoregressive decode throughput on the real chip.

The serving-side benchmark the training results don't cover: prefill
latency and steady-state decode tokens/s for the TransformerLM generate
path (``models/generate.py`` — one compiled program: prefill + lax.scan
over decode steps, cached across calls).

Decode at small batch is memory-bandwidth-bound: every generated token
re-reads the full parameter set (bf16: 2·N_params bytes) plus the growing
KV cache, so the per-token floor is  bytes_read / HBM_BW.  We report that
roofline next to the measurement, per batch size — batch amortizes the
parameter stream, which is the whole serving-throughput story.

Methodology: time generate() at max_new_tokens=1 (prefill + first token)
and at max_new_tokens=N; the difference isolates N-1 steady-state decode
steps.  Reference analogue: the reference's inference story is
``--evaluate`` (distributed.py:197-199); generation is the LM-family
counterpart built on the same harness.

Run on the TPU chip:
    PYTHONPATH=/root/repo python experiments/decode_bench.py
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

D_MODEL = int(os.environ.get("DECODE_BENCH_D", "1024"))
N_LAYERS = int(os.environ.get("DECODE_BENCH_LAYERS", "12"))
N_HEADS = int(os.environ.get("DECODE_BENCH_HEADS", "16"))
VOCAB = int(os.environ.get("DECODE_BENCH_VOCAB", "32000"))
PROMPT = int(os.environ.get("DECODE_BENCH_PROMPT", "512"))
NEW = int(os.environ.get("DECODE_BENCH_NEW", "257"))
REPS = int(os.environ.get("DECODE_BENCH_REPS", "3"))
HBM_GBPS = float(os.environ.get("DECODE_BENCH_HBM_GBPS", "819"))  # v5e


def _time(fn, reps: int) -> float:
    # Sync discipline (scripts/benchlib.py): on the tunneled axon backend
    # block_until_ready can return before the queue drains — a VALUE fetch
    # is the only reliable barrier, so reduce the tokens to a scalar.
    int(fn().sum())  # compile + warm
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        int(fn().sum())
        best = min(best, time.perf_counter() - t0)
    return best


def main() -> int:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.generate import generate
    from pytorch_distributed_tpu.models.transformer import TransformerLM

    cfg = dict(vocab_size=VOCAB, d_model=D_MODEL, n_heads=N_HEADS,
               n_layers=N_LAYERS)
    model = TransformerLM(**cfg, dtype=jnp.bfloat16)
    rng = np.random.default_rng(0)
    init_tokens = jnp.asarray(
        rng.integers(0, VOCAB, size=(1, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), init_tokens)["params"]
    params = jax.device_put(params)
    n_params = sum(x.size for x in jax.tree_util.tree_leaves(params))
    param_bytes = 2 * n_params  # decode streams the bf16 copy

    from pytorch_distributed_tpu.models.quant import quantize_lm_params

    qparams = jax.device_put(quantize_lm_params(params))
    # Streamed bytes: int8 kernels as-is; every fp leaf streams as the
    # bf16 compute copy (the f32->bf16 cast is hoisted out of the scan).
    q_bytes = sum(
        x.size * (1 if x.dtype == jnp.int8 else 2)
        for x in jax.tree_util.tree_leaves(qparams))

    results = {}
    for batch, sampling, quant in (
            (1, "greedy", ""), (8, "greedy", ""), (32, "greedy", ""),
            (8, "topk50_topp0.9", ""),
            (1, "greedy", "int8"), (8, "greedy", "int8")):
        prompt = jnp.asarray(
            rng.integers(0, VOCAB, size=(batch, PROMPT)).astype(np.int32))
        kw = dict(cfg, dtype=jnp.bfloat16, quant=quant)
        if sampling != "greedy":
            kw.update(temperature=1.0, top_k=50, top_p=0.9)
        tag = f"b{batch}_p{PROMPT}_{sampling}" + ("_int8w" if quant else "")
        p = qparams if quant else params
        try:
            t1 = _time(lambda: generate(p, prompt, 1, **kw), REPS)
            tn = _time(lambda: generate(p, prompt, NEW, **kw), REPS)
        except Exception as e:  # noqa: BLE001 — record per-config OOM/abort
            print(f"{tag}: FAILED {repr(e)[:200]}", flush=True)
            continue
        per_tok = (tn - t1) / max(NEW - 1, 1)
        toks_per_s = batch / per_tok
        # Per-step HBM floor: the streamed parameter bytes (bf16, or the
        # int8 tree's actual footprint) + the mean-filled KV cache (k and
        # v, bf16) for every sequence in the batch.
        mean_ctx = PROMPT + NEW / 2
        kv_bytes = 2 * N_LAYERS * batch * mean_ctx * D_MODEL * 2
        stream_bytes = q_bytes if quant else param_bytes
        floor_s = (stream_bytes + kv_bytes) / (HBM_GBPS * 1e9)
        results[tag] = {
            "prefill_plus_1tok_ms": round(t1 * 1e3, 2),
            "per_token_ms": round(per_tok * 1e3, 3),
            "decode_tokens_per_sec": round(toks_per_s, 0),
            "hbm_floor_ms": round(floor_s * 1e3, 3),
            "pct_of_bw_roofline": round(100 * floor_s / per_tok, 1),
        }
        print(f"{tag}: prefill+1 {t1*1e3:.1f} ms  decode "
              f"{per_tok*1e3:.3f} ms/tok  {toks_per_s:,.0f} tok/s  "
              f"({results[tag]['pct_of_bw_roofline']}% of HBM roofline)",
              flush=True)

    out = {
        "meta": {
            "d_model": D_MODEL, "n_layers": N_LAYERS, "n_heads": N_HEADS,
            "vocab": VOCAB, "prompt": PROMPT, "new_tokens": NEW,
            "params_m": round(n_params / 1e6, 1),
            "hbm_gbps_assumed": HBM_GBPS,
            "platform": jax.default_backend(),
            "what": "KV-cached generate(): prefill latency + steady-state "
                    "decode tok/s vs the params+KV HBM-stream floor",
            "topk_nucleus_note": "top-k+top-p samples from the sorted "
                    "k-vector (no full-vocab argsort in the scan): "
                    "6.696 -> 1.761 ms/tok measured at b8/vocab 32k",
        },
        "configs": results,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_decode.json"), "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print("wrote RESULTS_decode.json", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
