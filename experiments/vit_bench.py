#!/usr/bin/env python
"""ViT on-chip training bench: img/s + MFU — the MXU-native counterpart of
the (memory-bound) ResNet-50 headline.

VERDICT r2 item 2: the ViT family landed in round 2 with shape/numerics
tests only; this measures it.  For each arch: the full train step (fwd +
loss + bwd + SGD, bf16 policy, f32 softmax/LN) at ImageNet shapes, with

- **img/s/chip** under the same value-fetch sync discipline as bench.py;
- **MFU** = achieved matmul FLOP/s ÷ chip peak, with the FLOP count
  derived analytically from the architecture (3× forward for fwd+bwd);
- a flash-vs-dense attention micro-bench at ViT sequence length — at
  L≈197 attention is a few percent of total FLOPs (the table quantifies
  it), which is why the encoder uses XLA's dense attention and saves the
  Pallas flash path for the long-context LM family.

During the timed loop a TelemetrySampler writes ``vit_statistics.csv``
(the reference's statistics.sh 500 ms contract, statistics.sh:1-4).

Writes RESULTS_vit.json.  Run on the real chip (no env overrides):
    PYTHONPATH=/root/repo python experiments/vit_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PEAK_BF16_FLOPS = float(os.environ.get("VIT_PEAK_FLOPS", 197e12))  # v5e chip
ITERS = int(os.environ.get("VIT_ITERS", "20"))
# Smoke knobs (CPU shakeout only — chip runs use the defaults): shrink the
# image / divide the batches / redirect artifacts so a dry run can't leave
# bogus RESULTS_vit.json / vit_statistics.csv at the repo root.
IMAGE = int(os.environ.get("VIT_IMAGE", "224"))
BATCH_DIV = int(os.environ.get("VIT_BATCH_DIV", "1"))
ATTN_ITERS = int(os.environ.get("VIT_ATTN_ITERS", "50"))
_SMOKE = (IMAGE != 224 or BATCH_DIV != 1 or ATTN_ITERS != 50
          or ITERS != 20 or bool(os.environ.get("VIT_PLATFORM")))
# Any smoke knob forces artifacts off the repo root unless the caller
# explicitly chose a destination — a dry run must never overwrite the
# committed RESULTS_vit.json / vit_statistics.csv.
OUT_DIR = os.environ.get("VIT_OUT_DIR") or (
    __import__("tempfile").gettempdir() if _SMOKE else REPO)


def vit_flops_per_image(*, image: int, patch: int, d: int, layers: int,
                        heads: int, mlp: int, classes: int = 1000) -> float:
    """Analytic forward matmul FLOPs (2·MACs) for one image."""
    L = (image // patch) ** 2 + 1  # + class token
    patchify = L * (3 * patch * patch) * d * 2
    per_block = (
        3 * L * d * d * 2        # qkv projections
        + L * L * d * 2          # q·k^T (all heads)
        + L * L * d * 2          # scores·v
        + L * d * d * 2          # output projection
        + 2 * L * d * mlp * 2    # MLP fc1 + fc2
    )
    head = d * classes * 2
    return patchify + layers * per_block + head


ARCHS = {
    "vit_b_16": dict(patch=16, d=768, layers=12, heads=12, mlp=3072,
                     batch=256),
    # remat: unchecked, ViT-L/16 b128 stashes ~15 GB of activations —
    # past the 16 GB HBM, XLA spills, and measured MFU collapsed to 11.9%
    # (v5e, 2026-07-31).  Block-remat keeps it resident.
    "vit_l_16": dict(patch=16, d=1024, layers=24, heads=16, mlp=4096,
                     batch=128, remat=True),
}


def bench_arch(arch: str, spec: dict, image: int = IMAGE) -> dict:
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    batch = max(1, spec["batch"] // BATCH_DIV)
    mesh = data_parallel_mesh()
    model = models.create_model(
        arch, num_classes=1000, dtype=jnp.bfloat16,
        **({"remat": True} if spec.get("remat") else {}))
    variables = model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)), train=False
    )
    state = TrainState.create(variables, sgd_init(variables["params"]))
    step = make_train_step(model, mesh)

    rng = np.random.default_rng(0)
    device_batch = {
        "images": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype=jnp.bfloat16),
        "labels": jnp.asarray(
            rng.integers(0, 1000, size=batch).astype(np.int32)),
        "weights": jnp.ones((batch,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    for _ in range(3):
        state, metrics = step(state, device_batch, lr)
    float(metrics["loss"])  # pipeline flush (see bench.py note)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, metrics = step(state, device_batch, lr)
    assert np.isfinite(float(metrics["loss"]))
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    img_s = batch * ITERS / dt / n_chips
    fwd_flops = vit_flops_per_image(image=image, **{
        k: spec[k] for k in ("patch", "d", "layers", "heads", "mlp")})
    mfu = img_s * 3 * fwd_flops / PEAK_BF16_FLOPS
    step_ms = dt / ITERS * 1000
    print(f"{arch}: {img_s:,.1f} img/s/chip, step {step_ms:.1f} ms, "
          f"fwd {fwd_flops / 1e9:.1f} GFLOP/img, MFU {mfu * 100:.1f}%",
          flush=True)
    return {
        "img_per_sec_per_chip": round(img_s, 1),
        "step_ms": round(step_ms, 2),
        "batch": batch,
        "fwd_gflops_per_image": round(fwd_flops / 1e9, 2),
        # MFU counts the model's required 3x-forward FLOPs (standard
        # convention); under remat the chip additionally executes the
        # recompute pass, so the hardware-utilization ceiling is ~75%.
        "mfu_pct": round(mfu * 100, 1),
        "remat": bool(spec.get("remat", False)),
    }


def bench_attention(image: int = 224, patch: int = 16, d: int = 768,
                    heads: int = 12, batch: int = 256) -> dict:
    """Flash vs dense at ViT shapes (L≈197→256 padded for the kernel's
    block tiling): quantifies why flash is not the ViT lever."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.ops.flash_attention import flash_attention

    L = 256  # 197 padded up to the kernel's block granularity
    batch = max(1, batch // BATCH_DIV)
    hd = d // heads
    rng = np.random.default_rng(0)
    q, k, v = (
        jnp.asarray(rng.normal(size=(batch, L, heads, hd)),
                    dtype=jnp.bfloat16)
        for _ in range(3)
    )

    def dense(q, k, v):
        s = jnp.einsum("blhd,bmhd->bhlm", q, k).astype(jnp.float32)
        p = jax.nn.softmax(s / np.sqrt(hd), axis=-1).astype(q.dtype)
        return jnp.einsum("bhlm,bmhd->blhd", p, v)

    out = {}
    for name, fn in (
        ("dense", jax.jit(dense)),
        ("flash", jax.jit(lambda q, k, v: flash_attention(
            q, k, v, causal=False, block_q=128, block_k=256))),
    ):
        r = fn(q, k, v)
        r.block_until_ready()
        t0 = time.perf_counter()
        for _ in range(ATTN_ITERS):
            r = fn(q, k, v)
        r.block_until_ready()
        ms = (time.perf_counter() - t0) / ATTN_ITERS * 1000
        out[name + "_ms"] = round(ms, 3)
        print(f"attention {name}: {ms:.3f} ms  (B={batch} L={L} H={heads} "
              f"hd={hd})", flush=True)
    return out


def main() -> int:
    # Smoke runs steer off the tunneled-axon platform (sitecustomize
    # pre-sets it, so plain env doesn't work — same dance as
    # convergence_hard.py); chip runs leave VIT_PLATFORM unset.
    plat = os.environ.get("VIT_PLATFORM")
    if plat:
        import jax as _jax

        _jax.config.update("jax_platforms", plat)

    from pytorch_distributed_tpu.utils.telemetry import TelemetrySampler

    csv_path = os.path.join(OUT_DIR, "vit_statistics.csv")
    sampler = TelemetrySampler(csv_path, 0.5).start()
    try:
        results = {a: bench_arch(a, s) for a, s in ARCHS.items()}
        results["attention_micro"] = bench_attention()
    finally:
        sampler.stop()

    import jax

    attn = results["attention_micro"]
    fwd_b16 = vit_flops_per_image(image=IMAGE, patch=16, d=768, layers=12,
                                  heads=12, mlp=3072)
    L16 = (IMAGE // 16) ** 2 + 1  # tokens at the RUN's image size
    attn_frac = (12 * 2 * L16 * L16 * 768 * 2) / fwd_b16
    out = {
        "meta": {
            "platform": jax.devices()[0].platform,
            "device": str(jax.devices()[0]),
            "peak_bf16_flops": PEAK_BF16_FLOPS,
            "iters": ITERS,
            "precision": "bf16 compute, f32 LN/softmax/head",
            "note": "synthetic in-device data — isolates the compiled step "
                    "(same discipline as bench.py)",
            "attention_flop_fraction_vit_b_16": round(attn_frac, 4),
            "telemetry_csv": "vit_statistics.csv (statistics.sh contract)",
        },
        "results": results,
    }
    with open(os.path.join(OUT_DIR, "RESULTS_vit.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
