#!/usr/bin/env python
"""Flash-attention training-step benchmark: Pallas fwd+bwd vs XLA.

Round-1 verdict: the Pallas kernel only won on *forward*; training fell
back to an XLA blockwise backward.  This bench times a full fwd+bwd
(attention-only loss) at long context for three implementations:

- ``dense``        — XLA dense attention (materializes [L, L] scores)
- ``flash_xla``    — Pallas forward + XLA blockwise-recompute backward
- ``flash_pallas`` — Pallas forward + fused Pallas dq / dk/dv kernels

Writes RESULTS_flash.json.  Run on the TPU chip:
    python experiments/flash_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.ops.flash_attention import flash_attention
from pytorch_distributed_tpu.parallel.ring import dense_attention

B = int(os.environ.get("FLASH_BENCH_B", "4"))
H = int(os.environ.get("FLASH_BENCH_H", "8"))
D = int(os.environ.get("FLASH_BENCH_D", "128"))
LENGTHS = tuple(
    int(x) for x in os.environ.get("FLASH_BENCH_L", "2048,4096,8192").split(",")
)
ITERS = int(os.environ.get("FLASH_BENCH_ITERS", "10"))


def timed(fn, *args):
    for _ in range(3):
        out = fn(*args)
    float(out[0] if isinstance(out, tuple) else out)
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = fn(*args)
    float(out[0] if isinstance(out, tuple) else out)
    return (time.perf_counter() - t0) / ITERS


def main() -> int:
    results = {}
    for L in LENGTHS:
        rng = np.random.default_rng(0)
        mk = lambda: jnp.asarray(
            rng.normal(size=(B, L, H, D)).astype(np.float32) * 0.1
        ).astype(jnp.bfloat16)
        q, k, v = mk(), mk(), mk()
        row = {}

        def loss_dense(q, k, v):
            return (dense_attention(q, k, v, causal=True)
                    .astype(jnp.float32) ** 2).mean()

        def make_flash_loss(impl):
            def loss(q, k, v):
                return (flash_attention(q, k, v, True, 256, 1024, None, impl)
                        .astype(jnp.float32) ** 2).mean()
            return loss

        for name, loss in (
            ("dense", loss_dense),
            ("flash_xla", make_flash_loss("xla")),
            ("flash_pallas", make_flash_loss("pallas")),
        ):
            grad_fn = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

            def run(q, k, v, _g=grad_fn):
                g = _g(q, k, v)
                return g[0].astype(jnp.float32).mean()

            try:
                t = timed(run, q, k, v)
            except Exception as e:
                print(f"L={L} {name}: FAILED {e}", flush=True)
                continue
            row[name] = round(t * 1e3, 2)
            print(f"L={L} {name}: {t * 1e3:.2f} ms fwd+bwd", flush=True)
        if "dense" in row:
            for name in ("flash_xla", "flash_pallas"):
                if name in row:
                    row[f"{name}_speedup_vs_dense"] = round(
                        row["dense"] / row[name], 2)
        results[f"L{L}"] = row

    out = {
        "meta": {"B": B, "H": H, "D": D, "iters": ITERS,
                 "platform": jax.default_backend(),
                 "what": "attention-only fwd+bwd wall time, bf16"},
        "ms": results,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_flash.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
