#!/usr/bin/env python
"""Pipeline-schedule memory comparison: GPipe vs GPipe+remat vs 1F1B.

Compiles (does not run) the full LM train step for each schedule on an
8-stage CPU-simulated mesh at a realistic d_model, and reads XLA's compiled
peak-temp-buffer analysis — the activation-stash story in one table:

- gpipe          : autodiff stashes every in-stage intermediate, O(M·layers)
- gpipe + remat  : stashes one stage-*input* per tick, O(M)
- 1f1b           : interleaved schedule, stash bounded at 2(P-1)+1 — M-free

Writes RESULTS_pp_memory.json {config, rows: [{schedule, microbatches,
temp_bytes, ...}]}.  Evidence for VERDICT r2 item 5 (activation memory vs
GPipe at 8 stages / realistic d_model on the CPU mesh).
"""

import argparse
import json
import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=8"
    # Long pipeline steps serialize 8 device threads onto however many host
    # cores exist; XLA-CPU's default 40 s collective-rendezvous terminate
    # limit shoots the process mid-step on a 1-core host (observed at M=32).
    + " --xla_cpu_collective_call_terminate_timeout_seconds=1800"
    + " --xla_cpu_collective_call_warn_stuck_timeout_seconds=600"
).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402


def compiled_temp_bytes(schedule: str, remat: bool, n_micro: int,
                        d_model: int, seq: int, stages: int,
                        vocab: int, mb: int, time_iters: int = 0,
                        n_layers: int = 0, n_virtual: int = 1) -> dict:
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
        pp_specs,
    )
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel.tp import shard_state
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = build_mesh(MeshSpec(("data", "pipe"), (1, stages)),
                      jax.devices()[:stages])
    model = PipelinedTransformerLM(
        vocab_size=vocab, d_model=d_model, n_heads=8,
        n_layers=n_layers or stages,
        n_stages=stages, n_microbatches=n_micro, mesh=mesh,
        schedule=schedule, remat=remat, n_virtual=n_virtual,
    )
    B = mb * n_micro
    tokens = jnp.zeros((B, seq), jnp.int32)
    with mesh:
        params = model.init(jax.random.PRNGKey(0), tokens)["params"]
        spec = pp_specs(params)
        state = shard_state(
            TrainState.create({"params": params}, sgd_init(params)),
            spec, mesh,
        )
        step = make_lm_train_step(model, mesh, spec)
        toks = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
        compiled = step.lower(state, toks, jnp.float32(0.05)).compile()
    m = compiled.memory_analysis()
    row = {
        "schedule": schedule + ("+remat" if remat else ""),
        "microbatches": n_micro,
        "temp_bytes": int(m.temp_size_in_bytes),
        "argument_bytes": int(m.argument_size_in_bytes),
    }
    if time_iters:
        import time

        lr = jnp.float32(0.05)
        with mesh:
            state, _ = compiled(state, toks, lr)   # warm; state is donated,
            jax.block_until_ready(state.params)    # so chain the new one
            t0 = time.perf_counter()
            for _ in range(time_iters):
                state, _ = compiled(state, toks, lr)
            jax.block_until_ready(state.params)
            row["ms_per_step"] = round(
                (time.perf_counter() - t0) * 1000.0 / time_iters, 1)
    return row


def interleaved_section(args) -> None:
    """Same 8-layer model on a P=4 pipe mesh: 1F1B (2 layers/stage) vs
    interleaved 1F1B (V=2 single-layer chunks/device).  On real chips the
    interleave shrinks the bubble ~V x; on the serialized 1-core sim
    bubbles are free, so the comparable columns are the stash high-water
    (temp_bytes — the V x memory trade) and schedule compute overhead."""
    stages, n_layers, V = 4, 8, 2
    if args.stages != 8:
        raise SystemExit("--interleaved-only runs a fixed P=4 / 8-layer "
                         "comparison; --stages does not apply to it")
    if not os.path.exists(args.out):
        raise SystemExit(f"--interleaved-only appends to an existing "
                         f"{args.out}; run the main table first")
    rows = []
    for n_micro in args.micro:
        for schedule, n_virtual in (("1f1b", 1), ("interleaved", V)):
            r = compiled_temp_bytes(
                schedule, False, n_micro, args.d_model, args.seq, stages,
                args.vocab, args.mb, time_iters=args.time_iters,
                n_layers=n_layers, n_virtual=n_virtual)
            if n_virtual > 1:
                r["schedule"] = f"interleaved_v{n_virtual}"
            rows.append(r)
            print(f"M={n_micro:3d} {r['schedule']:15s} "
                  f"temp={r['temp_bytes']/2**20:9.1f} MiB "
                  f"ms/step={r.get('ms_per_step', '-')}", flush=True)
    with open(args.out) as f:
        out = json.load(f)
    out["interleaved_p4"] = {
        "config": {"d_model": args.d_model, "seq": args.seq,
                   "stages": stages, "n_layers": n_layers, "vocab":
                   args.vocab, "mb": args.mb, "n_virtual": V,
                   "note": "same 8-layer LM, P=4 pipe mesh: 2 layers/stage "
                           "(1f1b) vs V=2 single-layer chunks/device "
                           "(interleaved).  Bubble shrink needs real "
                           "parallel chips; here the columns quantify the "
                           "interleave's stash/memory trade and compute "
                           "overhead"},
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"appended interleaved_p4 section to {args.out}")


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--stages", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=1024)
    ap.add_argument("--mb", type=int, default=2, help="per-microbatch batch")
    ap.add_argument("--micro", type=int, nargs="+", default=[8, 32])
    ap.add_argument("--time-iters", type=int, default=2,
                    help="timed executions per config after one warm step "
                    "(0 = compile-only, the round-3 behavior)")
    ap.add_argument("--out", default="RESULTS_pp_memory.json")
    ap.add_argument("--interleaved-only", action="store_true",
                    help="append the P=4 interleaved-vs-1f1b section to an "
                    "existing --out file without re-running the main table")
    args = ap.parse_args()

    if args.interleaved_only:
        return interleaved_section(args)

    rows = []
    for n_micro in args.micro:
        for schedule, remat in (("gpipe", False), ("gpipe", True),
                                ("1f1b", False)):
            r = compiled_temp_bytes(schedule, remat, n_micro, args.d_model,
                                    args.seq, args.stages, args.vocab,
                                    args.mb, time_iters=args.time_iters)
            rows.append(r)
            print(f"M={n_micro:3d} {r['schedule']:12s} "
                  f"temp={r['temp_bytes']/2**20:9.1f} MiB "
                  f"ms/step={r.get('ms_per_step', '-')}", flush=True)

    out = {
        "config": {"d_model": args.d_model, "seq": args.seq,
                   "stages": args.stages, "vocab": args.vocab,
                   "mb": args.mb,
                   "note": "XLA compiled peak temp buffers, full train step "
                           "(fwd+bwd+SGD), 8-device CPU mesh, f32",
                   "timing_note": "ms_per_step on the 1-core host serializes "
                   "all 8 simulated stages, so pipeline BUBBLES cost no "
                   "wall-clock here; the column isolates per-schedule "
                   "compute overhead (remat's recompute tax, 1f1b's "
                   "scheduling overhead vs gpipe) — bubble-fraction deltas "
                   "need real parallel chips"},
        "rows": rows,
    }
    with open(args.out, "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
