#!/usr/bin/env python
"""Convergence experiment: the framework's first accuracy evidence.

The reference's correctness oracle is per-epoch top-1/top-5 on real data
(reference distributed.py:212,321-322) with ``best_acc1`` tracking
(:215-216).  This experiment reproduces that oracle end-to-end on a
deterministic, *learnable* ImageFolder tree (class-coded blob patterns +
noise — real JPEG decode, real augmentation, real sharded eval) and pins
the numerics claims that were previously compile-time-only:

- fp32 vs bf16 (the apex-AMP slot): top-1 curves must match within noise;
- accum=1 vs accum=4 (in-graph gradient accumulation): same;
- both must actually LEARN (final top-1 >= 90% on a 6-class problem a
  resnet18 solves easily).

Writes ``RESULTS_convergence.json`` next to this file and prints a table.

Run (CPU 8-device mesh, ~10-15 min on one core):
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/convergence.py

On a real TPU chip, drop the env vars (minutes).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

CLASSES = 6
PER_CLASS_TRAIN = 48
PER_CLASS_VAL = 16
IMAGE = 48
EPOCHS = int(os.environ.get("CONV_EPOCHS", "8"))
BATCH = 48


def make_dataset(root: str, seed: int = 0) -> None:
    """Class-coded images: dominant hue + blob position per class, plus
    per-image noise and jitter — learnable, not memorizable-trivial."""
    from PIL import Image

    rng = np.random.default_rng(seed)
    hues = np.linspace(0, 1, CLASSES, endpoint=False)
    for split, per in (("train", PER_CLASS_TRAIN), ("val", PER_CLASS_VAL)):
        for c in range(CLASSES):
            d = os.path.join(root, split, f"class{c}")
            os.makedirs(d, exist_ok=True)
            for i in range(per):
                img = rng.normal(0.45, 0.18, size=(IMAGE, IMAGE, 3))
                # class hue tint
                tint = np.array([
                    0.5 + 0.5 * np.cos(2 * np.pi * (hues[c] + k / 3.0))
                    for k in range(3)
                ])
                img += 0.25 * tint
                # class-positioned blob (jittered)
                ang = 2 * np.pi * c / CLASSES
                cy = IMAGE / 2 + (IMAGE / 4) * np.sin(ang) + rng.normal(0, 2)
                cx = IMAGE / 2 + (IMAGE / 4) * np.cos(ang) + rng.normal(0, 2)
                yy, xx = np.mgrid[0:IMAGE, 0:IMAGE]
                blob = np.exp(-(((yy - cy) ** 2 + (xx - cx) ** 2)
                                / (2 * (IMAGE / 8) ** 2)))
                img += 0.5 * blob[..., None]
                arr = (np.clip(img, 0, 1) * 255).astype(np.uint8)
                Image.fromarray(arr).save(os.path.join(d, f"{i:03d}.jpg"),
                                          quality=92)


def run_config(data_root: str, precision: str, accum: int, tmpdir: str):
    from pytorch_distributed_tpu.train.config import Config
    from pytorch_distributed_tpu.train.trainer import Trainer

    cfg = Config(
        data=data_root, arch="resnet18", batch_size=BATCH, epochs=EPOCHS,
        lr=0.02, print_freq=100, seed=0, image_size=IMAGE,
        precision=precision, accum_steps=accum,
        checkpoint_dir=os.path.join(tmpdir, f"{precision}_a{accum}"),
        workers=2,
    )
    t = Trainer(cfg)
    curve = []
    for epoch in range(EPOCHS):
        t.train_epoch(epoch)
        acc1 = t.validate()
        curve.append(round(float(acc1), 3))
    return curve


def main() -> int:
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    out_path = os.path.abspath(os.path.join(here, "..",
                                            "RESULTS_convergence.json"))
    with tempfile.TemporaryDirectory() as tmp:
        data_root = os.path.join(tmp, "data")
        make_dataset(data_root)
        results = {}
        fingerprint = [CLASSES, PER_CLASS_TRAIN, PER_CLASS_VAL, IMAGE,
                       EPOCHS, BATCH]
        if os.path.exists(out_path):  # accumulate across partial runs
            try:
                with open(out_path) as f:
                    prior = json.load(f)
                # Cached curves are only reusable for the SAME experiment
                # configuration — stale-config curves under fresh meta would
                # misdescribe themselves.
                if prior.get("fingerprint") == fingerprint:
                    results = prior.get("curves", {})
            except ValueError:  # truncated by a killed writer: start fresh
                pass
        only = os.environ.get("CONV_ONLY", "")
        # accum=2: BATCH/2 microbatches stay divisible by the 8-shard mesh.
        for name, precision, accum in (
            ("fp32_accum1", "fp32", 1),
            ("bf16_accum1", "bf16", 1),
            ("bf16_accum2", "bf16", 2),
        ):
            if only and name not in only.split(","):
                continue
            if name in results:
                print(f"=== {name}: cached ===", flush=True)
                continue
            print(f"=== {name} ===", flush=True)
            results[name] = run_config(data_root, precision, accum, tmp)
            # Incremental write: a late-config failure must not lose the
            # completed curves.
            with open(out_path, "w") as f:
                json.dump({"fingerprint": fingerprint, "curves": results},
                          f, indent=1)

    meta = {
        "oracle": "per-epoch val top-1, sharded exact eval "
                  "(reference distributed.py:212,321-322)",
        "dataset": f"{CLASSES}-class synthetic ImageFolder (JPEG), "
                   f"{CLASSES * PER_CLASS_TRAIN} train / "
                   f"{CLASSES * PER_CLASS_VAL} val, {IMAGE}px",
        "arch": "resnet18",
        "epochs": EPOCHS,
        "batch": BATCH,
        "platform": os.environ.get("JAX_PLATFORMS", "device-default"),
    }
    out = {"meta": meta, "fingerprint": fingerprint, "curves": results}
    with open(out_path, "w") as f:
        json.dump(out, f, indent=1)

    print(json.dumps(out, indent=1))
    finals = {k: v[-1] for k, v in results.items()}
    ok = True
    for k, v in finals.items():
        if v < 90.0:
            print(f"FAIL: {k} final top-1 {v} < 90%")
            ok = False
    spread = max(finals.values()) - min(finals.values())
    if spread > 8.0:
        print(f"FAIL: final top-1 spread {spread:.2f} > 8 points")
        ok = False
    print("convergence:", "OK" if ok else "MISMATCH",
          f"finals={finals} spread={spread:.2f}")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
