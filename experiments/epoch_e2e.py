#!/usr/bin/env python
"""Real-data epoch wall-clock: the reference's only published experiment,
end-to-end on this framework.

The reference times full ImageNet epochs (per-epoch CSV, reference
dataparallel.py:205-213) — JPEG decode, augmentation, H2D, train step.
This experiment does the same on a synthetic ImageNet-shaped JPEG
ImageFolder: real decode (PIL or the native C++ plane), real augmentation,
real async DeviceFeeder into the real compiled train step, one timed epoch
per wire mode.

Writes RESULTS_epoch.json.  Run on the TPU chip:
    PYTHONPATH=/root/repo python experiments/epoch_e2e.py

Honest-scaling note recorded in the output: this host has os.cpu_count()
cores (1 in the bench container, vs a real TPU-VM's ~100+); the loader
ceiling measured in RESULTS_loader.json is per-core, so the epoch number
here is host-IO-bound by construction.  The "compute_only_s" column is what
the same epoch costs with the chip never starving (step time × steps), i.e.
the epoch time on a host with enough loader cores.
"""

from __future__ import annotations

import itertools
import json
import os
import sys
import time

import numpy as np

N_IMAGES = int(os.environ.get("EPOCH_IMAGES", "2048"))
SRC = int(os.environ.get("EPOCH_SRC", "320"))
BATCH = int(os.environ.get("EPOCH_BATCH", "128"))
IMAGE = 224
ARCH = os.environ.get("EPOCH_ARCH", "resnet50")


def make_tree(root: str, n: int) -> int:
    """Writes ~n JPEGs over 8 classes; returns the actual count written."""
    from PIL import Image

    rng = np.random.default_rng(0)
    per = n // 8
    for c in range(8):
        d = os.path.join(root, "train", f"c{c}")
        os.makedirs(d, exist_ok=True)
        for i in range(per):
            arr = rng.integers(0, 256, size=(SRC, SRC, 3), dtype=np.uint8)
            Image.fromarray(arr).save(
                os.path.join(d, f"{i:04d}.jpg"), quality=85)
    return per * 8


def run_epoch(root: str, mode: str, kind: str, step, state, lr, feeder,
              workers: int):
    from pytorch_distributed_tpu.data import DataLoader, ImageFolder
    from pytorch_distributed_tpu.data import transforms as T

    tf = None if kind == "native" else T.train_transform_u8(IMAGE)
    ds = ImageFolder(os.path.join(root, "train"), transform=tf,
                     native_decode=kind == "native", image_size=IMAGE)
    loader = DataLoader(ds, BATCH, num_workers=workers, drop_last=True,
                        batch_mode=mode, random_flip=True)
    # Warm: compile + fill the prefetch queue, then stop the feeder early
    # (a few batches — not a full decode epoch).
    it = feeder(iter(loader))
    state, met = step(state, next(it), lr)
    float(met["loss"])
    for _ in itertools.islice(it, 2):
        pass
    close = getattr(it, "close", None)
    if close:
        close()
    # Timed epoch.
    t0 = time.perf_counter()
    steps = 0
    for batch in feeder(iter(loader)):
        state, met = step(state, batch, lr)
        steps += 1
    assert np.isfinite(float(met["loss"]))  # drains the device queue
    dt = time.perf_counter() - t0
    return state, dt, steps


def main() -> int:
    import tempfile

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.data.loader import DeviceFeeder
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    workers = int(os.environ.get("EPOCH_WORKERS", str(os.cpu_count() or 1)))
    mesh = data_parallel_mesh()
    model = models.create_model(ARCH, num_classes=1000, dtype=jnp.bfloat16,
                                stem="space_to_depth")
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, IMAGE, IMAGE, 3)), train=False)
    # The train step donates its state argument; every epoch needs a fresh
    # device tree, so keep the initial variables on host.
    host_vars = jax.tree.map(np.asarray, variables)

    def fresh_state():
        v = jax.tree.map(jnp.asarray, host_vars)
        return TrainState.create(v, sgd_init(v["params"]))

    step = make_train_step(model, mesh)
    feeder = DeviceFeeder(mesh)
    lr = jnp.float32(0.1)

    # Chip-only step time for the compute_only_s column.
    rng = np.random.default_rng(0)
    dev_b = {
        "images": jnp.asarray(rng.normal(size=(BATCH, IMAGE, IMAGE, 3)),
                              dtype=jnp.bfloat16),
        "labels": jnp.asarray(rng.integers(0, 1000, BATCH).astype(np.int32)),
        "weights": jnp.ones((BATCH,), jnp.float32),
    }
    st = fresh_state()
    for _ in range(3):
        st, met = step(st, dev_b, lr)
    float(met["loss"])
    t0 = time.perf_counter()
    for _ in range(10):
        st, met = step(st, dev_b, lr)
    float(met["loss"])
    step_s = (time.perf_counter() - t0) / 10

    results = {}
    with tempfile.TemporaryDirectory() as tmp:
        make_tree(tmp, N_IMAGES)
        for name, mode, kind in (
            ("pil_u8_host", "u8_host", "u8"),
            ("pil_u8_wire", "u8_wire", "u8"),
            ("native_u8_wire", "u8_wire", "native"),
        ):
            state = fresh_state()
            try:
                state, dt, steps = run_epoch(
                    tmp, mode, kind, step, state, lr, feeder, workers)
            except Exception as e:  # native .so may be absent
                print(f"{name}: SKIP ({e})", flush=True)
                continue
            imgs = steps * BATCH
            results[name] = {
                "epoch_s": round(dt, 2),
                "img_per_sec": round(imgs / dt, 1),
                "steps": steps,
                "compute_only_s": round(steps * step_s, 2),
            }
            print(f"{name}: {dt:.1f} s epoch ({imgs / dt:,.0f} img/s; "
                  f"compute-only {steps * step_s:.1f} s)", flush=True)

    out = {
        "meta": {
            "images": N_IMAGES, "src_px": SRC, "batch": BATCH, "arch": ARCH,
            "workers": workers, "cpus": os.cpu_count(),
            "platform": jax.default_backend(),
            "chip_step_ms": round(step_s * 1e3, 2),
            "note": "per-epoch wall-clock incl. JPEG decode/augment/H2D "
                    "(reference methodology, dataparallel.py:205-213); this "
                    "host is loader-bound at 1 core — compute_only_s is the "
                    "same epoch with enough loader cores",
        },
        "epochs": results,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_epoch.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
