#!/usr/bin/env python
"""A/B: continuous batching vs naive static (wave) batching, same load.

The serving engine's one tunable that matters for throughput is *when* a
freed decode slot is refilled.  ``mode="static"`` is the naive baseline:
admit a wave, decode until every member finishes, only then admit the
next wave — short requests sit done while the wave's longest member
drains, so slot utilization collapses under mixed output lengths.
``mode="continuous"`` refills any freed slot on the very next iteration
(vLLM-style iteration-level scheduling, arXiv 2309.06180).

Both arms replay the *identical* seeded Poisson trace (loadgen.py is
pure numpy, so two calls with the same ``LoadConfig`` produce the same
requests and arrival times) through the same compiled step functions
(`_make_steps` is cached, and a warmup run pays every compile before
either measured arm starts).  Greedy decode, so both arms also emit
bit-identical token streams — the A/B isolates scheduling, nothing else.
Each arm is best-of-``SERVING_AB_REPS`` to shave host-scheduling noise;
the load skews long (20% of outputs are 8-16x the short ones) because
that is exactly the regime wave batching is worst at, and the model is
big enough (d256 x 4L) that the compiled step, not Python dispatch,
dominates each iteration.

Writes RESULTS_serving.json and exits nonzero unless continuous beats
static by >= 2x tokens/s.

Run (CPU is fine — this measures scheduling, not FLOPs):
    PYTHONPATH=/root/repo JAX_PLATFORMS=cpu python experiments/serving_ab.py
"""

from __future__ import annotations

import json
import os

N_REQUESTS = int(os.environ.get("SERVING_AB_REQUESTS", "64"))
RATE_RPS = float(os.environ.get("SERVING_AB_RATE", "2000.0"))
MAX_BATCH = int(os.environ.get("SERVING_AB_BATCH", "8"))
REPS = int(os.environ.get("SERVING_AB_REPS", "2"))
SEED = int(os.environ.get("SERVING_AB_SEED", "0"))
MODEL = dict(vocab_size=256, d_model=256, n_heads=8, n_layers=4)
LOAD = dict(prompt_min=4, prompt_max=8, short_min=4, short_max=12,
            long_min=96, long_max=128, long_frac=0.2)


def _run_arm(mode: str, params, n_requests: int, reps: int = 1):
    from pytorch_distributed_tpu.serving.engine import ServingEngine
    from pytorch_distributed_tpu.serving.loadgen import (
        LoadConfig,
        generate_load,
    )

    best = None
    for _ in range(reps):
        eng = ServingEngine(
            params, max_batch=MAX_BATCH, kv_blocks=80, block_size=16,
            blocks_per_seq=9, chunk_size=8, max_new_tokens=128,
            mode=mode, seed=SEED, **MODEL)
        load = generate_load(LoadConfig(
            n_requests=n_requests, rate_rps=RATE_RPS, profile="mixed",
            vocab_size=MODEL["vocab_size"], seed=SEED, **LOAD))
        s = eng.run(load)
        if best is None or s["tokens_per_s"] > best["tokens_per_s"]:
            best = s
    return best


def main() -> int:
    from pytorch_distributed_tpu.serving.engine import init_lm_params

    params = init_lm_params(seed=SEED, block_size=16, **MODEL)

    _run_arm("continuous", params, 4)
    print("warmup done; both arms run fully compiled", flush=True)

    arms = {}
    for mode in ("static", "continuous"):
        s = _run_arm(mode, params, N_REQUESTS, reps=REPS)
        arms[mode] = s
        print(f"{mode:>10}: {s['completed']} done, {s['tokens']} tokens "
              f"in {s['wall_s']:.2f}s ({s['steps']} iterations) -> "
              f"{s['tokens_per_s']:.1f} tok/s, "
              f"TTFT p99 {s['ttft_p99_ms']:.1f}ms, "
              f"ITL p99 {s['itl_p99_ms']:.2f}ms", flush=True)

    ratio = arms["continuous"]["tokens_per_s"] / arms["static"][
        "tokens_per_s"]
    ok = (ratio >= 2.0
          and arms["continuous"]["completed"] == N_REQUESTS
          and arms["static"]["completed"] == N_REQUESTS
          and arms["continuous"]["tokens"] == arms["static"]["tokens"])
    out = {
        "meta": {
            "what": "continuous vs naive wave batching on the identical "
                    "seeded Poisson trace; greedy, so token streams are "
                    "bit-identical and the A/B isolates scheduling",
            "model": MODEL,
            "load": dict(LOAD, n_requests=N_REQUESTS, rate_rps=RATE_RPS,
                         profile="mixed", seed=SEED),
            "max_batch": MAX_BATCH,
            "reps": REPS,
            "platform": "cpu",
        },
        "static": arms["static"],
        "continuous": arms["continuous"],
        "speedup_tokens_per_s": round(ratio, 2),
        "iteration_ratio": round(arms["static"]["steps"]
                                 / arms["continuous"]["steps"], 2),
        "pass": bool(ok),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "RESULTS_serving.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1, sort_keys=False)
        f.write("\n")
    print(f"continuous/static speedup: {ratio:.2f}x tokens/s "
          f"({out['iteration_ratio']:.2f}x fewer iterations) "
          f"-> {'PASS' if ok else 'FAIL'}; wrote {path}", flush=True)
    return 0 if ok else 1


if __name__ == "__main__":
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    raise SystemExit(main())
