#!/usr/bin/env python
"""Gradient-compression A/B: loss parity + measured wire bytes for
``--grad-compress none | bf16 | int8`` on the 4-way CPU mesh.

The quantized gradient collective (ops/qcomm.py) claims two things at
once, and both are checkable on CPU:

1. **Convergence parity** — int8 block quantization *with error feedback*
   must track the f32 run: same synthetic task, same seed, same schedule;
   the final-loss delta is the oracle (the convergence.py spread-gate
   methodology, applied to loss since this is a fixed-step run).
2. **Wire reduction** — the compressed decomposition (all_to_all of int8
   payload + f32 block scales, then all_gather of the re-quantized
   shards) must move >= 3.5x fewer grad_sync wire bytes than the f32
   all-reduce.  Measured from the compiled HLO via the comm ledger
   (obs/comms.py), not asserted from the analytic formula — the fence is
   on what XLA actually lowered.

Every run uses the explicit-collectives shard_map step
(train/steps.py local_step), where compression is real wire traffic.
The model's parameter leaves are sized as multiples of
``n_data * block`` so padding overhead reflects realistic layers, not a
toy-bias worst case (a 10-element bias pads to its chunk boundary;
a 49k kernel doesn't pad at all).

Writes ``RESULTS_grad_compress.json``.  CPU-safe:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/grad_compress_sweep.py
"""

from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

DP = int(os.environ.get("GCS_DP", "4"))
HIDDEN = int(os.environ.get("GCS_HIDDEN", "256"))
CLASSES = int(os.environ.get("GCS_CLASSES", "8"))
STEPS = int(os.environ.get("GCS_STEPS", "40"))
BATCH = int(os.environ.get("GCS_BATCH", "32"))
LR = float(os.environ.get("GCS_LR", "0.05"))
SEED = int(os.environ.get("GCS_SEED", "0"))


def _build(mode: str, mesh):
    import warnings

    import flax.linen as nn
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(HIDDEN)(x))
            return nn.Dense(CLASSES)(x)

    model = MLP()
    variables = model.init(jax.random.PRNGKey(SEED),
                           jnp.zeros((1, 8, 8, 3)), train=False)
    residual = qcomm.init_residual(variables["params"], mode,
                                   explicit=True, n_data=DP)
    state = TrainState.create(variables, sgd_init(variables["params"]),
                              residual=residual)
    if mode in qcomm.QUANTIZED_MODES:
        state = state.replace(residual=jax.device_put(
            state.residual, NamedSharding(mesh, P("data"))))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # fp8 availability notes etc.
        step = make_train_step(model, mesh, explicit_collectives=True,
                               grad_compress=mode)
    return step, state


def _batches():
    """Learnable synthetic task: labels from a fixed random linear map of
    the flattened image — every mode sees the identical stream."""
    rng = np.random.default_rng(SEED)
    w_true = rng.normal(size=(8 * 8 * 3, CLASSES))
    for _ in range(STEPS):
        x = rng.normal(size=(BATCH, 8, 8, 3)).astype(np.float32)
        y = np.argmax(x.reshape(BATCH, -1) @ w_true, axis=-1).astype(np.int32)
        yield {
            "images": x,
            "labels": y,
            "weights": np.ones((BATCH,), np.float32),
        }


def run_mode(mode: str, mesh) -> dict:
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import comms

    step, state = _build(mode, mesh)
    lr = jnp.float32(LR)
    first_batch = None
    losses = []
    for batch in _batches():
        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        if first_batch is None:
            first_batch = jb
        state, metrics = step(state, jb, lr)
        losses.append(float(metrics["loss"]))
    ledger = comms.ledger_from_jitted(step, (state, first_batch, lr),
                                      step=f"img_{mode}", mesh=mesh)
    gs = ledger.by_phase().get("grad_sync",
                               {"count": 0, "bytes": 0, "wire_bytes": 0.0})
    return {
        "first_loss": round(losses[0], 6),
        "final_loss": round(losses[-1], 6),
        "grad_sync_collectives": int(gs["count"]),
        "grad_sync_payload_bytes": int(gs["bytes"]),
        "grad_sync_wire_bytes": round(float(gs["wire_bytes"]), 1),
        "grad_sync_encodings": {
            k: int(v)
            for k, v in ledger.phase_wire_encodings("grad_sync").items()},
        "total_wire_bytes": round(ledger.total_wire_bytes, 1),
    }


def main() -> int:
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    if len(jax.devices()) < DP:
        print(f"SKIP: need {DP} devices, have {len(jax.devices())} "
              f"(set XLA_FLAGS=--xla_force_host_platform_device_count=8)")
        return 0
    mesh = build_mesh(MeshSpec(("data",), (DP,)), jax.devices()[:DP])

    rows = {}
    for mode in ("none", "bf16", "int8"):
        rows[mode] = run_mode(mode, mesh)
        print(f"{mode}: final loss {rows[mode]['final_loss']:.4f}, "
              f"grad_sync wire {rows[mode]['grad_sync_wire_bytes']:.0f} B "
              f"({rows[mode]['grad_sync_encodings']})", flush=True)

    f32_loss = rows["none"]["final_loss"]
    f32_wire = rows["none"]["grad_sync_wire_bytes"]
    deltas = {m: round(abs(rows[m]["final_loss"] - f32_loss)
                       / max(abs(f32_loss), 1e-9), 6)
              for m in ("bf16", "int8")}
    wire_ratio = {m: round(f32_wire / rows[m]["grad_sync_wire_bytes"], 3)
                  for m in ("bf16", "int8")}

    out = {
        "bf16_cpu_note": (
            "on the CPU backend XLA's float-normalization pass promotes "
            "bf16 all-reduces back to f32 (convert-wrapped f32 collective "
            "in the compiled HLO), so measured bf16 wire bytes equal f32 "
            "here; on TPU the bf16 all-reduce is native and halves the "
            "wire.  int8/fp8 payloads are integer/opaque to that pass — "
            "their measured reduction is real on every backend."),
        "meta": {
            "dp": DP, "hidden": HIDDEN, "classes": CLASSES, "steps": STEPS,
            "batch": BATCH, "lr": LR, "seed": SEED,
            "platform": jax.default_backend(),
            "what": "A/B of --grad-compress modes on the explicit-"
                    "collectives image step (train/steps.py local_step, "
                    "4-way data mesh): identical synthetic stream and "
                    "seed per mode; final-loss delta vs f32 is the "
                    "convergence oracle (convergence.py spread-gate "
                    "methodology) and the comm ledger's grad_sync wire "
                    "bytes (obs/comms.py, from the compiled HLO) are the "
                    "wire-reduction oracle.  int8 rides the two-hop "
                    "quantized decomposition with error feedback "
                    "(ops/qcomm.py compressed_psum).",
        },
        "rows": rows,
        "final_loss_rel_delta_vs_f32": deltas,
        "grad_sync_wire_reduction_vs_f32": wire_ratio,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_grad_compress.json"),
              "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out), flush=True)
    # Falsifiable claims: int8+EF tracks f32 within 2% relative final
    # loss, and its measured grad_sync wire traffic shrinks >= 3.5x (the
    # ISSUE-8 acceptance floor; analytic best is ~3.94x at block=256).
    # bf16 is NOT asserted: CPU float normalization promotes bf16
    # collectives to f32 (see bf16_cpu_note), so its measured ratio is
    # 1.0 here and ~2x only on accelerators.
    assert deltas["int8"] <= 0.02, deltas
    assert wire_ratio["int8"] >= 3.5, wire_ratio
    return 0


if __name__ == "__main__":
    sys.exit(main())
