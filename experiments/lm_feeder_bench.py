#!/usr/bin/env python
"""LM feeder before/after: LMTrainer.fit end-to-end with and without the
token prefetch pipeline.

Round 2's LM hot loop did synchronous host batch assembly + ``device_put``
inside the step loop (VERDICT r2 "What's weak" #4); round 3 gave it the
AsyncFeeder.  This measures what that's worth END-TO-END — real
TextFileDataset windows (actual host work), the MFU-headline model shape,
``LMTrainer.fit`` steps/sec with ``prefetch=0`` (the old loop) vs
``prefetch=2`` (the feeder).

Merges a ``feeder_before_after`` block into RESULTS_lm.json.

Run on the real chip:
    PYTHONPATH=/root/repo python experiments/lm_feeder_bench.py
CPU smoke: prefix with XLA_FLAGS=--xla_force_host_platform_device_count=8
and shrink via LMFEED_D/LMFEED_LAYERS/LMFEED_STEPS.
"""

from __future__ import annotations

import glob
import json
import os
import sys
import time

import jax

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SEQ = int(os.environ.get("LMFEED_SEQ", "1024"))
D_MODEL = int(os.environ.get("LMFEED_D", "1024"))
N_LAYERS = int(os.environ.get("LMFEED_LAYERS", "12"))
N_HEADS = int(os.environ.get("LMFEED_HEADS", "16"))
BATCH = int(os.environ.get("LMFEED_B", "8"))
STEPS = int(os.environ.get("LMFEED_STEPS", "40"))


def main() -> int:
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import LMTrainer, TextFileDataset

    paths = []
    for pat in ("*.md", "docs/*.md", "pytorch_distributed_tpu/**/*.py"):
        paths.extend(sorted(glob.glob(os.path.join(REPO, pat),
                                      recursive=True)))
    # stride < seq so the corpus yields plenty of distinct windows — window
    # assembly is the host work whose overlap we are measuring.
    ds = TextFileDataset(paths, SEQ, stride=97)

    n = jax.device_count()
    mesh = build_mesh(MeshSpec(("data",), (n,)))
    model = TransformerLM(vocab_size=256, d_model=D_MODEL, n_heads=N_HEADS,
                          n_layers=N_LAYERS,
                          dtype=jax.numpy.bfloat16)

    rows = {}
    with mesh:
        for prefetch in (0, 2):
            t = LMTrainer(model, mesh, ds, BATCH, lr=1e-3,
                          prefetch=prefetch)
            t.fit(5, print_freq=1000)  # warm the cache + compile
            # TextFileDataset caches nothing; every batch re-slices windows.
            t0 = time.perf_counter()
            t.fit(STEPS, print_freq=1000)
            dt = time.perf_counter() - t0
            rows[f"prefetch_{prefetch}"] = {
                "steps_per_sec": round(STEPS / dt, 3),
                "ms_per_step": round(dt / STEPS * 1000, 2),
                "tokens_per_sec": round(STEPS * BATCH * SEQ / dt, 0),
            }
            print(f"prefetch={prefetch}: {rows[f'prefetch_{prefetch}']}",
                  flush=True)

    speedup = (rows["prefetch_2"]["steps_per_sec"]
               / rows["prefetch_0"]["steps_per_sec"])
    block = {
        "what": "LMTrainer.fit end-to-end (host window assembly + transfer "
                "+ compiled step), prefetch 0 (round-2 loop) vs 2 (feeder)",
        "model": {"d_model": D_MODEL, "n_layers": N_LAYERS,
                  "n_heads": N_HEADS, "seq": SEQ, "batch": BATCH,
                  "vocab": 256},
        "platform": jax.default_backend(),
        "rows": rows,
        "feeder_speedup": round(speedup, 3),
    }
    # Smokes must not pollute the committed chip results: LMFEED_OUT
    # redirects (e.g. /tmp/lm_smoke.json); chip runs leave it unset.
    out_path = os.environ.get("LMFEED_OUT",
                              os.path.join(REPO, "RESULTS_lm.json"))
    data = {}
    if os.path.exists(out_path):
        with open(out_path) as f:
            data = json.load(f)
    data["feeder_before_after"] = block
    with open(out_path, "w") as f:
        json.dump(data, f, indent=1)
    print(json.dumps(block, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())
