#!/usr/bin/env python
"""Recipe-comparison benchmark — the reference's fig1 experiment, TPU-native.

The reference's one published figure compares its recipes' epoch times on
identical work (reference README.md:15, assets/fig1): DataParallel ~3.5×
slower than DDP ≈ Horovod ≈ Apex.  This bench times the SAME training work
under each of this framework's recipe formulations on one configuration:

- ``gspmd_f32``      — GSPMD gradient sync, f32 (the `distributed` recipes)
- ``gspmd_bf16``     — GSPMD, bf16 compute policy (`apex`/`tpu_native` slot)
- ``explicit_bf16w`` — shard_map + psum with bf16 wire grads (`horovod` slot)
- ``explicit_bf16_zero`` — the horovod slot + ``--zero wus`` weight-update
  sharding (ZeRO-1): same wire bytes, 1/N optimizer state per chip
- ``dataparallel``   — single-process GSPMD (same compiled program: the
  README §3 claim that DP is NOT 3.5× slower here becomes a measured fact)

Writes RESULTS_recipes.json; run on the TPU chip:
    python experiments/recipe_bench.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BATCH = int(os.environ.get("RECIPE_BENCH_BATCH", "256"))
IMAGE = int(os.environ.get("RECIPE_BENCH_IMAGE", "224"))
ARCH = os.environ.get("RECIPE_BENCH_ARCH", "resnet50")
ITERS = int(os.environ.get("RECIPE_BENCH_ITERS", "20"))


def bench_config(name, dtype, explicit, grad_compress, zero="none"):
    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.parallel import zero as zero_lib
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = data_parallel_mesh()
    model = models.create_model(ARCH, num_classes=1000, dtype=dtype)
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, IMAGE, IMAGE, 3)), train=False)
    if zero == "wus" and explicit:
        momentum0 = zero_lib.init_wus_momentum(
            variables["params"], mesh.shape["data"],
            quantized=grad_compress in ("int8", "fp8"))
    else:
        momentum0 = sgd_init(variables["params"])
    state = TrainState.create(variables, momentum0)
    step = make_train_step(model, mesh, explicit_collectives=explicit,
                           grad_compress=grad_compress, zero=zero,
                           params=variables["params"])
    rng = np.random.default_rng(0)
    batch = {
        "images": jnp.asarray(
            rng.normal(size=(BATCH, IMAGE, IMAGE, 3)).astype(np.float32)),
        "labels": jnp.asarray(
            rng.integers(0, 1000, size=BATCH).astype(np.int32)),
        "weights": jnp.ones((BATCH,), jnp.float32),
    }
    lr = jnp.float32(0.1)
    for _ in range(3):
        state, met = step(state, batch, lr)
    float(met["loss"])  # value fetch = real sync on this platform
    t0 = time.perf_counter()
    for _ in range(ITERS):
        state, met = step(state, batch, lr)
    float(met["loss"])
    dt = (time.perf_counter() - t0) / ITERS
    rate = BATCH / dt / jax.device_count()
    print(f"{name}: {dt * 1e3:.1f} ms/step -> {rate:,.0f} img/s/chip",
          flush=True)
    return {"ms_per_step": round(dt * 1e3, 1),
            "img_per_sec_per_chip": round(rate, 1)}


def main() -> int:
    results = {}
    for name, dtype, explicit, gc, zero in (
        ("gspmd_f32", jnp.float32, False, None, "none"),
        ("gspmd_bf16", jnp.bfloat16, False, None, "none"),
        ("explicit_bf16_wire", jnp.bfloat16, True, "bf16", "none"),
        # --zero wus on the explicit step: reduce-scatter + sharded update
        # + delta all-gather; wire-parity with the ring all-reduce, so
        # step time should match explicit_bf16_wire within noise while
        # holding 1/N of the optimizer state (experiments/zero_memory.py).
        ("explicit_bf16_zero", jnp.bfloat16, True, "bf16", "wus"),
    ):
        results[name] = bench_config(name, dtype, explicit, gc, zero)

    out = {
        "meta": {
            "arch": ARCH, "batch": BATCH, "image": IMAGE, "iters": ITERS,
            "devices": jax.device_count(),
            "platform": jax.default_backend(),
            "reference": "fig1: DataParallel 3.48x slower than DDP on "
                         "4xV100 (reference README.md:15)",
            "dataparallel_note": "not benchmarked separately: the "
                                 "dataparallel recipe builds the SAME "
                                 "gspmd_bf16 step over the same mesh "
                                 "(single process, GSPMD) — there is no "
                                 "scatter/gather master-device bottleneck "
                                 "to measure, vs the reference's 3.48x",
        },
        "configs": results,
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_recipes.json"), "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
