#!/usr/bin/env python
"""Accuracy cost of int8 weight-only quantization, measured on real text.

The serving-side counterpart of RESULTS_lm_text.json: train the byte-LM on
the in-repo corpus, then score the SAME held-out windows with the fp
params and with the int8-quantized tree (models/quant.py) through one
shared eval implementation.  The deliverable is the perplexity delta —
the number a user trades for halving the decode parameter stream.

Writes ``RESULTS_quant_ppl.json``.  Run (CPU 8-device mesh, ~10 min):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/quant_ppl.py
"""

from __future__ import annotations

import json
import math
import os

import jax

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))

SEQ = int(os.environ.get("QUANTPPL_SEQ", "256"))
D_MODEL = int(os.environ.get("QUANTPPL_D", "128"))
STEPS = int(os.environ.get("QUANTPPL_STEPS", "300"))
BATCH = 16
EVAL_BATCHES = int(os.environ.get("QUANTPPL_EVAL_BATCHES", "8"))


def eval_ppl(model, params, ds, n_batches: int) -> float:
    """Mean held-out token perplexity — one implementation for both trees."""
    import jax.numpy as jnp
    import numpy as np

    total_nll, total_tok = 0.0, 0
    for b in range(n_batches):
        idx = [(b * BATCH + i) % len(ds) for i in range(BATCH)]
        win = np.stack([np.asarray(ds[i]) for i in idx])  # [B, SEQ] bytes
        # (SEQ-byte windows ⇒ SEQ-1 scored targets per window)
        tokens = jnp.asarray(win[:, :-1].astype(np.int32))
        targets = jnp.asarray(win[:, 1:].astype(np.int32))
        logits = model.apply({"params": params}, tokens)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)
        total_nll += float(nll.sum())
        total_tok += targets.size
    return math.exp(total_nll / total_tok)


def main() -> int:
    from experiments.lm_text import corpus_paths
    from pytorch_distributed_tpu.models.quant import quantize_lm_params
    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.train.lm import (
        LMTrainer,
        TextFileDataset,
        warmup_cosine_lr,
    )

    n = jax.device_count()
    mesh = build_mesh(MeshSpec(("data",), (n,)))
    paths = corpus_paths()
    train_ds = TextFileDataset(paths, SEQ, span=(0.0, 0.9))
    eval_ds = TextFileDataset(paths, SEQ, span=(0.9, 1.0))

    cfg = dict(vocab_size=256, d_model=D_MODEL, n_heads=4, n_layers=2)
    model = TransformerLM(**cfg)
    with mesh:
        trainer = LMTrainer(
            model, mesh, train_ds, BATCH, lr=0.5,
            lr_schedule=warmup_cosine_lr(0.5, max(10, STEPS // 20), STEPS),
            clip_grad_norm=1.0,
        )
        trainer.fit(STEPS, print_freq=max(50, STEPS // 4))
        params = jax.device_get(trainer.state.params)

    fp_ppl = eval_ppl(TransformerLM(**cfg), params, eval_ds, EVAL_BATCHES)
    q_ppl = eval_ppl(TransformerLM(**cfg, quant="int8"),
                     quantize_lm_params(params), eval_ds, EVAL_BATCHES)
    delta_pct = 100.0 * (q_ppl - fp_ppl) / fp_ppl
    print(f"held-out ppl: fp {fp_ppl:.3f}  int8 {q_ppl:.3f}  "
          f"delta {delta_pct:+.2f}%", flush=True)

    out = {
        "meta": {
            "what": "held-out byte-LM perplexity, fp vs int8 weight-only "
                    "(models/quant.py), same eval code and windows",
            "corpus": "in-repo corpus (experiments/lm_text.py split)",
            "model": {**cfg, "seq": SEQ},
            "steps": STEPS, "batch": BATCH,
            "eval_windows": EVAL_BATCHES * BATCH,
            "note": "SEQ-byte windows => SEQ-1 scored targets per window "
                    "(TextFileDataset returns SEQ bytes)",
        },
        "fp_ppl": round(fp_ppl, 3),
        "int8_ppl": round(q_ppl, 3),
        "delta_pct": round(delta_pct, 3),
    }
    with open(os.path.join(REPO, "RESULTS_quant_ppl.json"), "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print("wrote RESULTS_quant_ppl.json", flush=True)
    # Weight-only int8 at per-channel scales should cost ~nothing; fail
    # loudly if it doesn't, so the feature ships with a falsifiable claim.
    assert q_ppl <= fp_ppl * 1.05, (fp_ppl, q_ppl)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
