#!/usr/bin/env python
"""Per-device optimizer+gradient memory under ``--zero wus`` weight-update
sharding vs replicated DP, on a 4-way data mesh — plus the 40-step loss
parity and wire-byte parity that make the reclaim a free lunch.

Replicated DP (the reference layout, and every recipe's default) keeps the
full f32 momentum tree on every chip and all-reduces the full gradient
tree: per device that is ``4P (momentum) + 4P (synced grads) = 8P`` bytes
for ``P`` parameters.  ``--zero wus`` (parallel/zero.py, arxiv 2004.13336)
reduce-scatters gradients to a 1/N chunk, keeps momentum as that same 1/N
chunk, and all-gathers only the parameter delta: ``P + P = 2P`` per
device on the 4-way mesh — a ~4x reduction in the state this experiment
meters, at wire-byte parity (the ring all-reduce IS a reduce-scatter +
all-gather; WUS just applies the optimizer between the hops).

Three measurements per mode, same compiled-peak methodology as
experiments/fused_ce_memory.py:

1. **optimizer+gradient bytes** (the headline): live per-device momentum
   shard bytes (from the trained state's addressable shards) + the
   grad_sync-phase collective result bytes from the compiled comm ledger
   (obs/comms.py) — asserted >= 2x smaller under wus; the memory
   ledger's ``opt_state`` class peak (obs/memory.py) reproduces the
   reclaim from the compiled HLO alone, asserted >= 3.5x;
2. **compiled peak** (temp+argument+output, ``memory_analysis()``) —
   asserted not to regress;
3. **40-step A/B** on identical synthetic batches — final-loss relative
   delta asserted <= 0.1%, plus the analytic-vs-ledger grad_sync residual
   (obs/flops.py image_comm_bytes_zero) fenced at ±15% and the
   zero-vs-replicated wire ratio pinned near 1.

Writes ``RESULTS_zero_memory.json``.  CPU-safe (4 host devices):

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    PYTHONPATH=/root/repo python experiments/zero_memory.py
"""

from __future__ import annotations

import json
import os
import sys

import jax
import numpy as np

if "xla_force_host_platform_device_count" in os.environ.get("XLA_FLAGS", ""):
    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass

DP = int(os.environ.get("ZM_DP", "4"))
WIDTH = int(os.environ.get("ZM_WIDTH", "1024"))
STEPS = int(os.environ.get("ZM_STEPS", "40"))
BATCH = int(os.environ.get("ZM_BATCH", "32"))
IMAGE = 8
CLASSES = 10


def _model():
    import flax.linen as nn

    class MLP(nn.Module):
        @nn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(WIDTH)(x))
            x = nn.relu(nn.Dense(WIDTH)(x))
            return nn.Dense(CLASSES)(x)

    return MLP()


def _batches(rng):
    for _ in range(STEPS):
        yield {
            "images": rng.normal(size=(BATCH, IMAGE, IMAGE, 3)).astype(
                np.float32),
            "labels": rng.integers(0, CLASSES, size=BATCH).astype(np.int32),
            "weights": np.ones((BATCH,), np.float32),
        }


def run_mode(zero: str) -> dict:
    import jax.numpy as jnp

    from pytorch_distributed_tpu.obs import comms
    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh
    from pytorch_distributed_tpu.parallel import zero as zero_lib
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = build_mesh(MeshSpec(("data",), (DP,)), jax.devices()[:DP])
    model = _model()
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, IMAGE, IMAGE, 3)), train=False)
    params = variables["params"]
    if zero == "wus":
        momentum0 = zero_lib.init_wus_momentum(params, DP)
    else:
        momentum0 = sgd_init(params)
    state = TrainState.create(variables, momentum0, residual={})
    step = make_train_step(model, mesh, explicit_collectives=True, zero=zero)

    rng = np.random.default_rng(0)
    batches = list(_batches(rng))
    # One AOT compile feeds both ledgers: the comm ledger (wire parity)
    # and the memory ledger (the headline reclaim, now reproducible from
    # the ledger alone — no live-shard inspection needed).
    from pytorch_distributed_tpu.obs import memory

    ledger_args = (state, batches[0], jnp.float32(0.05))
    compiled = step.lower(*ledger_args).compile()
    text = compiled.as_text()
    ledger = comms.ledger_from_hlo_text(text, step=f"zero_{zero}",
                                        mesh_shape=dict(mesh.shape))
    ledger.peak_hbm_bytes = comms.compiled_peak_bytes(compiled)
    mled = memory.ledger_from_compiled(
        compiled, step=f"zero_{zero}", mesh_shape=dict(mesh.shape),
        arg_classes=memory.arg_classes_of(ledger_args), hlo_text=text)

    loss = None
    lr = jnp.float32(0.05)
    for b in batches:
        state, metrics = step(state, b, lr)
        loss = metrics["loss"]
    loss = float(loss)

    # Live per-device momentum bytes: one addressable shard per leaf.
    mom_bytes = sum(
        leaf.addressable_shards[0].data.nbytes
        for leaf in jax.tree_util.tree_leaves(state.momentum))
    # grad_sync phase = the *persistent* synced-grad buffer: the full
    # all-reduced tree (replicated) or the owned 1/N reduce-scatter chunk
    # (wus).  The wus delta all-gather lowers under the optimizer scope
    # and its output is transient (consumed by the fused update) — it
    # shows up in the compiled peak, which is asserted separately.
    grad_sync = ledger.by_phase().get("grad_sync",
                                      {"bytes": 0, "wire_bytes": 0.0})
    return {
        "final_loss": loss,
        "momentum_bytes_per_device": int(mom_bytes),
        "grad_sync_result_bytes": int(grad_sync["bytes"]),
        "total_result_bytes": int(ledger.total_bytes),
        "total_wire_bytes": float(ledger.total_wire_bytes),
        "opt_plus_grad_bytes": int(mom_bytes + grad_sync["bytes"]),
        "peak_hbm_bytes": int(ledger.peak_hbm_bytes),
        # Memory-ledger view (obs/memory.py): the optimizer-state class
        # peak is the per-device momentum footprint read from the compiled
        # HLO alone — it must reproduce the live-shard measurement above.
        "mem_opt_state_peak_bytes": int(
            mled.class_peaks().get("opt_state", 0)),
        "mem_peak_bytes": int(mled.peak_bytes),
        "mem_residual_pct": round(mled.residual_pct(), 2),
        "collectives_by_kind": {
            k: int(v["count"]) for k, v in ledger.by_kind().items()},
        "leaf_sizes": [int(np.prod(np.shape(leaf)))
                       for leaf in jax.tree_util.tree_leaves(params)],
    }


def main() -> int:
    from pytorch_distributed_tpu.obs.flops import (
        comm_residual_pct,
        image_comm_bytes_zero,
        zero_wire_parity,
    )

    if len(jax.devices()) < DP:
        print(f"SKIP: only {len(jax.devices())} devices (need {DP}; set "
              "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
              flush=True)
        return 0

    repl = run_mode("none")
    wus = run_mode("wus")
    for tag, row in (("replicated", repl), ("wus", wus)):
        print(f"{tag}: opt+grad {row['opt_plus_grad_bytes']} B/device "
              f"(momentum {row['momentum_bytes_per_device']}, grad_sync "
              f"{row['grad_sync_result_bytes']}), peak "
              f"{row['peak_hbm_bytes'] / 2**20:.1f} MiB, loss "
              f"{row['final_loss']:.6f}", flush=True)

    reclaim = repl["opt_plus_grad_bytes"] / max(1, wus["opt_plus_grad_bytes"])
    ledger_reclaim = (repl["mem_opt_state_peak_bytes"]
                      / max(1, wus["mem_opt_state_peak_bytes"]))
    loss_delta_pct = (100.0 * abs(wus["final_loss"] - repl["final_loss"])
                      / abs(repl["final_loss"]))
    wire_ratio = wus["total_wire_bytes"] / max(1.0, repl["total_wire_bytes"])

    # Analytic fence: the obs/flops.py zero model must agree with the
    # compiled ledger's total collective result bytes within ±15% (the
    # handful of 4-byte scalar metric psums are noise at this scale).
    predicted = image_comm_bytes_zero(
        wus["leaf_sizes"], dp=DP, metric_scalars=0)
    residual = comm_residual_pct(predicted.total_bytes,
                                 wus["total_result_bytes"])
    parity = zero_wire_parity(wus["leaf_sizes"], dp=DP)

    out = {
        "meta": {
            "dp": DP, "width": WIDTH, "steps": STEPS, "batch": BATCH,
            "platform": jax.default_backend(),
            "what": "per-device optimizer+gradient bytes (live momentum "
                    "shards + compiled grad_sync collective results) of the "
                    "explicit-collectives image step, --zero wus vs "
                    "replicated DP on a 4-way mesh; compiled-peak and "
                    "40-step loss parity ride along (fused_ce_memory.py "
                    "methodology).  Wire parity: the measured grad_sync "
                    "wire bytes and the obs/flops.py analytic model agree "
                    "that RS+AG costs what the ring all-reduce cost",
        },
        "replicated": repl,
        "wus": wus,
        "opt_grad_reclaim_factor": round(reclaim, 2),
        "opt_state_reclaim_from_mem_ledger": round(ledger_reclaim, 2),
        "final_loss_delta_pct": round(loss_delta_pct, 5),
        "wire_ratio_wus_over_repl": round(wire_ratio, 4),
        "analytic_total_bytes": round(predicted.total_bytes, 1),
        "analytic_vs_ledger_residual_pct": round(residual, 2),
        "analytic_wire_parity": {k: round(v, 4) for k, v in parity.items()},
    }
    here = os.path.dirname(os.path.abspath(__file__))
    with open(os.path.join(here, "..", "RESULTS_zero_memory.json"),
              "w") as f:
        json.dump(out, f, indent=1)
        f.write("\n")
    print(json.dumps(out), flush=True)

    # Falsifiable claims (the ISSUE-9 acceptance bar):
    # (N-1)/N of optimizer+synced-grad bytes reclaimed -> >= 2x on DP=4
    assert reclaim >= 2.0, reclaim
    # ...and the memory ledger reproduces the reclaim from the compiled
    # HLO alone: the replicated momentum class peak is ~4x the wus shard
    assert ledger_reclaim >= 3.5, (
        ledger_reclaim, repl["mem_opt_state_peak_bytes"],
        wus["mem_opt_state_peak_bytes"])
    # the static watermark tracks memory_analysis on both lowerings.
    # ±15% here (vs ±10% on the recipe sweep): this MLP is wide enough
    # that collective scratch dominates the temp set, and XLA:CPU
    # all-reduces the gradient tree in place — a sharing the conservative
    # watermark declines to assume, overshooting by roughly one grad tree.
    for row in (repl, wus):
        assert row["mem_residual_pct"] <= 15.0, row
    # equal-numerics: 40-step final loss within 0.1% of replicated DP
    assert loss_delta_pct <= 0.1, loss_delta_pct
    # free lunch: wus wire bytes within 5% of the all-reduce's (padding)
    assert wire_ratio <= 1.05, wire_ratio
    # the analytic model tracks the lowering
    assert residual <= 15.0, residual
    # compiled peak must not regress
    assert wus["peak_hbm_bytes"] <= repl["peak_hbm_bytes"] * 1.02, (
        wus["peak_hbm_bytes"], repl["peak_hbm_bytes"])
    return 0


if __name__ == "__main__":
    sys.exit(main())
