#!/usr/bin/env python
"""Per-architecture training throughput across the model zoo, on the chip.

The reference's surface is "any torchvision arch by name" (``models.__dict__
[args.arch]()``, distributed.py:134-139) but its single published experiment
times only one arch.  This sweep puts a real number on a representative
slice of the 36-arch zoo: full compiled train step (fwd+bwd+SGD, bf16
compute, f32 BN/softmax), synthetic in-device data, one chip — the same
discipline as bench.py, minus the resnet50-specific space-to-depth stem so
every row is the arch's *default* config (the tuned resnet50 headline lives
in BENCH_*.json).

Per-arch global batch starts at 256 and halves on OOM/VMEM-capacity
failure (deterministic errors fail the arch immediately); the fallback
batch is recorded in the row.  Inception runs its canonical 299 input;
everything else 224.

Run on the TPU chip:
    PYTHONPATH=/root/repo:/root/.axon_site python experiments/arch_bench.py
"""

from __future__ import annotations

import json
import os

import numpy as np

ITERS = int(os.environ.get("ARCH_BENCH_ITERS", "10"))
ARCHS = os.environ.get(
    "ARCH_BENCH_ARCHS",
    "alexnet,vgg16_bn,resnet18,resnet34,resnet50,resnet101,resnet152,"
    "wide_resnet50_2,resnext50_32x4d,densenet121,mobilenet_v2,"
    "inception_v3,vit_b_16",
).split(",")


def bench_arch(arch: str):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import models
    from pytorch_distributed_tpu.parallel import data_parallel_mesh
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    from pytorch_distributed_tpu.utils.benchstep import (
        looks_like_oom,
        measure_train_step,
    )

    image = 299 if arch == "inception_v3" else 224
    mesh = data_parallel_mesh()
    rng = np.random.default_rng(0)
    last_err = None
    for batch in (256, 128, 64):
        try:
            device_batch = {
                "images": jnp.asarray(
                    rng.normal(size=(batch, image, image, 3)),
                    dtype=jnp.bfloat16),
                "labels": jnp.asarray(
                    rng.integers(0, 1000, size=batch).astype(np.int32)),
                "weights": jnp.ones((batch,), jnp.float32),
            }
            model = models.create_model(
                arch, num_classes=1000, dtype=jnp.bfloat16)
            variables = model.init(
                jax.random.PRNGKey(0), jnp.zeros((1, image, image, 3)),
                train=False)
            n_params = sum(
                x.size for x in jax.tree_util.tree_leaves(
                    variables["params"]))
            state = TrainState.create(variables, sgd_init(variables["params"]))
            step = make_train_step(model, mesh)
            dt, _ = measure_train_step(
                step, state, device_batch, jnp.float32(0.1), iters=ITERS)
            return {
                "img_per_sec_per_chip": round(
                    batch / dt / jax.device_count(), 1),
                "ms_per_step": round(dt * 1e3, 2),
                "batch": batch,
                "image": image,
                "params_m": round(n_params / 1e6, 1),
            }
        except Exception as e:  # noqa: BLE001
            if not looks_like_oom(e):
                raise  # deterministic failure — halving cannot fix it
            last_err = e  # OOM/VMEM: halve the batch and retry
    raise RuntimeError(f"{arch} failed at every batch: {last_err!r}")


def main() -> int:
    import jax

    here = os.path.dirname(os.path.abspath(__file__))
    path = os.path.join(here, "..", "RESULTS_archs.json")
    # Resumable: keep rows already measured by a previous (partial) run so
    # a tunnel stall or timeout never costs completed archs.
    results = {}
    if os.path.exists(path):
        with open(path) as f:
            results = {k: v for k, v in json.load(f)["configs"].items()
                       if "error" not in v}

    def write():
        out = {
            "meta": {
                "platform": jax.default_backend(),
                "iters": ITERS,
                "precision": "bf16 compute, f32 BN/LN/softmax",
                "what": "full train step (fwd+bwd+SGD) per zoo arch, "
                        "default stem/config, synthetic in-device data, "
                        "one chip",
                "note": "resnet50's tuned (space-to-depth) headline is "
                        "BENCH_*.json; this table is the arch-by-name "
                        "surface (reference distributed.py:134-139) "
                        "measured as-is",
            },
            "configs": results,
        }
        tmp = path + ".tmp"  # atomic: a mid-write kill must not eat rows
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
            f.write("\n")
        os.replace(tmp, path)

    for arch in ARCHS:
        if arch in results:
            print(f"{arch}: kept from previous run", flush=True)
            continue
        try:
            row = bench_arch(arch)
        except Exception as e:  # noqa: BLE001 — record and continue
            print(f"{arch}: FAILED {repr(e)[:200]}", flush=True)
            results[arch] = {"error": repr(e)[:200]}
            write()
            continue
        results[arch] = row
        print(f"{arch}: {row['img_per_sec_per_chip']:,} img/s/chip  "
              f"({row['ms_per_step']} ms @ b{row['batch']}, "
              f"{row['params_m']}M params)", flush=True)
        write()
    print("wrote RESULTS_archs.json", flush=True)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
