#!/usr/bin/env bash
# Canonical launch lines, one per recipe — reference start.sh:1-5 parity.
# For smoke runs on a non-TPU host, prefix any line with
#   JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
# to simulate an 8-chip mesh on CPU.

# 1. self-contained multi-process DP (ref start.sh:1: python multiprocessing_distributed.py)
python -m pytorch_distributed_tpu.recipes.multiprocessing_distributed --data "$DATA"

# 2. external-launcher DP (ref start.sh:2: torch.distributed.launch --nproc_per_node=4 distributed.py)
#    On GPU-style clusters the launcher exports PTD_TPU_*; on a TPU pod none needed.
PTD_TPU_COORDINATOR=127.0.0.1:23456 PTD_TPU_NUM_PROCESSES=1 PTD_TPU_PROCESS_ID=0 \
  python -m pytorch_distributed_tpu.recipes.distributed --data "$DATA"

# 3. bf16 mixed precision (ref start.sh:3: torch.distributed.launch apex_distributed.py)
python -m pytorch_distributed_tpu.recipes.apex_distributed --data "$DATA"

# 4. explicit collectives + compressed wire grads (ref start.sh:4: horovodrun -np 4 horovod_distributed.py)
python -m pytorch_distributed_tpu.recipes.horovod_distributed --data "$DATA"
# python -m pytorch_distributed_tpu.recipes.horovod_distributed --data "$DATA" --sync-bn   # cross-replica BN moments (torch SyncBatchNorm; round 5)

# 5. multi-node SLURM / multi-slice pod (ref start.sh:5: srun -N2 --gres gpu:4 distributed_slurm_main.py)
# srun -N2 --ntasks-per-node=1 python -m pytorch_distributed_tpu.recipes.distributed_slurm_main --data "$DATA"

# 6. single-process DataParallel baseline (ref README.md:86: python dataparallel.py)
python -m pytorch_distributed_tpu.recipes.dataparallel --data "$DATA"

# 7. canonical TPU-native recipe (BASELINE.json north star)
python -m pytorch_distributed_tpu.recipes.tpu_native --data "$DATA" -a resnet50
# python -m pytorch_distributed_tpu.recipes.tpu_native --data "$DATA" -a resnet50 --fused-convbn   # BN-dx fold (round 4)

# 8. long-context LM pretraining (beyond reference): composable parallelism
python -m pytorch_distributed_tpu.recipes.lm_pretrain --tp 4 --seq-len 2048 -b 32 --steps 1000
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --sp 4 --seq-len 16384 -b 8 --steps 1000
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --tp 2 --sp 2 --seq-len 8192 -b 8 --steps 1000   # composed mesh
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --pp 4 --n-layers 8 -b 32 --steps 1000           # GPipe pipeline
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --pp 4 --schedule 1f1b --n-layers 8 -b 32 --microbatches 16 --steps 1000        # memory-bounded 1F1B
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --pp 4 --schedule interleaved --pp-virtual 2 --n-layers 8 -b 32 --steps 1000    # virtual-stage 1F1B
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --ep 4 --moe-top-k 2 -b 32 --steps 1000          # MoE top-2
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --pp 2 --sp 2 --tp 2 -b 16 --steps 1000          # quad mesh
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --fsdp --tp 2 -b 32 --steps 1000                 # ZeRO-3 + TP
# python -m pytorch_distributed_tpu.recipes.lm_pretrain --vocab 32000 --fused-ce 8 -b 16 --steps 1000     # fused tied-head+CE (big-vocab memory lever, round 5)

# 8b. LM serving (KV-cached decode; see also --tp N and --quant int8)
# python -m pytorch_distributed_tpu.recipes.lm_generate --resume runs/lm/checkpoint.msgpack --vocab 256 --prompt 'def main(' -n 64 --temperature 0.8 --top-p 0.9
# python -m pytorch_distributed_tpu.recipes.lm_generate --resume target.msgpack --spec-draft draft.msgpack --spec-gamma 4 --vocab 256 --prompt 'def main(' -n 64   # speculative decoding

# 9. full native input path on real data (C++ JPEG decode + u8 wire)
# python -m pytorch_distributed_tpu.recipes.tpu_native --data "$DATA" -a resnet50 --wire native
