"""GoogLeNet and Inception-v3 (flax.linen, NHWC) — the last two archs of the
reference's pinned torchvision-0.4 zoo namespace (reference
requirements.txt:2, introspected at distributed.py:21-23) missing from the
registry.

Structure follows the torchvision definitions (same branch widths, BN
``eps=1e-3``, bias-free convs) so top-1 oracles are comparable; TPU-first
choices are the same as the rest of the zoo: NHWC layout, bf16-capable
compute ``dtype`` with f32 BN statistics and an f32 classifier head.

Deliberate deltas (documented, not silent):

- **Aux classifiers are off by default** (``aux_logits=False``).  The
  reference's harness feeds a single logits tensor to the criterion
  (reference distributed.py:250-251); torchvision's train-mode tuple output
  would crash it.  With ``aux_logits=True`` the aux parameter trees exist
  (created at init, shapes input-size-independent) but the aux *compute*
  runs only under ``capture_aux=True``, which returns the aux logits for
  users who want the regularizer; ordinary forwards return main logits only
  and pay nothing for the heads.
- ``ceil_mode=True`` max pools are emulated with asymmetric (0,1) padding —
  identical arithmetic for the 224/299 input sizes these nets define
  (flax pools pad with ``-inf`` so the extra column never wins the max).
- torchvision's ``transform_input`` renormalization (a pretrained-weights
  compatibility shim) is not replicated; inputs follow the framework's own
  normalization pipeline (data/transforms.py).
"""

from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_tpu.models.simple import _adaptive_avg_pool


class BasicConv2d(nn.Module):
    """conv(bias=False) + BN(eps=1e-3) + ReLU — both nets' unit cell."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = ((0, 0), (0, 0))
    dtype: Any = jnp.float32
    bn_axis_name: Any = None  # SyncBN mesh axis (torch SyncBatchNorm ≙)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features, self.kernel, self.strides, padding=self.padding,
            use_bias=False, dtype=self.dtype, name="conv",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=0.9, epsilon=1e-3,
            dtype=self.dtype, name="bn", axis_name=self.bn_axis_name,
        )(x)
        return nn.relu(x)


def _ceil_max_pool(x):
    """3x3/s2 max pool with torch ``ceil_mode=True`` arithmetic."""
    return nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(0, 1), (0, 1)])


def _ceil_max_pool2(x):
    """2x2/s2 max pool with ``ceil_mode=True`` (torchvision GoogLeNet's
    maxpool4): pad odd spatial dims by one so the last element still forms
    a window (flax pads with -inf, so padding never wins the max)."""
    ph, pw = x.shape[1] % 2, x.shape[2] % 2
    return nn.max_pool(x, (2, 2), strides=(2, 2), padding=[(0, ph), (0, pw)])


# ------------------------------------------------------------------ GoogLeNet
class _Inception(nn.Module):
    """GoogLeNet inception block: 1x1 / 1x1→3x3 / 1x1→3x3 / pool→1x1.

    (torchvision implements the historical "5x5" branch as 3x3 — a known,
    kept quirk; widths below match it.)
    """

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        b1 = conv(self.c1, (1, 1), name="branch1")(x, train)
        b2 = conv(self.c3r, (1, 1), name="branch2_0")(x, train)
        b2 = conv(self.c3, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch2_1")(b2, train)
        b3 = conv(self.c5r, (1, 1), name="branch3_0")(x, train)
        b3 = conv(self.c5, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch3_1")(b3, train)
        b4 = nn.max_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)])
        b4 = conv(self.cp, (1, 1), name="branch4_1")(b4, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class _GoogLeNetAux(nn.Module):
    """Aux head: adaptive-4x4-avg-pool → 1x1 conv 128 → fc1024 → dropout .7
    → fc (torchvision geometry; adaptive pool keeps the fc1 shape 2048
    whatever the input size)."""

    num_classes: int
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = _adaptive_avg_pool(x, 4)
        x = BasicConv2d(128, (1, 1), dtype=self.dtype,
                        bn_axis_name=self.bn_axis_name,
                        name="conv")(x, train)
        x = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        x = nn.relu(nn.Dense(1024, name="fc1")(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        return nn.Dense(self.num_classes, name="fc2")(x)


class GoogLeNet(nn.Module):
    """GoogLeNet (Inception v1), torchvision widths."""

    num_classes: int = 1000
    aux_logits: bool = False
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True, capture_aux: bool = False):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        inc = functools.partial(_Inception, dtype=self.dtype,
                                bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        x = conv(64, (7, 7), (2, 2), ((3, 3), (3, 3)), name="conv1")(x, train)
        x = _ceil_max_pool(x)
        x = conv(64, (1, 1), name="conv2")(x, train)
        x = conv(192, (3, 3), padding=((1, 1), (1, 1)), name="conv3")(x, train)
        x = _ceil_max_pool(x)
        x = inc(64, 96, 128, 16, 32, 32, name="inception3a")(x, train)
        x = inc(128, 128, 192, 32, 96, 64, name="inception3b")(x, train)
        x = _ceil_max_pool(x)
        x = inc(192, 96, 208, 16, 48, 64, name="inception4a")(x, train)
        aux1 = aux2 = None
        # Aux heads materialize their params at init but skip the (discarded)
        # compute on ordinary forwards — only capture_aux pays for them.
        want_aux = self.aux_logits and (capture_aux or self.is_initializing())
        if want_aux:
            aux1 = _GoogLeNetAux(self.num_classes, self.dtype,
                                 bn_axis_name=self.bn_axis_name,
                                 name="aux1")(x, train)
        x = inc(160, 112, 224, 24, 64, 64, name="inception4b")(x, train)
        x = inc(128, 128, 256, 24, 64, 64, name="inception4c")(x, train)
        x = inc(112, 144, 288, 32, 64, 64, name="inception4d")(x, train)
        if want_aux:
            aux2 = _GoogLeNetAux(self.num_classes, self.dtype,
                                 bn_axis_name=self.bn_axis_name,
                                 name="aux2")(x, train)
        x = inc(256, 160, 320, 32, 128, 128, name="inception4e")(x, train)
        x = _ceil_max_pool2(x)
        x = inc(256, 160, 320, 32, 128, 128, name="inception5a")(x, train)
        x = inc(384, 192, 384, 48, 128, 128, name="inception5b")(x, train)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dropout(0.2, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32)
        )
        if capture_aux and self.aux_logits:
            return logits, (aux1, aux2)
        return logits


# --------------------------------------------------------------- Inception v3
class _InceptionA(nn.Module):
    pool_features: int
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        b1 = conv(64, (1, 1), name="branch1x1")(x, train)
        b5 = conv(48, (1, 1), name="branch5x5_1")(x, train)
        b5 = conv(64, (5, 5), padding=((2, 2), (2, 2)),
                  name="branch5x5_2")(b5, train)
        b3 = conv(64, (1, 1), name="branch3x3dbl_1")(x, train)
        b3 = conv(96, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch3x3dbl_2")(b3, train)
        b3 = conv(96, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch3x3dbl_3")(b3, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)],
                         count_include_pad=True)
        bp = conv(self.pool_features, (1, 1), name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class _InceptionB(nn.Module):
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        b3 = conv(384, (3, 3), (2, 2), name="branch3x3")(x, train)
        bd = conv(64, (1, 1), name="branch3x3dbl_1")(x, train)
        bd = conv(96, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch3x3dbl_2")(bd, train)
        bd = conv(96, (3, 3), (2, 2), name="branch3x3dbl_3")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, bd, bp], axis=-1)


class _InceptionC(nn.Module):
    c7: int
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        c7 = self.c7
        p71 = ((0, 0), (3, 3))  # 1x7
        p17 = ((3, 3), (0, 0))  # 7x1
        b1 = conv(192, (1, 1), name="branch1x1")(x, train)
        b7 = conv(c7, (1, 1), name="branch7x7_1")(x, train)
        b7 = conv(c7, (1, 7), padding=p71, name="branch7x7_2")(b7, train)
        b7 = conv(192, (7, 1), padding=p17, name="branch7x7_3")(b7, train)
        bd = conv(c7, (1, 1), name="branch7x7dbl_1")(x, train)
        bd = conv(c7, (7, 1), padding=p17, name="branch7x7dbl_2")(bd, train)
        bd = conv(c7, (1, 7), padding=p71, name="branch7x7dbl_3")(bd, train)
        bd = conv(c7, (7, 1), padding=p17, name="branch7x7dbl_4")(bd, train)
        bd = conv(192, (1, 7), padding=p71, name="branch7x7dbl_5")(bd, train)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)],
                         count_include_pad=True)
        bp = conv(192, (1, 1), name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class _InceptionD(nn.Module):
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        b3 = conv(192, (1, 1), name="branch3x3_1")(x, train)
        b3 = conv(320, (3, 3), (2, 2), name="branch3x3_2")(b3, train)
        b7 = conv(192, (1, 1), name="branch7x7x3_1")(x, train)
        b7 = conv(192, (1, 7), padding=((0, 0), (3, 3)),
                  name="branch7x7x3_2")(b7, train)
        b7 = conv(192, (7, 1), padding=((3, 3), (0, 0)),
                  name="branch7x7x3_3")(b7, train)
        b7 = conv(192, (3, 3), (2, 2), name="branch7x7x3_4")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2))
        return jnp.concatenate([b3, b7, bp], axis=-1)


class _InceptionE(nn.Module):
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        b1 = conv(320, (1, 1), name="branch1x1")(x, train)
        b3 = conv(384, (1, 1), name="branch3x3_1")(x, train)
        b3 = jnp.concatenate([
            conv(384, (1, 3), padding=((0, 0), (1, 1)),
                 name="branch3x3_2a")(b3, train),
            conv(384, (3, 1), padding=((1, 1), (0, 0)),
                 name="branch3x3_2b")(b3, train),
        ], axis=-1)
        bd = conv(448, (1, 1), name="branch3x3dbl_1")(x, train)
        bd = conv(384, (3, 3), padding=((1, 1), (1, 1)),
                  name="branch3x3dbl_2")(bd, train)
        bd = jnp.concatenate([
            conv(384, (1, 3), padding=((0, 0), (1, 1)),
                 name="branch3x3dbl_3a")(bd, train),
            conv(384, (3, 1), padding=((1, 1), (0, 0)),
                 name="branch3x3dbl_3b")(bd, train),
        ], axis=-1)
        bp = nn.avg_pool(x, (3, 3), strides=(1, 1), padding=[(1, 1), (1, 1)],
                         count_include_pad=True)
        bp = conv(192, (1, 1), name="branch_pool")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class _InceptionAux(nn.Module):
    """v3 aux head: 5x5 avg pool s3 → 128 1x1 → 768 5x5 → fc.

    Kernel shapes are FIXED (conv1 is always 5x5) so the parameter tree is
    input-size-independent and matches torchvision's at any size; at the
    canonical 299 input the math is exactly torchvision's (17x17 feature map
    → 5x5 pooled → VALID 5x5 conv → 1x1).  Smaller maps clamp the pool
    window and switch conv1 to SAME padding so the head stays well-defined.
    """

    num_classes: int
    dtype: Any
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool):
        H, W = x.shape[1:3]
        x = nn.avg_pool(x, (min(5, H), min(5, W)), strides=(3, 3))
        x = BasicConv2d(128, (1, 1), dtype=self.dtype,
                        bn_axis_name=self.bn_axis_name,
                        name="conv0")(x, train)
        pad = "VALID" if min(x.shape[1:3]) >= 5 else "SAME"
        x = BasicConv2d(768, (5, 5), padding=pad, dtype=self.dtype,
                        bn_axis_name=self.bn_axis_name,
                        name="conv1")(x, train)
        x = jnp.mean(x, axis=(1, 2)).astype(jnp.float32)
        return nn.Dense(self.num_classes, name="fc")(x)


class InceptionV3(nn.Module):
    """Inception v3 (299x299 canonical input; any size ≥ 75 works — the
    classifier head is a global mean pool and the aux head clamps its pool
    window on small feature maps)."""

    num_classes: int = 1000
    aux_logits: bool = False
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True, capture_aux: bool = False):
        conv = functools.partial(BasicConv2d, dtype=self.dtype,
                                 bn_axis_name=self.bn_axis_name)
        x = x.astype(self.dtype)
        x = conv(32, (3, 3), (2, 2), name="Conv2d_1a_3x3")(x, train)
        x = conv(32, (3, 3), name="Conv2d_2a_3x3")(x, train)
        x = conv(64, (3, 3), padding=((1, 1), (1, 1)),
                 name="Conv2d_2b_3x3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = conv(80, (1, 1), name="Conv2d_3b_1x1")(x, train)
        x = conv(192, (3, 3), name="Conv2d_4a_3x3")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2))
        x = _InceptionA(32, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_5b")(x, train)
        x = _InceptionA(64, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_5c")(x, train)
        x = _InceptionA(64, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_5d")(x, train)
        x = _InceptionB(self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_6a")(x, train)
        x = _InceptionC(128, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_6b")(x, train)
        x = _InceptionC(160, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_6c")(x, train)
        x = _InceptionC(160, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_6d")(x, train)
        x = _InceptionC(192, self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_6e")(x, train)
        aux = None
        if self.aux_logits and (capture_aux or self.is_initializing()):
            aux = _InceptionAux(self.num_classes, self.dtype,
                                bn_axis_name=self.bn_axis_name,
                                name="AuxLogits")(x, train)
        x = _InceptionD(self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_7a")(x, train)
        x = _InceptionE(self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_7b")(x, train)
        x = _InceptionE(self.dtype, bn_axis_name=self.bn_axis_name, name="Mixed_7c")(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32)
        )
        if capture_aux and self.aux_logits:
            return logits, aux
        return logits


def googlenet(num_classes: int = 1000, dtype: Any = jnp.float32, **kw):
    return GoogLeNet(num_classes=num_classes, dtype=dtype, **kw)


def inception_v3(num_classes: int = 1000, dtype: Any = jnp.float32, **kw):
    return InceptionV3(num_classes=num_classes, dtype=dtype, **kw)
