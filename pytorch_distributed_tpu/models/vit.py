"""Vision Transformer family (flax.linen, TPU-first).

Beyond the reference's torchvision-0.4 zoo (its requirements.txt:2 predates
ViT), but squarely inside this framework's brief: where ResNet-50 training
is HBM-roofline-bound on TPU (see ROADMAP.md), a ViT is the MXU-native image
model — the whole network is large matmuls.  Architecture follows
torchvision's ``vit_b_16``-style encoder (class token, learned position
embeddings, pre-LN blocks, GELU MLP) so the ``-a vit_b_16`` gesture matches
what torchvision users expect.

TPU-first choices:
- patchify as reshape + one Dense (a pure-layout transform feeding a single
  [N·P², 3·p²]×[3·p², D] matmul — no conv im2col, tiles straight onto the
  MXU);
- bf16 compute policy with f32 LayerNorm/softmax accumulation and an f32
  head (same policy as the rest of the zoo);
- static shapes throughout: position embeddings take their grid shape from
  the init-time input (no image-size constructor knob to keep in sync); the
  class token rides as sequence position 0.

Reference anchor for the zoo surface: reference distributed.py:21-23
(arch-by-name instantiation); harness contract: ``__call__(images, train)``
like every image model here.
"""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


class EncoderBlock(nn.Module):
    n_heads: int
    mlp_dim: int
    dropout: float = 0.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_1")(x)
        h = nn.MultiHeadDotProductAttention(
            num_heads=self.n_heads,
            dtype=self.dtype,
            dropout_rate=self.dropout,
            deterministic=not train,
            # Zoo-wide numerics policy: softmax accumulates in f32 even
            # under the bf16 compute policy (same as transformer.py's
            # explicit f32 score path).
            force_fp32_for_softmax=True,
            name="self_attention",
        )(h, h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32, name="ln_2")(x)
        h = nn.Dense(self.mlp_dim, dtype=self.dtype, name="mlp_fc1")(h)
        h = nn.gelu(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        h = nn.Dense(x.shape[-1], dtype=self.dtype, name="mlp_fc2")(h)
        h = nn.Dropout(self.dropout, deterministic=not train)(h)
        return x + h


class VisionTransformer(nn.Module):
    patch_size: int = 16
    d_model: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    dropout: float = 0.0
    dtype: Any = jnp.float32
    # Checkpoint each encoder block: the backward recomputes block
    # internals instead of stashing them, cutting activation memory from
    # O(layers · k·L·D) to O(layers · L·D) block boundaries.  ViT-L/16 at
    # b128 stashes ~15 GB unchecked — past the chip's 16 GB HBM, so XLA
    # spills and the measured MFU collapses (11.9% vs vit_b's 46.5% on
    # v5e); remat trades ~1/3 more matmul FLOPs for staying resident.
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        N, H, W, C = x.shape
        p = self.patch_size
        if H % p or W % p:
            raise ValueError(
                f"image {H}x{W} not divisible by patch size {p}")
        x = x.astype(self.dtype)
        # Patchify: [N, H/p, p, W/p, p, C] -> [N, L, p*p*C] (layout only),
        # then embed with one Dense — the MXU-friendly conv-stem equivalent.
        gh, gw = H // p, W // p
        x = (
            x.reshape(N, gh, p, gw, p, C)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(N, gh * gw, p * p * C)
        )
        x = nn.Dense(self.d_model, dtype=self.dtype, name="patch_embed")(x)

        cls = self.param(
            "cls_token", nn.initializers.zeros, (1, 1, self.d_model),
            jnp.float32,
        )
        x = jnp.concatenate(
            [jnp.broadcast_to(cls, (N, 1, self.d_model)).astype(x.dtype), x],
            axis=1,
        )
        # Position embeddings are shaped by the init-time input: stored in
        # GRID shape (1, gh, gw, D) — not flat token count — so applying at
        # a different resolution OR a different aspect ratio with the same
        # patch count fails loudly on param-shape mismatch instead of
        # silently reusing geometrically wrong positions.
        pos = self.param(
            "pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (1, gh, gw, self.d_model),
            jnp.float32,
        )
        cls_pos = self.param(
            "cls_pos_embedding",
            nn.initializers.normal(stddev=0.02),
            (1, 1, self.d_model),
            jnp.float32,
        )
        pos_seq = jnp.concatenate(
            [cls_pos, pos.reshape(1, gh * gw, self.d_model)], axis=1
        )
        x = x + pos_seq.astype(x.dtype)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)

        block_cls = EncoderBlock
        if self.remat:
            # static_argnums: train is a Python bool, not a tracer (arg 0
            # is the module instance under nn.remat's calling convention).
            block_cls = nn.remat(EncoderBlock, static_argnums=(2,))
        for i in range(self.n_layers):
            x = block_cls(
                self.n_heads, self.mlp_dim, self.dropout, self.dtype,
                name=f"encoder_{i}",
            )(x, train)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        # Classify from the class token (torchvision ViT convention).
        return nn.Dense(
            self.num_classes, dtype=jnp.float32, name="head"
        )(x[:, 0])


vit_b_16 = functools.partial(
    VisionTransformer, patch_size=16, d_model=768, n_layers=12, n_heads=12,
    mlp_dim=3072,
)
vit_b_32 = functools.partial(
    VisionTransformer, patch_size=32, d_model=768, n_layers=12, n_heads=12,
    mlp_dim=3072,
)
vit_l_16 = functools.partial(
    VisionTransformer, patch_size=16, d_model=1024, n_layers=24, n_heads=16,
    mlp_dim=4096,
)
