"""AlexNet and VGG families (flax.linen, NHWC, dtype-policy aware).

Zoo-surface parity with the torchvision architectures the reference
instantiates by name (reference distributed.py:21-23): same stage/channel
configurations as torchvision's alexnet and vgg11/13/16/19 (+bn variants),
so ``-a vgg16`` etc. work across recipes.  Classifier heads run in f32.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence, Union

import flax.linen as nn
import jax.numpy as jnp


class AlexNet(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        x = x.astype(self.dtype)
        x = nn.relu(conv(64, (11, 11), (4, 4), padding=[(2, 2), (2, 2)])(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(192, (5, 5), padding=[(2, 2), (2, 2)])(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(conv(384, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.relu(conv(256, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.relu(conv(256, (3, 3), padding=[(1, 1), (1, 1)])(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        # torchvision adaptive-avg-pools to 6x6 before the classifier.
        x = _adaptive_avg_pool(x, 6)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


def _adaptive_avg_pool(x, out: int):
    """torch AdaptiveAvgPool2d semantics: output bin (i, j) averages input
    rows [⌊iH/out⌋, ⌈(i+1)H/out⌉) × cols [⌊jW/out⌋, ⌈(j+1)W/out⌉).  The
    bin loop is static (out² iterations), so XLA sees plain slices."""
    B, H, W, C = x.shape
    if H == out and W == out:
        return x
    if H % out == 0 and W % out == 0:
        return nn.avg_pool(x, (H // out, W // out), (H // out, W // out))
    rows = []
    for i in range(out):
        h0, h1 = (i * H) // out, -(-((i + 1) * H) // out)
        cols = []
        for j in range(out):
            w0, w1 = (j * W) // out, -(-((j + 1) * W) // out)
            cols.append(jnp.mean(x[:, h0:h1, w0:w1, :], axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=1))
    return jnp.stack(rows, axis=1)


_VGG_CFGS = {
    "A": [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "B": [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    "D": [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
          512, 512, 512, "M"],
    "E": [64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M"],
}


class VGG(nn.Module):
    cfg: Sequence[Union[int, str]]
    batch_norm: bool = False
    num_classes: int = 1000
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.bn_axis_name is not None and not self.batch_norm:
            raise ValueError(
                "bn_axis_name (--sync-bn) on a plain VGG: this variant "
                "has no BatchNorm layers to synchronize — use the "
                "*_bn arch or drop the flag")
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        x = x.astype(self.dtype)
        for v in self.cfg:
            if v == "M":
                x = nn.max_pool(x, (2, 2), (2, 2))
            else:
                x = conv(int(v), (3, 3), padding=[(1, 1), (1, 1)])(x)
                if self.batch_norm:
                    x = nn.BatchNorm(
                        use_running_average=not train, momentum=0.9,
                        epsilon=1e-5, dtype=self.dtype,
                        axis_name=self.bn_axis_name,
                    )(x)
                x = nn.relu(x)
        x = _adaptive_avg_pool(x, 7)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(0.5, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


alexnet = functools.partial(AlexNet)
vgg11 = functools.partial(VGG, cfg=_VGG_CFGS["A"])
vgg13 = functools.partial(VGG, cfg=_VGG_CFGS["B"])
vgg16 = functools.partial(VGG, cfg=_VGG_CFGS["D"])
vgg19 = functools.partial(VGG, cfg=_VGG_CFGS["E"])
vgg11_bn = functools.partial(VGG, cfg=_VGG_CFGS["A"], batch_norm=True)
vgg13_bn = functools.partial(VGG, cfg=_VGG_CFGS["B"], batch_norm=True)
vgg16_bn = functools.partial(VGG, cfg=_VGG_CFGS["D"], batch_norm=True)
vgg19_bn = functools.partial(VGG, cfg=_VGG_CFGS["E"], batch_norm=True)
