"""ResNet family (flax.linen), TPU-first.

Capability parity with the torchvision zoo the reference instantiates by name
(``models.__dict__[args.arch]()``, reference distributed.py:21-23,134-139):
resnet18/34/50/101/152 plus the wide and ResNeXt variants, same
block/stage/width structure and BatchNorm placement as the torchvision
definitions, so top-1/top-5 oracles are comparable.

TPU-first choices:
- **NHWC** layout (XLA's native conv layout on TPU; MXU-friendly).
- ``dtype`` policy: params live in f32, compute may be bf16 — the
  apex-AMP-equivalent (SURVEY.md §7.1 "bf16 compute/param policy"); BatchNorm
  statistics always accumulate in f32.
- BatchNorm over a data-sharded batch under GSPMD computes *global* batch
  statistics (XLA inserts the cross-replica mean) — i.e. SyncBN semantics,
  strictly stronger than torch DDP's local-stats BN; documented delta.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ops.fused_bn import FusedBatchNormAct
from pytorch_distributed_tpu.ops.fused_conv_bn import conv1x1_bn

ModuleDef = Any


def _fuse_ok(fused: bool, conv: ModuleDef, norm: ModuleDef) -> bool:
    """Shared fold gate: only stock nn.Conv / FusedBatchNormAct semantics
    may be replaced by the fused ops — a custom ModuleDef (or a partial
    carrying settings the combinator doesn't forward) keeps the unfused
    composition, or its settings would be silently dropped."""
    if not fused:
        return False
    if getattr(norm, "func", norm) is not FusedBatchNormAct:
        return False
    if getattr(conv, "func", conv) is not nn.Conv:
        return False
    if set(getattr(conv, "keywords", {})) - {"dtype"}:
        return False
    return not (set(getattr(norm, "keywords", {}))
                - {"use_running_average", "momentum", "epsilon"})


def _fuse_kw(conv: ModuleDef, norm: ModuleDef) -> dict:
    nkw = getattr(norm, "keywords", {})
    ckw = getattr(conv, "keywords", {})
    return dict(
        use_running_average=bool(nkw.get("use_running_average", False)),
        momentum=nkw.get("momentum", 0.9),
        eps=nkw.get("epsilon", 1e-5),
        dtype=ckw.get("dtype", jnp.float32),
    )


class BasicBlock(nn.Module):
    filters: int
    strides: int = 1
    expansion: int = 1
    groups: int = 1
    base_width: int = 64
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = FusedBatchNormAct
    # Fold the stride-1 3x3 conv→BN pairs (both mains when strides == 1,
    # the second always) through ops/fused_conv_bn's whole-plane kernel;
    # strided slots keep the XLA backward.  Param paths identical either
    # way (same guarantee as Bottleneck).
    fused_convbn: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        if not _fuse_ok(self.fused_convbn, self.conv, self.norm):
            y = self.conv(self.filters, (3, 3),
                          (self.strides, self.strides),
                          padding=[(1, 1), (1, 1)], use_bias=False)(x)
            y = self.norm(relu=True)(y)
            y = self.conv(self.filters, (3, 3), padding=[(1, 1), (1, 1)],
                          use_bias=False)(y)
            y = self.norm(scale_init=nn.initializers.zeros)(y)
            if residual.shape != y.shape:
                residual = self.conv(self.filters * self.expansion, (1, 1),
                                     (self.strides, self.strides),
                                     use_bias=False)(residual)
                residual = self.norm()(residual)
            return nn.relu(y + residual)

        fkw = _fuse_kw(self.conv, self.norm)
        if self.strides == 1:
            y = conv1x1_bn(self, "Conv_0", "FusedBatchNormAct_0", x,
                           self.filters, relu=True, kernel_size=(3, 3),
                           **fkw)
        else:
            y = self.conv(self.filters, (3, 3),
                          (self.strides, self.strides),
                          padding=[(1, 1), (1, 1)], use_bias=False,
                          name="Conv_0")(x)
            y = self.norm(relu=True, name="FusedBatchNormAct_0")(y)
        y = conv1x1_bn(self, "Conv_1", "FusedBatchNormAct_1", y,
                       self.filters, relu=False,
                       scale_init=nn.initializers.zeros,
                       kernel_size=(3, 3), **fkw)
        if residual.shape != y.shape:
            if self.strides == 1:
                residual = conv1x1_bn(self, "Conv_2", "FusedBatchNormAct_2",
                                      residual,
                                      self.filters * self.expansion,
                                      relu=False, **fkw)
            else:
                residual = self.conv(self.filters * self.expansion, (1, 1),
                                     (self.strides, self.strides),
                                     use_bias=False,
                                     name="Conv_2")(residual)
                residual = self.norm(name="FusedBatchNormAct_2")(residual)
        return nn.relu(y + residual)


class Bottleneck(nn.Module):
    filters: int
    strides: int = 1
    expansion: int = 4
    groups: int = 1
    base_width: int = 64
    conv: ModuleDef = nn.Conv
    norm: ModuleDef = FusedBatchNormAct
    # Route the 1x1 stride-1 conv→BN pairs (2-3 of the 4 convs per block)
    # through the fused-backward op (ops/fused_conv_bn.py) — dy never hits
    # HBM.  Param paths are IDENTICAL either way (the fused combinator
    # declares through child scopes), so checkpoints interchange freely.
    fused_convbn: bool = False

    @nn.compact
    def __call__(self, x):
        residual = x
        width = int(self.filters * (self.base_width / 64.0)) * self.groups
        out_ch = self.filters * self.expansion
        if not _fuse_ok(self.fused_convbn, self.conv, self.norm):
            y = self.conv(width, (1, 1), use_bias=False)(x)
            y = self.norm(relu=True)(y)
            y = self.conv(width, (3, 3), (self.strides, self.strides),
                          padding=[(1, 1), (1, 1)], use_bias=False,
                          feature_group_count=self.groups)(y)
            y = self.norm(relu=True)(y)
            y = self.conv(out_ch, (1, 1), use_bias=False)(y)
            # Zero-init the last BN scale so blocks start as identity
            # (torchvision zero_init_residual analogue; helps large-batch SGD).
            y = self.norm(scale_init=nn.initializers.zeros)(y)
            if residual.shape != y.shape:
                residual = self.conv(out_ch, (1, 1),
                                     (self.strides, self.strides),
                                     use_bias=False)(residual)
                residual = self.norm()(residual)
            return nn.relu(y + residual)

        # Fused branch: explicit child names reproduce the auto-assigned
        # paths of the branch above, slot for slot.
        fkw = _fuse_kw(self.conv, self.norm)
        y = conv1x1_bn(self, "Conv_0", "FusedBatchNormAct_0", x, width,
                       relu=True, **fkw)
        if self.strides == 1 and self.groups == 1:
            # the middle 3x3 folds too (stride-1 SAME, ungrouped)
            y = conv1x1_bn(self, "Conv_1", "FusedBatchNormAct_1", y, width,
                           relu=True, kernel_size=(3, 3), **fkw)
        else:
            y = self.conv(width, (3, 3), (self.strides, self.strides),
                          padding=[(1, 1), (1, 1)], use_bias=False,
                          feature_group_count=self.groups,
                          name="Conv_1")(y)
            y = self.norm(relu=True, name="FusedBatchNormAct_1")(y)
        y = conv1x1_bn(self, "Conv_2", "FusedBatchNormAct_2", y, out_ch,
                       relu=False, scale_init=nn.initializers.zeros, **fkw)
        if residual.shape != y.shape:
            if self.strides == 1:
                residual = conv1x1_bn(self, "Conv_3", "FusedBatchNormAct_3",
                                      residual, out_ch, relu=False, **fkw)
            else:
                residual = self.conv(out_ch, (1, 1),
                                     (self.strides, self.strides),
                                     use_bias=False, name="Conv_3")(residual)
                residual = self.norm(name="FusedBatchNormAct_3")(residual)
        return nn.relu(y + residual)


class _SpaceToDepthStem(nn.Module):
    """7x7/s2/p3 stem conv, computed as a 4x4/s1 conv on 2x2-space-to-depth
    packed input — the MLPerf TPU ResNet trick.

    A 3-channel 224x224 conv leaves the MXU's 128-lane contraction dimension
    ~2% utilized; packing 2x2 spatial blocks into channels turns the same
    arithmetic into a 12-channel conv at 112x112 that XLA tiles far better.
    **Mathematically identical** to the standard stem (same 7x7 kernel
    parameters, zero-padded to 8x8 and repacked at trace time; even input
    sizes required): the parameter is still ``conv_init/kernel`` of shape
    (7, 7, 3, features), so checkpoints are interchangeable with the
    ``conv7`` stem — equivalence is asserted by tests/test_model_zoo.py.
    """

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        N, H, W, C = x.shape
        if H % 2 or W % 2:
            raise ValueError(
                f"space_to_depth stem needs even spatial dims, got {H}x{W}")
        w7 = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (7, 7, C, self.features), jnp.float32,
        )
        # Output row h' of the s2/p3 7x7 conv reads input rows 2h'-3..2h'+3.
        # Aligning the window to the packed grid means basing it at 2h'-4,
        # i.e. an 8x8 kernel whose first row/col is zero; tap j of that
        # kernel is tap j-1 of the 7x7 one.
        w8 = jnp.pad(w7, ((1, 0), (1, 0), (0, 0), (0, 0)))
        wp = (
            w8.reshape(4, 2, 4, 2, C, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(4, 4, 4 * C, self.features)
        )
        xp = (
            x.reshape(N, H // 2, 2, W // 2, 2, C)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(N, H // 2, W // 2, 4 * C)
        )
        return jax.lax.conv_general_dilated(
            xp.astype(self.dtype), wp.astype(self.dtype),
            (1, 1), ((2, 1), (2, 1)),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )


class ResNet(nn.Module):
    stage_sizes: Sequence[int]
    block_cls: Callable
    num_classes: int = 1000
    num_filters: int = 64
    groups: int = 1
    base_width: int = 64
    dtype: Any = jnp.float32
    stem: str = "conv7"  # "conv7" (torchvision) | "space_to_depth" (same math)
    fused_convbn: bool = False  # fold BN-backward dx into the 1x1 dgrad/wgrad
    # SyncBN under shard_map: psum BN moments over this mesh axis (torch
    # nn.SyncBatchNorm ≙).  None = per-shard statistics (torch DDP default).
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        norm_kw = dict(
            use_running_average=not train,
            momentum=0.9,           # torch BatchNorm2d momentum=0.1 ⇒ ema decay 0.9
            epsilon=1e-5,
        )
        if self.bn_axis_name is not None:
            # Only set when active: the keyword disables the conv+BN fold
            # gate (_fuse_ok), which has no synced-stats kernel.
            norm_kw["axis_name"] = self.bn_axis_name
        norm = functools.partial(FusedBatchNormAct, **norm_kw)
        x = x.astype(self.dtype)
        if self.stem == "space_to_depth":
            x = _SpaceToDepthStem(self.num_filters, self.dtype,
                                  name="conv_init")(x)
        elif self.stem == "conv7":
            x = conv(self.num_filters, (7, 7), (2, 2),
                     padding=[(3, 3), (3, 3)], use_bias=False,
                     name="conv_init")(x)
        else:
            raise ValueError(f"unknown stem {self.stem!r}")
        x = norm(name="bn_init", relu=True)(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = self.block_cls(
                    filters=self.num_filters * 2**i,
                    strides=strides,
                    groups=self.groups,
                    base_width=self.base_width,
                    conv=conv,
                    norm=norm,
                    fused_convbn=self.fused_convbn,
                )(x)
        x = jnp.mean(x, axis=(1, 2))  # global average pool
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)
        return x


# Stage configurations mirror torchvision's resnet table.
resnet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2], block_cls=BasicBlock)
resnet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=BasicBlock)
resnet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck)
resnet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck)
resnet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3], block_cls=Bottleneck)
wide_resnet50_2 = functools.partial(
    ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, base_width=128
)
wide_resnet101_2 = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck, base_width=128
)
resnext50_32x4d = functools.partial(
    ResNet, stage_sizes=[3, 4, 6, 3], block_cls=Bottleneck, groups=32, base_width=4
)
resnext101_32x8d = functools.partial(
    ResNet, stage_sizes=[3, 4, 23, 3], block_cls=Bottleneck, groups=32, base_width=8
)
