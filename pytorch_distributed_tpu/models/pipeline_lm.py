"""Pipeline-parallel TransformerLM: stage-stacked blocks + GPipe schedule.

Integrates ``parallel/pp.py``'s microbatch pipeline into the LM family
(round-1 gap: PP existed only over toy affine stages).  The ``n_layers``
transformer blocks are split into ``n_stages`` equal stages; stage
parameters are stacked on a leading ``pipe`` axis and sharded over the
``pipe`` mesh axis, while activations stream through the GPipe schedule
(``pipeline_apply``: shard_map + ppermute + scan — compiled once,
differentiable, synchronous semantics).  Composes with data parallelism
over a ``("data", "pipe")`` mesh.

Duck-typed to the flax ``init``/``apply`` surface ``LMTrainer`` and
``make_lm_train_step`` consume, but functional underneath: the pipeline
schedule needs raw per-stage parameter slices, which a lifted flax
transform cannot hand to ``shard_map`` cleanly.

Beyond-reference capability (SURVEY.md §2.3 "Explicitly absent": pipeline
parallelism)."""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from pytorch_distributed_tpu.models.transformer import Block
from pytorch_distributed_tpu.parallel.pp import pipeline_apply


class _Stage(nn.Module):
    """One pipeline stage: ``n_blocks`` sequential transformer blocks."""

    n_blocks: int
    n_heads: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_blocks):
            x = Block(self.n_heads, self.dtype, name=f"block_{i}")(x)
        return x


class PipelinedTransformerLM:
    """``init(rng, tokens) -> {"params": ...}``;
    ``apply({"params": ...}, tokens[, mutable]) -> logits`` — the LM-harness
    model surface, with the forward running the GPipe schedule."""

    def __init__(
        self,
        vocab_size: int,
        d_model: int,
        n_heads: int,
        n_layers: int,
        n_stages: int,
        n_microbatches: int,
        mesh: Mesh,
        dtype: Any = jnp.float32,
        pipe_axis: str = "pipe",
        tp_size: int = 1,
        model_axis: str = "model",
        sp_size: int = 1,
        seq_axis: str = "seq",
        schedule: str = "gpipe",
        remat: bool = False,
        n_virtual: int = 1,
    ):
        """``tp_size > 1``: Megatron tensor parallelism INSIDE each stage
        (``parallel/tp_stage.py`` — explicit psums under the pipeline's
        shard_map) over ``model_axis``; the mesh must carry that axis.
        ``sp_size > 1``: ring sequence parallelism inside each stage over
        ``seq_axis`` (composable with ``tp_size``).

        ``schedule``: ``"gpipe"`` (autodiff through the forward pipeline,
        activation stash O(M)), ``"1f1b"`` (manual-gradient schedule, stash
        bounded at 2(P-1)+1 stage-inputs — ``parallel/pp_1f1b.py``), or
        ``"interleaved"`` (virtual-stage 1F1B: ``n_virtual`` chunks per
        device cut the bubble from (P-1)/M to (P-1)/(M·V) at V× the
        bounded stash — ``parallel/pp_interleaved.py``; requires
        ``n_microbatches % n_stages == 0``).  ``remat=True`` checkpoints
        each stage under the gpipe schedule (the manual schedules
        rematerialize by construction).

        Layout note: under ``interleaved`` the stacked ``stages`` leaves
        hold C = P·V chunk slices in DEVICE-MAJOR order (position
        p·V + k = chunk k·P + p), so the standard leading-axis
        ``P('pipe')`` sharding lands each device's V chunks locally;
        checkpoints are therefore specific to (P, V) like they already
        are to the stage count."""
        if schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(f"unknown schedule {schedule!r}")
        if schedule in ("1f1b", "interleaved") and (tp_size > 1 or sp_size > 1):
            raise ValueError(
                f"schedule={schedule!r} currently supports plain stages "
                "(tp_size == sp_size == 1); use gpipe for TP/SP-in-stage"
            )
        self.n_virtual = n_virtual
        if n_virtual < 1 or (n_virtual > 1 and schedule != "interleaved"):
            raise ValueError(
                "n_virtual > 1 requires schedule='interleaved'")
        if schedule == "interleaved":
            if n_microbatches % n_stages:
                raise ValueError(
                    f"interleaved schedule needs n_microbatches "
                    f"{n_microbatches} divisible by n_stages {n_stages}")
            if n_layers % (n_stages * n_virtual):
                raise ValueError(
                    f"n_layers {n_layers} not divisible by n_stages × "
                    f"n_virtual = {n_stages * n_virtual}")
        elif n_layers % n_stages:
            raise ValueError(
                f"n_layers {n_layers} not divisible by n_stages {n_stages}"
            )
        if dict(mesh.shape).get(pipe_axis) != n_stages:
            raise ValueError(
                f"mesh '{pipe_axis}' axis {dict(mesh.shape).get(pipe_axis)} "
                f"!= n_stages {n_stages}"
            )
        if tp_size > 1:
            if dict(mesh.shape).get(model_axis) != tp_size:
                raise ValueError(
                    f"mesh '{model_axis}' axis "
                    f"{dict(mesh.shape).get(model_axis)} != tp_size {tp_size}"
                )
            if n_heads % tp_size or d_model % tp_size:
                raise ValueError(
                    f"tp_size {tp_size} must divide both n_heads {n_heads} "
                    f"and d_model {d_model}"
                )
        if sp_size > 1:
            if dict(mesh.shape).get(seq_axis) != sp_size:
                raise ValueError(
                    f"mesh '{seq_axis}' axis "
                    f"{dict(mesh.shape).get(seq_axis)} != sp_size {sp_size}"
                )
        self.schedule = schedule
        self.remat = remat
        self.sp_size = sp_size
        self.seq_axis = seq_axis
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.n_heads = n_heads
        self.n_stages = n_stages
        self.n_microbatches = n_microbatches
        self.mesh = mesh
        self.dtype = dtype
        self.pipe_axis = pipe_axis
        self.tp_size = tp_size
        self.model_axis = model_axis
        self.n_chunks = n_stages * n_virtual  # C (= n_stages unless interleaved)
        self.n_blocks = n_layers // self.n_chunks
        self._embed = nn.Embed(vocab_size, d_model, dtype=dtype, name="embed")
        self._ln_f = nn.LayerNorm(dtype=jnp.float32, name="ln_f")
        self._stage = _Stage(
            n_blocks=self.n_blocks, n_heads=n_heads, dtype=dtype
        )

    # ------------------------------------------------------------ flax-like
    def init(self, rng, tokens: jnp.ndarray):
        r_embed, r_stage, r_ln = jax.random.split(rng, 3)
        embed_p = self._embed.init(r_embed, tokens)["params"]
        x0 = jnp.zeros(tokens.shape + (self.d_model,), self.dtype)
        if self.tp_size > 1 or self.sp_size > 1:
            from pytorch_distributed_tpu.parallel.tp_stage import (
                init_stage_params,
            )

            stage_p = jax.vmap(
                lambda r: init_stage_params(r, self.d_model, self.n_blocks,
                                            dtype=self.dtype)
            )(jax.random.split(r_stage, self.n_stages))
        else:
            stage_p = jax.vmap(
                lambda r: self._stage.init(r, x0)["params"]
            )(jax.random.split(r_stage, self.n_chunks))
            if self.n_virtual > 1:
                # natural depth order → device-major chunk layout (see
                # the constructor's layout note).
                from pytorch_distributed_tpu.parallel.pp_interleaved import (
                    interleave_order,
                )

                perm = interleave_order(self.n_stages, self.n_virtual)
                stage_p = jax.tree_util.tree_map(
                    lambda a: a[perm], stage_p)
        ln_p = self._ln_f.init(r_ln, x0.astype(jnp.float32))["params"]
        return {"params": {"embed": embed_p, "stages": stage_p, "ln_f": ln_p}}

    def _stage_fn(self):
        if self.tp_size > 1 or self.sp_size > 1:
            from pytorch_distributed_tpu.parallel.tp_stage import (
                tp_stage_apply,
            )

            model = self.model_axis if self.tp_size > 1 else None
            seq = self.seq_axis if self.sp_size > 1 else None
            return lambda sp, xb: tp_stage_apply(
                sp, xb, self.n_heads, model_axis=model, seq_axis=seq)
        return lambda sp, xb: self._stage.apply({"params": sp}, xb)

    def _stage_specs(self):
        if self.tp_size > 1 or self.sp_size > 1:
            from pytorch_distributed_tpu.parallel.tp_stage import (
                stage_param_specs,
            )

            return stage_param_specs(
                self.n_blocks, self.pipe_axis,
                self.model_axis if self.tp_size > 1 else None)
        return None

    def has_manual_grads(self) -> bool:
        """``make_lm_train_step`` calls ``loss_and_grads`` instead of
        ``jax.value_and_grad`` when this returns True (the 1F1B-family
        schedules compute gradients inside their own scans)."""
        return self.schedule in ("1f1b", "interleaved")

    def loss_and_grads(self, params, tokens: jnp.ndarray):
        """``((loss, acc%), grads)`` via the 1F1B schedule — the signature
        ``jax.value_and_grad(loss_fn, has_aux=True)`` would produce, computed
        manually (see parallel/pp_1f1b.py)."""
        from pytorch_distributed_tpu.ops import cross_entropy
        from pytorch_distributed_tpu.parallel.pp_1f1b import (
            pipeline_1f1b_loss_and_grads,
        )

        embed_p, ln_p = params["embed"], params["ln_f"]
        x, embed_vjp = jax.vjp(
            lambda ep: self._embed.apply({"params": ep}, tokens), embed_p
        )

        def head_fn(hp, y, tok):
            h = self._ln_f.apply({"params": hp["ln_f"]},
                                 y.astype(jnp.float32))
            logits = self._embed.apply(
                {"params": hp["embed"]}, h, method=nn.Embed.attend
            ).astype(jnp.float32)
            v = logits.shape[-1]
            fl = logits[:, :-1].reshape(-1, v)
            ft = tok[:, 1:].reshape(-1)
            loss = cross_entropy(fl, ft)
            correct = jnp.sum(
                (jnp.argmax(fl, axis=-1) == ft).astype(jnp.float32))
            return loss, correct

        stage_fn = lambda sp, xb: self._stage.apply({"params": sp}, xb)
        if self.schedule == "interleaved":
            from pytorch_distributed_tpu.parallel.pp_interleaved import (
                interleaved_pipeline_loss_and_grads,
            )

            loss, correct, count, g_stage, g_head, dx = (
                interleaved_pipeline_loss_and_grads(
                    stage_fn, head_fn, params["stages"],
                    {"ln_f": ln_p, "embed": embed_p}, x, tokens,
                    self.n_microbatches, self.n_virtual, self.mesh,
                    pipe_axis=self.pipe_axis,
                )
            )
        else:
            loss, correct, count, g_stage, g_head, dx = (
                pipeline_1f1b_loss_and_grads(
                    stage_fn, head_fn, params["stages"],
                    {"ln_f": ln_p, "embed": embed_p}, x, tokens,
                    self.n_microbatches, self.mesh, pipe_axis=self.pipe_axis,
                )
            )
        (g_embed_in,) = embed_vjp(dx.astype(x.dtype))
        g_embed = jax.tree_util.tree_map(
            lambda a, b: a.astype(jnp.float32) + b.astype(jnp.float32),
            g_head["embed"], g_embed_in)
        grads = {"embed": g_embed, "stages": g_stage, "ln_f": g_head["ln_f"]}
        acc = correct / count  # fraction; the step scales to % like autodiff
        return (loss, acc), grads

    def apply(self, variables, tokens: jnp.ndarray, mutable=None,
              train: bool = True):
        p = variables["params"]
        x = self._embed.apply({"params": p["embed"]}, tokens)
        if self.n_virtual > 1:
            # Forward-only path (eval/generation): run the C chunks
            # sequentially in natural depth order — chunk k·P + p sits at
            # device-major position p·V + k.  Static indexing; GSPMD
            # fetches each chunk's slice where needed.  The bubble-free
            # interleaved schedule matters for the TRAIN step
            # (loss_and_grads); eval is forward-only and memory-light.
            from pytorch_distributed_tpu.parallel.pp_interleaved import (
                deinterleave_order,
            )

            # natural chunk c sits at device-major position inv[c]
            inv = deinterleave_order(self.n_stages, self.n_virtual)
            for c in range(self.n_chunks):
                chunk = jax.tree_util.tree_map(
                    lambda a, i=int(inv[c]): a[i], p["stages"])
                x = self._stage.apply({"params": chunk}, x)
        else:
            x = pipeline_apply(
                self._stage_fn(),
                p["stages"], x, self.n_microbatches, self.mesh,
                pipe_axis=self.pipe_axis,
                stage_param_specs=self._stage_specs(),
                seq_axis=self.seq_axis if self.sp_size > 1 else None,
                remat=self.remat,
            )
        x = self._ln_f.apply({"params": p["ln_f"]}, x.astype(jnp.float32))
        logits = self._embed.apply(
            {"params": p["embed"]}, x.astype(jnp.float32),
            method=nn.Embed.attend,
        ).astype(jnp.float32)
        return (logits, {}) if mutable is not None else logits


def pp_specs(params, pipe_axis: str = "pipe", model_axis=None):
    """PartitionSpec tree for ``PipelinedTransformerLM`` params: the stacked
    stage tree sharded on its leading (stage) axis, embed/ln replicated.
    With ``model_axis`` (tp_size > 1, tp_stage layout) the stage leaves get
    the Megatron column/row specs from ``parallel/tp_stage.py``."""
    stages = params["stages"]
    if isinstance(stages, dict) and "blocks" in stages:
        from pytorch_distributed_tpu.parallel.tp_stage import (
            stage_param_specs,
        )

        spec_tree = {
            k: jax.tree_util.tree_map(lambda _: P(), v)
            for k, v in params.items() if k != "stages"
        }
        spec_tree["stages"] = stage_param_specs(
            len(stages["blocks"]), pipe_axis, model_axis)
        return spec_tree

    def spec(path, leaf):
        names = [getattr(k, "key", str(k)) for k in path]
        if names and names[0] == "stages":
            return P(pipe_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
