"""Model registry mirroring the torchvision-zoo introspection surface.

The reference picks its architecture by string from the zoo namespace:
``model_names = sorted(name for name in models.__dict__ if …)`` and
``models.__dict__[args.arch]()`` (reference distributed.py:21-23,134-139).
Here the same two gestures are ``model_names()`` and
``create_model(name, …)``; constructors are also re-exported at module level
so ``models.__dict__[name]`` works verbatim.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax.numpy as jnp

from pytorch_distributed_tpu.models.resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
    resnext50_32x4d,
    resnext101_32x8d,
)

_REGISTRY: Dict[str, Callable] = {
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
    "wide_resnet50_2": wide_resnet50_2,
    "wide_resnet101_2": wide_resnet101_2,
    "resnext50_32x4d": resnext50_32x4d,
    "resnext101_32x8d": resnext101_32x8d,
}


def register(name: str, ctor: Callable) -> None:
    """Add a model family to the registry (used by models/transformer.py)."""
    _REGISTRY[name] = ctor
    globals()[name] = ctor


def model_names() -> List[str]:
    """Sorted architecture names (reference distributed.py:21-23)."""
    return sorted(_REGISTRY)


def create_model(name: str, num_classes: int = 1000, dtype: Any = jnp.float32, **kw):
    """``models.__dict__[arch]()`` equivalent (reference distributed.py:134-139)."""
    if name not in _REGISTRY:
        raise ValueError(f"unknown arch {name!r}; choose from {model_names()}")
    return _REGISTRY[name](num_classes=num_classes, dtype=dtype, **kw)
