"""Model registry mirroring the torchvision-zoo introspection surface.

The reference picks its architecture by string from the zoo namespace:
``model_names = sorted(name for name in models.__dict__ if …)`` and
``models.__dict__[args.arch]()`` (reference distributed.py:21-23,134-139).
Here the same two gestures are ``model_names()`` and
``create_model(name, …)``; constructors are also re-exported at module level
so ``models.__dict__[name]`` works verbatim.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import jax.numpy as jnp

from pytorch_distributed_tpu.models.resnet import (  # noqa: F401
    ResNet,
    resnet18,
    resnet34,
    resnet50,
    resnet101,
    resnet152,
    wide_resnet50_2,
    wide_resnet101_2,
    resnext50_32x4d,
    resnext101_32x8d,
)

from pytorch_distributed_tpu.models.transformer import (  # noqa: F401
    TransformerLM,
    transformer_lm,
)

# Image-classification zoo: the ``-a`` choices of every recipe CLI
# (reference distributed.py:21-23 surface).  Language models live in a
# separate registry — they take token inputs and train through the LM path,
# so exposing them as image-recipe archs would only offer a guaranteed crash.
from pytorch_distributed_tpu.models.simple import (  # noqa: F401
    alexnet, vgg11, vgg13, vgg16, vgg19,
    vgg11_bn, vgg13_bn, vgg16_bn, vgg19_bn,
)
from pytorch_distributed_tpu.models.densenet import (  # noqa: F401
    densenet121, densenet161, densenet169, densenet201,
)
from pytorch_distributed_tpu.models.mobilenet import mobilenet_v2  # noqa: F401
from pytorch_distributed_tpu.models.inception import (  # noqa: F401
    googlenet, inception_v3,
)
from pytorch_distributed_tpu.models.extra import (  # noqa: F401
    mnasnet0_5, mnasnet0_75, mnasnet1_0, mnasnet1_3,
    shufflenet_v2_x0_5, shufflenet_v2_x1_0,
    shufflenet_v2_x1_5, shufflenet_v2_x2_0,
    squeezenet1_0, squeezenet1_1,
)
from pytorch_distributed_tpu.models.vit import (  # noqa: F401
    VisionTransformer, vit_b_16, vit_b_32, vit_l_16,
)

_REGISTRY: Dict[str, Callable] = {
    "alexnet": alexnet,
    "vgg11": vgg11, "vgg13": vgg13, "vgg16": vgg16, "vgg19": vgg19,
    "vgg11_bn": vgg11_bn, "vgg13_bn": vgg13_bn,
    "vgg16_bn": vgg16_bn, "vgg19_bn": vgg19_bn,
    "densenet121": densenet121, "densenet161": densenet161,
    "densenet169": densenet169, "densenet201": densenet201,
    "mobilenet_v2": mobilenet_v2,
    "googlenet": googlenet,
    "inception_v3": inception_v3,
    "resnet18": resnet18,
    "resnet34": resnet34,
    "resnet50": resnet50,
    "resnet101": resnet101,
    "resnet152": resnet152,
    "wide_resnet50_2": wide_resnet50_2,
    "wide_resnet101_2": wide_resnet101_2,
    "resnext50_32x4d": resnext50_32x4d,
    "resnext101_32x8d": resnext101_32x8d,
    "squeezenet1_0": squeezenet1_0, "squeezenet1_1": squeezenet1_1,
    "shufflenet_v2_x0_5": shufflenet_v2_x0_5,
    "shufflenet_v2_x1_0": shufflenet_v2_x1_0,
    "shufflenet_v2_x1_5": shufflenet_v2_x1_5,
    "shufflenet_v2_x2_0": shufflenet_v2_x2_0,
    "mnasnet0_5": mnasnet0_5, "mnasnet0_75": mnasnet0_75,
    "mnasnet1_0": mnasnet1_0, "mnasnet1_3": mnasnet1_3,
    # Beyond the torchvision-0.4 namespace: the MXU-native image family.
    "vit_b_16": vit_b_16, "vit_b_32": vit_b_32, "vit_l_16": vit_l_16,
}


_LM_REGISTRY: Dict[str, Callable] = {
    "transformer_lm": transformer_lm,
}


def register(name: str, ctor: Callable, family: str = "image") -> None:
    """Add a model to a registry family ('image' or 'lm')."""
    (_REGISTRY if family == "image" else _LM_REGISTRY)[name] = ctor
    globals()[name] = ctor


def model_names() -> List[str]:
    """Sorted image-arch names — the recipe-CLI ``-a`` surface
    (reference distributed.py:21-23)."""
    return sorted(_REGISTRY)


def lm_model_names() -> List[str]:
    """Sorted language-model arch names (long-context family)."""
    return sorted(_LM_REGISTRY)


def create_model(name: str, num_classes: int = 1000, dtype: Any = jnp.float32, **kw):
    """``models.__dict__[arch]()`` equivalent (reference distributed.py:134-139).

    Resolves both families; ``num_classes`` plays the vocab-size role for LMs.
    """
    registry = _REGISTRY if name in _REGISTRY else _LM_REGISTRY
    if name not in registry:
        raise ValueError(
            f"unknown arch {name!r}; choose from {model_names() + lm_model_names()}"
        )
    return registry[name](num_classes=num_classes, dtype=dtype, **kw)
