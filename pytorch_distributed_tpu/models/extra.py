"""SqueezeNet, ShuffleNetV2 and MNASNet families (flax.linen, NHWC).

Completes parity with the zoo the reference instantiates by name: its pinned
torchvision 0.4 namespace (reference requirements.txt:2, introspected at
distributed.py:21-23) includes ``squeezenet1_0/1_1``,
``shufflenet_v2_x0_5..x2_0`` and ``mnasnet0_5..1_3`` — families the
round-1 zoo lacked.  Same config tables as torchvision, TPU-first layout
(NHWC, BN in f32 stats, depthwise convs via ``feature_group_count``).
"""

from __future__ import annotations

import functools
from typing import Any, Sequence, Tuple

import flax.linen as nn
import jax.numpy as jnp


# --------------------------------------------------------------- SqueezeNet
class _Fire(nn.Module):
    squeeze: int
    e1: int
    e3: int
    dtype: Any

    @nn.compact
    def __call__(self, x):
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        s = nn.relu(conv(self.squeeze, (1, 1))(x))
        a = nn.relu(conv(self.e1, (1, 1))(s))
        b = nn.relu(conv(self.e3, (3, 3), padding=[(1, 1), (1, 1)])(s))
        return jnp.concatenate([a, b], axis=-1)


class SqueezeNet(nn.Module):
    version: str = "1_0"
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(nn.Conv, dtype=self.dtype)
        pool = functools.partial(
            nn.max_pool, window_shape=(3, 3), strides=(2, 2))
        x = x.astype(self.dtype)
        fire = lambda s, e1, e3: _Fire(s, e1, e3, self.dtype)
        if self.version == "1_0":
            x = nn.relu(conv(96, (7, 7), (2, 2))(x))
            x = pool(x)
            x = fire(16, 64, 64)(x)
            x = fire(16, 64, 64)(x)
            x = fire(32, 128, 128)(x)
            x = pool(x)
            x = fire(32, 128, 128)(x)
            x = fire(48, 192, 192)(x)
            x = fire(48, 192, 192)(x)
            x = fire(64, 256, 256)(x)
            x = pool(x)
            x = fire(64, 256, 256)(x)
        else:  # 1_1
            x = nn.relu(conv(64, (3, 3), (2, 2))(x))
            x = pool(x)
            x = fire(16, 64, 64)(x)
            x = fire(16, 64, 64)(x)
            x = pool(x)
            x = fire(32, 128, 128)(x)
            x = fire(32, 128, 128)(x)
            x = pool(x)
            x = fire(48, 192, 192)(x)
            x = fire(48, 192, 192)(x)
            x = fire(64, 256, 256)(x)
            x = fire(64, 256, 256)(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        # final conv classifier (f32 head like the rest of the zoo)
        x = nn.relu(nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32,
                            name="classifier")(x.astype(jnp.float32)))
        return jnp.mean(x, axis=(1, 2))


# -------------------------------------------------------------- ShuffleNetV2
def _channel_shuffle(x: jnp.ndarray, groups: int = 2) -> jnp.ndarray:
    B, H, W, C = x.shape
    x = x.reshape(B, H, W, groups, C // groups)
    x = x.swapaxes(3, 4)
    return x.reshape(B, H, W, C)


class _ShuffleUnit(nn.Module):
    out_ch: int
    stride: int
    dtype: Any
    bn_axis_name: Any = None  # SyncBN mesh axis (torch SyncBatchNorm ≙)

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name)
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        branch = self.out_ch // 2

        def dw(h, ch, stride):
            return norm()(conv(ch, (3, 3), (stride, stride),
                               padding=[(1, 1), (1, 1)],
                               feature_group_count=ch)(h))

        if self.stride == 1:
            a, b = jnp.split(x, 2, axis=-1)
            b = nn.relu(norm()(conv(branch, (1, 1))(b)))
            b = dw(b, branch, 1)
            b = nn.relu(norm()(conv(branch, (1, 1))(b)))
        else:
            a = dw(x, x.shape[-1], self.stride)
            a = nn.relu(norm()(conv(branch, (1, 1))(a)))
            b = nn.relu(norm()(conv(branch, (1, 1))(x)))
            b = dw(b, branch, self.stride)
            b = nn.relu(norm()(conv(branch, (1, 1))(b)))
        return _channel_shuffle(jnp.concatenate([a, b], axis=-1))


class ShuffleNetV2(nn.Module):
    stage_out: Sequence[int]  # (c2, c3, c4, c_final)
    num_classes: int = 1000
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name)
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        x = x.astype(self.dtype)
        x = nn.relu(norm()(conv(24, (3, 3), (2, 2),
                                padding=[(1, 1), (1, 1)])(x)))
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=[(1, 1), (1, 1)])
        for stage, repeats in zip(self.stage_out[:3], (4, 8, 4)):
            x = _ShuffleUnit(stage, 2, self.dtype,
                             bn_axis_name=self.bn_axis_name)(x, train)
            for _ in range(repeats - 1):
                x = _ShuffleUnit(stage, 1, self.dtype,
                                 bn_axis_name=self.bn_axis_name)(x, train)
        x = nn.relu(norm()(conv(self.stage_out[3], (1, 1))(x)))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


# ------------------------------------------------------------------ MNASNet
class _MBConv(nn.Module):
    out_ch: int
    stride: int
    expand: int
    kernel: int
    dtype: Any
    bn_axis_name: Any = None  # SyncBN mesh axis (torch SyncBatchNorm ≙)

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name)
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        pad = self.kernel // 2
        h = x
        if self.expand != 1:
            h = nn.relu(norm()(conv(hidden, (1, 1))(h)))
        h = conv(hidden, (self.kernel, self.kernel),
                 (self.stride, self.stride), padding=[(pad, pad), (pad, pad)],
                 feature_group_count=hidden)(h)
        h = nn.relu(norm()(h))
        h = norm()(conv(self.out_ch, (1, 1))(h))
        if self.stride == 1 and in_ch == self.out_ch:
            return x + h
        return h


def _round_to_8(v: float) -> int:
    new_v = max(8, int(v + 4) // 8 * 8)
    if new_v < 0.9 * v:
        new_v += 8
    return new_v


# (expand, channels, repeats, stride, kernel) — torchvision MNASNet B1 table.
_MNAS_SETTINGS: Tuple = (
    (3, 24, 3, 2, 3),
    (3, 40, 3, 2, 5),
    (6, 80, 3, 2, 5),
    (6, 96, 2, 1, 3),
    (6, 192, 4, 2, 5),
    (6, 320, 1, 1, 3),
)


class MNASNet(nn.Module):
    alpha: float = 1.0
    num_classes: int = 1000
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name)
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        x = x.astype(self.dtype)
        c32 = _round_to_8(32 * self.alpha)
        c16 = _round_to_8(16 * self.alpha)
        x = nn.relu(norm()(conv(c32, (3, 3), (2, 2),
                                padding=[(1, 1), (1, 1)])(x)))
        # sepconv stem block
        x = conv(c32, (3, 3), padding=[(1, 1), (1, 1)],
                 feature_group_count=c32)(x)
        x = nn.relu(norm()(x))
        x = norm()(conv(c16, (1, 1))(x))
        for expand, ch, repeats, stride, kernel in _MNAS_SETTINGS:
            out = _round_to_8(ch * self.alpha)
            x = _MBConv(out, stride, expand, kernel, self.dtype,
                        bn_axis_name=self.bn_axis_name)(x, train)
            for _ in range(repeats - 1):
                x = _MBConv(out, 1, expand, kernel, self.dtype,
                            bn_axis_name=self.bn_axis_name)(x, train)
        x = nn.relu(norm()(conv(1280, (1, 1))(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(
            x.astype(jnp.float32))


def squeezenet1_0(num_classes=1000, dtype=jnp.float32, **kw):
    return SqueezeNet("1_0", num_classes, dtype, **kw)


def squeezenet1_1(num_classes=1000, dtype=jnp.float32, **kw):
    return SqueezeNet("1_1", num_classes, dtype, **kw)


def _shuffle(stage_out):
    def ctor(num_classes=1000, dtype=jnp.float32, **kw):
        return ShuffleNetV2(stage_out, num_classes, dtype, **kw)

    return ctor


shufflenet_v2_x0_5 = _shuffle((48, 96, 192, 1024))
shufflenet_v2_x1_0 = _shuffle((116, 232, 464, 1024))
shufflenet_v2_x1_5 = _shuffle((176, 352, 704, 1024))
shufflenet_v2_x2_0 = _shuffle((244, 488, 976, 2048))


def _mnas(alpha):
    def ctor(num_classes=1000, dtype=jnp.float32, **kw):
        return MNASNet(alpha, num_classes, dtype, **kw)

    return ctor


mnasnet0_5 = _mnas(0.5)
mnasnet0_75 = _mnas(0.75)
mnasnet1_0 = _mnas(1.0)
mnasnet1_3 = _mnas(1.3)
