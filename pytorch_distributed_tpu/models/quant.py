"""Int8 weight-only quantization for LM serving.

Batch-1 decode is bound by the parameter HBM stream (measured 72% of the
params+KV roofline, RESULTS_decode.json), so halving the bytes the chip
reads per token is the one lever that moves it: block Dense kernels are
stored int8 (per-output-channel symmetric scales, f32) and dequantized on
the fly — XLA fuses the int8→bf16 convert into the matmul's operand load,
so HBM sees int8 while the MXU still computes in bf16.  Embedding/head and
norms stay full precision (the embedding doubles as the tied output head;
its lookup is a gather, not a streamed matmul).

Post-training, weight-only: no calibration data needed, activations stay
bf16.  ``quantize_lm_params`` converts a trained fp tree in one pass;
``TransformerLM(quant="int8")`` consumes the converted tree (same scope
names, ``kernel`` → ``w_q`` + ``scale``).
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

# Block Dense modules that stream the bulk of the parameter bytes per
# decoded token (SelfAttention qkv/proj, MLP fc1/fc2 — models/transformer.py).
QUANT_MODULES = ("qkv", "proj", "fc1", "fc2")


class QuantDense(nn.Module):
    """Dense over an int8 kernel with per-output-channel f32 scales.

    ``y = (x @ w_q.astype(dtype)) * scale [+ bias]`` — numerically the
    dequantized matmul, but the kernel lives in HBM as int8 (half the
    bf16 bytes, a quarter of f32)."""

    features: int
    dtype: Any = jnp.float32
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        import jax

        in_features = x.shape[-1]
        w_q = self.param("w_q", nn.initializers.zeros,
                         (in_features, self.features), jnp.int8)
        scale = self.param("scale", nn.initializers.ones,
                           (self.features,), jnp.float32)
        # Pin the dequant next to the matmul: the astype is loop-invariant
        # inside the decode scan, and hoisting it would materialize a bf16
        # copy of every kernel in HBM — exactly the 2x parameter stream
        # this module exists to remove.  The barrier keeps the int8->bf16
        # convert fused into the matmul's operand load.
        w_q = jax.lax.optimization_barrier(w_q)
        y = jnp.dot(x.astype(self.dtype), w_q.astype(self.dtype))
        y = y * scale.astype(y.dtype)
        if self.use_bias:
            bias = self.param("bias", nn.initializers.zeros,
                              (self.features,), jnp.float32)
            y = y + bias.astype(y.dtype)
        return y


def quantize_kernel(kernel) -> tuple:
    """``[in, out]`` fp kernel → (int8 ``w_q``, f32 per-out-channel scale)."""
    w = np.asarray(kernel, np.float32)
    scale = np.abs(w).max(axis=0) / 127.0
    scale = np.where(scale == 0.0, 1.0, scale)  # all-zero channels
    w_q = np.clip(np.round(w / scale[None, :]), -127, 127).astype(np.int8)
    return jnp.asarray(w_q), jnp.asarray(scale.astype(np.float32))


def quantize_lm_params(params):
    """Convert a trained TransformerLM ``params`` tree for ``quant="int8"``.

    Every ``kernel`` under a ``QUANT_MODULES`` scope becomes ``w_q`` +
    ``scale`` (bias, norms, embedding untouched); the result matches the
    param structure ``TransformerLM(quant="int8")`` initializes."""

    converted = 0

    def walk(tree, name):
        nonlocal converted
        # Mapping (not just dict): flax FrozenDict subtrees must be walked
        # too, or the conversion silently no-ops below the top level.
        if not isinstance(tree, Mapping):
            return tree
        if (name in QUANT_MODULES and "kernel" in tree
                and getattr(tree["kernel"], "ndim", 0) == 2):
            # The ndim guard skips MoE expert stacks ([E, in, out] kernels
            # under the same fc1/fc2 scope names, models/moe.py) — experts
            # stay fp; only plain block Dense kernels quantize.
            w_q, scale = quantize_kernel(tree["kernel"])
            out = {k: v for k, v in tree.items() if k != "kernel"}
            out.update(w_q=w_q, scale=scale)
            converted += 1
            return out
        return {k: walk(v, k) for k, v in tree.items()}

    out = walk(dict(params), "")
    if converted == 0:
        raise ValueError(
            "quantize_lm_params converted no kernels — the tree has no "
            f"2-D 'kernel' under any of {QUANT_MODULES}; is this a "
            "TransformerLM params tree (or already quantized)?")
    return out
