"""Autoregressive generation for the LM family: KV-cached decode with
greedy or temperature/top-k sampling.

The serving-side counterpart of the training harness (the reference's
inference story is ``--evaluate``; generation is the LM-family analogue).
``TransformerLM(decode=True, max_len=N)`` switches attention into cached
mode: the prompt prefills the per-layer key/value caches in one pass, then
each generated token attends over the filled prefix — O(L) per token
instead of O(L²), all under one jit (prefill + a ``lax.scan`` over steps,
static shapes throughout).
"""

from __future__ import annotations

import functools
from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.transformer import TransformerLM


def filter_logits(logits, temperature: float, top_k: int,
                  top_p: float) -> jnp.ndarray:
    """Temperature + top-k + nucleus filtering over ``[..., V]`` logits —
    the module's SAMPLING DISTRIBUTION in logit form (f32, -inf outside
    the kept set).  Shared by ``generate`` and speculative decoding, which
    must agree exactly on p/q for the acceptance math to be lossless.

    ``temperature`` must be > 0 here (greedy is the caller's argmax
    fast path)."""
    logits = logits.astype(jnp.float32) / temperature
    if top_k > 0:
        # lax.top_k returns values already sorted descending, so both
        # the k-th-value threshold AND the nucleus cutoff come from the
        # k-vector — no full-vocab argsort inside the decode scan
        # (6.696 -> 1.761 ms/tok measured at b8 / vocab 32k).
        vals = jax.lax.top_k(logits, min(top_k, logits.shape[-1]))[0]
        cut = vals[..., -1:]
        if 0.0 < top_p < 1.0:
            # Renormalized over the survivors (identical to softmaxing
            # the -inf-masked full vocab), keep the smallest descending
            # prefix reaching top_p mass; its last value is the cutoff.
            probs = jax.nn.softmax(vals, axis=-1)
            mass_before = jnp.cumsum(probs, axis=-1) - probs
            kept = jnp.where(mass_before < top_p, vals, jnp.inf)
            # NB: dropping by value threshold keeps ALL tokens tied at
            # the cutoff (the full-sort path half-drops ties by sorted
            # position) — matching the module's top-k tie convention.
            cut = jnp.maximum(
                cut, jnp.min(kept, axis=-1, keepdims=True))
        return jnp.where(logits < cut, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        # Nucleus: keep the smallest prefix (by descending probability)
        # whose mass reaches top_p — i.e. drop tokens whose preceding
        # cumulative mass already covers it.  Static shapes: sort +
        # cumsum + gather back through the inverse permutation.
        order = jnp.argsort(-logits, axis=-1)
        sorted_probs = jax.nn.softmax(
            jnp.take_along_axis(logits, order, axis=-1), axis=-1)
        mass_before = jnp.cumsum(sorted_probs, axis=-1) - sorted_probs
        drop_sorted = mass_before >= top_p
        inv = jnp.argsort(order, axis=-1)
        drop = jnp.take_along_axis(drop_sorted, inv, axis=-1)
        return jnp.where(drop, -jnp.inf, logits)
    return logits


@functools.lru_cache(maxsize=32)
def _make_run(
    B: int,
    P: int,
    max_new_tokens: int,
    vocab_size: int,
    d_model: int,
    n_heads: int,
    n_layers: int,
    dtype: Any,
    temperature: float,
    top_k: int,
    top_p: float,
    quant: str = "",
    flash_prefill: bool = False,
):
    """Build (and cache) the compiled prefill+decode program.

    Everything that changes the traced graph is a key here; repeated
    ``generate()`` calls with the same shapes/config reuse one compiled
    executable instead of re-tracing per call (the jit cache is keyed on
    function identity, so a closure defined inside ``generate`` would
    recompile on every invocation).
    """
    model = TransformerLM(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, dtype=jnp.dtype(dtype), attn_impl="dense",
        decode=True, max_len=P + max_new_tokens, quant=quant,
        flash_prefill=flash_prefill,
    )

    # Zeroed cache built from abstract shapes only — no throwaway forward
    # pass, no discarded second parameter set.
    cache_shapes = jax.eval_shape(
        lambda p: model.init(jax.random.PRNGKey(0), p),
        jax.ShapeDtypeStruct((B, P), jnp.int32),
    )["cache"]

    def pick(logits, key):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        logits = filter_logits(logits, temperature, top_k, top_p)
        return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)

    @jax.jit
    def run(params, prompt, key):
        cache = jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
        )
        logits, mut = model.apply(
            {"params": params, "cache": cache}, prompt, mutable=["cache"]
        )
        cache = mut["cache"]
        key, sub = jax.random.split(key)
        tok = pick(logits[:, -1, :], sub)

        def body(carry, _):
            cache, tok, key = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"],
            )
            key, sub = jax.random.split(key)
            ntok = pick(logits[:, -1, :], sub)
            return (mut["cache"], ntok, key), ntok

        if max_new_tokens == 1:
            return tok[:, None]
        (_, _, _), rest = jax.lax.scan(
            body, (cache, tok, key), None, length=max_new_tokens - 1
        )
        return jnp.concatenate([tok[:, None], rest.T], axis=1)

    return run


def generate(
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    vocab_size: int,
    d_model: int,
    n_heads: int,
    n_layers: int,
    dtype: Any = jnp.float32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    seed: int = 0,
    quant: str = "",
    flash_prefill: "bool | None" = None,
) -> jnp.ndarray:
    """Decode ``max_new_tokens`` continuations of ``prompt [B, P]``.

    ``params``: a trained TransformerLM's ``params`` tree (decode mode uses
    the same parameter structure).  ``temperature=0`` is greedy argmax;
    ``temperature>0`` samples from softmax(logits/T), truncated to the
    ``top_k`` most likely tokens and/or the nucleus holding ``top_p``
    probability mass (both filters compose, k first).  Returns
    ``[B, max_new_tokens]`` int32.  Compiled programs are cached on
    (shapes, model config, sampling config) — calling this in a loop reuses
    one executable.
    """
    B, P = prompt.shape
    if flash_prefill is None:
        # generate() prefills the prompt as ONE block at cache index 0 —
        # exactly the flash_prefill contract — so long, aligned prompts
        # take the fused kernel (no O(P·max_len) dense score tensor)
        # under the shared auto policy.  Callers running the program
        # SHARDED (tp_generate) pass False: the Pallas call has no SPMD
        # partitioning rule.
        from pytorch_distributed_tpu.ops.flash_attention import (
            pick_attention_impl,
        )

        flash_prefill = pick_attention_impl(P, "auto") == "flash"
    run = _make_run(
        B, P, max_new_tokens, vocab_size, d_model, n_heads, n_layers,
        jnp.dtype(dtype).name,
        float(temperature), int(top_k), float(top_p), quant,
        bool(flash_prefill),
    )
    return run(params, prompt, jax.random.PRNGKey(seed))


def greedy_generate(params, prompt, max_new_tokens, **kw):
    """Greedy decode (``generate`` with temperature 0)."""
    if kw.get("temperature"):
        raise ValueError(
            "greedy_generate is temperature-0 by definition; call generate() "
            f"for sampling (got temperature={kw['temperature']})"
        )
    kw.pop("temperature", None)
    return generate(params, prompt, max_new_tokens, temperature=0.0, **kw)


def tp_generate(params, prompt, max_new_tokens, *, mesh, **kw):
    """Model-parallel decode: Megatron-sharded params over ``mesh``'s
    ``model`` axis (qkv/fc1 column-, proj/fc2 row-parallel, vocab-sharded
    embedding — ``parallel/tp.py``), same compiled prefill+scan program.
    XLA places the two per-block all-reduces and propagates head-sharding
    into the KV caches, so decode state is sharded too — the serving-side
    counterpart of TP training, for models too big for one chip.

    ``jit`` specializes on the committed input shardings, so TP and
    single-device calls coexist in the program cache."""
    from pytorch_distributed_tpu.parallel.tp import shard_pytree, tp_specs

    sharded = shard_pytree(params, tp_specs(params), mesh)
    # The Pallas prefill kernel has no SPMD partitioning rule — keep the
    # sharded program on the dense prefill path (GSPMD partitions it).
    kw.setdefault("flash_prefill", False)
    return generate(sharded, prompt, max_new_tokens, **kw)
