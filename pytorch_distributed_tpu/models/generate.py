"""Autoregressive generation for the LM family: KV-cached greedy decode.

The serving-side counterpart of the training harness (the reference's
inference story is ``--evaluate``; generation is the LM-family analogue).
``TransformerLM(decode=True, max_len=N)`` switches attention into cached
mode: the prompt prefills the per-layer key/value caches in one pass, then
each generated token attends over the filled prefix — O(L) per token
instead of O(L²), all under one jit (prefill + a ``lax.scan`` over steps,
static shapes throughout).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.transformer import TransformerLM


def greedy_generate(
    params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    vocab_size: int,
    d_model: int,
    n_heads: int,
    n_layers: int,
    dtype: Any = jnp.float32,
) -> jnp.ndarray:
    """Greedy-decode ``max_new_tokens`` continuations of ``prompt [B, P]``.

    ``params``: a trained TransformerLM's ``params`` tree (decode mode uses
    the same parameter structure).  Returns ``[B, max_new_tokens]`` int32.
    """
    B, P = prompt.shape
    model = TransformerLM(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, dtype=dtype, attn_impl="dense",
        decode=True, max_len=P + max_new_tokens,
    )
    # Zeroed cache built from abstract shapes only — no throwaway forward
    # pass, no discarded second parameter set.
    cache_shapes = jax.eval_shape(
        lambda: model.init(jax.random.PRNGKey(0), prompt)
    )["cache"]
    cache0 = jax.tree_util.tree_map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
    )

    @jax.jit
    def run(params, prompt, cache):
        logits, mut = model.apply(
            {"params": params, "cache": cache}, prompt, mutable=["cache"]
        )
        cache = mut["cache"]
        tok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)

        def body(carry, _):
            cache, tok = carry
            logits, mut = model.apply(
                {"params": params, "cache": cache}, tok[:, None],
                mutable=["cache"],
            )
            ntok = jnp.argmax(logits[:, -1, :], axis=-1).astype(jnp.int32)
            return (mut["cache"], ntok), ntok

        if max_new_tokens == 1:
            return tok[:, None]
        (_, _), rest = jax.lax.scan(
            body, (cache, tok), None, length=max_new_tokens - 1
        )
        return jnp.concatenate([tok[:, None], rest.T], axis=1)

    return run(params, prompt, cache0)
