"""MobileNetV2 (flax.linen, NHWC) — torchvision-config parity
(inverted residuals, width-multiplier support; reference zoo surface)."""

from __future__ import annotations

import functools
from typing import Any

import flax.linen as nn
import jax.numpy as jnp


def _make_divisible(v: float, divisor: int = 8) -> int:
    new_v = max(divisor, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


class _InvertedResidual(nn.Module):
    out_ch: int
    stride: int
    expand: int
    dtype: Any
    bn_axis_name: Any = None  # SyncBN mesh axis (torch SyncBatchNorm ≙)

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        in_ch = x.shape[-1]
        hidden = in_ch * self.expand
        h = x
        if self.expand != 1:
            h = nn.relu6(norm()(conv(hidden, (1, 1))(h)))
        h = conv(hidden, (3, 3), (self.stride, self.stride),
                 padding=[(1, 1), (1, 1)], feature_group_count=hidden)(h)
        h = nn.relu6(norm()(h))
        h = norm()(conv(self.out_ch, (1, 1))(h))
        if self.stride == 1 and in_ch == self.out_ch:
            return x + h
        return h


# (expand, channels, repeats, stride) — torchvision mobilenet_v2 table.
_SETTINGS = [
    (1, 16, 1, 1),
    (6, 24, 2, 2),
    (6, 32, 3, 2),
    (6, 64, 4, 2),
    (6, 96, 3, 1),
    (6, 160, 3, 2),
    (6, 320, 1, 1),
]


class MobileNetV2(nn.Module):
    num_classes: int = 1000
    width_mult: float = 1.0
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        x = x.astype(self.dtype)
        ch = _make_divisible(32 * self.width_mult)
        x = nn.relu6(norm()(conv(ch, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])(x)))
        for expand, c, reps, s in _SETTINGS:
            out_ch = _make_divisible(c * self.width_mult)
            for i in range(reps):
                x = _InvertedResidual(
                    out_ch, s if i == 0 else 1, expand, self.dtype,
                    bn_axis_name=self.bn_axis_name,
                )(x, train)
        last = _make_divisible(1280 * max(1.0, self.width_mult))
        x = nn.relu6(norm()(conv(last, (1, 1))(x)))
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(0.2, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


mobilenet_v2 = functools.partial(MobileNetV2)
