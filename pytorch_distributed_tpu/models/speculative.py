"""Speculative decoding: draft-model proposals, target-model rejection
sampling (Leviathan et al. 2023 / Chen et al. 2023).

Small-batch decode pays one full parameter stream per token
(RESULTS_decode.json: b1 at 72% of the HBM roofline), so the only way
past it at batch 1 is fewer target passes per token: a cheap draft model
proposes ``gamma`` tokens autoregressively, the target scores the whole
block in ONE cached forward (the masked cache attention handles L>1
blocks at any index), and rejection sampling keeps the output distributed
EXACTLY as target-only sampling:

- accept draft token x_i with prob min(1, p_i(x_i)/q_i(x_i));
- at the first rejection, emit a sample from norm(max(p_i − q_i, 0));
- if all gamma survive, sample a bonus token from the target's last
  distribution — up to gamma+1 tokens per target pass.

p and q are the *post-filter* sampling distributions (shared
``filter_logits``), so temperature/top-k/top-p compose losslessly.
Greedy (temperature 0) uses one-hot p/q: the output equals the target's
own greedy stream token-for-token, regardless of the draft — the test
suite pins that.

Cache bookkeeping: the target's scoring pass writes k/v for every
proposed token; on a rejection at offset a we only rewind each layer's
``cache_index`` to n+a — the stale k/v beyond it are overwritten before
they can ever be attended (positions are rewritten before attention, and
the causal mask hides everything past the current query block).
"""

from __future__ import annotations

import functools
from typing import Any, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.generate import filter_logits
from pytorch_distributed_tpu.models.transformer import TransformerLM


@functools.lru_cache(maxsize=64)
def _make_block_apply(L: int, B: int, max_len: int, vocab_size: int,
                      d_model: int, n_heads: int, n_layers: int,
                      dtype_name: str, quant: str):
    """Jitted cached-model application of an ``[B, L]`` token block:
    returns (logits[B, L, V], new_cache).  One compiled program per block
    length — speculative rounds reuse two of these (draft L=1, target
    L=gamma+1) plus the prefill lengths."""
    model = TransformerLM(
        vocab_size=vocab_size, d_model=d_model, n_heads=n_heads,
        n_layers=n_layers, dtype=jnp.dtype(dtype_name), attn_impl="dense",
        decode=True, max_len=max_len, quant=quant,
    )
    cache_shapes = jax.eval_shape(
        lambda p: model.init(jax.random.PRNGKey(0), p),
        jax.ShapeDtypeStruct((B, L), jnp.int32),
    )["cache"]

    @jax.jit
    def fresh_cache():
        return jax.tree_util.tree_map(
            lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes)

    # NOT donated, deliberately (shardlint donation audit): the cache is
    # dead after every call, but XLA dedups identical executable outputs
    # into one buffer — every layer's equal cache_index scalar comes back
    # aliased — so donate_argnums=(1,) on the returned tree trips PJRT's
    # "attempt to donate the same buffer twice" at the next call.  The k/v
    # double-buffer is the price of the shared-buffer layout.
    @jax.jit
    def apply(params, cache, tokens):
        logits, mut = model.apply(
            {"params": params, "cache": cache}, tokens, mutable=["cache"])
        return logits, mut["cache"]

    return fresh_cache, apply


def _set_cache_index(cache, value):
    """Rewind every layer's cache_index (stale k/v beyond it are dead —
    rewritten before any query can attend to them)."""
    val = jnp.asarray(value, jnp.int32)
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: (
            val if getattr(path[-1], "key", "") == "cache_index" else leaf),
        cache,
    )


def _accept(p: np.ndarray, q: np.ndarray, x: int, rng,
            greedy: bool) -> bool:
    """Accept draft token ``x`` with prob min(1, p(x)/q(x)).  Greedy
    one-hots reduce exactly to argmax equality (no rng draw)."""
    p_x, q_x = p[x], q[x]
    if greedy:
        return p_x > 0.0
    return rng.uniform() < min(1.0, p_x / max(q_x, 1e-20))


def _resample(p: np.ndarray, q: np.ndarray, rng, greedy: bool) -> int:
    """Sample from the residual norm(max(p − q, 0)); degenerate p == q
    falls back to p itself (the residual is then undefined 0/0)."""
    resid = np.maximum(p - q, 0.0)
    total = resid.sum()
    if total <= 0:
        resid, total = p, p.sum()
    resid = resid / total
    return int(np.argmax(resid)) if greedy else int(
        rng.choice(len(resid), p=resid))


def _dist(logits_row, temperature, top_k, top_p):
    """[V] logits -> [V] probability vector of the ACTUAL sampling
    distribution (one-hot argmax when greedy)."""
    if temperature <= 0.0:
        probs = np.zeros(logits_row.shape[-1], np.float64)
        probs[int(np.argmax(logits_row))] = 1.0
        return probs
    filt = filter_logits(jnp.asarray(logits_row), temperature, top_k, top_p)
    probs = np.asarray(jax.nn.softmax(filt, axis=-1), np.float64)
    # Renormalize in float64: the float32-accumulated softmax sum deviates
    # from 1 by up to ~1e-7 at vocab 32k, past numpy Generator.choice's
    # ~1.5e-8 sum-to-1 tolerance.
    return probs / probs.sum()


def speculative_generate(
    target_params,
    draft_params,
    prompt: jnp.ndarray,
    max_new_tokens: int,
    *,
    target_cfg: dict,
    draft_cfg: dict,
    gamma: int = 4,
    dtype: Any = jnp.float32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    seed: int = 0,
    quant: str = "",
    draft_quant: str = "",
) -> Tuple[jnp.ndarray, dict]:
    """Decode ``[B=1, P]`` prompt continuations with draft speculation.

    ``target_cfg``/``draft_cfg``: dicts of vocab_size/d_model/n_heads/
    n_layers (the two vocabularies must match).  Returns ``(tokens
    [1, max_new_tokens] int32, stats)`` where stats records target passes
    and the mean accepted-per-round — the speedup numerator.  Output is
    distributed exactly as target-only sampling (greedy: identical
    stream); randomness is driven by a seeded host RNG.
    """
    if target_cfg["vocab_size"] != draft_cfg["vocab_size"]:
        raise ValueError("target and draft must share a vocabulary")
    if prompt.shape[0] != 1:
        raise ValueError("speculative decode is batch-1 (serving latency)")
    B, P = prompt.shape
    V = target_cfg["vocab_size"]
    max_len = P + max_new_tokens + gamma + 1  # scoring may overshoot
    dt = jnp.dtype(dtype).name
    rng = np.random.default_rng(seed)

    def mk(cfg, L, q):
        return _make_block_apply(
            L, B, max_len, cfg["vocab_size"], cfg["d_model"],
            cfg["n_heads"], cfg["n_layers"], dt, q)

    t_fresh, t_prefill = mk(target_cfg, P, quant)
    _, t_score = mk(target_cfg, gamma + 1, quant)
    d_fresh, d_prefill = mk(draft_cfg, P, draft_quant)
    _, d_step = mk(draft_cfg, 1, draft_quant)

    # Prefill both caches; the target's last-position logits seed x_cur.
    t_logits, t_cache = t_prefill(target_params, t_fresh(), prompt)
    _, d_cache = d_prefill(draft_params, d_fresh(), prompt)
    p0 = _dist(np.asarray(t_logits)[0, -1], temperature, top_k, top_p)
    x_cur = int(rng.choice(V, p=p0)) if temperature > 0 else int(np.argmax(p0))

    out = [x_cur]
    n = P  # tokens whose k/v are final in both caches
    target_passes = 1
    accepted_hist = []
    while len(out) < max_new_tokens:
        g = min(gamma, max_new_tokens - len(out))
        # keep ONE compiled score shape: pad the block with draft steps
        # even when fewer are needed; extras are discarded.
        # --- draft proposes gamma tokens (collect its q distributions)
        d_tokens, q_dists = [], []
        tok = x_cur
        for _ in range(gamma):
            dl, d_cache = d_step(
                draft_params, d_cache, jnp.full((1, 1), tok, jnp.int32))
            q = _dist(np.asarray(dl)[0, -1], temperature, top_k, top_p)
            tok = int(rng.choice(V, p=q)) if temperature > 0 \
                else int(np.argmax(q))
            d_tokens.append(tok)
            q_dists.append(q)
        # --- target scores [x_cur, d_1..d_gamma] in one pass
        block = jnp.asarray([[x_cur] + d_tokens], jnp.int32)
        tl, t_cache = t_score(target_params, t_cache, block)
        target_passes += 1
        p_dists = [
            _dist(np.asarray(tl)[0, i], temperature, top_k, top_p)
            for i in range(gamma + 1)
        ]
        # --- rejection sampling
        accepted = 0
        for i in range(g):
            x_i = d_tokens[i]
            if not _accept(p_dists[i], q_dists[i], x_i, rng,
                           greedy=temperature <= 0):
                x_cur = _resample(p_dists[i], q_dists[i], rng,
                                  greedy=temperature <= 0)
                break
            accepted += 1
            out.append(x_i)
            if len(out) >= max_new_tokens:
                break
        else:
            # Every proposal survived (the no-break path implies
            # accepted == g == gamma: accepting fewer than gamma means
            # either a rejection broke out, or max_new_tokens was hit —
            # also a break): bonus token from the target's last
            # distribution (position gamma of the scored block).  The
            # draft never consumed its own last proposal — feed it so
            # the draft cache has no hole at position n+gamma (the
            # rewind below cannot repair a missing entry).
            _, d_cache = d_step(
                draft_params, d_cache,
                jnp.full((1, 1), d_tokens[-1], jnp.int32))
            pg = p_dists[g]
            x_cur = (int(rng.choice(V, p=pg)) if temperature > 0
                     else int(np.argmax(pg)))
        accepted_hist.append(accepted)
        if len(out) < max_new_tokens:
            out.append(x_cur)
        # --- rewind: the scoring pass advanced both caches past the
        # accepted prefix; only cache_index needs to move back.
        n += 1 + accepted  # x_cur (previous) + accepted draft tokens
        t_cache = _set_cache_index(t_cache, n)
        d_cache = _set_cache_index(d_cache, n)

    stats = {
        "target_passes": target_passes,
        "tokens": len(out[:max_new_tokens]),
        "mean_accepted": (float(np.mean(accepted_hist))
                          if accepted_hist else 0.0),
        "tokens_per_target_pass":
            len(out[:max_new_tokens]) / max(target_passes, 1),
    }
    return jnp.asarray([out[:max_new_tokens]], jnp.int32), stats
