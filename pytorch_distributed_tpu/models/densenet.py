"""DenseNet family (flax.linen, NHWC) — torchvision-config parity
(densenet121/161/169/201; reference zoo surface, distributed.py:21-23)."""

from __future__ import annotations

import functools
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp


class _DenseLayer(nn.Module):
    growth: int
    dtype: Any
    bn_axis_name: Any = None  # SyncBN mesh axis (torch SyncBatchNorm ≙)

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        h = nn.relu(norm()(x))
        h = conv(4 * self.growth, (1, 1))(h)
        h = nn.relu(norm()(h))
        h = conv(self.growth, (3, 3), padding=[(1, 1), (1, 1)])(h)
        return jnp.concatenate([x, h], axis=-1)


class DenseNet(nn.Module):
    block_config: Sequence[int]
    growth: int = 32
    init_features: int = 64
    num_classes: int = 1000
    dtype: Any = jnp.float32
    # SyncBN under shard_map (--sync-bn): flax BatchNorm pmeans the batch
    # moments over this mesh axis.  None = per-shard statistics.
    bn_axis_name: Any = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        norm = functools.partial(
            nn.BatchNorm, use_running_average=not train, momentum=0.9,
            epsilon=1e-5, dtype=self.dtype, axis_name=self.bn_axis_name,
        )
        conv = functools.partial(nn.Conv, dtype=self.dtype, use_bias=False)
        x = x.astype(self.dtype)
        x = conv(self.init_features, (7, 7), (2, 2), padding=[(3, 3), (3, 3)])(x)
        x = nn.relu(norm()(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])
        for bi, layers in enumerate(self.block_config):
            for li in range(layers):
                x = _DenseLayer(self.growth, self.dtype,
                                bn_axis_name=self.bn_axis_name,
                                name=f"block{bi}_layer{li}")(x, train)
            if bi != len(self.block_config) - 1:
                # Transition: 1x1 conv halving channels + 2x2 avg pool.
                x = nn.relu(norm()(x))
                x = conv(x.shape[-1] // 2, (1, 1))(x)
                x = nn.avg_pool(x, (2, 2), (2, 2))
        x = nn.relu(norm()(x))
        x = jnp.mean(x, axis=(1, 2))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="fc")(x)


densenet121 = functools.partial(DenseNet, block_config=(6, 12, 24, 16))
densenet161 = functools.partial(
    DenseNet, block_config=(6, 12, 36, 24), growth=48, init_features=96
)
densenet169 = functools.partial(DenseNet, block_config=(6, 12, 32, 32))
densenet201 = functools.partial(DenseNet, block_config=(6, 12, 48, 32))
