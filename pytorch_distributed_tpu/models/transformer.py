"""Decoder-only transformer LM with optional ring-attention sequence
parallelism — the framework's long-context model family.

Beyond-reference capability (the reference is image-classification only,
SURVEY.md §5.7), first-class per the framework brief.  The same module runs:

- single-device / pure-DP with dense attention;
- sequence-parallel over a ``seq`` mesh axis via ``parallel/ring.py``'s ring
  attention (KV blocks rotate on ICI, online softmax, O(L/P) memory).

TPU-first choices: pre-LN blocks (stable in bf16), RoPE positions (position
math is local so sequence sharding needs no global gather), GELU MLP at 4×
width, f32 layernorm/softmax accumulation under a bf16 compute policy.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from pytorch_distributed_tpu.parallel.ring import dense_attention, ring_self_attention


def rope(x: jnp.ndarray, base: float = 10000.0, offset=0) -> jnp.ndarray:
    """Rotary position embedding over [B, L, H, D] (global positions — under
    GSPMD the position index is computed on the full array, so sequence
    sharding stays transparent).  ``offset`` shifts positions for KV-cached
    decoding (may be a traced scalar)."""
    B, L, H, D = x.shape
    half = D // 2
    freqs = 1.0 / (base ** (jnp.arange(0, half, dtype=jnp.float32) / half))
    pos = offset + jnp.arange(L, dtype=jnp.float32)
    ang = pos[:, None] * freqs[None, :]                               # [L, half]
    cos = jnp.cos(ang)[None, :, None, :]
    sin = jnp.sin(ang)[None, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def _pick_attention(L: int, attn_impl: str):
    """Shared 'auto' flash/dense policy — ops/flash_attention.py."""
    from pytorch_distributed_tpu.ops.flash_attention import pick_attention_impl

    return pick_attention_impl(L, attn_impl)


def _dense_cls(quant: str):
    """nn.Dense, or the int8 weight-only variant (models/quant.py)."""
    if not quant:
        return nn.Dense
    if quant == "int8":
        from pytorch_distributed_tpu.models.quant import QuantDense

        return QuantDense
    raise ValueError(f"unknown quant mode {quant!r} (expected '' or 'int8')")


class SelfAttention(nn.Module):
    n_heads: int
    dtype: Any = jnp.float32
    mesh: Optional[Mesh] = None
    ring: bool = False
    attn_impl: str = "auto"  # auto | dense | flash
    decode: bool = False     # KV-cached autoregressive mode
    max_len: int = 0         # cache capacity (decode mode)
    sp_impl: str = "ring"    # ring | a2a (Ulysses-style all-to-all SP)
    quant: str = ""          # "" | "int8" weight-only (serving)
    flash_prefill: bool = False  # fused-kernel prompt prefill (decode mode)

    @nn.compact
    def __call__(self, x):
        B, L, C = x.shape
        D = C // self.n_heads
        dense = _dense_cls(self.quant)
        qkv = dense(3 * C, use_bias=False, dtype=self.dtype, name="qkv")(x)
        q, k, v = jnp.split(qkv, 3, axis=-1)
        shape = (B, L, self.n_heads, D)
        q, k, v = (t.reshape(shape) for t in (q, k, v))
        if self.decode:
            return self._decode_attend(q, k, v, B, L, C, D)
        q, k = rope(q), rope(k)
        if self.ring:
            if self.mesh is None:
                raise ValueError(
                    "sequence parallelism requires a mesh with a 'seq' axis")
            if self.sp_impl == "a2a":
                from pytorch_distributed_tpu.parallel.ulysses import (
                    a2a_self_attention,
                )

                out = a2a_self_attention(q, k, v, self.mesh, causal=True,
                                         inner=self.attn_impl)
            else:
                out = ring_self_attention(q, k, v, self.mesh, causal=True)
        elif _pick_attention(L, self.attn_impl) == "flash":
            from pytorch_distributed_tpu.ops.flash_attention import flash_attention

            out = flash_attention(q, k, v, True)
        else:
            out = dense_attention(q, k, v, causal=True)
        out = out.reshape(B, L, C)
        return _dense_cls(self.quant)(
            C, use_bias=False, dtype=self.dtype, name="proj")(out)

    def _decode_attend(self, q, k, v, B, L, C, D):
        """KV-cached attention: new tokens' k/v land in the cache at the
        running index (prefill writes the whole prompt at once, generation
        steps write one token); q attends over the filled prefix with a
        static-shape mask.  Cache lives in the flax "cache" collection —
        created at ``init``, threaded by the caller via ``mutable``."""
        if self.max_len <= 0:
            raise ValueError("decode mode needs max_len > 0 (cache capacity)")
        # During init this variable doesn't exist yet: create the zeroed
        # cache but DON'T advance it — the returned cache must start at
        # index 0, not wherever the init trace's dummy tokens left it.
        initializing = not self.has_variable("cache", "cached_key")
        ck = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros((B, self.max_len, self.n_heads, D), self.dtype))
        cv = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros((B, self.max_len, self.n_heads, D), self.dtype))
        ci = self.variable("cache", "cache_index",
                           lambda: jnp.zeros((), jnp.int32))
        if initializing:
            q, k = rope(q), rope(k)
            out = dense_attention(q, k, v, causal=True).reshape(B, L, C)
            return _dense_cls(self.quant)(
                C, use_bias=False, dtype=self.dtype, name="proj")(out)
        idx = ci.value
        q = rope(q, offset=idx)
        k = rope(k, offset=idx)
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(ck.value.dtype), (0, idx, 0, 0))
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(cv.value.dtype), (0, idx, 0, 0))
        ci.value = idx + L
        if L > 1 and self.flash_prefill:
            # Prefill via the fused kernel.  OPT-IN (generate() sets it):
            # assumes a multi-token block only arrives as THE prompt at
            # cache index 0 — then causal attention within the block is
            # the whole answer, no O(L·max_len) dense score tensor.
            # Chunked-prefill callers must leave this off: a later chunk
            # needs the masked cache attention below.
            from pytorch_distributed_tpu.ops.flash_attention import (
                flash_attention,
            )

            out = flash_attention(q, k, v, True).reshape(B, L, C)
            return _dense_cls(self.quant)(
                C, use_bias=False, dtype=self.dtype, name="proj")(out)
        keys, values = ck.value, cv.value                 # [B, Lmax, H, D]
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q.astype(jnp.float32),
            keys.astype(jnp.float32)) / (D ** 0.5)
        kpos = jnp.arange(self.max_len)
        qpos = idx + jnp.arange(L)
        mask = kpos[None, None, None, :] <= qpos[None, None, :, None]
        scores = jnp.where(mask, scores, -1e30)
        w = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum(
            "bhqk,bkhd->bqhd", w, values.astype(jnp.float32)
        ).astype(q.dtype).reshape(B, L, C)
        return _dense_cls(self.quant)(
            C, use_bias=False, dtype=self.dtype, name="proj")(out)


class Block(nn.Module):
    n_heads: int
    dtype: Any = jnp.float32
    mesh: Optional[Mesh] = None
    ring: bool = False
    attn_impl: str = "auto"
    moe_experts: int = 0  # >0 replaces the dense MLP with an MoE layer
    moe_top_k: int = 1
    decode: bool = False
    max_len: int = 0
    sp_impl: str = "ring"
    quant: str = ""
    flash_prefill: bool = False

    @nn.compact
    def __call__(self, x):
        C = x.shape[-1]
        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        x = x + SelfAttention(self.n_heads, self.dtype, self.mesh, self.ring,
                              self.attn_impl, decode=self.decode,
                              max_len=self.max_len, sp_impl=self.sp_impl,
                              quant=self.quant,
                              flash_prefill=self.flash_prefill,
                              name="attn")(h)
        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        if self.moe_experts > 0:
            from pytorch_distributed_tpu.models.moe import MoEMLP

            h = MoEMLP(self.moe_experts, dtype=self.dtype,
                       top_k=self.moe_top_k, name="moe")(h)
        else:
            dense = _dense_cls(self.quant)
            h = dense(4 * C, dtype=self.dtype, name="fc1")(h)
            h = nn.gelu(h)
            h = dense(C, dtype=self.dtype, name="fc2")(h)
        return x + h


class TransformerLM(nn.Module):
    """Next-token LM.  ``__call__(tokens[B, L]) -> logits[B, L, vocab]``."""

    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 8
    dtype: Any = jnp.float32
    mesh: Optional[Mesh] = None
    ring: bool = False
    attn_impl: str = "auto"
    remat: bool = False  # rematerialize blocks: activations recomputed in
    #                      backward — O(sqrt) memory for long context
    #                      (the jax.checkpoint HBM/FLOPs trade, brief §HBM)
    moe_experts: int = 0  # >0: MoE MLP in every block (expert parallelism)
    moe_top_k: int = 1    # 1 = Switch routing; 2 = Mixtral-style top-2
    decode: bool = False  # KV-cached autoregressive inference mode
    max_len: int = 0      # cache capacity (decode mode)
    sp_impl: str = "ring"  # ring | a2a (Ulysses-style; parallel/ulysses.py)
    quant: str = ""        # "" | "int8" weight-only block kernels (serving;
    #                        params from models/quant.py:quantize_lm_params)
    flash_prefill: bool = False  # decode mode: fused-kernel prompt prefill
    #                              (single-block prompts only — generate())

    @nn.compact
    def __call__(self, tokens, train: bool = True,
                 return_hidden: bool = False):
        embed = nn.Embed(self.vocab_size, self.d_model, dtype=self.dtype,
                         name="embed")
        x = embed(tokens)
        block_cls = nn.remat(Block) if self.remat else Block
        for i in range(self.n_layers):
            x = block_cls(self.n_heads, self.dtype, self.mesh, self.ring,
                          self.attn_impl, self.moe_experts, self.moe_top_k,
                          decode=self.decode, max_len=self.max_len,
                          sp_impl=self.sp_impl, quant=self.quant,
                          flash_prefill=self.flash_prefill,
                          name=f"block_{i}")(x)
        x = nn.LayerNorm(dtype=jnp.float32, name="ln_f")(x)
        if return_hidden:
            # Pre-head hidden states for the fused tied-head+CE loss
            # (ops/fused_ce.py) — the [B, L, vocab] logits tensor never
            # materializes; the caller projects per row chunk against
            # params["embed"]["embedding"].
            return x
        # Tied output head (embed.attend) keeps params lean at long context.
        return embed.attend(x.astype(jnp.float32)).astype(jnp.float32)


def transformer_lm(num_classes: int = 32000, dtype: Any = jnp.float32, **kw):
    """Registry adapter: ``num_classes`` plays the vocab-size role."""
    return TransformerLM(vocab_size=num_classes, dtype=dtype, **kw)
