"""Mixture-of-Experts MLP with expert parallelism over an ``expert`` mesh axis.

Beyond-reference capability completing the framework's parallelism menu
(dp / tp / sp / **ep**).  Switch-Transformer-style top-1 routing (or
GShard/Mixtral-style top-k with renormalized gates, ``top_k > 1``) with a
capacity limit, expressed as dense dispatch/combine einsums — the
GSPMD-idiomatic formulation: expert parameters are stacked on a leading
``E`` axis and sharded ``P('expert', …)``; XLA lowers the dispatch einsum to
the all-to-all token exchange across the expert axis.  No hand-written
routing collectives.

The router's auxiliary load-balancing loss (Switch eq. 4: ``E · Σ_e f_e·p_e``)
is recorded via ``self.sow("losses", …)``; the LM step collects it with
``mutable=["losses"]`` and adds it to the objective.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp


class _FFN(nn.Module):
    d_model: int
    d_hidden: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.d_hidden, dtype=self.dtype, name="fc1")(x)
        h = nn.gelu(h)
        return nn.Dense(self.d_model, dtype=self.dtype, name="fc2")(h)


class MoEMLP(nn.Module):
    n_experts: int
    capacity_factor: float = 1.25
    aux_coef: float = 0.01
    dtype: Any = jnp.float32
    # 1 = Switch (gate = raw top prob); >1 = GShard/Mixtral-style top-k with
    # renormalized gates and sequential capacity (first choices queue first).
    top_k: int = 1

    @nn.compact
    def __call__(self, x):
        B, L, C = x.shape
        E = self.n_experts
        S = B * L
        k = min(self.top_k, E)
        cap = max(1, int(self.capacity_factor * k * S / E))
        tokens = x.reshape(S, C)

        # Router runs in f32 (standard for stability).
        logits = nn.Dense(E, dtype=jnp.float32, name="router")(
            tokens.astype(jnp.float32)
        )
        probs = jax.nn.softmax(logits, axis=-1)                  # [S, E]
        topk_probs, topk_idx = jax.lax.top_k(probs, k)           # [S, k]
        if k == 1:
            gates = topk_probs                                   # Switch
        else:
            gates = topk_probs / jnp.maximum(
                topk_probs.sum(-1, keepdims=True), 1e-9
            )

        # Dispatch/combine accumulated choice-by-choice: choice c's tokens
        # take queue positions after all kept earlier-choice tokens (the
        # priority ordering GShard prescribes).
        dispatch = jnp.zeros((S, E, cap), jnp.float32)
        combine = jnp.zeros((S, E, cap), jnp.float32)
        counts = jnp.zeros((E,), jnp.float32)
        for c in range(k):
            onehot = jax.nn.one_hot(topk_idx[:, c], E, dtype=jnp.float32)
            pos = (jnp.cumsum(onehot, axis=0) - 1.0) + counts[None, :]
            pos_in_expert = jnp.sum(pos * onehot, axis=-1).astype(jnp.int32)
            keep = (pos_in_expert < cap).astype(jnp.float32)
            d_c = (
                onehot[:, :, None]
                * jax.nn.one_hot(pos_in_expert, cap, dtype=jnp.float32)[:, None, :]
                * keep[:, None, None]
            )                                                     # [S, E, cap]
            dispatch = dispatch + d_c
            combine = combine + d_c * gates[:, c][:, None, None]
            counts = counts + jnp.sum(onehot * keep[:, None], axis=0)

        # Aux loss (Switch eq. 4) on the first-choice assignment.
        frac = jnp.mean(
            jax.nn.one_hot(topk_idx[:, 0], E, dtype=jnp.float32), axis=0
        )
        imp = jnp.mean(probs, axis=0)
        self.sow("losses", "moe_aux", self.aux_coef * E * jnp.sum(frac * imp))

        expert_in = jnp.einsum(
            "sec,sd->ecd", dispatch, tokens.astype(jnp.float32)
        ).astype(self.dtype)                                      # [E, cap, C]

        experts = nn.vmap(
            _FFN,
            in_axes=0, out_axes=0,
            variable_axes={"params": 0},   # stacked params, leading E axis
            split_rngs={"params": True},
            metadata_params={nn.PARTITION_NAME: "expert"},
        )(d_model=C, d_hidden=4 * C, dtype=self.dtype, name="experts")
        expert_out = experts(expert_in)                           # [E, cap, C]

        out = jnp.einsum(
            "sec,ecd->sd", combine, expert_out.astype(jnp.float32)
        )
        return out.reshape(B, L, C).astype(x.dtype)


def moe_specs(params, expert_axis: str = "expert"):
    """PartitionSpec tree: expert-stacked params sharded on their leading
    axis; everything else replicated.  Compose with tp.py's ``state_specs``."""
    from jax.sharding import PartitionSpec as P

    def spec(path, leaf):
        names = [getattr(k, "key", str(k)) for k in path]
        if "experts" in names:
            return P(expert_axis, *([None] * (leaf.ndim - 1)))
        return P()

    return jax.tree_util.tree_map_with_path(spec, params)
