"""pytorch_distributed_tpu — a TPU-native distributed training framework.

A brand-new framework with the capability matrix of
tczhangzhi/pytorch-distributed (see /root/repo/SURVEY.md): one canonical
ImageNet-classification training harness offered as a matrix of
interchangeable distributed-training recipes, built idiomatically on
JAX/XLA for TPU:

- ``parallel/``  — device meshes over ICI/DCN, ``jax.distributed`` bootstrap,
  collective helpers, sequence-parallel ring attention.  Replaces the
  reference's NCCL / Horovod / SLURM rendezvous stacks (SURVEY.md §5.8).
- ``data/``      — sharded, epoch-reshuffled, double-buffered input pipeline.
  Replaces ``DistributedSampler`` + the apex CUDA-stream ``data_prefetcher``
  (reference apex_distributed.py:115-169).
- ``models/``    — model registry (ResNet family and friends) mirroring the
  torchvision-zoo introspection surface (reference distributed.py:21-23).
- ``ops/``       — loss / metric ops and Pallas TPU kernels.
- ``train/``     — the canonical harness: meters, LR schedule, SGD, jitted
  SPMD train/eval steps, checkpointing, epoch driver
  (reference distributed.py:228-395).
- ``recipes/``   — one entry point per reference script, same flag surface.
- ``utils/``     — CSV timers and TPU telemetry (reference statistics.sh).
"""

__version__ = "0.1.0"

from pytorch_distributed_tpu import models  # noqa: F401  (registry import)
