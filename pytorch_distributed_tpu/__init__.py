"""pytorch_distributed_tpu — a TPU-native distributed training framework.

A brand-new framework with the capability matrix of
tczhangzhi/pytorch-distributed (see /root/repo/SURVEY.md): one canonical
ImageNet-classification training harness offered as a matrix of
interchangeable distributed-training recipes, built idiomatically on
JAX/XLA for TPU:

- ``parallel/``  — device meshes over ICI/DCN, ``jax.distributed`` bootstrap,
  collective helpers, sequence-parallel ring attention.  Replaces the
  reference's NCCL / Horovod / SLURM rendezvous stacks (SURVEY.md §5.8).
- ``data/``      — sharded, epoch-reshuffled, double-buffered input pipeline.
  Replaces ``DistributedSampler`` + the apex CUDA-stream ``data_prefetcher``
  (reference apex_distributed.py:115-169).
- ``models/``    — model registry (ResNet family and friends) mirroring the
  torchvision-zoo introspection surface (reference distributed.py:21-23).
- ``ops/``       — loss / metric ops and Pallas TPU kernels.
- ``train/``     — the canonical harness: meters, LR schedule, SGD, jitted
  SPMD train/eval steps, checkpointing, epoch driver
  (reference distributed.py:228-395).
- ``recipes/``   — one entry point per reference script, same flag surface.
- ``utils/``     — CSV timers and TPU telemetry (reference statistics.sh).
"""

__version__ = "0.1.0"

import jax as _jax

if not hasattr(_jax, "shard_map"):
    # jax 0.4.x ships shard_map under jax.experimental with the replication
    # check spelled ``check_rep``; newer jax promotes it to jax.shard_map
    # with ``check_vma``.  The framework is written against the promoted
    # API — bridge it here (this package is imported before any module
    # that does ``from jax import shard_map``).
    from jax.experimental.shard_map import shard_map as _shard_map_old

    def _shard_map_compat(f, *, mesh, in_specs, out_specs, check_vma=True,
                          **kw):
        return _shard_map_old(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=check_vma, **kw)

    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    # Promoted alongside jax.shard_map; on 0.4.x the idiom is psum(1, axis)
    # (special-cased to return the static axis size, not a collective).
    def _axis_size_compat(axis_name):
        return _jax.lax.psum(1, axis_name)

    _jax.lax.axis_size = _axis_size_compat

from pytorch_distributed_tpu import models  # noqa: F401  (registry import)
