"""Recipe 9 — sample from a trained TransformerLM checkpoint.

The serving end of the LM story: load a checkpoint written by
``lm_pretrain`` (msgpack or orbax), prefill the prompt into the KV caches,
and decode with greedy / temperature / top-k / nucleus sampling — one
compiled program, cached across calls (``models/generate.py``).

The reference's inference surface is ``--evaluate`` on the image harness
(distributed.py:197-199); this is the text-family analogue.  With a byte
vocab (``--vocab 256``, the ``TextFileDataset`` convention: bytes ARE the
tokens) ``--prompt`` is encoded as UTF-8 bytes and the continuation is
decoded back to text.

Examples:

    python -m pytorch_distributed_tpu.recipes.lm_generate \
        --resume runs/lm/checkpoint.msgpack --vocab 256 --d-model 256 \
        --n-heads 8 --n-layers 4 --prompt "def main(" -n 64 \
        --temperature 0.8 --top-p 0.9
    python -m pytorch_distributed_tpu.recipes.lm_generate --random-init \
        --prompt-tokens 1,2,3 -n 8        # smoke, no checkpoint needed
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_tpu.models.generate import generate
from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.train.checkpoint import load_checkpoint
from pytorch_distributed_tpu.train.optim import sgd_init
from pytorch_distributed_tpu.train.state import TrainState


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="sample from a TransformerLM")
    p.add_argument("--resume", default="",
                   help="checkpoint path (msgpack file or orbax dir) from "
                        "lm_pretrain; model flags must match its arch")
    p.add_argument("--random-init", action="store_true",
                   help="skip the checkpoint and sample from fresh init "
                        "(smoke/testing)")
    p.add_argument("--vocab", type=int, default=256)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--prompt", default="",
                   help="text prompt (byte-encoded; requires --vocab >= 256)")
    p.add_argument("--prompt-tokens", default="",
                   help="comma-separated token ids (alternative to --prompt)")
    p.add_argument("-n", "--max-new-tokens", type=int, default=64)
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy")
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--top-p", type=float, default=0.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--precision", choices=("fp32", "bf16"), default="fp32")
    p.add_argument("--tp", type=int, default=1,
                   help="model-parallel decode over this many devices "
                        "(Megatron-sharded params + KV caches)")
    p.add_argument("--quant", choices=("", "int8"), default="",
                   help="int8 = weight-only quantized block kernels "
                        "(halves the parameter HBM stream that bounds "
                        "small-batch decode)")
    # Speculative decoding: a cheap draft proposes gamma tokens, the
    # target scores the block in ONE cached pass; rejection sampling keeps
    # the output distributed exactly as target-only (models/speculative.py).
    p.add_argument("--spec-draft", default="",
                   help="enable speculative decoding: draft checkpoint "
                        "path, or 'random' for a fresh-init draft (smoke)")
    p.add_argument("--spec-d-model", type=int, default=0,
                   help="draft d_model (default: target d_model // 4)")
    p.add_argument("--spec-n-heads", type=int, default=0,
                   help="draft n_heads (default: max(1, target // 4))")
    p.add_argument("--spec-n-layers", type=int, default=0,
                   help="draft n_layers (default: max(1, target // 4))")
    p.add_argument("--spec-gamma", type=int, default=4,
                   help="draft tokens proposed per target scoring pass")
    return p


def _encode_prompt(args) -> np.ndarray:
    if args.prompt_tokens:
        toks = [int(t) for t in args.prompt_tokens.split(",")]
    elif args.prompt:
        if args.vocab < 256:
            raise SystemExit("--prompt needs --vocab >= 256 (byte tokens); "
                             "use --prompt-tokens for small vocabs")
        toks = list(args.prompt.encode("utf-8"))
    else:
        raise SystemExit("provide --prompt or --prompt-tokens")
    bad = [t for t in toks if not 0 <= t < args.vocab]
    if bad:
        raise SystemExit(f"prompt tokens out of range [0,{args.vocab}): {bad}")
    return np.asarray(toks, np.int32)[None, :]


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if not args.random_init and not args.resume:
        raise SystemExit("provide --resume CHECKPOINT (or --random-init)")
    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    cfg = dict(vocab_size=args.vocab, d_model=args.d_model,
               n_heads=args.n_heads, n_layers=args.n_layers)

    model = TransformerLM(**cfg, dtype=dtype)
    init_tokens = jnp.zeros((1, 8), jnp.int32)
    variables = model.init(jax.random.PRNGKey(args.seed), init_tokens)
    params = variables["params"]
    if args.resume:
        template = TrainState.create(
            {"params": params}, sgd_init(params))
        state, meta = load_checkpoint(args.resume, template)
        params = state.params
        print(f"loaded {args.resume} (epoch {meta.get('epoch')}, "
              f"arch {meta.get('arch') or 'transformer_lm'})")

    if args.quant:
        from pytorch_distributed_tpu.models.quant import quantize_lm_params

        params = quantize_lm_params(params)

    prompt = jnp.asarray(_encode_prompt(args))
    sample_kw = dict(cfg, dtype=dtype, temperature=args.temperature,
                     top_k=args.top_k, top_p=args.top_p, seed=args.seed,
                     quant=args.quant)
    if args.spec_draft:
        if args.tp > 1:
            raise SystemExit("--spec-draft is batch-1 single-device "
                             "serving; it does not compose with --tp")
        from pytorch_distributed_tpu.models.speculative import (
            speculative_generate,
        )

        draft_cfg = dict(
            vocab_size=args.vocab,
            d_model=args.spec_d_model or max(32, args.d_model // 4),
            n_heads=args.spec_n_heads or max(1, args.n_heads // 4),
            n_layers=args.spec_n_layers or max(1, args.n_layers // 4),
        )
        draft_model = TransformerLM(**draft_cfg, dtype=dtype)
        draft_params = draft_model.init(
            jax.random.PRNGKey(args.seed + 1), init_tokens)["params"]
        if args.spec_draft != "random":
            d_template = TrainState.create(
                {"params": draft_params}, sgd_init(draft_params))
            d_state, d_meta = load_checkpoint(args.spec_draft, d_template)
            draft_params = d_state.params
            print(f"loaded draft {args.spec_draft} "
                  f"(epoch {d_meta.get('epoch')})")
        out, stats = speculative_generate(
            params, draft_params, prompt, args.max_new_tokens,
            target_cfg=cfg, draft_cfg=draft_cfg, gamma=args.spec_gamma,
            dtype=dtype, temperature=args.temperature, top_k=args.top_k,
            top_p=args.top_p, seed=args.seed, quant=args.quant)
        print(f"speculative: {stats['target_passes']} target passes for "
              f"{stats['tokens']} tokens "
              f"({stats['tokens_per_target_pass']:.2f} tok/pass, "
              f"mean accepted {stats['mean_accepted']:.2f}/{args.spec_gamma})")
    elif args.tp > 1:
        from pytorch_distributed_tpu.models.generate import tp_generate
        from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

        mesh = build_mesh(MeshSpec(("model",), (args.tp,)),
                          jax.devices()[:args.tp])
        out = tp_generate(params, prompt, args.max_new_tokens, mesh=mesh,
                          **sample_kw)
    else:
        out = generate(params, prompt, args.max_new_tokens, **sample_kw)
    toks = np.asarray(out)[0].tolist()
    print("tokens:", toks)
    if args.vocab >= 256 and args.prompt:
        # Byte-LM convention: ids < 256 are bytes.  Ids beyond that (possible
        # when --vocab > 256) have no byte meaning — render each as U+FFFD so
        # the text line never silently drops a generated token.
        text = b"".join(
            bytes([t]) if t < 256 else "�".encode() for t in toks
        ).decode("utf-8", "replace")
        print("text:", repr(args.prompt + text))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
