"""Recipe 8 — long-context LM pretraining over composable dp×sp×tp (or
dp×pp, dp×ep) meshes.

Beyond-reference recipe (the reference is image-only): next-token training
of the TransformerLM with the framework's parallelism menu —

- ``--tp N``  tensor parallelism (Megatron-style sharded qkv/proj/fc1/fc2 +
  vocab-sharded embedding; XLA inserts the per-block all-reduces)
- ``--sp N``  sequence parallelism over the ``seq`` axis — ``--sp-impl
  ring`` (KV rotation) or ``a2a`` (Ulysses-style all-to-all re-slice to
  head-sharded; the inner attention sees the full sequence and can run
  the Pallas flash kernel); **composes with --tp**: one ``(data, seq,
  model)`` mesh, heads sharded over ``model`` inside either formulation
- ``--pp N``  pipeline parallelism (GPipe stages over ``pipe``); composes
  with the data axis AND with ``--tp``/``--sp``, which then run *inside*
  each stage (``parallel/tp_stage.py``) — up to all four axes in one
  ``(data, pipe, seq, model)`` mesh
- ``--ep N``  expert parallelism (MoE model variant; exclusive of
  --tp/--sp/--pp, composes with --fsdp: non-expert leaves and the free
  dims of the expert stacks shard over ``data``)
- remaining devices form the ``data`` axis (gradient psum)

Examples (8 simulated chips):

    python -m pytorch_distributed_tpu.recipes.lm_pretrain --tp 4 \
        --d-model 512 --n-layers 4 --seq-len 512 -b 16 --steps 50
    python -m pytorch_distributed_tpu.recipes.lm_pretrain --sp 2 --tp 2 \
        --seq-len 8192 -b 8 --steps 20
    python -m pytorch_distributed_tpu.recipes.lm_pretrain --pp 4 \
        --n-layers 8 -b 16 --steps 20
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.ft.elastic import ElasticSim
from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh, initialize
from pytorch_distributed_tpu.parallel.tp import replicated_like, tp_specs
from pytorch_distributed_tpu.train.lm import (
    LMTrainer,
    SyntheticTokenDataset,
    TextFileDataset,
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU LM pretraining (long context)")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("-b", "--batch-size", type=int, default=32,
                   help="global batch (sequences)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--warmup-steps", type=int, default=0,
                   help=">0: linear warmup then cosine decay to 10%% of "
                        "--lr over --steps (fixed lr otherwise)")
    p.add_argument("--clip-grad-norm", type=float, default=0.0,
                   help=">0: in-graph global-norm gradient clipping")
    p.add_argument("--fused-ce", type=int, default=0, metavar="CHUNKS",
                   help="fused tied-head+CE loss in CHUNKS row blocks "
                        "(ops/fused_ce.py): the [B,L,vocab] logits tensor "
                        "never materializes — big-vocab HBM/memory lever; "
                        "0 = unfused (exact parity tested either way)")
    p.add_argument("--fused-ce-mode", default="auto",
                   choices=("auto", "replicated", "dp", "tp"),
                   dest="fused_ce_mode",
                   help="fused-CE sharding variant: dp keeps the backward's "
                        "dE accumulator as a [V/k, D] vocab-row shard per "
                        "device (data-sharded meshes); tp consumes the "
                        "--tp vocab-sharded embedding directly inside "
                        "shard_map (no replication of e or dE); auto picks "
                        "from the mesh + param specs; replicated = the "
                        "original GSPMD path")
    p.add_argument("--accum-steps", type=int, default=1,
                   help="gradient accumulation microbatches inside the "
                        "compiled step (long-context memory relief; "
                        "redundant with --pp, whose schedule already "
                        "microbatches)")
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel (ring) size")
    p.add_argument("--sp-impl", choices=("ring", "a2a"), default="ring",
                   help="SP formulation: ring (ppermute KV rotation, no "
                        "head constraint) or a2a (Ulysses-style all-to-all "
                        "to head-sharded, inner attention sees the full "
                        "sequence and can use the Pallas flash kernel; "
                        "needs n_heads divisible by sp*tp)")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel size (MoE MLPs, one expert/device)")
    p.add_argument("--moe-top-k", type=int, default=1,
                   help="experts per token for --ep (1=Switch, 2=Mixtral-style)")
    p.add_argument("--pp", type=int, default=1,
                   help="pipeline-parallel size (GPipe stages over a 'pipe' "
                        "mesh axis; composes with the data axis, --tp and "
                        "--sp — Megatron TP / ring SP run inside each stage)")
    p.add_argument("--microbatches", type=int, default=0,
                   help="pipeline microbatches (default: pp)")
    p.add_argument("--schedule", choices=("gpipe", "1f1b", "interleaved"),
                   default="gpipe",
                   help="pipeline schedule: gpipe (autodiff, stash O(M)); "
                        "1f1b (manual gradients, stash bounded at 2(pp-1)+1 "
                        "microbatches — parallel/pp_1f1b.py); interleaved "
                        "(virtual-stage 1f1b, --pp-virtual chunks/device: "
                        "bubble/(V) at V x stash — parallel/pp_interleaved.py)")
    p.add_argument("--pp-virtual", type=int, default=2, dest="pp_virtual",
                   help="model chunks per device under --schedule "
                        "interleaved (V; n-layers must divide by pp*V)")
    p.add_argument("--remat", action="store_true",
                   help="checkpoint each pipeline stage (gpipe schedule): "
                        "stash stage inputs only, recompute activations in "
                        "backward")
    p.add_argument("--fsdp", action="store_true",
                   help="shard parameters + optimizer state over the data "
                        "axis (ZeRO-3 layout; GSPMD paths, composes with "
                        "--tp/--sp/--ep and with --pp: stage params gather "
                        "at the pipeline boundary, grads reduce-scatter "
                        "back)")
    p.add_argument("--precision", choices=("fp32", "bf16"), default="bf16")
    p.add_argument("--zero", choices=("none", "wus"), default="none",
                   help="ZeRO-style weight-update sharding (parallel/"
                        "zero.py): 'wus' gives momentum leaves fsdp_specs "
                        "data-axis shardings (composed over the --tp/--pp "
                        "layout) while params stay in their declared "
                        "layout — 1/N optimizer bytes per device, same "
                        "numerics and checkpoint format.  Lighter than "
                        "--fsdp (which also shards the params; that is the "
                        "ZeRO-3 layout, this is ZeRO-1)")
    p.add_argument("--grad-compress", choices=("none", "bf16", "int8", "fp8"),
                   default="none", dest="grad_compress",
                   help="gradient-sync compression (ops/qcomm.py): bf16 "
                        "round-trip cast, or int8/fp8 block quantization "
                        "with error feedback.  The LM step is GSPMD, so "
                        "quantized modes run as a numerics emulation "
                        "under the default GSPMD step (wire bytes "
                        "unchanged; convergence effects real) — add "
                        "--overlap bucketed on a pure-DP run to switch to "
                        "the explicit shard_map step where the wire "
                        "really carries the compressed collectives")
    p.add_argument("--overlap", choices=("none", "bucketed"),
                   default="none",
                   help="comm-overlap scheduler (parallel/overlap.py): "
                        "bucketed runs the pure-DP step as explicit "
                        "shard_map collectives with ~--bucket-mb MiB "
                        "reverse-autodiff grad buckets, each issued as "
                        "its own psum so sync overlaps the remaining "
                        "backward; bit-equal numerics.  Pure DP only "
                        "(no --tp/--sp/--pp/--fsdp/--fused-ce/"
                        "--accum-steps/--zero/--elastic)")
    p.add_argument("--bucket-mb", type=float, default=4.0,
                   dest="bucket_mb", metavar="MIB",
                   help="target gradient bucket size in MiB for --overlap "
                        "bucketed (smaller = more overlap, more "
                        "collectives)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-p", "--print-freq", type=int, default=10)
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--resume", type=str, default=None, metavar="PATH",
                   help="resume from a checkpoint: restores params/momentum "
                        "AND the exact step (the ft record), so the run "
                        "continues mid-stream instead of restarting")
    p.add_argument("--save-steps", type=int, default=0, dest="save_steps",
                   metavar="N",
                   help="also checkpoint every N steps (step-granular "
                        "resume: preemption/SIGKILL loses at most N steps); "
                        "0 = end-of-run only")
    p.add_argument("--preempt-signals", type=str, default="term",
                   dest="preempt_signals", metavar="SIGS",
                   help="comma-separated signals that trigger checkpoint-"
                        "and-exit at the next step boundary (default "
                        "'term'; add 'int' for interactive Ctrl-C runs)")
    p.add_argument("--nan-guard", action="store_true", dest="nan_guard",
                   help="divergence guard: skip non-finite steps in-graph; "
                        "after --ft-rollback-k consecutive bad steps, roll "
                        "back to the last-good state with an LR backoff")
    p.add_argument("--ft-rollback-k", type=int, default=3,
                   dest="ft_rollback_k", metavar="K",
                   help="consecutive non-finite steps before rollback")
    p.add_argument("--ft-check-every", type=int, default=10,
                   dest="ft_check_every", metavar="N",
                   help="drain the guard's buffered flags every N steps "
                        "(one amortized host sync)")
    p.add_argument("--ft-lr-backoff", type=float, default=0.5,
                   dest="ft_lr_backoff", metavar="F",
                   help="LR multiplier applied at each rollback")
    p.add_argument("--elastic", action="store_true", dest="elastic",
                   help="elastic training (ft/elastic.py): on rank loss "
                        "re-mesh to the survivors and continue from the "
                        "last-good snapshot; on rank join re-shard and "
                        "re-admit (plain-dp meshes only)")
    p.add_argument("--min-ranks", type=int, default=1, dest="min_ranks",
                   metavar="N",
                   help="elastic shrink floor: refuse changes that would "
                        "take the data axis below N ranks")
    p.add_argument("--rescale-lr", choices=("none", "linear", "sqrt"),
                   default="none", dest="rescale_lr",
                   help="LR/global-batch rule across an elastic world "
                        "change: none = global batch constant, LR "
                        "untouched; linear/sqrt = per-rank batch constant, "
                        "LR scaled by (new/old) or sqrt(new/old)")
    p.add_argument("--dataset-length", type=int, default=4096)
    p.add_argument("--text-glob", type=str, default=None,
                   help="train on real files: byte-level LM over this glob "
                        "(e.g. 'src/**/*.py'); forces --vocab 256 and "
                        "replaces the synthetic dataset")
    p.add_argument("--metrics-jsonl", type=str, default=None,
                   dest="metrics_jsonl", metavar="PATH",
                   help="append one structured JSON record per train step "
                        "(step-time EMA/p50/p95, tokens/s, loss, lr, "
                        "in-graph grad/param norms) to this file; "
                        "summarize with scripts/obs_report.py")
    p.add_argument("--hb-dir", type=str, default=None, dest="hb_dir",
                   metavar="DIR",
                   help="shared heartbeat directory: each mesh process "
                        "appends {pid, step, t} beats; obs_report.py flags "
                        "stragglers by step lag / beat age")
    p.add_argument("--hb-interval", type=float, default=5.0,
                   dest="hb_interval_s", metavar="SEC",
                   help="minimum seconds between heartbeats (default 5)")
    p.add_argument("--mfu", action="store_true",
                   help="report per-step MFU/HFU in the metrics JSONL: the "
                        "analytic LM FLOPs model (obs/flops.py — fused-CE, "
                        "remat, and pipeline schedules accounted) over the "
                        "chips' peak FLOPs")
    p.add_argument("--goodput", action="store_true",
                   help="track the goodput/badput ledger live (nan-skips, "
                        "rollback discards, preemption gaps, recompiles, "
                        "stalls) and print the summary at end of fit")
    p.add_argument("--watch-recompiles", action="store_true",
                   dest="watch_recompiles",
                   help="recompile watchdog (obs/watchdog.py): flag any "
                        "post-warmup recompilation of the jitted step as "
                        "an anomaly event via jax.monitoring")
    p.add_argument("--comm-ledger", type=str, default=None,
                   dest="comm_ledger", metavar="PATH",
                   help="write the step's itemized communication ledger "
                        "(per-collective bytes/fan-out/scope, obs/comms.py) "
                        "to PATH and stamp model_comm_bytes/comm_wire_bytes/"
                        "collective_count into each metrics record; costs "
                        "one extra AOT compile of the step")
    p.add_argument("--mem-ledger", type=str, default=None,
                   dest="mem_ledger", metavar="PATH",
                   help="write the step's static HBM memory ledger "
                        "(live-range watermark, top buffers at peak, "
                        "class/phase breakdown, obs/memory.py) to PATH and "
                        "stamp mem_peak_bytes into each metrics record; "
                        "rides the --comm-ledger AOT lowering so the pair "
                        "costs one shared compile")
    p.add_argument("--lowering-cache", type=str, default=None,
                   dest="lowering_cache", metavar="DIR",
                   help="persist the ledger AOT lowering's artifacts "
                        "(<step>.hlo + <step>.json, analysis/lowering.py "
                        "layout) under DIR for post-hoc text-only "
                        "re-analysis")
    p.add_argument("--flight-rec", type=str, default=None,
                   dest="flight_rec", metavar="DIR",
                   help="flight recorder (obs/flightrec.py): bounded "
                        "in-memory ring of step/collective/ft events "
                        "dumped to DIR/flightrec_rank<k>.json on any "
                        "death path (signal, rollback, checkpoint "
                        "corruption, unhandled exception, hang watchdog); "
                        "merge dumps with scripts/postmortem.py")
    p.add_argument("--hang-timeout", type=float, default=30.0,
                   dest="hang_timeout", metavar="SEC",
                   help="hang-watchdog floor: flag a step exceeding "
                        "max(SEC, 4×p95), emit a `hang` ft_event with the "
                        "last-entered collective, and dump the flight "
                        "ring pre-mortem (needs --flight-rec)")
    p.add_argument("--metrics-port", type=int, default=0,
                   dest="metrics_port", metavar="PORT",
                   help="serve live Prometheus metrics on PORT + rank "
                        "(obs/export.py; one daemon thread per rank, "
                        "latest drained record; 0 disables; watch the "
                        "fleet with scripts/obs_live.py)")
    p.add_argument("--alerts", type=str, default=None, dest="alerts",
                   metavar="RULES",
                   help="declarative alert rules (obs/alerts.py): a JSON "
                        "rules file or 'default' for the built-in set; "
                        "firing alerts are booked as `alert` ft_events "
                        "in the metrics JSONL and exported to /metrics")
    p.add_argument("--step-attr", action="store_true", dest="step_attr",
                   help="exact per-step wall-time attribution "
                        "(obs/stepattr.py): stamp attr_* fields — compute "
                        "/ exposed_comm / host_sync / data_wait / other, "
                        "summing to step_time exactly — into every "
                        "metrics record; analyze with "
                        "scripts/obs_roofline.py")
    p.add_argument("--eval-every", type=int, default=0,
                   help="run held-out eval (loss/ppl) every N steps; "
                        "0 = end-of-run only")
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--no-eval", action="store_true",
                   help="disable the held-out eval entirely")
    p.add_argument("--generate", type=int, default=0, metavar="N",
                   help="after training, greedy-decode N tokens from a "
                        "dataset prompt (plain dp runs only)")
    return p


def main(argv=None) -> float:
    args = build_parser().parse_args(argv)
    ctx = initialize()
    n = jax.device_count()
    if args.ep > 1 and (args.tp > 1 or args.sp > 1 or args.pp > 1):
        raise SystemExit("--ep is exclusive (MoE model variant); "
                         "--tp composes with --sp or --pp")
    if args.warmup_steps >= args.steps and args.warmup_steps > 0:
        raise SystemExit(f"--warmup-steps {args.warmup_steps} must be < "
                         f"--steps {args.steps} (no room for cosine decay)")
    if args.sp > 1 and args.seq_len % args.sp:
        raise SystemExit(f"--seq-len {args.seq_len} not divisible by "
                         f"--sp {args.sp}")
    if args.schedule in ("1f1b", "interleaved") and args.pp <= 1:
        raise SystemExit(f"--schedule {args.schedule} requires --pp > 1")
    if args.schedule in ("1f1b", "interleaved") and (args.tp > 1
                                                     or args.sp > 1):
        raise SystemExit(f"--schedule {args.schedule} supports plain "
                         "stages; use gpipe for TP/SP-in-stage")
    if args.schedule == "interleaved":
        micro = args.microbatches or args.pp
        if micro % args.pp:
            raise SystemExit(f"--schedule interleaved needs --microbatches "
                             f"{micro} divisible by --pp {args.pp}")
        if args.n_layers % (args.pp * args.pp_virtual):
            raise SystemExit(f"--n-layers {args.n_layers} not divisible by "
                             f"pp*V = {args.pp * args.pp_virtual}")
    if args.remat and args.pp <= 1:
        raise SystemExit("--remat applies to the pipeline stages "
                         "(requires --pp > 1)")
    if args.fused_ce and args.pp > 1:
        raise SystemExit("--fused-ce applies to the non-pipelined loss "
                         "path (the pipeline schedules own their loss "
                         "head); drop --pp or --fused-ce")
    if args.accum_steps > 1 and args.pp > 1:
        raise SystemExit("--accum-steps with --pp is redundant: the pipeline "
                         "schedule already microbatches; raise "
                         "--microbatches instead")
    if n % (args.tp * args.sp * args.ep * args.pp):
        raise SystemExit(f"{n} devices not divisible by tp*sp*ep*pp")
    if args.pp > 1 and args.n_layers % args.pp:
        raise SystemExit(f"--n-layers {args.n_layers} not divisible by "
                         f"--pp {args.pp} stages")
    if args.pp > 1:
        micro = args.microbatches or args.pp
        # data axis of the pp(×sp)(×tp) mesh
        pp_dp = n // (args.pp * args.tp * args.sp)
        if args.batch_size % micro:
            raise SystemExit(f"-b {args.batch_size} not divisible by "
                             f"{micro} pipeline microbatches")
        if (args.batch_size // micro) % pp_dp:
            raise SystemExit(
                f"per-microbatch batch {args.batch_size // micro} not "
                f"divisible by the data axis ({pp_dp} replicas)")
    if args.moe_top_k < 1:
        raise SystemExit(f"--moe-top-k must be >= 1, got {args.moe_top_k}")
    if args.moe_top_k > 1 and args.ep <= 1:
        raise SystemExit("--moe-top-k requires --ep > 1 (it selects experts "
                         "per token in the MoE model variant)")
    if args.generate > 0 and (args.tp > 1 or args.sp > 1 or args.ep > 1
                              or args.pp > 1):
        raise SystemExit("--generate supports plain dp runs only")
    if args.elastic and (args.tp > 1 or args.sp > 1 or args.ep > 1
                         or args.pp > 1 or args.fsdp):
        raise SystemExit("--elastic re-meshes the data axis and supports "
                         "plain dp runs only (drop --tp/--sp/--ep/--pp/"
                         "--fsdp)")
    if not args.elastic and args.rescale_lr != "none":
        raise SystemExit("--rescale-lr applies to elastic world changes; "
                         "add --elastic")
    if args.overlap == "bucketed" and (
            args.tp > 1 or args.sp > 1 or args.ep > 1 or args.pp > 1
            or args.fsdp or args.fused_ce or args.accum_steps > 1
            or args.zero != "none" or args.elastic):
        raise SystemExit("--overlap bucketed runs the explicit shard_map "
                         "pure-DP step only; drop --tp/--sp/--ep/--pp/"
                         "--fsdp/--fused-ce/--accum-steps/--zero/--elastic")
    if args.sp_impl == "a2a" and args.sp > 1:
        if args.pp > 1:
            raise SystemExit("--sp-impl a2a does not run inside pipeline "
                             "stages yet; use the ring schedule with --pp")
        if args.n_heads % (args.sp * args.tp):
            raise SystemExit(f"--sp-impl a2a shards heads: --n-heads "
                             f"{args.n_heads} must be divisible by "
                             f"sp*tp = {args.sp * args.tp}")
    if args.tp > 1 and args.sp > 1 and args.n_heads % args.tp:
        # Composed with ring SP the attention heads are explicitly sharded
        # over 'model' (ring.py shard_map specs); pure GSPMD TP has no such
        # constraint.
        raise SystemExit(f"--n-heads {args.n_heads} not divisible by "
                         f"--tp {args.tp} (required when combined with --sp)")
    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32
    if args.text_glob:
        args.vocab = TextFileDataset.vocab  # before the model is built

    if args.ep > 1:
        mesh = build_mesh(MeshSpec(("data", "expert"), (n // args.ep, args.ep)))
        model = TransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, dtype=dtype, moe_experts=args.ep,
            moe_top_k=args.moe_top_k,
        )
        specs = "ep"
    elif args.pp > 1:
        from pytorch_distributed_tpu.models.pipeline_lm import (
            PipelinedTransformerLM,
        )

        axes = ["data", "pipe"]
        shape = [n // (args.pp * args.tp * args.sp), args.pp]
        if args.sp > 1:  # ring SP inside each stage (tp_stage.py)
            axes.append("seq")
            shape.append(args.sp)
        if args.tp > 1:  # Megatron TP inside each stage (tp_stage.py)
            axes.append("model")
            shape.append(args.tp)
        mesh = build_mesh(MeshSpec(tuple(axes), tuple(shape)))
        model = PipelinedTransformerLM(
            vocab_size=args.vocab, d_model=args.d_model,
            n_heads=args.n_heads, n_layers=args.n_layers,
            n_stages=args.pp,
            n_microbatches=args.microbatches or args.pp,
            mesh=mesh, dtype=dtype, tp_size=args.tp, sp_size=args.sp,
            schedule=args.schedule, remat=args.remat,
            n_virtual=(args.pp_virtual
                       if args.schedule == "interleaved" else 1),
        )
        specs = "pp"
    else:
        # Composable dp × sp × tp mesh: the data axis takes the remaining
        # devices; 'model' is innermost so Megatron's per-block all-reduces
        # ride the fastest ICI hops (parallel/mesh.py note).
        axes, shape = ["data"], [n // (args.tp * args.sp)]
        if args.sp > 1:
            axes.append("seq")
            shape.append(args.sp)
        if args.tp > 1:
            axes.append("model")
            shape.append(args.tp)
        mesh = build_mesh(MeshSpec(tuple(axes), tuple(shape)))
        model = TransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, dtype=dtype,
            mesh=mesh if args.sp > 1 else None, ring=args.sp > 1,
            sp_impl=args.sp_impl,
        )
        specs = "tp" if args.tp > 1 else None

    if args.text_glob:
        # hold out the 10% tail for eval only when eval will run
        train_span = (0.0, 1.0) if args.no_eval else (0.0, 0.9)
        try:
            dataset = TextFileDataset(args.text_glob, args.seq_len,
                                      span=train_span)
        except ValueError as e:
            raise SystemExit(
                f"--text-glob corpus too small for --seq-len "
                f"{args.seq_len} ({e}); add files or shorten --seq-len"
            ) from e
    else:
        dataset = SyntheticTokenDataset(
            args.dataset_length, args.seq_len, args.vocab, seed=args.seed
        )
    with mesh:
        # Init batch must cover the data axis (the ring shard_map divides the
        # batch dim during init tracing too).
        tokens0 = jnp.zeros((dict(mesh.shape).get("data", 1), args.seq_len),
                            jnp.int32)
        params_shape = None
        if specs in ("tp", "ep", "pp") or args.fsdp:
            params_shape = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(args.seed), tokens0)
            )["params"]
        if specs in ("tp", "ep", "pp"):
            if specs == "tp":
                specs = tp_specs(params_shape)
            elif specs == "pp":
                from pytorch_distributed_tpu.models.pipeline_lm import pp_specs

                specs = pp_specs(
                    params_shape,
                    model_axis="model" if args.tp > 1 else None,
                )
            else:
                from pytorch_distributed_tpu.models.moe import moe_specs

                specs = moe_specs(params_shape)
        if args.fsdp:
            from pytorch_distributed_tpu.parallel.fsdp import fsdp_specs

            specs = fsdp_specs(params_shape, mesh, base_specs=specs)
        if args.no_eval:
            eval_dataset = None
        elif args.text_glob:
            try:
                eval_dataset = TextFileDataset(  # held-out corpus tail
                    args.text_glob, args.seq_len, span=(0.9, 1.0))
            except ValueError as e:
                raise SystemExit(
                    f"the held-out 10% corpus tail is too small for "
                    f"--seq-len {args.seq_len} ({e}); add files, shorten "
                    f"--seq-len, or pass --no-eval to train on the full "
                    f"corpus") from e
        else:
            eval_dataset = SyntheticTokenDataset(
                max(args.dataset_length // 10, args.batch_size),
                args.seq_len, args.vocab, seed=args.seed + 1,
            )
        schedule = None
        if args.warmup_steps > 0:
            from pytorch_distributed_tpu.train.lm import warmup_cosine_lr

            schedule = warmup_cosine_lr(args.lr, args.warmup_steps, args.steps)
        # Preemption guard (previously only the image Trainer self-
        # installed one; the LM recipe ran unguarded): --preempt-signals
        # SIGTERM (pod reclaim) by default, SIGINT opt-in for interactive
        # runs.  Installed here (main thread — a Python signal-handler
        # restriction) and chained/uninstalled around fit.
        import threading

        from pytorch_distributed_tpu.utils.preempt import (
            PreemptionGuard,
            parse_signals,
        )

        guard = None
        if threading.current_thread() is threading.main_thread():
            guard = PreemptionGuard(
                signals=parse_signals(args.preempt_signals)).install()
        trainer = LMTrainer(
            model, mesh, dataset, args.batch_size, lr=args.lr,
            param_specs=specs, seed=args.seed, is_primary=ctx.is_primary,
            checkpoint_dir=args.checkpoint_dir,
            eval_dataset=eval_dataset, eval_every=args.eval_every,
            eval_batches=args.eval_batches,
            lr_schedule=schedule, clip_grad_norm=args.clip_grad_norm,
            accum_steps=args.accum_steps, fused_ce_chunks=args.fused_ce,
            fused_ce_mode=args.fused_ce_mode,
            metrics_jsonl=args.metrics_jsonl, hb_dir=args.hb_dir,
            hb_interval_s=args.hb_interval_s,
            mfu=args.mfu, goodput=args.goodput,
            watch_recompiles=args.watch_recompiles,
            comm_ledger=args.comm_ledger,
            mem_ledger=args.mem_ledger,
            lowering_cache=args.lowering_cache,
            save_steps=args.save_steps, resume=args.resume,
            nan_guard=args.nan_guard, ft_rollback_k=args.ft_rollback_k,
            ft_check_every=args.ft_check_every,
            ft_lr_backoff=args.ft_lr_backoff,
            preempt=guard,
            grad_compress=args.grad_compress,
            zero=args.zero,
            overlap=args.overlap,
            bucket_mb=args.bucket_mb,
            elastic=(ElasticSim(dict(mesh.shape).get("data", 1),
                                min_ranks=args.min_ranks)
                     if args.elastic else None),
            rescale_lr=args.rescale_lr,
            flight_rec=args.flight_rec,
            hang_timeout=args.hang_timeout,
            metrics_port=args.metrics_port,
            alerts=args.alerts,
            step_attr=args.step_attr,
        )
        try:
            final_loss = trainer.fit(args.steps, print_freq=args.print_freq)
        finally:
            if guard is not None:
                guard.uninstall()
        if args.generate > 0:  # plain-dp only, validated with the args above
            import jax as _jax
            import numpy as _np

            from pytorch_distributed_tpu.models.generate import greedy_generate

            prompt = dataset.batch(0, 1)[:, : min(16, args.seq_len // 2)]
            params = _jax.device_get(trainer.state.params)
            toks = greedy_generate(
                params, prompt, args.generate, vocab_size=args.vocab,
                d_model=args.d_model, n_heads=args.n_heads,
                n_layers=args.n_layers, dtype=dtype,
            )
            print(" * Generated:", " ".join(map(str, _np.asarray(toks)[0])),
                  flush=True)
    print(f" * Final loss {final_loss:.4f}", flush=True)
    return final_loss


if __name__ == "__main__":
    main()
