"""Recipe 8 — long-context LM pretraining over dp × (tp | sp) meshes.

Beyond-reference recipe (the reference is image-only): next-token training
of the TransformerLM with the framework's parallelism menu —

- ``--tp N``  tensor parallelism (Megatron-style sharded qkv/proj/fc1/fc2 +
  vocab-sharded embedding; XLA inserts the per-block all-reduces)
- ``--sp N``  sequence parallelism (ring attention over the ``seq`` axis)
- remaining devices form the ``data`` axis (gradient psum)

Examples (8 simulated chips):

    python -m pytorch_distributed_tpu.recipes.lm_pretrain --tp 4 \
        --d-model 512 --n-layers 4 --seq-len 512 -b 16 --steps 50
    python -m pytorch_distributed_tpu.recipes.lm_pretrain --sp 4 \
        --seq-len 8192 -b 8 --steps 20
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.transformer import TransformerLM
from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh, initialize
from pytorch_distributed_tpu.parallel.tp import replicated_like, tp_specs
from pytorch_distributed_tpu.train.lm import LMTrainer, SyntheticTokenDataset


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description="TPU LM pretraining (long context)")
    p.add_argument("--vocab", type=int, default=1024)
    p.add_argument("--d-model", type=int, default=256)
    p.add_argument("--n-heads", type=int, default=8)
    p.add_argument("--n-layers", type=int, default=2)
    p.add_argument("--seq-len", type=int, default=256)
    p.add_argument("-b", "--batch-size", type=int, default=32,
                   help="global batch (sequences)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--lr", type=float, default=1e-2)
    p.add_argument("--tp", type=int, default=1, help="tensor-parallel size")
    p.add_argument("--sp", type=int, default=1,
                   help="sequence-parallel (ring) size")
    p.add_argument("--ep", type=int, default=1,
                   help="expert-parallel size (MoE MLPs, one expert/device)")
    p.add_argument("--precision", choices=("fp32", "bf16"), default="bf16")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-p", "--print-freq", type=int, default=10)
    p.add_argument("--checkpoint-dir", type=str, default=None)
    p.add_argument("--dataset-length", type=int, default=4096)
    p.add_argument("--eval-every", type=int, default=0,
                   help="run held-out eval (loss/ppl) every N steps; "
                        "0 = end-of-run only")
    p.add_argument("--eval-batches", type=int, default=8)
    p.add_argument("--no-eval", action="store_true",
                   help="disable the held-out eval entirely")
    return p


def main(argv=None) -> float:
    args = build_parser().parse_args(argv)
    ctx = initialize()
    n = jax.device_count()
    if sum(x > 1 for x in (args.tp, args.sp, args.ep)) > 1:
        raise SystemExit("--tp/--sp/--ep cannot be combined yet (use one)")
    if n % (args.tp * args.sp * args.ep):
        raise SystemExit(f"{n} devices not divisible by tp*sp*ep")
    dtype = jnp.bfloat16 if args.precision == "bf16" else jnp.float32

    if args.ep > 1:
        mesh = build_mesh(MeshSpec(("data", "expert"), (n // args.ep, args.ep)))
        model = TransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, dtype=dtype, moe_experts=args.ep,
        )
        specs = "ep"
    elif args.sp > 1:
        mesh = build_mesh(MeshSpec(("data", "seq"), (n // args.sp, args.sp)))
        model = TransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, dtype=dtype, mesh=mesh, ring=True,
        )
        specs = None  # params replicated; sequence axis carries the sharding
    else:
        axes = ("data", "model") if args.tp > 1 else ("data",)
        shape = (n // args.tp, args.tp) if args.tp > 1 else (n,)
        mesh = build_mesh(MeshSpec(axes, shape))
        model = TransformerLM(
            vocab_size=args.vocab, d_model=args.d_model, n_heads=args.n_heads,
            n_layers=args.n_layers, dtype=dtype,
        )
        specs = "tp" if args.tp > 1 else None

    dataset = SyntheticTokenDataset(
        args.dataset_length, args.seq_len, args.vocab, seed=args.seed
    )
    with mesh:
        tokens0 = jnp.zeros((1, args.seq_len), jnp.int32)
        if specs in ("tp", "ep"):
            params_shape = jax.eval_shape(
                lambda: model.init(jax.random.PRNGKey(args.seed), tokens0)
            )["params"]
            if specs == "tp":
                specs = tp_specs(params_shape)
            else:
                from pytorch_distributed_tpu.models.moe import moe_specs

                specs = moe_specs(params_shape)
        eval_dataset = (
            None if args.no_eval else SyntheticTokenDataset(
                max(args.dataset_length // 10, args.batch_size),
                args.seq_len, args.vocab, seed=args.seed + 1,
            )
        )
        trainer = LMTrainer(
            model, mesh, dataset, args.batch_size, lr=args.lr,
            param_specs=specs, seed=args.seed, is_primary=ctx.is_primary,
            checkpoint_dir=args.checkpoint_dir,
            eval_dataset=eval_dataset, eval_every=args.eval_every,
            eval_batches=args.eval_batches,
        )
        final_loss = trainer.fit(args.steps, print_freq=args.print_freq)
    print(f" * Final loss {final_loss:.4f}", flush=True)
    return final_loss


if __name__ == "__main__":
    main()
