"""Recipe 5 — explicit-collective DP with compressed gradient wire format.

Reference: horovod_distributed.py (``hvd.init``; ``hvd.DistributedOptimizer``
per-parameter ring-allreduce hooks with ``Compression.fp16`` wire
compression; ``hvd.broadcast_parameters``; allreduce-as-barrier,
horovod_distributed.py:102-108,125,149,158-164; start.sh:4).

TPU-native delta: the step is expressed with **explicit collectives** —
``shard_map`` over the data axis with a hand-written ``psum``
(train/steps.py ``local_step``) — the moral equivalent of Horovod's
explicit ring allreduce, vs. the GSPMD recipes where XLA infers it.
Gradients cross the wire in **bf16** by default (``--grad-compress bf16``),
reproducing fp16 gradient compression with bf16's safer exponent range —
and ``--grad-compress int8`` (or ``fp8``) upgrades the sync to the
block-quantized two-hop collective with error feedback (ops/qcomm.py),
cutting grad wire bytes ~4x vs f32.  Parameter broadcast
≙ params born replicated on the mesh; the allreduce-doubles-as-barrier trick
is moot — XLA steps are bulk-synchronous.  BatchNorm is per-shard (local),
exactly like the GPU original's unsynced BN (see train/steps.py docstring).

``--zero wus`` upgrades this explicit step to weight-update sharding
(parallel/zero.py): the grad allreduce becomes a hand-written
reduce-scatter, momentum lives as sharded 1/N chunks, and the parameter
delta is all-gathered once per step — and it composes with
``--grad-compress int8``, putting *both* wire hops on the quantized qcomm
path with error feedback (the recommended DP configuration, TUTORIAL §4).
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (explicit collectives + compressed wire grads)",
        argv,
        explicit_collectives=True,
        grad_compress_default="bf16",
    )


if __name__ == "__main__":
    main()
