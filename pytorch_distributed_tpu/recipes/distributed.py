"""Recipe 2 — multi-process DP, external launcher.

Reference: distributed.py (``torch.distributed.launch --nproc_per_node=4``
sets env + ``--local_rank``; ``dist.init_process_group('nccl')``,
distributed.py:73-76,132; start.sh:2).

TPU-native delta: the launcher contract is environment variables
(``PTD_TPU_COORDINATOR / PTD_TPU_NUM_PROCESSES / PTD_TPU_PROCESS_ID`` — the
``env://`` analogue), consumed by ``jax.distributed.initialize``; on a TPU
pod the runtime metadata supplies them and no launcher is needed at all.
Gradient sync is GSPMD: XLA fuses the allreduce into the step program where
DDP hooks it onto backward (distributed.py:147-148).  ``--zero wus`` shards
the optimizer state 1/N over the data axis (parallel/zero.py — the
sharding-spec expression of weight-update sharding; ZeRO-1 ≙ torch's
ZeroRedundancyOptimizer, which DDP users bolt on for exactly this memory
ceiling).
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (multi-process DP, external launcher)", argv
    )


if __name__ == "__main__":
    main()
