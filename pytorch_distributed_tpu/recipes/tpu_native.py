"""Recipe 7 — the canonical TPU-native recipe (BASELINE.json's north star:
"add a sixth, TPU-native recipe alongside the five").

Everything on: bf16 compute policy, GSPMD gradient sync fused into the step,
sharded exact-masked evaluation, double-buffered device feeding, rank-0
checkpointing with resume, epoch CSV.  On a pod this same entry point spans
hosts via TPU runtime metadata with zero launcher ceremony.  ``--zero wus``
(parallel/zero.py) drops per-chip optimizer bytes to 1/N via fsdp_specs
momentum shardings; checkpoints stay interchangeable with every other
recipe (gather-on-save).
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (canonical TPU-native recipe)",
        argv,
        precision_default="bf16",
        epoch_csv_default="tpu_native.csv",
    )


if __name__ == "__main__":
    main()
