"""Recipe 4 — mixed-precision DP (the apex/AMP slot).

Reference: apex_distributed.py (``amp.initialize(model, optimizer)`` O1
cast-patching + dynamic loss scaling + apex DDP flat-buffer allreduce +
CUDA-stream ``data_prefetcher``, apex_distributed.py:115-169,216-217,328-329;
start.sh:3).

TPU-native delta: bf16 keeps fp32's exponent range, so the whole AMP
apparatus — cast lists, ``scale_loss``, overflow-skip steps — reduces to a
compute-dtype policy: params stay f32 masters, matmuls/convs run bf16 on the
MXU, loss and BN statistics accumulate f32 (models/resnet.py).  The
prefetcher's copy/compute overlap is the DeviceFeeder's background async
transfers (data/loader.py).  The reference's double-normalize quirk
(SURVEY.md §7.5: transform Normalize *and* GPU-side sub_/div_ with 0-255
constants) is documented, not replicated.  ``--zero wus`` additionally
shards the f32 optimizer state 1/N over the data axis (parallel/zero.py) —
under bf16 compute the f32 momentum masters are exactly the bytes worth
sharding first.
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (bf16 mixed precision DP)",
        argv,
        precision_default="bf16",
    )


if __name__ == "__main__":
    main()
