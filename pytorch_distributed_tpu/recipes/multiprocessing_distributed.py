"""Recipe 3 — self-contained multi-process DP (no external launcher).

Reference: multiprocessing_distributed.py (``mp.spawn(main_worker, nprocs)``
inside the script, explicit ``tcp://127.0.0.1:23456`` rendezvous,
multiprocessing_distributed.py:114,132-135; start.sh:1).

TPU-native delta: JAX is one process per *host*, with every local chip
already addressable, so the reference's per-GPU process fan-out collapses
into the runtime — this recipe is the self-contained shape: plain
``python -m``, explicit coordinator default (127.0.0.1, the reference's TCP
address analogue) when ``PTD_TPU_NUM_PROCESSES`` asks for more than one
process, else single-process over all local chips.  This is the minimum
end-to-end slice of SURVEY.md §7.3.  Accepts ``--zero wus`` like every
recipe (parallel/zero.py weight-update sharding; Trainer threads it from
the shared Config).
"""

import os

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    # Explicit-rendezvous parity: default the coordinator like the
    # reference's hardcoded tcp://127.0.0.1:23456 when multi-process.
    if "PTD_TPU_NUM_PROCESSES" in os.environ:
        os.environ.setdefault("PTD_TPU_COORDINATOR", "127.0.0.1:23456")
    return run_recipe(
        "TPU ImageNet Training (self-contained multi-process DP)", argv
    )


if __name__ == "__main__":
    main()
