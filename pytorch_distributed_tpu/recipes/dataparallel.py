"""Recipe 1 — single-process data parallelism over all local chips.

Reference: dataparallel.py (``nn.DataParallel(model, device_ids,
output_device)``, dataparallel.py:118-119,138; launched as plain ``python
main.py``, README.md:86).

TPU-native delta: where DataParallel replicates the module and
scatter/gathers through GPU0 each step (the reference's own docs call it
"not recommended" — 3.5× slower than DDP, BASELINE.md), one XLA program over
a local ``data`` mesh is *already* fully parallel: no master device, no
gather bottleneck, same step math as every other recipe.  The per-epoch CSV
(dataparallel.py:188,205-213) is on by default, same file name.

``--zero wus`` lifts the replicated-optimizer ceiling (parallel/zero.py):
momentum takes fsdp_specs shardings under this GSPMD step and XLA inserts
the reduce-scatter/all-gather weight-update pair — 1/N optimizer bytes per
chip, identical numerics.
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (single-process data parallel)",
        argv,
        epoch_csv_default="dataparallel.csv",
        bootstrap=False,  # single process drives all local chips
    )


if __name__ == "__main__":
    main()
