"""Recipe 6 — multi-node / multi-slice training under SLURM.

Reference: distributed_slurm_main.py (``srun -N2 --gres gpu:4`` launches one
task per node; rank = ``SLURM_PROCID * ngpus + gpu``; rendezvous via
``file://<dist_file>.<SLURM_JOBID>`` on a shared FS,
distributed_slurm_main.py:124-140; start.sh:5).

TPU-native delta: ``parallel/dist.py`` derives coordinator/process-count/
process-id from the SLURM environment directly — no shared-file store, no
``mp.spawn`` fan-out (JAX is one process per host) — and *fixes* the
reference's latent inconsistencies rather than replicating them
(SURVEY.md §3.5): world size counts processes (not nodes), the global batch
divides by total world size (not per-node device count,
distributed_slurm_main.py:154), metrics are globally reduced (the reference
prints per-rank metrics, :272-275), and only rank 0 checkpoints (the
reference races, :237-243).  Across slices the mesh's data axis spans DCN;
within a slice, ICI.  ``--dist-file`` is accepted for launch-line parity but
unused.  Per-epoch CSV on by default, same name (:209).  At multi-slice
scale ``--zero wus`` (parallel/zero.py) matters most: optimizer state
shards 1/N across the full data axis while checkpoints keep the replicated
param-shaped layout, so a 2-slice run restores a 1-slice checkpoint and
vice versa.
"""

from pytorch_distributed_tpu.recipes._common import run_recipe


def main(argv=None) -> float:
    return run_recipe(
        "TPU ImageNet Training (multi-node SLURM / multi-slice pod)",
        argv,
        epoch_csv_default="distributed.csv",
    )


if __name__ == "__main__":
    main()
