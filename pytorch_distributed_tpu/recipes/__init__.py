"""The recipe matrix: one entry point per reference script.

The reference ships six ~400-line scripts whose shared ~260-line harness is
byte-identical and whose real content is a ~40-line strategy delta
(SURVEY.md §0).  Here the harness lives once in ``train/`` and each recipe is
*only* its delta — launch shape, mesh, precision, and gradient-sync
expression:

| recipe | reference script | TPU-native delta |
|---|---|---|
| ``dataparallel``                 | dataparallel.py                | single process, all local chips, GSPMD |
| ``distributed``                  | distributed.py                 | external launcher env bootstrap (PTD_TPU_*) |
| ``multiprocessing_distributed``  | multiprocessing_distributed.py | self-contained bootstrap, explicit coordinator |
| ``apex_distributed``             | apex_distributed.py            | bf16 compute policy (AMP slot) |
| ``horovod_distributed``          | horovod_distributed.py         | explicit shard_map psum + bf16 wire grads |
| ``distributed_slurm_main``       | distributed_slurm_main.py      | SLURM env → multi-host mesh over DCN |
| ``tpu_native``                   | (BASELINE.json north star)     | canonical: bf16 + GSPMD + everything on |

Launch commands live in ``start.sh`` (reference start.sh:1-5 parity).
"""
