"""Shared recipe runner — the once-written equivalent of the reference's
byte-identical per-script harness block (SURVEY.md §0)."""

from __future__ import annotations

import random
from typing import Optional

import numpy as np

from pytorch_distributed_tpu.parallel import DistContext, data_parallel_mesh, initialize
from pytorch_distributed_tpu.train.config import Config, parse_config
from pytorch_distributed_tpu.train.trainer import Trainer


def seed_everything(seed: Optional[int]) -> None:
    """Reference main() seeding (distributed.py:116-124).  XLA programs are
    deterministic given fixed PRNG keys, so no cudnn.deterministic analogue
    is needed — the seed flows into jax.random.PRNGKey and the samplers."""
    if seed is not None:
        random.seed(seed)
        np.random.seed(seed)


def run_recipe(
    description: str,
    argv=None,
    precision_default: Optional[str] = None,
    explicit_collectives: bool = False,
    wire_dtype=None,
    grad_compress_default: Optional[str] = None,
    zero_default: Optional[str] = None,
    epoch_csv_default: Optional[str] = None,
    bootstrap: bool = True,
) -> float:
    cfg: Config = parse_config(argv, description=description)
    seed_everything(cfg.seed)
    if cfg.precision is None:  # explicit --precision always wins
        cfg.precision = precision_default or "fp32"
    if cfg.grad_compress is None:  # explicit --grad-compress always wins
        cfg.grad_compress = grad_compress_default
    if cfg.zero is None:  # explicit --zero always wins
        cfg.zero = zero_default
    if epoch_csv_default is not None and cfg.epoch_csv is None:
        cfg.epoch_csv = epoch_csv_default
    ctx = initialize() if bootstrap else DistContext(0, 1, None)
    mesh = data_parallel_mesh()
    trainer = Trainer(
        cfg,
        mesh=mesh,
        ctx=ctx,
        explicit_collectives=explicit_collectives,
        wire_dtype=wire_dtype,
    )
    return trainer.fit()
