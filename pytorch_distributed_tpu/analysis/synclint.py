"""synclint: the cross-rank collective-congruence verifier.

shardlint answers "is each step's *layout* right?"; synclint answers the
orthogonal question multi-process meshes die on: **do all ranks execute
a congruent collective schedule down every reachable host path?**  One
rank issuing a different collective sequence than its peers does not
error — it hangs the whole job in NCCL/ICI, which is exactly what the
PR 13 flight recorder diagnoses post-mortem.  Synclint moves that class
pre-launch with three layers:

1. **HLO congruence** (this module): extract each recipe's ordered
   per-device collective schedule (kind, channel id, replica groups,
   shapes) from the already-compiled module text — riding the shared
   lowering sweep, zero extra compiles — and verify replica-group
   partition validity (disjoint, in-range, uniform, covering) plus
   schedule well-formedness.  The canonical schedule is pinned into
   ``analysis/baseline.json`` as a sha256 digest; drift = error.
2. **Host control-flow desync** (analysis/astlint.py desync pass, driven
   by the ``SYNC_SCOPES`` registry here): flag jitted-step / collective
   calls reachable under rank-dependent or locally-data-dependent
   branches not routed through a ``# synclint: agreement`` point.
3. **Protocol model check** (analysis/syncproto.py): explicit-state
   exploration of the repo's multi-step protocols (divergence rollback,
   elastic shrink/grow, checkpoint fallback, preemption stop).

Everything in this module except :func:`sweep` is pure text/AST work —
no jax import — so the CLI selftest and the drill fixtures run jax-free.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis import astlint
from pytorch_distributed_tpu.analysis import hlo as hlo_mod
from pytorch_distributed_tpu.analysis import syncproto
from pytorch_distributed_tpu.analysis.report import Finding, StepReport

# ------------------------------------------------- layer 1: HLO congruence

# collective-permute's source_target_pairs may repeat a device across
# pairs (a ring names every device twice) — the disjoint-partition rule
# applies to every *other* collective's replica groups.
_PERMUTE_KINDS = frozenset({"collective-permute"})


@dataclasses.dataclass
class ScheduleEntry:
    """One collective in a module's ordered per-device schedule."""

    kind: str                      # normalized opcode (-start folded in)
    channel_id: int                # -1 when the op carries none
    groups: Optional[List[List[int]]]  # explicit member ids, or None
    shapes: List[hlo_mod.Shape]
    name: str                      # HLO instruction name (not digested)
    source: str                    # "file:line" metadata (not digested)
    computation: str

    def canonical(self) -> list:
        """The digested identity: everything every rank must agree on,
        nothing the compiler is free to rename.  Instruction names and
        source metadata are excluded — they churn across point releases
        without changing what goes on the wire."""
        return [
            self.kind,
            self.channel_id,
            self.groups if self.groups is not None else "none",
            sorted([dt, list(dims)] for dt, dims in self.shapes),
        ]


def extract_schedule(hlo_text: str) -> List[ScheduleEntry]:
    """The module's ordered collective schedule, async pairs counted once
    at their ``-start`` (the payload op; ``-done`` is bookkeeping)."""
    out: List[ScheduleEntry] = []
    for ins in hlo_mod.parse_instructions(hlo_text):
        if ins.opcode not in hlo_mod._COLLECTIVE_SET:
            continue
        kind = ins.opcode[:-len("-start")] \
            if ins.opcode.endswith("-start") else ins.opcode
        _, source = hlo_mod.parse_op_metadata(ins.line)
        out.append(ScheduleEntry(
            kind=kind,
            channel_id=hlo_mod.parse_channel_id(ins.line),
            groups=hlo_mod.parse_replica_group_members(ins.line),
            shapes=list(ins.shapes),
            name=ins.name,
            source=source,
            computation=ins.computation))
    return out


def schedule_digest(schedule: Sequence[ScheduleEntry]) -> str:
    """sha256 over the canonical ordered schedule — the baseline pin."""
    payload = json.dumps([e.canonical() for e in schedule],
                         sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(payload.encode()).hexdigest()


def verify_congruence(hlo_text: str, name: str,
                      n_devices: Optional[int] = None) -> List[Finding]:
    """Replica-group partition validity for every collective in the
    module.  With one SPMD module shared by all devices, cross-device
    congruence *is* partition validity: every device must appear in
    exactly one group of every collective it participates in (disjoint,
    in-range, uniform sizes, and — when the mesh size is known — exactly
    covering).  A malformed partition means some device waits on a
    rendezvous its peers never enter."""
    findings: List[Finding] = []
    for i, entry in enumerate(extract_schedule(hlo_text)):
        where = f"{name}:#{i}:{entry.kind}"
        groups = entry.groups
        if groups is None:
            continue  # single-device module: nothing to partition
        if entry.kind in _PERMUTE_KINDS:
            # pairs, not a partition: sources and targets must each be
            # unique or two sends race into one receive buffer
            srcs = [g[0] for g in groups if len(g) == 2]
            tgts = [g[1] for g in groups if len(g) == 2]
            if any(len(g) != 2 for g in groups):
                findings.append(Finding(
                    kind="collective-incongruence", severity="error",
                    where=where,
                    message=f"malformed source_target_pairs {groups}"))
            elif len(set(srcs)) != len(srcs) or len(set(tgts)) != len(tgts):
                findings.append(Finding(
                    kind="collective-incongruence", severity="error",
                    where=where,
                    message=(f"collective-permute pairs are not a "
                             f"permutation: sources {srcs} targets {tgts}")))
            continue
        flat = [d for g in groups for d in g]
        sizes = {len(g) for g in groups}
        if len(sizes) > 1:
            findings.append(Finding(
                kind="collective-incongruence", severity="error",
                where=where,
                message=(f"replica groups have mismatched sizes "
                         f"{sorted(sizes)}: {groups} — ranks in the small "
                         "group rendezvous with fewer peers than the op "
                         "declares elsewhere")))
        if len(set(flat)) != len(flat):
            dupes = sorted({d for d in flat if flat.count(d) > 1})
            findings.append(Finding(
                kind="collective-incongruence", severity="error",
                where=where,
                message=(f"device id(s) {dupes} appear in more than one "
                         f"replica group: {groups} — a device cannot "
                         "participate twice in one collective")))
        if n_devices is not None and flat:
            oob = sorted(d for d in set(flat) if not 0 <= d < n_devices)
            if oob:
                findings.append(Finding(
                    kind="collective-incongruence", severity="error",
                    where=where,
                    message=(f"device id(s) {oob} out of range for the "
                             f"{n_devices}-device mesh: {groups}")))
            missing = sorted(set(range(n_devices)) - set(flat))
            if missing and not oob and len(set(flat)) == len(flat):
                findings.append(Finding(
                    kind="collective-incongruence", severity="error",
                    where=where,
                    message=(f"device id(s) {missing} participate in no "
                             f"replica group of this collective: {groups} "
                             "— they fall out of sync with every peer "
                             "that does")))
    return findings


def sync_report(name: str, hlo_text: str,
                mesh_shape: Optional[Dict[str, int]] = None) -> StepReport:
    """Layer-1 verdict for one module: congruence findings + the digest."""
    n_devices: Optional[int] = None
    if mesh_shape:
        n_devices = 1
        for v in mesh_shape.values():
            n_devices *= v
    schedule = extract_schedule(hlo_text)
    report = StepReport(name=name, mesh_shape=dict(mesh_shape or {}),
                        collectives=hlo_mod.collect_collectives(
                            hlo_mod.parse_instructions(hlo_text)),
                        sync_digest=schedule_digest(schedule))
    for f in verify_congruence(hlo_text, name, n_devices=n_devices):
        report.add(f)
    return report


def diff_digest(report: StepReport,
                entry: Optional[Dict[str, Any]]) -> List[Finding]:
    """Digest-only baseline diff (the synclint CLI's fence; shardlint's
    full diff in report.diff_against_baseline includes the same check)."""
    ref = (entry or {}).get("sync_digest")
    if not ref:
        return [Finding(
            kind="sync-digest-drift", severity="warn", where=report.name,
            message="no collective-schedule digest pinned for this step; "
                    "run scripts/synclint.py --update-baseline (or "
                    "shardlint --sync --update-baseline) to pin it")]
    if report.sync_digest != ref:
        return [Finding(
            kind="sync-digest-drift", severity="error",
            where=f"{report.name}:sync_digest",
            message=(f"collective-schedule digest drifted: "
                     f"{report.sync_digest[:12]} vs baseline {ref[:12]} — "
                     "the ordered collective sequence changed; audit the "
                     "reorder, then --update-baseline to re-pin"))]
    return []


# ------------------------------------------- layer 2: host desync scopes

# Registered desync-lint scopes: every host function that gates jitted
# steps or collective-issuing calls, as (path relative to the package
# root, qualified function names).  Superset of core.HOT_LOOPS — the
# host-sync lint cares about *blocking* in loops; this pass cares about
# *branching* anywhere a collective is reachable.
SYNC_SCOPES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("train/trainer.py", ("Trainer.train_epoch", "Trainer.fit",
                          "Trainer._fit_epochs", "Trainer._preempt_agreed")),
    ("train/lm.py", ("LMTrainer.fit", "LMTrainer._preempt_agreed")),
    ("ft/divergence.py", ("DivergenceGuard.drain", "StateKeeper.update")),
    ("ft/elastic.py", ("ElasticSim.poll", "ElasticCoordinator.decide")),
    ("serving/engine.py", ("ServingEngine.step", "ServingEngine.run")),
)


def lint_sync_scopes() -> StepReport:
    """Run the astlint desync pass over every registered scope."""
    import pytorch_distributed_tpu as pkg

    base = os.path.dirname(os.path.abspath(pkg.__file__))
    report = StepReport(name="sync-scopes")
    for rel, functions in SYNC_SCOPES:
        path = os.path.join(base, rel)
        for f in astlint.lint_desync_file(path, hot_functions=functions):
            report.add(f)
    return report


# --------------------------------------------------- layer 3: protocols

def check_protocols() -> StepReport:
    """Verify the shipped protocol models (analysis/syncproto.py)."""
    report = StepReport(name="sync-protocols")
    for f in syncproto.check_protocols():
        report.add(f)
    return report


# -------------------------------------------------------- the composition

def annotate_reports(reports: Sequence[StepReport]) -> None:
    """Fold layer 1 into an existing shardlint sweep in place: for every
    mesh'd recipe report, attach the schedule digest and any congruence
    findings off the *already cached* lowering (zero extra compiles —
    ``core.get_lowering`` memoizes, and the sweep that produced these
    reports already paid each compile)."""
    from pytorch_distributed_tpu.analysis import core

    for r in reports:
        if r.name not in core.RECIPES or not r.mesh_shape:
            continue
        low = core.get_lowering(r.name)
        sub = sync_report(r.name, low.text, low.mesh_shape)
        r.sync_digest = sub.sync_digest
        for f in sub.findings:
            r.add(f)


def sweep(names: Optional[Sequence[str]] = None) -> List[StepReport]:
    """Layer-1 reports for every (or the named subset of) mesh'd recipe,
    off the shared lowering cache.  Imports jax transitively; the CLI's
    ``--hlo-cache``/``--selftest`` paths avoid it."""
    from pytorch_distributed_tpu.analysis import core

    selected = list(core.RECIPES) if names is None else list(names)
    unknown = [n for n in selected if n not in core.RECIPES]
    if unknown:
        raise KeyError(f"unknown steps {unknown}; "
                       f"known: {list(core.RECIPES)}")
    reports = []
    for name in selected:
        low = core.get_lowering(name)
        if not low.mesh_shape:
            continue  # single-device: no cross-rank schedule to verify
        reports.append(sync_report(name, low.text, low.mesh_shape))
    return reports


def sweep_cached(cache_dir: str,
                 names: Optional[Sequence[str]] = None) -> List[StepReport]:
    """Layer-1 reports from persisted lowering artifacts (<name>.hlo +
    <name>.json under ``cache_dir``) — no jax import, no compile."""
    from pytorch_distributed_tpu.analysis.lowering import CachedLowering

    if names is None:
        names = sorted(
            f[:-len(".hlo")] for f in os.listdir(cache_dir)
            if f.endswith(".hlo"))
    reports = []
    for name in names:
        cached = CachedLowering.load(cache_dir, name)
        if not cached.mesh_shape:
            continue
        reports.append(sync_report(name, cached.text, cached.mesh_shape))
    return reports


# ------------------------------------------------------ planted fixtures

# The rank-divergent branch fixture: the statically-caught half of
# `chaoskit drill desync` and the astlint-side selftest.  Line numbers
# matter to the tests — keep the planted sites stable.
PLANTED_DESYNC_SRC = '''\
def fit(self, steps):
    for i in range(steps):
        state, metrics = self.step_fn(state, batch)      # agreed path
        if jax.process_index() == 0:                     # planted desync
            self.save_checkpoint(state, i)               # rank-gated gather
        flag = float(metrics["diverged"])                # local read
        if flag > 0.5:                                   # planted desync
            state = self.rollback(state)
    return state


def rollback(self, state):
    return psum(state, "data")                           # collective-issuing
'''


def planted_desync_findings() -> List[Finding]:
    """The desync pass run over the planted fixture — must flag both the
    rank-gated checkpoint gather and the locally-gated rollback psum."""
    return astlint.lint_desync_source(
        PLANTED_DESYNC_SRC, "planted_desync.py", hot_functions=("fit",))
