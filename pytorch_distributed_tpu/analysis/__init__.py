"""shardlint: static HLO/jaxpr analysis for sharding/memory/collective
hazards (see analysis/core.py for the detector catalogue and
scripts/shardlint.py for the CLI).

Import layering: ``hlo`` and ``report`` are pure text/dataclass modules
(no jax import — unit-testable on string fixtures); ``jaxpr``, ``astlint``
and ``core`` import jax lazily so that merely importing the package never
initializes a backend."""

from pytorch_distributed_tpu.analysis.report import (  # noqa: F401
    Finding,
    KINDS,
    SEVERITIES,
    StepReport,
    diff_against_baseline,
    load_baseline,
    render_table,
    save_baseline,
)
