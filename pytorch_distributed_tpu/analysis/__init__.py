"""shardlint: static HLO/jaxpr analysis for sharding/memory/collective
hazards (see analysis/core.py for the detector catalogue and
scripts/shardlint.py for the CLI).

Import layering: ``hlo`` and ``report`` are pure text/dataclass modules
(no jax import — unit-testable on string fixtures); ``jaxpr``, ``astlint``
and ``core`` import jax lazily so that merely importing the package never
initializes a backend.  ``lowering`` is the shared AOT sweep service
(one compile per recipe, persisted ``<name>.hlo``/``<name>.json``
artifacts, the process-wide compile-count budget) that every static
consumer — detectors, both ledgers, autoplan validation — rides.
``synclint`` and ``syncproto`` (the cross-rank collective-congruence
verifier, scripts/synclint.py) follow the same discipline: pure
text/AST/state-machine work with jax imported only inside the
recipe-sweep entry points."""

from pytorch_distributed_tpu.analysis.report import (  # noqa: F401
    Finding,
    KINDS,
    SEVERITIES,
    StepReport,
    diff_against_baseline,
    load_baseline,
    render_table,
    save_baseline,
)
