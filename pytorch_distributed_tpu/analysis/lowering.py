"""The first-class lowering service: one AOT sweep, persisted, shared.

Every consumer of compiled-step truth in the repo — test_shardlint's
detector fences, test_comms'/test_memory's ledger parity checks, the
``shardlint --comm-ledger/--mem-ledger`` receipts, the trainers' opt-in
ledger emission, and ``scripts/autoplan.py``'s top-k validation — is a
pure function of one lowered+compiled step.  This module promotes the
session-scoped ``get_lowering`` conftest fixture into a process-wide
service so all of them provably ride ONE sweep:

- ``LoweringService.get(name)`` memoizes lower+compile per recipe
  (delegating to ``analysis.core``'s in-memory cache) and persists the
  artifacts on first build;
- ``persist``/``load`` define the on-disk **artifact layout**:

      <cache_dir>/<name>.hlo    post-optimization HLO text
      <cache_dir>/<name>.json   {"name", "mesh_shape",
                                 "measured_peak_bytes", "arg_classes"}

  Subprocess consumers (the obs_memory CLI, report tooling, autoplan
  re-runs) read these files instead of recompiling — ``CachedLowering``
  rebuilds both ledgers from text alone, no jax required;
- ``aot_ledgers`` is the trainers' path: one *counted* AOT compile of
  the live train step feeding both opt-in receipts (``--comm-ledger`` +
  ``--mem-ledger``), optionally persisted to the same layout;
- ``compile_count()`` / ``compile_budget()`` / ``assert_compile_budget``
  expose the process-wide compile counter and the tier-1 budget fence:
  static analyses beyond the sweep itself must pay ZERO extra compiles.

Cache-reuse contract: a ``.hlo``/``.json`` pair is written once per step
per cache dir and never invalidated within a process — recipes are
deterministic functions of the checked-in step builders, so the first
build is authoritative for the session.  Cross-session reuse is safe
only for text re-analysis (ledgers, detectors); anything needing the
live ``compiled`` object recompiles via ``get``.

Persistent *compilation* caching (jax's ``jax_compilation_cache_dir``)
is separate and version-gated here: on jaxlib 0.4.x re-executing a
deserialized cached executable on the CPU backend aborts the process
("Fatal Python error: Aborted", observed on jax 0.4.37 in
test_trainer's train step), so ``maybe_enable_persistent_cache`` hard-
disables it for the known-bad range and on newer jaxlibs only enables
after a populate+warm round-trip self-check passes in subprocesses
(the failure mode is a process abort — it cannot be try/except'd).
"""

from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis import core

# Extra counted compiles tier-1 tolerates beyond the recipe sweep itself.
# Measured usage is exactly 2: the planted synthetic-bad step (memoized in
# ``core.get_synthetic_bad_lowering`` — selftest and test_shardlint share
# the one compile) and test_shardlint's undonated-opportunity probe.  The
# allowance leaves headroom for two more probes before the budget assert
# (tests/test_plan.py, tests/test_recipes.py) fails CI — a change that
# sneaks per-consumer recompiles back in blows through it immediately.
EXTRA_COMPILE_ALLOWANCE = 4


def compile_count() -> int:
    """Process-wide AOT lower+compile sweeps paid so far (analysis.core's
    counter: the recipe sweep, analyze_jitted probes, and the trainers'
    ``aot_ledgers`` all increment it)."""
    return core.compile_count()


def compile_budget() -> int:
    """The tier-1 ceiling: one compile per recipe plus the fixed probe
    allowance.  Shardlint detectors + comm ledger + mem ledger + autoplan
    top-k validation must all fit under it together."""
    return len(core.RECIPES) + EXTRA_COMPILE_ALLOWANCE


def assert_compile_budget() -> None:
    n, budget = compile_count(), compile_budget()
    assert n <= budget, (
        f"compile_count {n} exceeds the tier-1 budget {budget}: a static "
        f"consumer (shardlint/ledger/autoplan fence) stopped riding the "
        f"shared lowering sweep (analysis/lowering.py)")


# ------------------------------------------------------------ persistence

def persist(cache_dir, name: str, *, text: str, mesh_shape: Dict[str, int],
            measured_peak_bytes: int, arg_classes: Dict[str, Any]) -> None:
    """Write one step's artifact pair (idempotent: first build wins)."""
    os.makedirs(str(cache_dir), exist_ok=True)
    hlo_path = os.path.join(str(cache_dir), f"{name}.hlo")
    if os.path.exists(hlo_path):
        return
    with open(hlo_path, "w") as f:
        f.write(text)
    with open(os.path.join(str(cache_dir), f"{name}.json"), "w") as f:
        json.dump({
            "name": name,
            "mesh_shape": mesh_shape,
            "measured_peak_bytes": int(measured_peak_bytes),
            "arg_classes": arg_classes,
        }, f)


@dataclasses.dataclass
class CachedLowering:
    """A persisted lowering re-read from disk: enough for every pure-text
    analysis (both ledgers, the HLO detectors) with no jax import and no
    recompile — what subprocess consumers and post-hoc tooling use."""

    name: str
    text: str
    mesh_shape: Dict[str, int]
    measured_peak_bytes: int
    arg_classes: Dict[str, Any]

    @classmethod
    def load(cls, cache_dir, name: str) -> "CachedLowering":
        with open(os.path.join(str(cache_dir), f"{name}.hlo")) as f:
            text = f.read()
        with open(os.path.join(str(cache_dir), f"{name}.json")) as f:
            meta = json.load(f)
        return cls(name=name, text=text,
                   mesh_shape=dict(meta.get("mesh_shape") or {}),
                   measured_peak_bytes=int(meta.get("measured_peak_bytes", 0)),
                   arg_classes=meta.get("arg_classes") or {})

    def comm_ledger(self):
        from pytorch_distributed_tpu.obs import comms

        return comms.ledger_from_hlo_text(self.text, step=self.name,
                                          mesh_shape=self.mesh_shape)

    def mem_ledger(self):
        from pytorch_distributed_tpu.obs import memory

        return memory.ledger_from_hlo_text(
            self.text, step=self.name, mesh_shape=self.mesh_shape,
            arg_classes=self.arg_classes,
            measured_peak_bytes=self.measured_peak_bytes)


class LoweringService:
    """The shared sweep with on-disk persistence.

    ``get`` returns the live ``core.Lowering`` (compiling at most once per
    step per process via core's memo) and drops the artifact pair under
    ``cache_dir`` on first build.  ``load`` hands back the disk view.
    """

    def __init__(self, cache_dir: Optional[str] = None):
        if cache_dir is None:
            cache_dir = os.environ.get("PTD_LOWERING_CACHE") or os.path.join(
                tempfile.gettempdir(), "ptd_lowering_cache")
        self.cache_dir = str(cache_dir)

    def get(self, name: str) -> core.Lowering:
        from pytorch_distributed_tpu.obs import comms, memory

        low = core.get_lowering(name)
        persist(self.cache_dir, name, text=low.text,
                mesh_shape=low.mesh_shape,
                measured_peak_bytes=comms.compiled_peak_bytes(low.compiled),
                arg_classes=memory.arg_classes_of(low.args))
        return low

    def load(self, name: str) -> CachedLowering:
        return CachedLowering.load(self.cache_dir, name)

    def has(self, name: str) -> bool:
        return os.path.exists(os.path.join(self.cache_dir, f"{name}.hlo"))

    def names(self) -> List[str]:
        try:
            return sorted(f[:-4] for f in os.listdir(self.cache_dir)
                          if f.endswith(".hlo"))
        except OSError:
            return []

    # Budget plumbing, re-exported so fixtures can hand out one object.
    compile_count = staticmethod(compile_count)
    compile_budget = staticmethod(compile_budget)


_SERVICE: Optional[LoweringService] = None


def service(cache_dir: Optional[str] = None) -> LoweringService:
    """The process singleton.  The first caller pins the cache dir; later
    callers passing a different one get a fresh non-singleton instance
    (tests with tmp dirs) rather than silently retargeting the shared one."""
    global _SERVICE
    if _SERVICE is None:
        _SERVICE = LoweringService(cache_dir)
        return _SERVICE
    if cache_dir is not None and str(cache_dir) != _SERVICE.cache_dir:
        return LoweringService(cache_dir)
    return _SERVICE


# ------------------------------------------------- trainer ledger path

def aot_ledgers(jitted, args: Sequence[Any], *, step: str,
                mesh_shape: Dict[str, int], want_comm: bool = True,
                want_mem: bool = True, cache_dir: Optional[str] = None):
    """One counted AOT compile of a live train step feeding both opt-in
    receipts — the trainers' ``--comm-ledger``/``--mem-ledger`` path.

    Returns ``(comm_ledger_or_None, mem_ledger_or_None)``.  Unlike the
    recipe sweep this lowers the *trainer's own* jitted step against its
    real shardings; it still books against the same process-wide compile
    counter so the budget fence sees every AOT compile in the process,
    and with ``cache_dir`` set it persists the same artifact layout the
    recipe sweep writes (step name as the stem)."""
    from pytorch_distributed_tpu.obs import comms, memory

    core.count_compile()
    compiled = jitted.lower(*args).compile()
    text = compiled.as_text()
    measured = comms.compiled_peak_bytes(compiled)
    arg_classes = memory.arg_classes_of(args)
    comm_ledger = mem_ledger = None
    if want_comm:
        comm_ledger = comms.ledger_from_hlo_text(text, step=step,
                                                 mesh_shape=mesh_shape)
        comm_ledger.peak_hbm_bytes = measured
    if want_mem:
        mem_ledger = memory.ledger_from_compiled(
            compiled, step=step, mesh_shape=mesh_shape,
            arg_classes=arg_classes, hlo_text=text)
    if cache_dir:
        persist(cache_dir, step, text=text, mesh_shape=mesh_shape,
                measured_peak_bytes=measured, arg_classes=arg_classes)
    return comm_ledger, mem_ledger


# ------------------------------------- persistent compilation cache guard

# jaxlib versions where the round-trip is KNOWN to abort the process:
# the whole 0.4.x line (observed on jaxlib 0.4.36 / jax 0.4.37, CPU
# backend — re-executing a deserialized executable dies with "Fatal
# Python error: Aborted").  Kept as a range, not a list: every 0.4.x we
# tried fails, and probing one costs a crashed subprocess anyway.
_KNOWN_BAD_BELOW = (0, 5, 0)

_SELFCHECK_SNIPPET = """\
import jax, jax.numpy as jnp
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_compilation_cache_dir", {cache_dir!r})
try:
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
except Exception:
    pass
f = jax.jit(lambda x: (x * 2.0 + 1.0).sum())
print(float(f(jnp.arange(64.0))))
"""


def jaxlib_version_tuple(version: Optional[str] = None) -> Tuple[int, ...]:
    if version is None:
        import jaxlib

        version = jaxlib.__version__
    parts: List[int] = []
    for tok in str(version).split(".")[:3]:
        digits = "".join(c for c in tok if c.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def persistent_cache_known_bad(version: Optional[str] = None) -> bool:
    return jaxlib_version_tuple(version) < _KNOWN_BAD_BELOW


def persistent_cache_selfcheck(cache_dir: str, *, timeout: float = 120.0,
                               _runner=None) -> bool:
    """Populate + warm round-trip in fresh subprocesses: run the snippet
    twice against ``cache_dir``; the second run deserializes the first's
    entry, which is exactly the path that aborts on bad jaxlibs — only a
    subprocess survives probing it.  Verdict is memoized per jaxlib
    version in ``<cache_dir>/selfcheck.json`` so the pair of interpreter
    launches is paid once per cache dir, not once per session."""
    os.makedirs(cache_dir, exist_ok=True)
    ver = ".".join(map(str, jaxlib_version_tuple()))
    memo_path = os.path.join(cache_dir, "selfcheck.json")
    try:
        with open(memo_path) as f:
            memo = json.load(f)
        if memo.get("jaxlib") == ver:
            return bool(memo.get("ok"))
    except (OSError, ValueError):
        pass
    snippet = _SELFCHECK_SNIPPET.format(cache_dir=cache_dir)
    runner = _runner or (lambda: subprocess.run(
        [sys.executable, "-c", snippet], timeout=timeout,
        capture_output=True, text=True))
    ok = True
    outs = []
    try:
        for _ in range(2):  # populate, then warm (deserialize + execute)
            r = runner()
            if r.returncode != 0:
                ok = False
                break
            outs.append(r.stdout.strip())
        else:
            ok = len(outs) == 2 and outs[0] == outs[1] and outs[0] != ""
    except (subprocess.TimeoutExpired, OSError):
        ok = False
    try:
        with open(memo_path, "w") as f:
            json.dump({"jaxlib": ver, "ok": ok}, f)
    except OSError:
        pass
    return ok


# The gate verdict is logged exactly once per interpreter session: the
# gate is funneled through by every test session (conftest) and CLI
# entry, and the one stderr line — detected jaxlib + enabled/disabled +
# why — is the breadcrumb the ROADMAP's "revisit at jaxlib 0.5.0" item
# needs when reading CI logs.  Reset by tests to assert the logging.
_GATE_VERDICT_LOGGED = False


def _log_gate_verdict(verdict: Dict[str, Any]) -> None:
    global _GATE_VERDICT_LOGGED
    if _GATE_VERDICT_LOGGED:
        return
    _GATE_VERDICT_LOGGED = True
    state = "enabled" if verdict.get("enabled") else "disabled"
    ver = ".".join(map(str, jaxlib_version_tuple()))
    print(f"[lowering] persistent compilation cache {state} "
          f"(jaxlib {ver}): {verdict['reason']}", file=sys.stderr)


def maybe_enable_persistent_cache(
        cache_dir: Optional[str] = None) -> Dict[str, Any]:
    """Version-gated re-attempt of jax's persistent compilation cache.

    Known-bad jaxlibs (< 0.5.0) short-circuit to disabled WITHOUT running
    the self-check — the failure mode is a process abort, so probing on a
    version already documented bad buys nothing and costs two interpreter
    launches.  On newer jaxlibs the populate+warm subprocess round-trip
    must pass before the cache dir is handed to jax.  ``PTD_PERSISTENT_
    CACHE=0`` force-disables; ``=1`` skips the version gate but NOT the
    self-check.  Returns ``{"enabled": bool, "reason": str}``; the
    detected jaxlib + verdict is logged to stderr once per session."""
    verdict = _gate_persistent_cache(cache_dir)
    _log_gate_verdict(verdict)
    return verdict


def _gate_persistent_cache(
        cache_dir: Optional[str] = None) -> Dict[str, Any]:
    env = os.environ.get("PTD_PERSISTENT_CACHE", "")
    if env == "0":
        return {"enabled": False, "reason": "disabled by PTD_PERSISTENT_CACHE=0"}
    ver = ".".join(map(str, jaxlib_version_tuple()))
    if env != "1" and persistent_cache_known_bad():
        return {"enabled": False, "reason": (
            f"jaxlib {ver} is in the known-bad range (< "
            f"{'.'.join(map(str, _KNOWN_BAD_BELOW))}): deserialized CPU "
            "executables abort the process (see tests/conftest.py NOTE)")}
    if cache_dir is None:
        cache_dir = os.environ.get("PTD_JAX_CACHE_DIR") or os.path.join(
            tempfile.gettempdir(), "ptd_jax_compilation_cache")
    if not persistent_cache_selfcheck(cache_dir):
        return {"enabled": False, "reason": (
            f"jaxlib {ver}: populate+warm round-trip self-check failed "
            f"in {cache_dir}")}
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    return {"enabled": True,
            "reason": f"jaxlib {ver}: round-trip self-check passed",
            "cache_dir": cache_dir}
