"""Typed findings and per-step reports for the shardlint static analyzer.

The analyzer (analysis/core.py) walks a jitted step's jaxpr and compiled
HLO and emits ``Finding`` records in a small closed vocabulary of hazard
kinds, so CI can gate on severity instead of grepping HLO text per PR:

- ``replicated-large-tensor`` (error) — an intermediate materialized at its
  full global size on every device of a >1-device mesh (the PR-1 fused-CE
  ``[V, D]`` dE accumulator class; arxiv 2004.13336's silent-DP-waste).
- ``replicated-state`` (info) — a train-state-shaped value updated at full
  size per device: the *declared* pure-DP layout, flagged as the standing
  FSDP opportunity rather than a regression.
- ``lost-donation`` (error) — ``donate_argnums`` was passed but XLA's
  ``input_output_alias`` map covers fewer donated leaves than expected
  (shape/dtype/sharding mismatch silently drops the alias).
- ``no-donation`` (warn) — a step that threads train state through without
  donating it at all.
- ``dtype-promotion`` (warn) — a large bf16/f16 intermediate upcast to f32
  (``convert_element_type`` in the jaxpr, global shape ≥ threshold).
- ``collective-regression`` (error) — per-step collective count/bytes above
  the checked-in baseline (EQuARX-style collective-bytes budget).
- ``memory-budget`` (error) — per-device peak HBM (temp + argument + output
  from ``memory_analysis()``) above the checked-in per-step budget: the
  PR-1 replicated-accumulator class caught by *bytes*, not pattern.
- ``host-sync`` (error) — a blocking device→host conversion inside a train
  hot loop (analysis/astlint.py).
- ``collective-incongruence`` (error) — a recipe's collective schedule
  fails cross-device congruence or replica-group partition validity
  (analysis/synclint.py layer 1: duplicate/out-of-range device ids,
  non-covering partitions, mismatched group sizes).
- ``sync-digest-drift`` (error) — the canonical collective-schedule digest
  of a recipe no longer matches the checked-in baseline pin: the *order*
  or shape of the collective sequence changed, which is a cross-rank
  deadlock risk even when counts and bytes stay inside budget.
- ``collective-desync`` (error) — a jitted-step or collective-issuing call
  reachable under a rank-dependent or locally-data-dependent branch that
  is not routed through an agreement point (astlint desync pass).
- ``protocol-desync`` (error) — the explicit-state protocol explorer found
  a reachable interleaving where ranks disagree on the next collective
  (analysis/syncproto.py).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

SEVERITIES = ("error", "warn", "info")

# Fractional headroom on the pinned per-step peak-HBM budget before a
# ``memory-budget`` error fires (compiler scheduling jitter, not hazards).
MEM_BUDGET_SLACK = 0.02

KINDS = (
    "replicated-large-tensor",
    "replicated-state",
    "lost-donation",
    "no-donation",
    "dtype-promotion",
    "collective-regression",
    "memory-budget",
    "host-sync",
    "collective-incongruence",
    "sync-digest-drift",
    "collective-desync",
    "protocol-desync",
)


@dataclasses.dataclass
class Finding:
    """One typed hazard. ``where`` is a recipe/step name or ``file:line``."""

    kind: str
    severity: str
    where: str
    message: str
    bytes: int = 0
    shape: Tuple[int, ...] = ()
    dtype: str = ""

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown finding kind {self.kind!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["shape"] = list(self.shape)
        return d

    def __str__(self) -> str:
        loc = f" {self.dtype}{list(self.shape)}" if self.shape else ""
        size = f" ({self.bytes / 2**20:.2f} MiB)" if self.bytes else ""
        return (f"[{self.severity}] {self.kind} @ {self.where}:{loc}{size} "
                f"{self.message}")


@dataclasses.dataclass
class StepReport:
    """Everything the analyzer learned about one jitted step."""

    name: str
    mesh_shape: Dict[str, int] = dataclasses.field(default_factory=dict)
    findings: List[Finding] = dataclasses.field(default_factory=list)
    # per collective opcode: {"count": n, "bytes": per-device payload bytes}
    collectives: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # compiled per-device sizes from XLA's memory analysis (0 if unavailable)
    memory: Dict[str, int] = dataclasses.field(default_factory=dict)
    # donation accounting: requested/expected/aliased leaf counts + bytes
    donation: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # canonical collective-schedule digest (analysis/synclint.py); "" when
    # the sync layer did not run or the step has no mesh
    sync_digest: str = ""

    def add(self, finding: Finding) -> None:
        self.findings.append(finding)

    def by_kind(self, kind: str) -> List[Finding]:
        return [f for f in self.findings if f.kind == kind]

    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == "error"]

    def to_dict(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "mesh_shape": dict(self.mesh_shape),
            "findings": [f.to_dict() for f in self.findings],
            "collectives": self.collectives,
            "memory": self.memory,
            "donation": self.donation,
            "sync_digest": self.sync_digest,
        }


# --------------------------------------------------------------- baselines

def baseline_entry(report: StepReport) -> Dict[str, Any]:
    """The part of a report that is pinned against CI: the collective
    budget.  Findings are gated directly by severity, not baselined.

    ``total_bytes`` pins the cross-kind sum so a reshuffle that trades,
    say, all-gathers for a bigger all-reduce while raising the wire total
    still fails, even when no single kind exceeds its own line.

    ``peak_hbm_bytes`` pins the per-device compiled footprint (temp +
    argument + output from ``memory_analysis()``) so a layout change that
    silently re-replicates state fails shardlint by *bytes*.

    ``sync_digest`` pins the canonical *ordered* collective schedule
    (analysis/synclint.py): two modules can match every count/bytes line
    above yet reorder collectives relative to each other, which is exactly
    the cross-rank deadlock class — so order is pinned by digest."""
    out = {
        "collectives": {
            k: {"count": v["count"], "bytes": v["bytes"]}
            for k, v in sorted(report.collectives.items())
        },
        "total_bytes": sum(v["bytes"] for v in report.collectives.values()),
        "peak_hbm_bytes": sum(report.memory.values()),
    }
    if report.sync_digest:
        out["sync_digest"] = report.sync_digest
    return out


def diff_against_baseline(report: StepReport,
                          entry: Optional[Dict[str, Any]]) -> List[Finding]:
    """Compare a report's collective budget with its baseline entry.

    Regressions (more ops, or more per-device payload bytes, of any
    collective kind — including kinds the baseline never saw) are
    error-severity ``collective-regression`` findings; improvements come
    back as info so the operator knows the baseline is stale."""
    if entry is None:
        return [Finding(
            kind="collective-regression", severity="warn", where=report.name,
            message="no baseline entry for this step; run "
                    "scripts/shardlint.py --update-baseline to pin it",
        )]
    findings: List[Finding] = []
    base = entry.get("collectives", {})
    kinds = sorted(set(base) | set(report.collectives))
    for kind in kinds:
        now = report.collectives.get(kind, {"count": 0, "bytes": 0})
        ref = base.get(kind, {"count": 0, "bytes": 0})
        if now["count"] > ref["count"] or now["bytes"] > ref["bytes"]:
            findings.append(Finding(
                kind="collective-regression", severity="error",
                where=f"{report.name}:{kind}",
                bytes=now["bytes"] - ref["bytes"],
                message=(f"{kind} budget exceeded: {now['count']} ops / "
                         f"{now['bytes']} B vs baseline {ref['count']} ops / "
                         f"{ref['bytes']} B"),
            ))
        elif now["count"] < ref["count"] or now["bytes"] < ref["bytes"]:
            findings.append(Finding(
                kind="collective-regression", severity="info",
                where=f"{report.name}:{kind}",
                message=(f"{kind} below baseline ({now['count']} ops / "
                         f"{now['bytes']} B vs {ref['count']} / "
                         f"{ref['bytes']}): refresh with --update-baseline"),
            ))
    # the per-step total budget (absent from pre-comm-ledger baselines:
    # skipped until --update-baseline refreshes the pin)
    ref_total = entry.get("total_bytes")
    if ref_total is not None:
        now_total = sum(v["bytes"] for v in report.collectives.values())
        if now_total > ref_total:
            findings.append(Finding(
                kind="collective-regression", severity="error",
                where=f"{report.name}:total",
                bytes=now_total - ref_total,
                message=(f"per-step collective bytes budget exceeded: "
                         f"{now_total} B total vs baseline {ref_total} B"),
            ))
    # the per-step peak-HBM budget (absent from pre-mem-ledger baselines:
    # skipped until --update-baseline refreshes the pin).  A small slack
    # absorbs scheduler jitter across compiler point releases; a real
    # re-replication blows through it by whole buffer sizes.
    ref_peak = entry.get("peak_hbm_bytes")
    if ref_peak is not None and report.memory:
        now_peak = sum(report.memory.values())
        if now_peak > ref_peak * (1 + MEM_BUDGET_SLACK):
            findings.append(Finding(
                kind="memory-budget", severity="error",
                where=f"{report.name}:peak_hbm",
                bytes=now_peak - ref_peak,
                message=(f"per-device peak HBM budget exceeded: {now_peak} B "
                         f"vs baseline {ref_peak} B "
                         f"(+{100.0 * (now_peak - ref_peak) / ref_peak:.1f}%)"),
            ))
        elif now_peak < ref_peak * (1 - MEM_BUDGET_SLACK):
            findings.append(Finding(
                kind="memory-budget", severity="info",
                where=f"{report.name}:peak_hbm",
                message=(f"peak HBM below baseline ({now_peak} B vs "
                         f"{ref_peak} B): refresh with --update-baseline"),
            ))
    # the pinned collective-schedule digest (absent from pre-synclint
    # baselines: skipped until --update-baseline refreshes the pin).
    # Drift is always an error — a reordered schedule deadlocks a
    # multi-process mesh even when every count/bytes budget holds.
    ref_digest = entry.get("sync_digest")
    if ref_digest and report.sync_digest \
            and report.sync_digest != ref_digest:
        findings.append(Finding(
            kind="sync-digest-drift", severity="error",
            where=f"{report.name}:sync_digest",
            message=(f"collective-schedule digest drifted: "
                     f"{report.sync_digest[:12]} vs baseline "
                     f"{ref_digest[:12]} — the ordered collective "
                     "sequence changed; audit the reorder, then "
                     "--update-baseline to re-pin"),
        ))
    return findings


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def save_baseline(path: str, reports: Sequence[StepReport]) -> None:
    data = {r.name: baseline_entry(r) for r in reports}
    with open(path, "w") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


def render_table(reports: Sequence[StepReport]) -> str:
    """Human summary: one row per step + its findings underneath."""
    lines = []
    for r in reports:
        coll = ", ".join(
            f"{k}×{v['count']}" for k, v in sorted(r.collectives.items())
        ) or "none"
        errs = len(r.errors())
        lines.append(
            f"{r.name:<24} mesh={r.mesh_shape or '{}'} "
            f"collectives: {coll}  findings: {len(r.findings)} "
            f"({errs} errors)")
        for f in r.findings:
            lines.append(f"    {f}")
    return "\n".join(lines)
