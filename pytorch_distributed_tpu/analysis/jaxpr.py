"""Jaxpr walking: global (pre-partitioning) shapes for the analyzer.

The compiled HLO (analysis/hlo.py) only shows *per-device* shapes; the
jaxpr is where the global view lives — every equation's output aval is a
global logical shape.  The replicated-tensor detector cross-references the
two: a global-shaped intermediate that shows up at FULL size in the
per-device module is materialized on every device (replicated, or
all-gathered) rather than sharded.

``shard_map`` bodies are excluded from the global-shape set: their avals
are already per-shard, so matching them against per-device HLO shapes
would flag perfectly sharded values (the explicit-collectives step, the
pipeline schedules).  Detectors that need them (dtype promotions) still
recurse inside with the ``local`` flag.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

Shape = Tuple[str, Tuple[int, ...]]  # (HLO dtype name, dims)

# numpy/jax dtype name -> HLO shape-token dtype name
_DTYPE_TO_HLO = {
    "bool": "pred", "int4": "s4", "uint4": "u4",
    "int8": "s8", "int16": "s16", "int32": "s32", "int64": "s64",
    "uint8": "u8", "uint16": "u16", "uint32": "u32", "uint64": "u64",
    "bfloat16": "bf16", "float16": "f16", "float32": "f32",
    "float64": "f64", "complex64": "c64", "complex128": "c128",
}

_LOCAL_PRIMITIVES = ("shard_map",)


def hlo_dtype(dtype) -> str:
    return _DTYPE_TO_HLO.get(np.dtype(dtype).name, np.dtype(dtype).name)


def aval_shape(aval) -> Optional[Shape]:
    """(hlo dtype, dims) for a ShapedArray-like aval; None for abstract
    tokens/etc. that carry no shape."""
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return None
    try:
        return (hlo_dtype(dtype), tuple(int(d) for d in shape))
    except TypeError:  # symbolic dims — out of scope
        return None


def aval_bytes(aval) -> int:
    s = aval_shape(aval)
    if s is None:
        return 0
    from pytorch_distributed_tpu.analysis.hlo import shape_bytes

    return shape_bytes(s)


def _sub_jaxprs(eqn) -> List:
    subs = []
    for v in eqn.params.values():
        items = v if isinstance(v, (list, tuple)) else [v]
        for item in items:
            if hasattr(item, "eqns"):           # plain Jaxpr
                subs.append(item)
            elif hasattr(item, "jaxpr") and hasattr(
                    getattr(item, "jaxpr"), "eqns"):  # ClosedJaxpr
                subs.append(item.jaxpr)
    return subs


def iter_eqns(jaxpr, local: bool = False) -> Iterator[Tuple[object, bool]]:
    """Depth-first ``(eqn, is_shard_map_local)`` over a jaxpr and every
    sub-jaxpr (pjit/scan/while/cond/custom-vjp/remat bodies)."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)  # accept ClosedJaxpr
    for eqn in jx.eqns:
        yield eqn, local
        sub_local = local or eqn.primitive.name in _LOCAL_PRIMITIVES
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub, sub_local)


def source_summary(eqn) -> str:
    """``file:line (fn)`` for an equation, best-effort."""
    try:
        from jax._src import source_info_util

        return source_info_util.summarize(eqn.source_info)
    except Exception:
        return "<unknown>"


def loop_carry_shapes(jaxpr) -> Dict[Shape, Dict[str, object]]:
    """Shapes carried through a ``scan``/``while`` OUTSIDE any shard_map.

    A large *replicated* loop carry is the PR-1 hazard class in its exact
    form — an accumulator (the fused-CE ``[V, D]`` dE sums) rebuilt on every
    device every iteration — and is distinguishable from the one-shot
    param-shaped intermediates of the declared pure-DP layout (grads,
    updated params), which match entry-parameter shapes and only rate an
    info finding.  Maps carry shape -> {"primitive", "source"}."""
    carries: Dict[Shape, Dict[str, object]] = {}
    for eqn, local in iter_eqns(jaxpr):
        if local:
            continue
        name = eqn.primitive.name
        if name == "scan":
            n_carry = int(eqn.params.get("num_carry", 0))
            carry_vars = eqn.outvars[:n_carry]
        elif name == "while":
            carry_vars = eqn.outvars
        else:
            continue
        for var in carry_vars:
            s = aval_shape(getattr(var, "aval", None))
            if s is None or s in carries:
                continue
            carries[s] = {
                "primitive": name,
                "source": source_summary(eqn),
            }
    return carries


def global_intermediate_shapes(
    jaxpr, min_bytes: int = 0,
) -> Dict[Shape, Dict[str, object]]:
    """Global-logical-shape index of every intermediate ≥ ``min_bytes``.

    Maps (dtype, dims) -> {"bytes", "primitive", "source"} for the first
    equation producing that shape outside any shard_map body.  Input avals
    (constvars/invars) are not included — entry parameters are excluded on
    the HLO side by opcode instead."""
    from pytorch_distributed_tpu.analysis.hlo import shape_bytes

    index: Dict[Shape, Dict[str, object]] = {}
    for eqn, local in iter_eqns(jaxpr):
        if local:
            continue
        for var in eqn.outvars:
            s = aval_shape(getattr(var, "aval", None))
            if s is None:
                continue
            n = shape_bytes(s)
            if n < min_bytes or s in index:
                continue
            index[s] = {
                "bytes": n,
                "primitive": eqn.primitive.name,
                "source": source_summary(eqn),
            }
    return index


def find_dtype_promotions(jaxpr, min_bytes: int) -> List[Dict[str, object]]:
    """Large low-precision→f32/f64 ``convert_element_type`` equations.

    Matmul f32 accumulation via ``preferred_element_type`` does NOT appear
    here (it is not a convert); this catches materialized upcasts — the
    backward-pass f32 copies of big bf16 activations that double their
    footprint."""
    out: List[Dict[str, object]] = []
    for eqn, _ in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        in_aval = getattr(eqn.invars[0], "aval", None)
        out_aval = getattr(eqn.outvars[0], "aval", None)
        src = aval_shape(in_aval)
        dst = aval_shape(out_aval)
        if src is None or dst is None:
            continue
        if src[0] not in ("bf16", "f16") or dst[0] not in ("f32", "f64"):
            continue
        n = aval_bytes(out_aval)
        if n < min_bytes:
            continue
        out.append({
            "shape": dst[1], "from": src[0], "to": dst[0], "bytes": n,
            "source": source_summary(eqn),
        })
    return out
