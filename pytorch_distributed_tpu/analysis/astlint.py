"""Host-sync lint: a Python-AST pass over training hot loops.

The whole framework is built around lazy metric conversion — the jitted
step returns *unready* device scalars and the meters / MetricsLogger
convert them at display/flush cadence (train/meters.py, obs/metrics.py),
so the hot loop never blocks on a device→host transfer.  One careless
``float(metrics["loss"])`` inside the step loop silently reinstates the
reference's 3-syncs-per-batch behavior (SURVEY.md §3.1a) and no test
notices: throughput just quietly drops.

This pass makes the discipline mechanical.  For each *hot* function
(``core.HOT_LOOPS`` names the step-driving loops; planted sources can be
linted directly), every ``for``/``while`` body is scanned for blocking
device→host conversions:

- ``float(...)`` / ``int(...)`` builtins (the ``.item()``-equivalent)
- ``.item()`` / ``.block_until_ready()`` / ``.copy_to_host_async()`` wait
  calls
- ``np.asarray`` / ``np.array`` / ``numpy.asarray`` / ``numpy.array``
- ``jax.device_get``

Nested function definitions inside a loop are skipped (defining a closure
is not a sync), and a line ending in ``# shardlint: allow-sync`` is
exempt — the escape hatch for a loop that genuinely must sync (e.g. an
eval loop doing exact host-side aggregation, which is a *documented*
per-batch sync, not an accident).

The second pass here is the *desync* lint (synclint layer 2): for each
registered hot function it flags any jitted-step or collective-issuing
call reachable under a branch whose condition is rank-dependent
(``jax.process_index()``, ``rank`` locals, pids) or locally-data-
dependent (``float()``/``.item()`` host reads, clocks, ``random``,
filesystem probes) and not routed through an agreement point.  A branch
every rank evaluates identically is fine; a branch only *this* rank can
see is how one rank skips an all-reduce its peers are blocked in — the
PR 13 two-rank hang class, caught before launch instead of by the
watchdog.  Two markers scope the verdicts:

- ``# synclint: agreement`` on an ``if``/``while`` line declares the
  condition an agreement point (the preemption-agreement all-reduce,
  the membership-epoch poll); on a ``def`` line it blesses every branch
  in that function.
- ``# synclint: allow`` on a collective call line (or a ``def`` line)
  suppresses the finding — the documented escape hatch mirroring
  ``allow-sync``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from pytorch_distributed_tpu.analysis.report import Finding

ALLOW_MARKER = "shardlint: allow-sync"

# Builtin calls that force a device->host sync when fed a jax array.
SYNC_BUILTINS = frozenset({"float", "int"})
# Method calls that block on (or force) a transfer.
SYNC_METHODS = frozenset({"item", "block_until_ready"})
# module.attr calls: {module alias: {attr, ...}}
SYNC_QUALIFIED: Dict[str, frozenset] = {
    "np": frozenset({"asarray", "array"}),
    "numpy": frozenset({"asarray", "array"}),
    "jax": frozenset({"device_get"}),
}


def _sync_call_label(node: ast.Call) -> Optional[str]:
    """A short label for a blocking call, or None if the call is benign."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in SYNC_BUILTINS:
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.attr in SYNC_QUALIFIED.get(
                fn.value.id, ()):
            return f"{fn.value.id}.{fn.attr}()"
        if fn.attr in SYNC_METHODS:
            return f".{fn.attr}()"
    return None


class _LoopScanner(ast.NodeVisitor):
    """Collects sync calls inside loop bodies, skipping nested defs."""

    def __init__(self, lines: Sequence[str]):
        self.lines = lines
        self.hits: List[tuple] = []  # (ast.Call, label)
        self._loop_depth = 0

    def _allowed(self, node: ast.AST) -> bool:
        i = getattr(node, "lineno", 0) - 1
        return 0 <= i < len(self.lines) and ALLOW_MARKER in self.lines[i]

    def visit_For(self, node):  # noqa: N802 (ast API)
        self._loop_body(node)

    visit_AsyncFor = visit_For  # noqa: N815

    def visit_While(self, node):  # noqa: N802
        self._loop_body(node)

    def _loop_body(self, node) -> None:
        self._loop_depth += 1
        for child in node.body:
            self.visit(child)
        self._loop_depth -= 1
        for child in node.orelse:
            self.visit(child)

    def visit_FunctionDef(self, node):  # noqa: N802
        # A def inside a hot loop only *defines*; don't descend.
        if self._loop_depth == 0:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815
    visit_Lambda = visit_FunctionDef  # noqa: N815

    def visit_Call(self, node):  # noqa: N802
        if self._loop_depth > 0 and not self._allowed(node):
            label = _sync_call_label(node)
            if label is not None:
                self.hits.append((node, label))
        self.generic_visit(node)


class _HotFunctionFinder(ast.NodeVisitor):
    """Maps qualified names (``Class.method`` / ``fn``) to their defs."""

    def __init__(self):
        self.defs: Dict[str, ast.AST] = {}
        self._stack: List[str] = []

    def _enter(self, node):
        self._stack.append(node.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.defs[".".join(self._stack)] = node
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _enter  # noqa: N815
    visit_AsyncFunctionDef = _enter  # noqa: N815
    visit_ClassDef = _enter  # noqa: N815


def lint_source(
    source: str,
    path: str = "<string>",
    hot_functions: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint ``source``; returns host-sync findings.

    ``hot_functions``: qualified names (``LMTrainer.fit``) whose loop
    bodies are in scope.  ``None`` means every function in the source is
    treated as hot — the mode tests and ``--selftest`` use on planted
    sources."""
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    finder = _HotFunctionFinder()
    finder.visit(tree)
    if hot_functions is None:
        targets: Dict[str, ast.AST] = dict(finder.defs)
    else:
        targets = {}
        missing: Set[str] = set()
        for name in hot_functions:
            if name in finder.defs:
                targets[name] = finder.defs[name]
            else:
                missing.add(name)
        if missing:
            raise ValueError(
                f"hot functions {sorted(missing)} not found in {path}; "
                "update core.HOT_LOOPS after renames")
    findings: List[Finding] = []
    for qualname, node in sorted(targets.items()):
        scanner = _LoopScanner(lines)
        for stmt in getattr(node, "body", []):
            scanner.visit(stmt)
        for call, label in scanner.hits:
            findings.append(Finding(
                kind="host-sync",
                severity="error",
                where=f"{path}:{call.lineno}",
                message=(f"blocking {label} inside the {qualname} hot loop "
                         "— convert lazily (meters/MetricsLogger) or mark "
                         f"'# {ALLOW_MARKER}' if the sync is deliberate"),
            ))
    return findings


def lint_file(path: str,
              hot_functions: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path) as f:
        return lint_source(f.read(), path=path, hot_functions=hot_functions)


# ------------------------------------------------------- desync pass (L2)

AGREEMENT_MARKER = "synclint: agreement"
DESYNC_ALLOW_MARKER = "synclint: allow"

# Rank-identity sources: a condition touching these can evaluate
# differently on different processes by construction.
RANK_NAMES = frozenset({"rank", "local_rank", "world_rank", "proc_id",
                        "process_id"})
RANK_CALLS = frozenset({"process_index", "getpid", "gethostname"})
# Locally-observed data: host reads of device values, clocks, RNG,
# filesystem probes, and the repo's own local-state drains (a divergence
# flag, a membership poll) — identical *types* of decision, same hazard:
# only this rank sees the value the branch keys on.  Sites where such a
# value is in fact agreed (all-reduced in-step, epoch-committed by the
# coordinator) declare it with ``# synclint: agreement``.
LOCAL_CALLS = frozenset({"item", "time", "monotonic", "perf_counter",
                         "random", "uniform", "exists", "isfile",
                         "getenv", "float", "int", "drain", "poll"})
LOCAL_ATTRS = frozenset({"triggered", "should_stop"})

# Default collective-issuing call names: jax collectives, the jitted-step
# convention, and the gather-everything checkpoint paths.  Inter-
# procedural propagation extends this set with any same-module function
# that (transitively) calls one of these.
COLLECTIVE_CALLS = frozenset({
    "psum", "pmean", "pmax", "pmin", "all_gather", "all_reduce",
    "all_to_all", "ppermute", "sync_global_devices",
    "step_fn", "train_step", "eval_step", "update_fn",
    "save_checkpoint", "_save_checkpoint", "restore_checkpoint",
    # every rank must restore/re-mesh in lockstep: a snapshot restore
    # re-materializes sharded state and a re-mesh re-grids it — a rank
    # doing either alone leaves its peers' next collective unmatched
    "restore", "remesh",
})


def _final_name(func: ast.AST) -> Optional[str]:
    """The last path component of a call target (``f`` / ``mod.f`` /
    ``self.f`` all resolve to ``f``)."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def collective_functions(tree: ast.AST,
                         seeds: frozenset = COLLECTIVE_CALLS) -> Set[str]:
    """Names of module functions that transitively issue a collective.

    Builds a last-component call graph over every def in the module and
    runs the obvious fixpoint: a function is collective-issuing when it
    calls a seed or another collective-issuing function.  Last-component
    matching (``self.f`` ≡ ``f``) deliberately over-approximates — for a
    *verifier* a false edge is a nuisance, a missed edge is a hang."""
    finder = _HotFunctionFinder()
    finder.visit(tree)
    calls: Dict[str, Set[str]] = {}
    for qualname, node in finder.defs.items():
        short = qualname.rsplit(".", 1)[-1]
        out = calls.setdefault(short, set())
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                name = _final_name(sub.func)
                if name is not None:
                    out.add(name)
    issuing: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for fn, callees in calls.items():
            if fn in issuing:
                continue
            if callees & seeds or callees & issuing:
                issuing.add(fn)
                changed = True
    return issuing


class _TaintMap:
    """Flow-insensitive name-taint fixpoint over one function body.

    An assignment line carrying ``# synclint: agreement`` is a taint
    *sink*: its targets are declared agreed (the membership-epoch poll,
    the all-reduced divergence flag) and stay clean — the assignment-
    statement half of the agreement-anchor contract."""

    def __init__(self, node: ast.AST, lines: Sequence[str] = ()):
        self.taints: Dict[str, str] = {}  # name -> "rank" | "local"
        self._lines = lines
        body = getattr(node, "body", [])
        changed = True
        while changed:
            changed = False
            for stmt in body:
                for sub in ast.walk(stmt):
                    targets: List[ast.AST] = []
                    value = None
                    if isinstance(sub, ast.Assign):
                        targets, value = sub.targets, sub.value
                    elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                        targets, value = [sub.target], sub.value
                    elif isinstance(sub, ast.NamedExpr):
                        targets, value = [sub.target], sub.value
                    elif isinstance(sub, (ast.For, ast.AsyncFor)):
                        targets, value = [sub.target], sub.iter
                    if value is None:
                        continue
                    i = getattr(sub, "lineno", 0) - 1
                    if (0 <= i < len(self._lines)
                            and AGREEMENT_MARKER in self._lines[i]):
                        continue  # declared agreement point: taint sink
                    taint = self.expr_taint(value)
                    if taint is None:
                        continue
                    for tgt in targets:
                        for leaf in ast.walk(tgt):
                            if isinstance(leaf, ast.Name):
                                if self._add(leaf.id, taint):
                                    changed = True

    def _add(self, name: str, taint: str) -> bool:
        prev = self.taints.get(name)
        if prev == taint or prev == "rank":
            return False
        self.taints[name] = taint  # None -> taint, "local" -> "rank"
        return True

    def expr_taint(self, expr: ast.AST) -> Optional[str]:
        """``"rank"`` / ``"local"`` / None for an expression ("rank"
        dominates when both appear)."""
        found: Optional[str] = None
        for sub in ast.walk(expr):
            taint = None
            if isinstance(sub, ast.Name):
                if sub.id in RANK_NAMES:
                    taint = "rank"
                elif sub.id in self.taints:
                    taint = self.taints[sub.id]
            elif isinstance(sub, ast.Attribute):
                if sub.attr in RANK_NAMES:
                    taint = "rank"
                elif sub.attr in LOCAL_ATTRS:
                    taint = "local"
            elif isinstance(sub, ast.Call):
                name = _final_name(sub.func)
                if name in RANK_CALLS:
                    taint = "rank"
                elif name in LOCAL_CALLS:
                    taint = "local"
            if taint == "rank":
                return "rank"
            found = found or taint
        return found


class _DesyncScanner(ast.NodeVisitor):
    """Collects collective calls guarded by tainted, un-agreed branches."""

    def __init__(self, lines: Sequence[str], taints: _TaintMap,
                 issuing: Set[str], fn_blessed: bool):
        self.lines = lines
        self.taints = taints
        self.issuing = issuing
        self.fn_blessed = fn_blessed  # def-line agreement marker
        # (branch lineno, taint kind) for active tainted un-agreed guards
        self.guards: List[tuple] = []
        self.hits: List[tuple] = []  # (call node, label, guard lineno, taint)

    def _marked(self, lineno: int, marker: str) -> bool:
        i = lineno - 1
        return 0 <= i < len(self.lines) and marker in self.lines[i]

    def _branch(self, node, test) -> None:
        taint = self.taints.expr_taint(test)
        guarded = (taint is not None and not self.fn_blessed
                   and not self._marked(node.lineno, AGREEMENT_MARKER))
        if guarded:
            self.guards.append((node.lineno, taint))
        for child in node.body:
            self.visit(child)
        for child in getattr(node, "orelse", []):
            self.visit(child)
        if guarded:
            self.guards.pop()

    def visit_If(self, node):  # noqa: N802
        self._branch(node, node.test)

    def visit_While(self, node):  # noqa: N802
        self._branch(node, node.test)

    def visit_FunctionDef(self, node):  # noqa: N802
        pass  # a nested def only *defines*; its body runs elsewhere

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815
    visit_Lambda = visit_FunctionDef  # noqa: N815

    def visit_Call(self, node):  # noqa: N802
        name = _final_name(node.func)
        if (self.guards and name is not None
                and (name in COLLECTIVE_CALLS or name in self.issuing)
                and not self._marked(node.lineno, DESYNC_ALLOW_MARKER)):
            lineno, taint = self.guards[0]  # outermost divergence point
            self.hits.append((node, name, lineno, taint))
        self.generic_visit(node)


def lint_desync_source(
    source: str,
    path: str = "<string>",
    hot_functions: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Desync-lint ``source``; returns ``collective-desync`` findings.

    Same contract as :func:`lint_source`: ``hot_functions`` names the
    in-scope qualified defs (None = every def, the planted-source mode).
    A finding names both the collective call and the branch site so the
    operator sees the full divergence story in one line."""
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    finder = _HotFunctionFinder()
    finder.visit(tree)
    if hot_functions is None:
        targets: Dict[str, ast.AST] = dict(finder.defs)
    else:
        targets = {}
        missing: Set[str] = set()
        for name in hot_functions:
            if name in finder.defs:
                targets[name] = finder.defs[name]
            else:
                missing.add(name)
        if missing:
            raise ValueError(
                f"hot functions {sorted(missing)} not found in {path}; "
                "update the synclint SYNC_SCOPES registry after renames")
    issuing = collective_functions(tree)
    findings: List[Finding] = []
    for qualname, node in sorted(targets.items()):
        fn_blessed = (
            (0 <= node.lineno - 1 < len(lines)
             and AGREEMENT_MARKER in lines[node.lineno - 1])
            or (0 <= node.lineno - 1 < len(lines)
                and DESYNC_ALLOW_MARKER in lines[node.lineno - 1]))
        scanner = _DesyncScanner(lines, _TaintMap(node, lines), issuing,
                                 fn_blessed)
        for stmt in getattr(node, "body", []):
            scanner.visit(stmt)
        for call, label, branch_line, taint in scanner.hits:
            kind_txt = ("rank-dependent" if taint == "rank"
                        else "locally-data-dependent")
            findings.append(Finding(
                kind="collective-desync",
                severity="error",
                where=f"{path}:{call.lineno}",
                message=(f"collective call {label}() in {qualname} is "
                         f"reachable under a {kind_txt} branch at "
                         f"{path}:{branch_line} with no agreement point "
                         "— a rank that takes the other arm skips the "
                         "collective its peers are blocked in; route the "
                         "decision through an agreed value and mark the "
                         f"branch '# {AGREEMENT_MARKER}', or mark the "
                         f"call '# {DESYNC_ALLOW_MARKER}'"),
            ))
    return findings


def lint_desync_file(
    path: str,
    hot_functions: Optional[Iterable[str]] = None,
) -> List[Finding]:
    with open(path) as f:
        return lint_desync_source(f.read(), path=path,
                                  hot_functions=hot_functions)
