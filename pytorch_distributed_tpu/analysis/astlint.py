"""Host-sync lint: a Python-AST pass over training hot loops.

The whole framework is built around lazy metric conversion — the jitted
step returns *unready* device scalars and the meters / MetricsLogger
convert them at display/flush cadence (train/meters.py, obs/metrics.py),
so the hot loop never blocks on a device→host transfer.  One careless
``float(metrics["loss"])`` inside the step loop silently reinstates the
reference's 3-syncs-per-batch behavior (SURVEY.md §3.1a) and no test
notices: throughput just quietly drops.

This pass makes the discipline mechanical.  For each *hot* function
(``core.HOT_LOOPS`` names the step-driving loops; planted sources can be
linted directly), every ``for``/``while`` body is scanned for blocking
device→host conversions:

- ``float(...)`` / ``int(...)`` builtins (the ``.item()``-equivalent)
- ``.item()`` / ``.block_until_ready()`` / ``.copy_to_host_async()`` wait
  calls
- ``np.asarray`` / ``np.array`` / ``numpy.asarray`` / ``numpy.array``
- ``jax.device_get``

Nested function definitions inside a loop are skipped (defining a closure
is not a sync), and a line ending in ``# shardlint: allow-sync`` is
exempt — the escape hatch for a loop that genuinely must sync (e.g. an
eval loop doing exact host-side aggregation, which is a *documented*
per-batch sync, not an accident).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Sequence, Set

from pytorch_distributed_tpu.analysis.report import Finding

ALLOW_MARKER = "shardlint: allow-sync"

# Builtin calls that force a device->host sync when fed a jax array.
SYNC_BUILTINS = frozenset({"float", "int"})
# Method calls that block on (or force) a transfer.
SYNC_METHODS = frozenset({"item", "block_until_ready"})
# module.attr calls: {module alias: {attr, ...}}
SYNC_QUALIFIED: Dict[str, frozenset] = {
    "np": frozenset({"asarray", "array"}),
    "numpy": frozenset({"asarray", "array"}),
    "jax": frozenset({"device_get"}),
}


def _sync_call_label(node: ast.Call) -> Optional[str]:
    """A short label for a blocking call, or None if the call is benign."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in SYNC_BUILTINS:
        return f"{fn.id}()"
    if isinstance(fn, ast.Attribute):
        if isinstance(fn.value, ast.Name) and fn.attr in SYNC_QUALIFIED.get(
                fn.value.id, ()):
            return f"{fn.value.id}.{fn.attr}()"
        if fn.attr in SYNC_METHODS:
            return f".{fn.attr}()"
    return None


class _LoopScanner(ast.NodeVisitor):
    """Collects sync calls inside loop bodies, skipping nested defs."""

    def __init__(self, lines: Sequence[str]):
        self.lines = lines
        self.hits: List[tuple] = []  # (ast.Call, label)
        self._loop_depth = 0

    def _allowed(self, node: ast.AST) -> bool:
        i = getattr(node, "lineno", 0) - 1
        return 0 <= i < len(self.lines) and ALLOW_MARKER in self.lines[i]

    def visit_For(self, node):  # noqa: N802 (ast API)
        self._loop_body(node)

    visit_AsyncFor = visit_For  # noqa: N815

    def visit_While(self, node):  # noqa: N802
        self._loop_body(node)

    def _loop_body(self, node) -> None:
        self._loop_depth += 1
        for child in node.body:
            self.visit(child)
        self._loop_depth -= 1
        for child in node.orelse:
            self.visit(child)

    def visit_FunctionDef(self, node):  # noqa: N802
        # A def inside a hot loop only *defines*; don't descend.
        if self._loop_depth == 0:
            self.generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef  # noqa: N815
    visit_Lambda = visit_FunctionDef  # noqa: N815

    def visit_Call(self, node):  # noqa: N802
        if self._loop_depth > 0 and not self._allowed(node):
            label = _sync_call_label(node)
            if label is not None:
                self.hits.append((node, label))
        self.generic_visit(node)


class _HotFunctionFinder(ast.NodeVisitor):
    """Maps qualified names (``Class.method`` / ``fn``) to their defs."""

    def __init__(self):
        self.defs: Dict[str, ast.AST] = {}
        self._stack: List[str] = []

    def _enter(self, node):
        self._stack.append(node.name)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self.defs[".".join(self._stack)] = node
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _enter  # noqa: N815
    visit_AsyncFunctionDef = _enter  # noqa: N815
    visit_ClassDef = _enter  # noqa: N815


def lint_source(
    source: str,
    path: str = "<string>",
    hot_functions: Optional[Iterable[str]] = None,
) -> List[Finding]:
    """Lint ``source``; returns host-sync findings.

    ``hot_functions``: qualified names (``LMTrainer.fit``) whose loop
    bodies are in scope.  ``None`` means every function in the source is
    treated as hot — the mode tests and ``--selftest`` use on planted
    sources."""
    tree = ast.parse(source, filename=path)
    lines = source.splitlines()
    finder = _HotFunctionFinder()
    finder.visit(tree)
    if hot_functions is None:
        targets: Dict[str, ast.AST] = dict(finder.defs)
    else:
        targets = {}
        missing: Set[str] = set()
        for name in hot_functions:
            if name in finder.defs:
                targets[name] = finder.defs[name]
            else:
                missing.add(name)
        if missing:
            raise ValueError(
                f"hot functions {sorted(missing)} not found in {path}; "
                "update core.HOT_LOOPS after renames")
    findings: List[Finding] = []
    for qualname, node in sorted(targets.items()):
        scanner = _LoopScanner(lines)
        for stmt in getattr(node, "body", []):
            scanner.visit(stmt)
        for call, label in scanner.hits:
            findings.append(Finding(
                kind="host-sync",
                severity="error",
                where=f"{path}:{call.lineno}",
                message=(f"blocking {label} inside the {qualname} hot loop "
                         "— convert lazily (meters/MetricsLogger) or mark "
                         f"'# {ALLOW_MARKER}' if the sync is deliberate"),
            ))
    return findings


def lint_file(path: str,
              hot_functions: Optional[Iterable[str]] = None) -> List[Finding]:
    with open(path) as f:
        return lint_source(f.read(), path=path, hot_functions=hot_functions)
