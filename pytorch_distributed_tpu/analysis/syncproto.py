"""Protocol model check: explicit-state exploration of rank state machines.

Synclint layer 3.  Layers 1–2 prove each *compiled module* is congruent
and each *hot loop* routes its branches through agreement points; this
layer closes the remaining gap — multi-step **protocols** (divergence
skip/rollback, elastic shrink/grow, checkpoint prev-fallback) where the
hazard is an emergent interleaving, not a single branch.  The PR 13
flight recorder diagnoses exactly these post-mortem: rank 0 decided to
stop/skip/re-mesh on information rank 1 never saw, and both died blocked
in different collectives.  Here the same bug class is found *before
launch* by exhaustive exploration of a tiny abstraction.

The abstraction
---------------
Every rank runs the same straight-line program (SPMD) over four opcodes:

- ``("coll", name)`` — issue collective ``name``.  Collectives are the
  only synchronization points: all ranks must issue the *same* next
  collective or the job deadlocks (bulk-synchronous semantics — exactly
  what NCCL/ICI gives you).
- ``("branch", scope, var, then_pc, else_pc, site)`` — branch on boolean
  ``var``.  ``scope="agreed"`` means every rank reads the same value (the
  preemption-agreement all-reduce, a membership epoch); ``scope="local"``
  means each rank reads its *own* value (a signal flag, a local file
  probe, a local divergence verdict).  ``site`` labels the source idiom
  for the counterexample report.
- ``("goto", pc)`` — unconditional jump.
- ``("end",)`` — the rank terminates.

Branch predicates are memoized per path: an agreed var takes one global
boolean per exploration path, a local var one boolean per (rank, path).
Because everything between collectives is rank-local, two ranks can only
interact at collective boundaries — so a path is a deadlock iff the
per-rank *collective traces* diverge: at the first differing index one
rank is blocked in a collective its peers never issue (or has terminated
while a peer blocks).  The explorer enumerates every valuation (the
models are tiny: ≤3 vars, 2 ranks) and simulates each rank to completion,
which is sound and complete for this abstraction.

The punchline is structural: a program whose only branches are *agreed*
keeps all ranks in lockstep — verifiably safe.  One *local* branch
guarding a collective (or an early ``end``) and the explorer hands back
the exact valuation, the divergence frontier, and the branch to blame.
Nothing here imports jax; it is pure stdlib, unit-testable anywhere.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Sequence, Tuple

from pytorch_distributed_tpu.analysis.report import Finding

# A rank that executes more than this many opcodes is looping forever on
# a constant predicate — a modelling bug, reported as such.
STEP_CAP = 10_000


@dataclasses.dataclass(frozen=True)
class Program:
    """One SPMD protocol model: every rank runs ``instrs`` from pc 0."""

    name: str
    instrs: Tuple[tuple, ...]
    n_ranks: int = 2


@dataclasses.dataclass
class Counterexample:
    """A reachable valuation under which ranks desync."""

    model: str
    valuation: Dict[str, object]          # var -> bool | per-rank tuple
    traces: List[List[str]]               # per-rank collective traces
    frontier: int                         # first differing trace index
    next_colls: List[str]                 # per-rank blocked collective/END
    blame_site: str                       # the local branch that diverged
    blame_var: str

    def __str__(self) -> str:
        ranks = ", ".join(f"rank{r} -> {c}"
                          for r, c in enumerate(self.next_colls))
        return (f"{self.model}: at collective #{self.frontier} "
                f"{ranks}; diverged on local predicate "
                f"'{self.blame_var}' at {self.blame_site}")


def _variables(program: Program) -> Tuple[List[str], List[str]]:
    """(agreed vars, local vars) in first-appearance order."""
    agreed: List[str] = []
    local: List[str] = []
    for ins in program.instrs:
        if ins[0] != "branch":
            continue
        _, scope, var, _, _, _ = ins
        bucket = agreed if scope == "agreed" else local
        if var not in bucket:
            bucket.append(var)
    return agreed, local


def _run_rank(program: Program, rank: int,
              agreed_vals: Dict[str, bool],
              local_vals: Dict[Tuple[str, int], bool]) -> List[str]:
    """Simulate one rank to termination; returns its collective trace."""
    trace: List[str] = []
    pc, steps = 0, 0
    while True:
        steps += 1
        if steps > STEP_CAP:
            raise RuntimeError(
                f"{program.name}: rank {rank} exceeded {STEP_CAP} opcodes "
                "— the model loops on a constant predicate")
        ins = program.instrs[pc]
        op = ins[0]
        if op == "end":
            return trace
        if op == "coll":
            trace.append(ins[1])
            pc += 1
        elif op == "goto":
            pc = ins[1]
        elif op == "branch":
            _, scope, var, then_pc, else_pc, _site = ins
            val = (agreed_vals[var] if scope == "agreed"
                   else local_vals[(var, rank)])
            pc = then_pc if val else else_pc
        else:
            raise ValueError(f"{program.name}: unknown opcode {op!r}")


def _blame(program: Program, local_vals: Dict[Tuple[str, int], bool],
           n_ranks: int) -> Tuple[str, str]:
    """The first local predicate whose per-rank values differ."""
    for ins in program.instrs:
        if ins[0] != "branch" or ins[1] != "local":
            continue
        _, _, var, _, _, site = ins
        vals = {local_vals[(var, r)] for r in range(n_ranks)}
        if len(vals) > 1:
            return site, var
    return ("<unknown>", "<unknown>")


def explore(program: Program) -> Optional[Counterexample]:
    """Exhaustively check every branch valuation; None means verified.

    Returns the *first* counterexample found (deterministic order: agreed
    valuations outer, local valuations inner, False before True)."""
    agreed_vars, local_vars = _variables(program)
    n = program.n_ranks
    local_slots = [(v, r) for v in local_vars for r in range(n)]
    for agreed_bits in itertools.product(
            (False, True), repeat=len(agreed_vars)):
        agreed_vals = dict(zip(agreed_vars, agreed_bits))
        for local_bits in itertools.product(
                (False, True), repeat=len(local_slots)):
            local_vals = dict(zip(local_slots, local_bits))
            traces = [_run_rank(program, r, agreed_vals, local_vals)
                      for r in range(n)]
            frontier = _divergence_frontier(traces)
            if frontier is None:
                continue
            site, var = _blame(program, local_vals, n)
            valuation: Dict[str, object] = dict(agreed_vals)
            for v in local_vars:
                valuation[v] = tuple(local_vals[(v, r)] for r in range(n))
            return Counterexample(
                model=program.name, valuation=valuation, traces=traces,
                frontier=frontier,
                next_colls=[t[frontier] if frontier < len(t) else "END"
                            for t in traces],
                blame_site=site, blame_var=var)
    return None


def _divergence_frontier(traces: Sequence[Sequence[str]]) -> Optional[int]:
    """First index where the per-rank collective traces disagree, or
    None when every rank issues the identical sequence."""
    longest = max(len(t) for t in traces)
    for i in range(longest):
        slots = [t[i] if i < len(t) else "END" for t in traces]
        if len(set(slots)) > 1:
            return i
    return None


# ------------------------------------------------------------ the models
#
# Each builder returns a Program abstracting one repo protocol.  The
# ``agreed`` flag selects the shipped idiom (decision routed through an
# agreement collective / membership epoch — verifiably safe) or the buggy
# local variant synclint exists to catch (each rank trusts its own view).

def divergence_model(agreed: bool = True) -> Program:
    """ft/divergence.py skip/rollback: after each step's grad all-reduce,
    the guard may roll state back via StateKeeper.restore (a gather).  The
    shipped flag is all-reduced *inside* the step, so every rank reads the
    same verdict; the buggy variant branches on a per-rank loss check."""
    scope = "agreed" if agreed else "local"
    site = ("ft/divergence.py:DivergenceGuard.drain" if agreed
            else "ft/divergence.py:<local loss check>")
    return Program(
        name=f"divergence-{'agreed' if agreed else 'local'}",
        instrs=(
            ("coll", "grad_allreduce"),          # 0: step 1
            ("branch", scope, "diverged", 2, 3, site),   # 1
            ("coll", "rollback_gather"),         # 2: StateKeeper.restore
            ("coll", "grad_allreduce"),          # 3: step 2
            ("end",),                            # 4
        ))


def elastic_model(agreed: bool = True) -> Program:
    """ft/elastic.py shrink/grow: the coordinator bumps a membership
    epoch, every rank re-meshes at the *same* step, and the post-shrink
    collective is a different op (smaller replica groups — spelled here
    as ``allreduce_w4`` vs ``allreduce_w8``).  The buggy variant lets each
    rank act on its own liveness probe: one re-meshes to world=4 while the
    other all-reduces at world=8 — the PR 13 two-rank hang, statically."""
    scope = "agreed" if agreed else "local"
    site = ("ft/elastic.py:ElasticCoordinator.decide" if agreed
            else "ft/elastic.py:<local liveness probe>")
    return Program(
        name=f"elastic-shrink-{'agreed' if agreed else 'local'}",
        instrs=(
            ("coll", "allreduce_w8"),            # 0: full-world step
            ("branch", scope, "shrink", 2, 4, site),     # 1
            ("coll", "remesh_gather"),           # 2: re-grid state
            ("coll", "allreduce_w4"),            # 3: shrunk-world step
            ("goto", 5),                         # 4 -> skip to join
            ("end",),                            # 5
        ))


def checkpoint_model(agreed: bool = True) -> Program:
    """checkpoint prev-fallback: when the newest checkpoint fails
    verification, restore falls back to the previous one — both restores
    gather sharded leaves, but they are *different* gathers (different
    step's layouts).  Shipped: the fallback verdict is agreed before any
    rank touches storage.  Buggy: each rank probes its own local copy."""
    scope = "agreed" if agreed else "local"
    site = ("utils/checkpoint.py:<agreed fallback verdict>" if agreed
            else "utils/checkpoint.py:<local os.path.exists probe>")
    return Program(
        name=f"checkpoint-fallback-{'agreed' if agreed else 'local'}",
        instrs=(
            ("branch", scope, "corrupt", 1, 3, site),    # 0
            ("coll", "restore_prev_gather"),     # 1: previous save's gather
            ("goto", 4),                         # 2
            ("coll", "restore_gather"),          # 3: newest save's gather
            ("coll", "step_allreduce"),          # 4: first step after
            ("end",),                            # 5
        ))


def preempt_model(agreed: bool = True) -> Program:
    """utils/preempt.py stop decision: a SIGTERM lands on *one* rank; if
    it exits on its local flag the survivors block forever in the next
    grad all-reduce — the exact two-rank hang `chaoskit drill hang`
    reproduces live and the PR 13 watchdog diagnoses post-mortem.  The
    shipped PreemptionAgreement all-reduces the flag so every rank stops
    at the same step boundary."""
    scope = "agreed" if agreed else "local"
    site = ("utils/preempt.py:PreemptionAgreement.should_stop" if agreed
            else "utils/preempt.py:<local guard.triggered flag>")
    return Program(
        name=f"preempt-{'agreed' if agreed else 'local'}",
        instrs=(
            ("coll", "grad_allreduce"),          # 0: step 1
            ("branch", scope, "stop", 3, 2, site),       # 1
            ("coll", "grad_allreduce"),          # 2: step 2
            ("end",),                            # 3: drain + exit
        ))


# name -> (builder(agreed) , description).  ``check_protocols`` verifies
# the agreed variants; the local variants are the planted half of the
# selftest (each MUST yield a counterexample or the explorer is broken).
MODELS: Dict[str, tuple] = {
    "divergence-skip-rollback": (
        divergence_model, "DivergenceGuard skip/rollback vs StateKeeper"),
    "elastic-shrink-grow": (
        elastic_model, "elastic re-mesh epoch vs the active world's step"),
    "checkpoint-prev-fallback": (
        checkpoint_model, "restore-time fallback to the previous save"),
    "preempt-stop": (
        preempt_model, "SIGTERM stop decision vs in-flight collectives"),
}


def check_protocols() -> List[Finding]:
    """Verify every shipped (agreed) protocol model; a counterexample in
    one of these is an error — the repo's own idiom would deadlock."""
    findings: List[Finding] = []
    for key, (builder, desc) in sorted(MODELS.items()):
        cex = explore(builder(agreed=True))
        if cex is not None:
            findings.append(Finding(
                kind="protocol-desync", severity="error",
                where=f"proto:{key}",
                message=f"{desc}: {cex}"))
        else:
            findings.append(Finding(
                kind="protocol-desync", severity="info",
                where=f"proto:{key}",
                message=f"{desc}: verified desync-free "
                        "(all branch valuations explored)"))
    return findings


def planted_counterexamples() -> List[Finding]:
    """Run the buggy (local-predicate) variants: every one must desync.
    These are the planted fixtures — the selftest and ``chaoskit drill
    desync`` assert the explorer still finds each hang."""
    findings: List[Finding] = []
    for key, (builder, desc) in sorted(MODELS.items()):
        cex = explore(builder(agreed=False))
        if cex is None:
            raise AssertionError(
                f"protocol explorer missed the planted desync in the "
                f"local variant of {key} — the model checker is broken")
        findings.append(Finding(
            kind="protocol-desync", severity="error",
            where=f"proto:{key}:local-variant",
            message=f"{desc}: {cex}"))
    return findings
