"""shardlint core: lower every jitted step builder on a CPU mesh and walk
its jaxpr + compiled HLO for the hazard classes that previously needed a
hand-grep per PR.

The analyzer cross-references two views of one program:

- the **jaxpr** (``jax.make_jaxpr`` over the jitted step) carries *global*
  logical shapes for every intermediate, plus structure: which values are
  scan/while loop carries, which convert_element_type equations upcast,
  which subtrees sit inside ``shard_map`` (already per-shard — excluded
  from the global view);
- the **post-optimization HLO** (``jitted.lower(...).compile().as_text()``)
  carries *per-device* truth: post-SPMD shapes, explicit collectives, and
  the ``input_output_alias`` donation map.

A global-shaped intermediate that shows up at FULL size in the per-device
module is materialized on every device — replicated (or all-gathered)
rather than sharded.  Severity follows structure:

- a **loop carry** at full global size is ``replicated-large-tensor``
  (error): an accumulator rebuilt per device per iteration — exactly the
  PR-1 fused-CE ``[V, D]`` dE bug, and the silent-DP-waste class of
  arxiv 2004.13336;
- a param-shaped one-shot intermediate (grads, updated params) is the
  *declared* pure-DP layout → ``replicated-state`` (info), the standing
  FSDP opportunity, not a regression;
- anything else at full size is ``replicated-large-tensor`` (error).

Donation accounting maps ``donate_argnums`` arguments to flattened entry
parameters and checks XLA actually aliased each one (``lost-donation``);
steps that never donate are probed for shape-matching input/output pairs
(``no-donation``).  Collective counts/bytes are pinned against
``analysis/baseline.json`` (EQuARX-style per-step collective budget,
arxiv 2506.17615).  The host-sync lint (analysis/astlint.py) runs over the
``HOT_LOOPS`` registry.

Donation audit record (why the sweep's expectations are what they are):

- ``make_train_step`` / ``make_lm_train_step`` donate state (argnum 0) —
  this covers all three pipeline schedules too, since gpipe/1f1b/
  interleaved steps are jitted through ``make_lm_train_step`` (the
  schedules themselves are shard_map bodies, not jit boundaries);
- ``make_eval_step`` / ``make_lm_eval_step`` must NOT donate: the trainer
  reuses one state across every eval batch, and the batch inputs have no
  shape-compatible outputs (metrics are scalars), so donating them would
  only produce XLA unused-donation warnings;
- speculative decode (models/speculative.py) does NOT donate its KV
  caches even though they are dead after each ``apply`` call: XLA dedups
  identical executable outputs (every layer's equal ``cache_index``
  scalar aliases one buffer), so donating the returned tree raises PJRT's
  "attempt to donate the same buffer twice" on the next call — attempted
  and reverted, documented at the jit site.
"""

from __future__ import annotations

import dataclasses
import os
from collections import Counter, OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from pytorch_distributed_tpu.analysis import astlint
from pytorch_distributed_tpu.analysis import hlo as hlo_mod
from pytorch_distributed_tpu.analysis import jaxpr as jaxpr_mod
from pytorch_distributed_tpu.analysis.report import Finding, StepReport

# Replicated intermediates / upcasts below these sizes are noise at scale;
# tests and --selftest pass smaller thresholds to probe tiny fixtures.
DEFAULT_MIN_REPLICATED_BYTES = 1 << 20
DEFAULT_MIN_PROMOTION_BYTES = 1 << 20
# Missing donated leaves above this are errors (below: info — e.g. a step
# counter XLA chose not to alias is odd but harmless).
DEFAULT_MIN_DONATION_BYTES = 1 << 10
# A never-donating step warns only when at least this much input memory
# shape-matches its outputs.
DEFAULT_NO_DONATION_BYTES = 1 << 20

# Hot training loops lint_hot_loops() enforces the lazy-sync discipline
# on, as (path relative to the package root, qualified function names).
HOT_LOOPS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("train/trainer.py", ("Trainer.train_epoch",)),
    ("train/lm.py", ("LMTrainer.fit",)),
)

# Tiny-but-structured sweep configs: small enough that every step compiles
# in seconds on the CPU mesh, big enough that shardings are nontrivial.
_LM = dict(vocab=64, d_model=32, n_heads=4, seq=16, batch=8)


def _leaf_bytes(leaf) -> int:
    try:
        return int(np.prod(leaf.shape, dtype=np.int64)
                   * np.dtype(leaf.dtype).itemsize)
    except Exception:
        return 0


@dataclasses.dataclass
class Lowering:
    """One recipe's lowered + compiled step, cached for reuse.

    Lowering and compiling the 12 step builders dominates shardlint's
    (and the test suite's) wall clock on the 1-core CI host; every
    analysis downstream of compilation — hazard detectors, collective
    budgets, the comm ledger — is pure text/jaxpr walking over this
    record, so one sweep can feed them all (``get_lowering``)."""

    name: str
    jitted: Any
    args: Tuple[Any, ...]
    donate: Optional[Tuple[int, ...]]
    mesh: Any
    text: str          # post-optimization HLO
    compiled: Any
    closed: Any        # closed jaxpr

    @property
    def mesh_shape(self) -> Dict[str, int]:
        return dict(self.mesh.shape) if self.mesh is not None else {}


def lower_jitted(jitted, args: Sequence[Any], *, name: str, mesh=None,
                 donate: Optional[Sequence[int]] = None) -> Lowering:
    """The expensive half of the analysis: lower + compile + jaxpr."""
    import jax

    count_compile()
    compiled = jitted.lower(*args).compile()
    return Lowering(
        name=name, jitted=jitted, args=tuple(args),
        donate=None if donate is None else tuple(donate), mesh=mesh,
        text=compiled.as_text(), compiled=compiled,
        closed=jax.make_jaxpr(jitted)(*args))


_LOWERING_CACHE: Dict[str, Lowering] = {}
_COMPILE_COUNT = 0


def compile_count() -> int:
    """AOT lower+compile sweeps paid by this process so far.  The
    zero-extra-compiles fence: tests snapshot it around the memory-ledger
    sweep to prove ledgering rides the cached lowerings, and
    analysis/lowering.py's budget assert fences the process total."""
    return _COMPILE_COUNT


def count_compile() -> None:
    """Book one AOT compile against the process-wide counter.  External
    lower+compile paths (the trainers' ledger emission via
    ``lowering.aot_ledgers``) call this so the compile budget sees every
    sweep in the process, not just the recipe cache's."""
    global _COMPILE_COUNT
    _COMPILE_COUNT += 1


def get_lowering(name: str) -> Lowering:
    """Session-memoized lowering for one recipe.  The detectors and the
    comm ledger are pure functions of this record, so repeated
    ``analyze_recipe`` calls (tests probing different thresholds, the
    comms sweep, the baseline diff) share one compile."""
    low = _LOWERING_CACHE.get(name)
    if low is None:
        jitted, args, donate, mesh = RECIPES[name]()
        low = lower_jitted(jitted, args, name=name, mesh=mesh, donate=donate)
        _LOWERING_CACHE[name] = low
    return low


def clear_lowering_cache() -> None:
    _LOWERING_CACHE.clear()


def analyze_jitted(
    jitted,
    args: Sequence[Any],
    *,
    name: str,
    mesh=None,
    donate: Optional[Sequence[int]] = None,
    **thresholds,
) -> StepReport:
    """Lower + compile one jitted step and emit its StepReport.

    ``donate``: the argnums the *caller* claims are donated — a tuple
    triggers the lost-donation check, ``()`` the no-donation opportunity
    probe, ``None`` skips donation accounting entirely (single-purpose
    kernels with no state)."""
    return analyze_lowering(
        lower_jitted(jitted, args, name=name, mesh=mesh, donate=donate),
        **thresholds)


def analyze_lowering(
    low: Lowering,
    *,
    min_replicated_bytes: int = DEFAULT_MIN_REPLICATED_BYTES,
    min_promotion_bytes: int = DEFAULT_MIN_PROMOTION_BYTES,
    min_donation_bytes: int = DEFAULT_MIN_DONATION_BYTES,
    declared_zero: bool = False,
) -> StepReport:
    """The cheap half: run every detector over an existing Lowering.

    ``declared_zero``: the step claims ``--zero wus`` weight-update
    sharding (parallel/zero.py), so replicated param-shaped optimizer
    state is no longer the *declared* layout — the ``replicated-state``
    info finding promotes to a hard error (the WUS sharding silently
    fell back to replicated DP)."""
    name, text, closed = low.name, low.text, low.closed
    args, donate = low.args, low.donate

    mesh_shape = low.mesh_shape
    n_devices = 1
    for v in mesh_shape.values():
        n_devices *= v

    report = StepReport(name=name, mesh_shape=mesh_shape)
    instrs = hlo_mod.parse_instructions(text)
    report.collectives = hlo_mod.collect_collectives(instrs)
    try:
        ma = low.compiled.memory_analysis()
        report.memory = {
            k: int(getattr(ma, k))
            for k in ("temp_size_in_bytes", "argument_size_in_bytes",
                      "output_size_in_bytes")
            if hasattr(ma, k)
        }
    except Exception:
        report.memory = {}

    if n_devices > 1:
        param_shapes = set(hlo_mod.entry_parameter_shapes(text))
        index = hlo_mod.nonparameter_shape_index(instrs)
        carries = jaxpr_mod.loop_carry_shapes(closed)
        globals_ = jaxpr_mod.global_intermediate_shapes(
            closed, min_bytes=min_replicated_bytes)
        for shape, meta in sorted(globals_.items(),
                                  key=lambda kv: -kv[1]["bytes"]):
            ins = index.get(shape)
            if ins is None:
                continue  # per-device shape is smaller: properly sharded
            dtype, dims = shape
            # Gradients of replicated params often appear transposed
            # (dot_general layout) — still the declared DP state layout.
            param_shaped = (shape in param_shapes
                            or (dtype, dims[::-1]) in param_shapes)
            carry = carries.get(shape)
            if carry is not None:
                report.add(Finding(
                    kind="replicated-large-tensor", severity="error",
                    where=name, bytes=meta["bytes"], shape=dims, dtype=dtype,
                    message=(
                        f"loop-carried accumulator materialized at full "
                        f"global size on every device of the {n_devices}-"
                        f"device mesh (carry of {carry['primitive']} at "
                        f"{carry['source']}; HLO {ins.opcode} '{ins.name}')"
                        " — shard the carry (the PR-1 fused-CE dE class)"),
                ))
            elif param_shaped:
                if declared_zero:
                    report.add(Finding(
                        kind="replicated-state", severity="error",
                        where=name, bytes=meta["bytes"], shape=dims,
                        dtype=dtype,
                        message=(
                            f"param-shaped intermediate ({meta['primitive']}"
                            f" at {meta['source']}) updated at full size per "
                            "device under a step declared --zero wus — the "
                            "weight-update sharding fell back to replicated "
                            "DP (check the momentum shardings reach the jit "
                            "in_shardings)"),
                    ))
                else:
                    report.add(Finding(
                        kind="replicated-state", severity="info",
                        where=name, bytes=meta["bytes"], shape=dims,
                        dtype=dtype,
                        message=(
                            f"param-shaped intermediate ({meta['primitive']}"
                            f" at {meta['source']}) updated at full size per "
                            "device — the declared replicated (pure-DP) "
                            "state layout; standing FSDP/ZeRO opportunity"),
                    ))
            else:
                report.add(Finding(
                    kind="replicated-large-tensor", severity="error",
                    where=name, bytes=meta["bytes"], shape=dims, dtype=dtype,
                    message=(
                        f"intermediate ({meta['primitive']} at "
                        f"{meta['source']}; HLO {ins.opcode} '{ins.name}') "
                        f"materialized at full global size on every device "
                        f"of the {n_devices}-device mesh — add a sharding"),
                ))

    for prom in jaxpr_mod.find_dtype_promotions(closed, min_promotion_bytes):
        report.add(Finding(
            kind="dtype-promotion", severity="warn", where=name,
            bytes=prom["bytes"], shape=tuple(prom["shape"]),
            dtype=prom["to"],
            message=(f"{prom['from']}->{prom['to']} upcast of a large "
                     f"intermediate at {prom['source']} — doubles its "
                     "footprint; keep backward math in the narrow dtype or "
                     "use preferred_element_type for accumulation"),
        ))

    if donate is not None:
        _donation_findings(report, text, args, tuple(donate),
                           min_donation_bytes)
    return report


def _donation_findings(report: StepReport, text: str, args: Sequence[Any],
                       donate: Tuple[int, ...], min_bytes: int) -> None:
    import jax

    aliased = set(hlo_mod.aliased_param_numbers(text))
    flat: List[Tuple[Any, Any]] = []  # (key path, leaf) in entry-param order
    ranges: List[Tuple[int, int]] = []
    pos = 0
    for a in args:
        leaves, _ = jax.tree_util.tree_flatten_with_path(a)
        ranges.append((pos, pos + len(leaves)))
        flat.extend(leaves)
        pos += len(leaves)
    report.donation = {"aliased_params": sorted(aliased), "arg_leaves": pos}
    if donate:
        n_entry = len(hlo_mod.entry_parameter_shapes(text))
        if n_entry and n_entry != pos:
            # Unused-argument pruning / constant hoisting changed the
            # parameter list; the leaf->parameter-number mapping would be
            # wrong, so don't guess.
            report.donation["note"] = (
                f"entry parameter count {n_entry} != flattened arg leaf "
                f"count {pos}; donation mapping skipped")
            return
        expected = set()
        for argnum in donate:
            expected |= set(range(*ranges[argnum]))
        missing = sorted(expected - aliased)
        missing_bytes = sum(_leaf_bytes(flat[i][1]) for i in missing)
        report.donation.update({
            "expected": len(expected),
            "aliased": len(expected & aliased),
            "missing": missing,
            "missing_bytes": missing_bytes,
        })
        if missing:
            names = ", ".join(
                f"arg{_argnum_of(ranges, i)}{jax.tree_util.keystr(flat[i][0])}"
                for i in missing[:6])
            more = "" if len(missing) <= 6 else f" (+{len(missing) - 6} more)"
            report.add(Finding(
                kind="lost-donation",
                severity="error" if missing_bytes >= min_bytes else "info",
                where=report.name, bytes=missing_bytes,
                message=(
                    f"{len(missing)} donated leaves not input/output-aliased "
                    f"by XLA: {names}{more} — a shape/dtype/sharding mismatch "
                    "between the donated input and every output drops the "
                    "donation silently (double-buffered state)"),
            ))
    else:
        if not aliased:
            big_in = Counter(
                s for s in hlo_mod.entry_parameter_shapes(text)
                if hlo_mod.shape_bytes(s) >= min_bytes)
            outs = Counter(hlo_mod.entry_output_shapes(text))
            opportunity = sum(
                hlo_mod.shape_bytes(s) * min(c, outs[s])
                for s, c in big_in.items() if s in outs)
            report.donation["opportunity_bytes"] = opportunity
            if opportunity >= max(min_bytes, DEFAULT_NO_DONATION_BYTES):
                report.add(Finding(
                    kind="no-donation", severity="warn", where=report.name,
                    bytes=opportunity,
                    message=(
                        f"step never donates, but "
                        f"{opportunity / 2**20:.1f} MiB of inputs shape-"
                        "match outputs — pass donate_argnums for state that "
                        "is dead after the step"),
                ))


def _argnum_of(ranges: Sequence[Tuple[int, int]], leaf_index: int) -> int:
    for argnum, (lo, hi) in enumerate(ranges):
        if lo <= leaf_index < hi:
            return argnum
    return -1


# ------------------------------------------------------------- host-sync

def lint_hot_loops() -> StepReport:
    """Run the astlint pass over the registered training hot loops."""
    import pytorch_distributed_tpu as pkg

    base = os.path.dirname(os.path.abspath(pkg.__file__))
    report = StepReport(name="hot-loops")
    for rel, functions in HOT_LOOPS:
        path = os.path.join(base, rel)
        for f in astlint.lint_file(path, hot_functions=functions):
            report.add(f)
    return report


# ------------------------------------------------------------ the sweep

def _require_devices(n: int) -> None:
    import jax

    if len(jax.devices()) < n:
        raise RuntimeError(
            f"shardlint needs a {n}-way CPU mesh; run with XLA_FLAGS="
            f"'--xla_force_host_platform_device_count={max(n, 8)}' set "
            "before jax is imported (scripts/shardlint.py does this)")


def _mesh(axes: Tuple[str, ...], shape: Tuple[int, ...]):
    import jax

    from pytorch_distributed_tpu.parallel import MeshSpec, build_mesh

    n = 1
    for s in shape:
        n *= s
    _require_devices(n)
    return build_mesh(MeshSpec(axes, shape), jax.devices()[:n])


def _image_batch(batch=16, image=8, classes=10, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return {
        "images": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), jnp.float32),
        "labels": jnp.asarray(
            rng.integers(0, classes, size=batch), jnp.int32),
        "weights": jnp.ones((batch,), jnp.float32),
    }


def _tiny_image_model(classes=10):
    import flax.linen as nn

    class TinyMLP(nn.Module):
        """BN-free classifier: isolates the step/collective plumbing."""

        classes: int = 10

        @nn.compact
        def __call__(self, x, train: bool = True):
            x = x.reshape((x.shape[0], -1))
            x = nn.relu(nn.Dense(32)(x))
            return nn.Dense(self.classes)(x)

    return TinyMLP(classes=classes)


def _image_state(model, grad_compress: str = "none", explicit: bool = False,
                 n_data: int = 4):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8, 8, 3)), train=False)
    residual = qcomm.init_residual(variables["params"], grad_compress,
                                   explicit=explicit, n_data=n_data)
    return TrainState.create(variables, sgd_init(variables["params"]),
                             residual=residual)


def _recipe_train_image(explicit: bool, grad_compress: str = "none",
                        overlap: str = "none", bucket_mb: float = 4.0):
    import jax.numpy as jnp

    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = _mesh(("data",), (4,))
    model = _tiny_image_model()
    state = _image_state(model, grad_compress=grad_compress,
                         explicit=explicit)
    step = make_train_step(model, mesh, explicit_collectives=explicit,
                           grad_compress=grad_compress, overlap=overlap,
                           bucket_mb=bucket_mb)
    return step, (state, _image_batch(), jnp.float32(0.1)), (0,), mesh


def _recipe_train_image_zero(grad_compress: str = "none"):
    """Explicit-collectives image step under ``--zero wus`` (parallel/
    zero.py): the hand-written grad allreduce becomes a reduce-scatter +
    delta all-gather and momentum lives as stacked 1/N chunks."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.parallel import zero as zero_lib
    from pytorch_distributed_tpu.train.state import TrainState
    from pytorch_distributed_tpu.train.steps import make_train_step

    mesh = _mesh(("data",), (4,))
    model = _tiny_image_model()
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 8, 8, 3)), train=False)
    quantized = grad_compress in qcomm.QUANTIZED_MODES
    residual = qcomm.init_residual(variables["params"], grad_compress,
                                   explicit=True, n_data=4)
    state = TrainState.create(
        variables,
        zero_lib.init_wus_momentum(variables["params"], 4,
                                   quantized=quantized),
        residual=residual)
    step = make_train_step(model, mesh, explicit_collectives=True,
                           grad_compress=grad_compress, zero="wus")
    return step, (state, _image_batch(), jnp.float32(0.1)), (0,), mesh


def _recipe_lm_overlap(grad_compress: str = "none"):
    """Explicit shard_map DP LM step under the bucketed comm-overlap
    scheduler (parallel/overlap.py): the grad sync lowers as per-bucket
    collectives scope-labeled ``b<k>``, and with ``--grad-compress int8``
    the compiled wire carries s8 payloads + f32 scales — the HLO-ledger
    evidence that compression rides the real collectives, not a numerics
    emulation."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.ops import qcomm
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = _mesh(("data",), (4,))
    model = TransformerLM(
        vocab_size=_LM["vocab"], d_model=_LM["d_model"],
        n_heads=_LM["n_heads"], n_layers=1)
    tokens = jnp.zeros((_LM["batch"], _LM["seq"]), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    residual = qcomm.init_residual(params, grad_compress, explicit=True,
                                   n_data=4)
    state = TrainState.create({"params": params}, sgd_init(params),
                              residual=residual)
    # ~8 KiB buckets so even the tiny model splits into several buckets
    # and the ledger exercises multi-bucket b<k> attribution.
    step = make_lm_train_step(model, mesh, replicated_like(params),
                              grad_compress=grad_compress,
                              overlap="bucketed", bucket_mb=1 / 128)
    return step, (state, tokens, jnp.float32(0.1)), (0,), mesh


def _recipe_train_lm_zero():
    """GSPMD LM step with ``zero='wus'``: momentum leaves take fsdp_specs
    data-axis shardings, XLA derives the weight-update collectives."""
    import jax.numpy as jnp

    mesh = _mesh(("data",), (4,))
    _, _, state, tokens, step = _lm_setup(mesh, zero="wus")
    return step, (state, tokens, jnp.float32(0.1)), (0,), mesh


def _recipe_eval_image():
    from pytorch_distributed_tpu.train.steps import make_eval_step

    mesh = _mesh(("data",), (4,))
    model = _tiny_image_model()
    state = _image_state(model)
    step = make_eval_step(model, mesh)
    return step, (state, _image_batch()), (), mesh


def _lm_setup(mesh, specs=None, **step_kw):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    model = TransformerLM(
        vocab_size=_LM["vocab"], d_model=_LM["d_model"],
        n_heads=_LM["n_heads"], n_layers=1)
    tokens = jnp.zeros((_LM["batch"], _LM["seq"]), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    if specs is None:
        specs = replicated_like(params)
    elif callable(specs):
        specs = specs(params)
    state = TrainState.create({"params": params}, sgd_init(params))
    if step_kw.get("zero") == "wus":
        step_kw["params"] = params  # wus sizes its momentum specs from these
    step = make_lm_train_step(model, mesh, specs, **step_kw)
    return model, specs, state, tokens, step


def _recipe_lm_train(fused_ce_mode: Optional[str]):
    import jax.numpy as jnp

    mesh = _mesh(("data",), (4,))
    kw = {} if fused_ce_mode is None else dict(
        fused_ce_chunks=2, fused_ce_mode=fused_ce_mode)
    _, _, state, tokens, step = _lm_setup(mesh, **kw)
    return step, (state, tokens, jnp.float32(0.1)), (0,), mesh


def _recipe_lm_fused_tp():
    import jax.numpy as jnp

    from pytorch_distributed_tpu.parallel.tp import tp_specs

    mesh = _mesh(("data", "model"), (2, 2))
    _, _, state, tokens, step = _lm_setup(
        mesh, specs=tp_specs, fused_ce_chunks=2, fused_ce_mode="tp")
    return step, (state, tokens, jnp.float32(0.1)), (0,), mesh


def _recipe_lm_eval():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.transformer import TransformerLM
    from pytorch_distributed_tpu.parallel.tp import replicated_like
    from pytorch_distributed_tpu.train.lm import make_lm_eval_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    mesh = _mesh(("data",), (4,))
    model = TransformerLM(
        vocab_size=_LM["vocab"], d_model=_LM["d_model"],
        n_heads=_LM["n_heads"], n_layers=1)
    tokens = jnp.zeros((_LM["batch"], _LM["seq"]), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_eval_step(model, mesh, replicated_like(params))
    return step, (state, tokens), (), mesh


def _recipe_pipeline(schedule: str):
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.pipeline_lm import (
        PipelinedTransformerLM,
        pp_specs,
    )
    from pytorch_distributed_tpu.train.lm import make_lm_train_step
    from pytorch_distributed_tpu.train.optim import sgd_init
    from pytorch_distributed_tpu.train.state import TrainState

    if schedule == "interleaved":
        mesh = _mesh(("data", "pipe"), (2, 2))
        stages, micro, virtual = 2, 2, 2
    else:
        mesh = _mesh(("data", "pipe"), (1, 4))
        stages, micro, virtual = 4, 4, 1
    model = PipelinedTransformerLM(
        vocab_size=_LM["vocab"], d_model=_LM["d_model"],
        n_heads=_LM["n_heads"], n_layers=4, n_stages=stages,
        n_microbatches=micro, mesh=mesh, schedule=schedule,
        n_virtual=virtual)
    tokens = jnp.zeros((_LM["batch"], _LM["seq"]), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), tokens)["params"]
    state = TrainState.create({"params": params}, sgd_init(params))
    step = make_lm_train_step(model, mesh, pp_specs(params))
    return step, (state, tokens, jnp.float32(0.1)), (0,), mesh


def _recipe_decode():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.generate import _make_run
    from pytorch_distributed_tpu.models.transformer import TransformerLM

    B, P, new = 2, 8, 4
    run = _make_run(B, P, new, _LM["vocab"], _LM["d_model"],
                    _LM["n_heads"], 1, "float32", 0.0, 0, 0.0, "", False)
    model = TransformerLM(
        vocab_size=_LM["vocab"], d_model=_LM["d_model"],
        n_heads=_LM["n_heads"], n_layers=1, attn_impl="dense",
        decode=True, max_len=P + new)
    prompt = jnp.zeros((B, P), jnp.int32)
    params = model.init(jax.random.PRNGKey(0), prompt)["params"]
    # Single-device decode: no mesh, no donation by design (the cache is
    # created inside the jit; params are reused across calls).
    return run, (params, prompt, jax.random.PRNGKey(0)), None, None


def _recipe_serve(phase: str):
    """The serving engine's jitted steps (serving/engine.py), at the
    engine's own tiny reference shapes.  ``_make_steps`` is lru-cached,
    so these lowerings ARE the callables a same-config engine runs — the
    recipe sweep, shardlint, and the ledgers fence serving traffic with
    no second trace.  No donation (pools thread through like the decode
    cache); a 1-device data mesh so the baseline sweep books the entry.
    """
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.serving.engine import _make_steps
    from pytorch_distributed_tpu.serving.kvpool import init_pools

    B, NB, BS, W, C = 2, 8, 4, 4, 8
    steps = _make_steps(_LM["vocab"], _LM["d_model"], _LM["n_heads"], 1,
                        BS, 0.0, 0, 1.0, "")
    pk, pv = init_pools(1, NB, BS, _LM["n_heads"],
                        _LM["d_model"] // _LM["n_heads"])
    table1 = jnp.zeros((1, W), jnp.int32)
    params = steps.model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 1), jnp.int32), pk, pv,
        table1, jnp.zeros((1, 1), jnp.int32))["params"]
    key = jax.random.PRNGKey(0)
    mesh = _mesh(("data",), (1,))
    if phase == "prefill":
        args = (params, pk, pv, jnp.zeros((1, C), jnp.int32),
                jnp.int32(0), jnp.int32(C), table1, key)
        return steps.prefill, args, None, mesh
    args = (params, pk, pv, jnp.zeros((B,), jnp.int32),
            jnp.zeros((B,), jnp.int32), jnp.zeros((B, W), jnp.int32), key)
    return steps.decode, args, None, mesh


# Every jitted step builder in the framework, as zero-arg constructors
# returning (jitted, example_args, donate_argnums-or-None, mesh-or-None).
RECIPES: "OrderedDict[str, Callable[[], tuple]]" = OrderedDict([
    ("train_image_gspmd", lambda: _recipe_train_image(False)),
    ("train_image_explicit", lambda: _recipe_train_image(True)),
    # Compressed gradient sync (ops/qcomm.py) over the explicit shard_map
    # path: the pinned per-kind byte budgets make an accidental f32
    # fallback in grad_sync a hard collective-regression error.
    ("train_image_bf16", lambda: _recipe_train_image(True, "bf16")),
    ("train_image_int8", lambda: _recipe_train_image(True, "int8")),
    # Weight-update sharding (parallel/zero.py): the pinned reduce-scatter
    # / all-gather budgets make an accidental allreduce fallback (or a
    # momentum layout regression) a hard collective-regression error.
    # Bucketed comm-overlap scheduler (parallel/overlap.py): grad sync
    # splits into per-bucket collectives (scope b<k>) so each can overlap
    # the remaining backward.  Bucketing must not change totals — the
    # pinned budgets fence a bucket-count or per-bucket-bytes drift, and
    # the int8 variant pins that compression survives onto the real wire.
    ("train_image_bucketed",
     lambda: _recipe_train_image(True, overlap="bucketed",
                                 bucket_mb=1 / 128)),
    ("lm_train_bucketed", lambda: _recipe_lm_overlap()),
    ("lm_train_bucketed_int8", lambda: _recipe_lm_overlap("int8")),
    ("train_image_zero", _recipe_train_image_zero),
    ("train_lm_zero", _recipe_train_lm_zero),
    ("eval_image", _recipe_eval_image),
    ("lm_train_dp", lambda: _recipe_lm_train(None)),
    ("lm_fused_ce_replicated", lambda: _recipe_lm_train("replicated")),
    ("lm_fused_ce_dp", lambda: _recipe_lm_train("dp")),
    ("lm_fused_ce_tp", _recipe_lm_fused_tp),
    ("lm_eval", _recipe_lm_eval),
    ("lm_pp_gpipe", lambda: _recipe_pipeline("gpipe")),
    ("lm_pp_1f1b", lambda: _recipe_pipeline("1f1b")),
    ("lm_pp_interleaved", lambda: _recipe_pipeline("interleaved")),
    ("decode_greedy", _recipe_decode),
    ("serve_prefill", lambda: _recipe_serve("prefill")),
    ("serve_decode", lambda: _recipe_serve("decode")),
])


# Recipes that declare --zero wus: analyze_recipe promotes their
# replicated-state finding from info to error (the declared layout IS
# sharded optimizer state, so a replicated fallback is a regression).
ZERO_RECIPES = frozenset({"train_image_zero", "train_lm_zero"})


def analyze_recipe(name: str, **thresholds) -> StepReport:
    """Analyze one recipe, reusing the session's cached lowering: only the
    first call per step pays the compile; threshold variations re-run just
    the detectors."""
    thresholds.setdefault("declared_zero", name in ZERO_RECIPES)
    return analyze_lowering(get_lowering(name), **thresholds)


def comm_ledger_for(name: str):
    """The itemized comm ledger (obs/comms.py) for one recipe, off the
    shared lowering cache."""
    from pytorch_distributed_tpu.obs import comms

    low = get_lowering(name)
    return comms.ledger_from_hlo_text(low.text, step=name,
                                      mesh_shape=low.mesh_shape)


def sweep_comm_ledgers(names: Optional[Sequence[str]] = None):
    """Ledgers for every (or the named subset of) recipe step builders —
    what ``scripts/shardlint.py --comm-ledger`` serializes to
    ``comm_ledger.json``."""
    selected = list(RECIPES) if names is None else [
        n for n in names if n in RECIPES]
    return [comm_ledger_for(n) for n in selected]


def mem_ledger_for(name: str):
    """The live-range memory ledger (obs/memory.py) for one recipe, off
    the shared lowering cache — the ``memory_analysis()`` ground truth
    and per-argument buffer classes ride the same compiled record, so
    the whole sweep is zero extra compiles."""
    from pytorch_distributed_tpu.obs import comms, memory

    low = get_lowering(name)
    return memory.ledger_from_hlo_text(
        low.text, step=name, mesh_shape=low.mesh_shape,
        arg_classes=memory.arg_classes_of(low.args),
        measured_peak_bytes=comms.compiled_peak_bytes(low.compiled))


def sweep_mem_ledgers(names: Optional[Sequence[str]] = None):
    """Memory ledgers for every (or the named subset of) recipe step —
    ``scripts/shardlint.py --mem-ledger`` serializes these to
    ``mem_ledger.json``."""
    selected = list(RECIPES) if names is None else [
        n for n in names if n in RECIPES]
    return [mem_ledger_for(n) for n in selected]


def analyze_all(names: Optional[Sequence[str]] = None,
                include_lint: bool = True, **thresholds) -> List[StepReport]:
    """Analyze every recipe step (or the named subset) + the hot-loop lint."""
    selected = list(RECIPES) if names is None else list(names)
    unknown = [n for n in selected if n not in RECIPES and n != "hot-loops"]
    if unknown:
        raise KeyError(f"unknown steps {unknown}; known: {list(RECIPES)}")
    reports = [analyze_recipe(n, **thresholds)
               for n in selected if n in RECIPES]
    if include_lint and (names is None or "hot-loops" in selected):
        reports.append(lint_hot_loops())
    return reports


def baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


# ----------------------------------------------------------- the selftest

def build_synthetic_bad_step(mesh, data_axis: str = "data"):
    """A step with all three compiled-level hazards planted:

    1. a replicated ``f32[2048, 128]`` (1 MiB) scan-carry accumulator;
    2. a ``bf16[8, 65536]`` → f32 (2 MiB) materialized upcast;
    3. a donated argument no output can alias (the donation is lost).

    Returns ``(jitted, args, donate_argnums)`` for ``analyze_jitted``."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    N, D = 2048, 128
    B, F = 8, 65536

    def bad_step(w, xb):
        act = (xb * jnp.bfloat16(1.5)).astype(jnp.float32)  # planted upcast
        s = jnp.sum(act) / act.size

        def body(c, _):
            return c * 0.999 + s, ()

        # planted replicated accumulator: a full-size global carry on a
        # >1-device mesh (nothing shards it)
        acc, _ = jax.lax.scan(
            body, jnp.full((N, D), s, jnp.float32), jnp.arange(4))
        # outputs deliberately share no shape with w: donation is lost
        return acc.astype(jnp.bfloat16), s + jnp.sum(w)

    rep = NamedSharding(mesh, P())
    jitted = jax.jit(
        bad_step,
        in_shardings=(rep, NamedSharding(mesh, P(data_axis, None))),
        out_shardings=(rep, rep),
        donate_argnums=(0,),
    )
    args = (jnp.ones((N, D // 2), jnp.float32),
            jnp.ones((B, F), jnp.bfloat16))
    return jitted, args, (0,)


_SYNTHETIC_BAD_LOWERING: Optional[Lowering] = None


def get_synthetic_bad_lowering() -> Lowering:
    """Session-memoized lowering of the planted synthetic-bad step on the
    4-way data mesh — the same one-compile discipline as
    :func:`get_lowering`, so ``selftest`` and the shardlint tests share a
    single compile instead of each paying their own."""
    global _SYNTHETIC_BAD_LOWERING
    if _SYNTHETIC_BAD_LOWERING is None:
        mesh = _mesh(("data",), (4,))
        jitted, args, donate = build_synthetic_bad_step(mesh)
        _SYNTHETIC_BAD_LOWERING = lower_jitted(
            jitted, args, name="synthetic-bad", mesh=mesh, donate=donate)
    return _SYNTHETIC_BAD_LOWERING


_PLANTED_SYNC_SRC = '''\
def fit(self, steps):
    total = 0.0
    for i in range(steps):
        state, metrics = self.step_fn(state, batch)
        total += float(metrics["loss"])          # planted sync 1
        acc = np.asarray(metrics["acc"])         # planted sync 2
        metrics["loss"].block_until_ready()      # planted sync 3
        ok = float(metrics["loss"])  # shardlint: allow-sync
    return total


def assemble(batch):
    # not a hot loop member unless selected; float() here is host-side
    for row in batch:
        yield float(row)
'''


def selftest(verbose: bool = False) -> Dict[str, Any]:
    """Planted-hazard checks: every detector must fire on the synthetic bad
    step and stay silent on the fenced-good fused-CE paths.  Raises
    ``AssertionError`` on any miss; returns a summary dict."""
    V, Dm = _LM["vocab"], _LM["d_model"]
    summary: Dict[str, Any] = {}

    def log(msg):
        if verbose:
            print(f"  [selftest] {msg}")

    # 1. planted hazards all detected (memoized: one compile per session
    #    shared with the shardlint tests)
    rep = analyze_lowering(get_synthetic_bad_lowering())
    kinds = {f.kind for f in rep.findings}
    assert "replicated-large-tensor" in kinds, rep.findings
    assert any(f.kind == "replicated-large-tensor" and f.shape == (2048, 128)
               for f in rep.findings), rep.findings
    assert "dtype-promotion" in kinds, rep.findings
    assert "lost-donation" in kinds, rep.findings
    summary["synthetic_bad_findings"] = len(rep.findings)
    log(f"synthetic bad step: {sorted(kinds)}")

    # 2. planted host syncs: exactly the 3 unsuppressed calls in fit()
    lint = astlint.lint_source(_PLANTED_SYNC_SRC, "planted.py",
                               hot_functions=("fit",))
    assert len(lint) == 3, lint
    summary["planted_syncs"] = len(lint)
    log("planted host syncs: 3/3")

    # 3. the real hot loops are currently clean
    hot = lint_hot_loops()
    assert not hot.findings, hot.findings
    log("hot loops clean")

    # 4. fused-CE fence: replicated mode carries the full [V, D] dE per
    # device; dp and tp modes must not (the PR-1 regression fence)
    bad = analyze_recipe("lm_fused_ce_replicated",
                         min_replicated_bytes=4096)
    assert any(f.kind == "replicated-large-tensor" and f.shape == (V, Dm)
               for f in bad.findings), bad.findings
    for mode in ("lm_fused_ce_dp", "lm_fused_ce_tp"):
        good = analyze_recipe(mode, min_replicated_bytes=4096)
        assert not good.by_kind("replicated-large-tensor"), (
            mode, good.findings)
        log(f"{mode}: no replicated accumulator")
    summary["fused_ce_fence"] = "ok"

    # 5. the LM train step's donation fully aliases
    donated = analyze_recipe("lm_train_dp")
    assert donated.donation.get("missing") == [], donated.donation
    assert not donated.by_kind("lost-donation"), donated.findings
    summary["lm_train_donation"] = donated.donation.get("aliased")
    log(f"lm_train_dp aliased {donated.donation.get('aliased')} leaves")
    summary["ok"] = True
    return summary
